"""CRAM 3.0 container/slice codec, clean-room from the CRAM specification.

Round 1 refused CRAM alignment decode (the reference accepts CRAM
everywhere via samtools/biogo: covstats/covstats.go:229 smoove
shared.NewReader, depth/depth.go:45 samtools, indexcov/indexcov.go:359-371
CRAM headers). This module decodes CRAM 3.0 records into the same
columnar ``ReadColumns`` feed the BAM path produces, so depth / covstats
/ cohortdepth accept .cram inputs.

Scope (everything the depth tools need):
  - file definition, containers, blocks (raw/gzip/bzip2/lzma/rANS-4x8)
  - compression header: preservation map (RN/AP/RR/SM/TD), data-series
    and tag encoding maps
  - codecs: EXTERNAL, HUFFMAN (canonical, incl. the common 0-bit
    single-symbol case), BETA, GAMMA, BYTE_ARRAY_LEN, BYTE_ARRAY_STOP
  - slice decode: BF/CF/RI/RL/AP(delta)/RG/RN/mate/TL+tags/features/
    MQ/QS with ref-span reconstruction from features (S/I/i/D/N/H/P)
  - .crai-driven random access (container offsets per region)

Bases themselves are not reconstructed (depth counts alignment spans,
never sequence), so reference-based decoding (RR) only needs feature
bookkeeping — no FASTA round trip. A fixture writer (CramWriter) and a
rANS-4x8 order-0 encoder live alongside so the test suite can fabricate
hermetic .cram files and round-trip the decoder without copying any
reference test data.
"""

from __future__ import annotations

import gzip
import struct
import zlib
from dataclasses import dataclass, field

import numpy as np

from ..obs.logging import get_logger as _get_logger

CRAM_MAGIC = b"CRAM"

# block compression methods
M_RAW, M_GZIP, M_BZIP2, M_LZMA, M_RANS = 0, 1, 2, 3, 4
M_RANSNX16, M_ARITH, M_FQZCOMP, M_TOK3 = 5, 6, 7, 8
# block content types
CT_FILE_HEADER, CT_COMP_HEADER, CT_SLICE_HEADER = 0, 1, 2
CT_EXTERNAL, CT_CORE = 4, 5

# CRAM record flags (CF)
CF_QS_STORED = 0x1
CF_DETACHED = 0x2
CF_MATE_DOWNSTREAM = 0x4
CF_NO_SEQ = 0x8

# BAM flag bits reconstructed from MF
MF_MATE_REVERSE = 0x1
MF_MATE_UNMAPPED = 0x2
BAM_MREVERSE = 0x20
BAM_MUNMAP = 0x8


# ---------------------------------------------------------------- itf8

def read_itf8(buf: memoryview, pos: int) -> tuple[int, int]:
    b0 = buf[pos]
    if b0 < 0x80:
        return b0, pos + 1
    if b0 < 0xC0:
        return ((b0 & 0x7F) << 8) | buf[pos + 1], pos + 2
    if b0 < 0xE0:
        return ((b0 & 0x3F) << 16) | (buf[pos + 1] << 8) | buf[pos + 2], \
            pos + 3
    if b0 < 0xF0:
        return ((b0 & 0x1F) << 24) | (buf[pos + 1] << 16) | \
            (buf[pos + 2] << 8) | buf[pos + 3], pos + 4
    v = ((b0 & 0x0F) << 28) | (buf[pos + 1] << 20) | \
        (buf[pos + 2] << 12) | (buf[pos + 3] << 4) | (buf[pos + 4] & 0x0F)
    # interpret as signed 32-bit
    if v & 0x80000000:
        v -= 1 << 32
    return v, pos + 5


def write_itf8(v: int) -> bytes:
    v &= 0xFFFFFFFF
    if v < 0x80:
        return bytes([v])
    if v < 0x4000:
        return bytes([0x80 | (v >> 8), v & 0xFF])
    if v < 0x200000:
        return bytes([0xC0 | (v >> 16), (v >> 8) & 0xFF, v & 0xFF])
    if v < 0x10000000:
        return bytes([0xE0 | (v >> 24), (v >> 16) & 0xFF, (v >> 8) & 0xFF,
                      v & 0xFF])
    return bytes([0xF0 | ((v >> 28) & 0x0F), (v >> 20) & 0xFF,
                  (v >> 12) & 0xFF, (v >> 4) & 0xFF, v & 0x0F])


def read_ltf8(buf: memoryview, pos: int) -> tuple[int, int]:
    b0 = buf[pos]
    n_extra = 0
    mask = 0x80
    while n_extra < 8 and (b0 & mask):
        n_extra += 1
        mask >>= 1
    if n_extra == 0:
        return b0, pos + 1
    if n_extra < 8:
        v = b0 & (0xFF >> (n_extra + 1))
    else:
        v = 0
    for i in range(n_extra):
        v = (v << 8) | buf[pos + 1 + i]
    if n_extra == 8 and v & (1 << 63):
        v -= 1 << 64
    return v, pos + 1 + n_extra


def write_ltf8(v: int) -> bytes:
    v &= (1 << 64) - 1
    if v < 0x80:
        return bytes([v])
    for n in range(1, 8):  # n extra bytes; (7 - n) value bits in byte 0
        if v < (1 << (7 + 7 * n)):
            prefix = (0xFF << (8 - n)) & 0xFF
            body = v.to_bytes(n + 1, "big")
            return bytes([prefix | body[0]]) + body[1:]
    return bytes([0xFF]) + v.to_bytes(8, "big")


# --------------------------------------------------------- rANS 4x8

RANS_LOW = 1 << 23
TF_SHIFT = 12
TOTFREQ = 1 << TF_SHIFT


def _read_u7(buf, pos):
    """rANS frequency value: 1 byte (<128) or 2 bytes (0x80|hi, lo)."""
    b0 = buf[pos]
    if b0 < 0x80:
        return b0, pos + 1
    return ((b0 & 0x7F) << 8) | buf[pos + 1], pos + 2


def _write_u7(v: int) -> bytes:
    if v < 0x80:
        return bytes([v])
    return bytes([0x80 | (v >> 8), v & 0xFF])


def _read_freqs0(buf, pos):
    freqs = np.zeros(256, dtype=np.int64)
    sym = buf[pos]
    pos += 1
    last_sym = sym
    rle = 0
    while True:
        f, pos = _read_u7(buf, pos)
        freqs[sym] = f
        if rle > 0:
            rle -= 1
            sym += 1
        else:
            sym = buf[pos]
            pos += 1
            # unmasked comparison: last_sym 255 must NOT treat the 0x00
            # terminator as an adjacent-run marker (255 + 1 = 256 != 0)
            if sym == last_sym + 1:
                rle = buf[pos]
                pos += 1
            last_sym = sym
        if sym == 0 and rle == 0:
            break
    return freqs, pos


def _rans_decode_0(buf, pos, out_len):
    freqs, pos = _read_freqs0(buf, pos)
    cum = np.zeros(257, dtype=np.int64)
    np.cumsum(freqs, out=cum[1:])
    # symbol lookup table over the 4096 range
    lut = np.zeros(TOTFREQ, dtype=np.uint8)
    for s in np.nonzero(freqs)[0]:
        lut[cum[s]:cum[s + 1]] = s
    R = list(struct.unpack_from("<4I", buf, pos))
    pos += 16
    out = bytearray(out_len)
    n = len(buf)
    for i in range(out_len):
        j = i & 3
        x = R[j]
        m = x & (TOTFREQ - 1)
        s = lut[m]
        out[i] = s
        x = int(freqs[s]) * (x >> TF_SHIFT) + m - int(cum[s])
        while x < RANS_LOW and pos < n:
            x = (x << 8) | buf[pos]
            pos += 1
        R[j] = x
    return bytes(out)


def _rans_decode_1(buf, pos, out_len):
    # outer RLE over contexts, inner order-0 tables
    freqs = np.zeros((256, 256), dtype=np.int64)
    cums = np.zeros((256, 257), dtype=np.int64)
    ctx = buf[pos]
    pos += 1
    last_ctx = ctx
    rle = 0
    luts = {}
    while True:
        f, pos = _read_freqs0(buf, pos)
        freqs[ctx] = f
        np.cumsum(f, out=cums[ctx][1:])
        lut = np.zeros(TOTFREQ, dtype=np.uint8)
        for s in np.nonzero(f)[0]:
            lut[cums[ctx][s]:cums[ctx][s + 1]] = s
        luts[ctx] = lut
        if rle > 0:
            rle -= 1
            ctx += 1
        else:
            ctx = buf[pos]
            pos += 1
            if ctx == last_ctx + 1:  # unmasked: see _read_freqs0
                rle = buf[pos]
                pos += 1
            last_ctx = ctx
        if ctx == 0 and rle == 0:
            break
    R = list(struct.unpack_from("<4I", buf, pos))
    pos += 16
    out = bytearray(out_len)
    n = len(buf)
    F = out_len >> 2
    last = [0, 0, 0, 0]
    idx = [j * F for j in range(4)]
    ends = [F, 2 * F, 3 * F, out_len]
    i = 0
    while True:
        done = True
        for j in range(4):
            if idx[j] >= ends[j]:
                continue
            done = False
            x = R[j]
            c = last[j]
            m = x & (TOTFREQ - 1)
            if c not in luts:
                # a context byte with no frequency table means the
                # stream is corrupt or foreign — fail loudly instead of
                # silently desynchronizing on symbol 0
                raise ValueError("cram: rans missing order-1 context")
            s = luts[c][m]
            out[idx[j]] = s
            x = int(freqs[c][s]) * (x >> TF_SHIFT) + m - int(cums[c][s])
            while x < RANS_LOW and pos < n:
                x = (x << 8) | buf[pos]
                pos += 1
            R[j] = x
            last[j] = s
            idx[j] += 1
        i += 1
        if done:
            break
    return bytes(out)


def rans_decode(data: bytes) -> bytes:
    buf = memoryview(data)
    order = buf[0]
    # compressed size u32, uncompressed size u32
    out_len = struct.unpack_from("<I", buf, 5)[0]
    if out_len == 0:
        return b""
    if order in (0, 1):
        from . import native

        fast = native.rans4x8_decode(data, 9, order, out_len)
        if fast is not None:
            return fast
        return (_rans_decode_0 if order == 0 else _rans_decode_1)(
            buf, 9, out_len)
    raise ValueError(f"cram: unknown rANS order {order}")


def _normalize_freqs(freqs: np.ndarray, total: int,
                     target: int = TOTFREQ) -> np.ndarray:
    """Counts → per-symbol frequencies summing exactly to ``target``
    (TOTFREQ for 4x8; the Nx16 codec passes its shift-derived total).

    Rare symbols floor-clamp to 1, which can push the sum ABOVE target
    for large skewed alphabets (e.g. 200 singleton symbols); the deficit
    is then shaved from the largest entries (each kept ≥ 1) rather than
    blindly subtracted from one argmax, which could go negative.
    """
    present = freqs > 0
    norm = np.maximum((freqs * target) // max(total, 1),
                      present.astype(np.int64))
    diff = target - int(norm.sum())
    if diff >= 0:
        norm[int(np.argmax(norm))] += diff
        return norm
    while diff < 0:
        big = int(np.argmax(norm))
        if norm[big] <= 1:
            # all present symbols at 1 and still over TOTFREQ: >4096
            # distinct symbols is impossible for a byte alphabet
            raise ValueError("rans: degenerate distribution")
        take = min(-diff, int(norm[big]) - 1)
        norm[big] -= take
        diff += take
    return norm


def _serialize_rle(symbols, payload_fn) -> bytearray:
    """The rANS table outer structure shared by both orders: ascending
    symbol/context bytes with adjacent-run RLE (marker byte sym+1, then
    the count of FURTHER consecutive entries), each entry followed by
    ``payload_fn(symbol)`` bytes, 0x00-terminated."""
    table = bytearray()
    i = 0
    while i < len(symbols):
        run = 0
        while (i + run + 1 < len(symbols)
               and symbols[i + run + 1] == symbols[i + run] + 1):
            run += 1
        table.append(int(symbols[i]))
        table += payload_fn(int(symbols[i]))
        if run:
            table.append(int(symbols[i] + 1))
            table.append(run - 1)
            for k in range(1, run + 1):
                table += payload_fn(int(symbols[i + k]))
        i += run + 1
    table.append(0)
    return table


def _serialize_freqs0(norm: np.ndarray) -> bytearray:
    """Order-0 frequency table bytes (RLE over adjacent symbols)."""
    return _serialize_rle(np.nonzero(norm > 0)[0],
                          lambda s: _write_u7(int(norm[s])))


def rans_encode_0(data: bytes) -> bytes:
    """Order-0 rANS 4x8 encoder (for fixtures + decoder round-trips)."""
    if len(data) == 0:
        return b"\x00" + struct.pack("<II", 0, 0)
    arr = np.frombuffer(data, dtype=np.uint8)
    freqs = np.bincount(arr, minlength=256).astype(np.int64)
    norm = _normalize_freqs(freqs, len(arr))
    cum = np.zeros(257, dtype=np.int64)
    np.cumsum(norm, out=cum[1:])
    table = _serialize_freqs0(norm)

    # encode backwards with 4 interleaved states
    R = [RANS_LOW] * 4
    payload = bytearray()
    for i in range(len(arr) - 1, -1, -1):
        s = int(arr[i])
        j = i & 3
        f = int(norm[s])
        x = R[j]
        x_max = ((RANS_LOW >> TF_SHIFT) << 8) * f
        while x >= x_max:
            payload.append(x & 0xFF)
            x >>= 8
        R[j] = ((x // f) << TF_SHIFT) + (x % f) + int(cum[s])
    states = b"".join(struct.pack("<I", R[j]) for j in range(4))
    body = bytes(table) + states + bytes(reversed(payload))
    return b"\x00" + struct.pack("<II", len(body), len(arr)) + body


def rans_encode_1(data: bytes) -> bytes:
    """Order-1 rANS 4x8 encoder — validation twin for the order-1
    decoder (real CRAMs use o1 for base/quality streams; our block
    writer uses o0/gzip). Four interleaved streams over quarters, each
    symbol coded in its in-stream predecessor's context, encoded in the
    exact reverse of the decoder's consumption order.
    """
    n = len(data)
    if n < 4:
        raise ValueError("rans o1 needs at least 4 bytes")
    arr = np.frombuffer(data, dtype=np.uint8)
    F = n >> 2
    quarter_lo = [0, F, 2 * F, 3 * F]
    quarter_hi = [F, 2 * F, 3 * F, n]

    counts = np.zeros((256, 256), dtype=np.int64)
    totals = np.zeros(256, dtype=np.int64)
    for j in range(4):
        lo, hi = quarter_lo[j], quarter_hi[j]
        prevs = np.concatenate(([0], arr[lo:hi - 1]))
        np.add.at(counts, (prevs, arr[lo:hi]), 1)
        np.add.at(totals, prevs, 1)

    norm = np.zeros((256, 256), dtype=np.int64)
    cums = np.zeros((256, 257), dtype=np.int64)
    ctxs = np.nonzero(totals > 0)[0]
    for c in ctxs:
        norm[c] = _normalize_freqs(counts[c], int(totals[c]))
        np.cumsum(norm[c], out=cums[c][1:])

    # outer context table: RLE over contexts, inner o0 table each
    table = _serialize_rle(ctxs, lambda c: _serialize_freqs0(norm[c]))

    # decoder consumption order: for i ascending, streams 0..3 each
    # decode their i-th in-quarter symbol (stream 3 alone in the tail);
    # encode by walking that order backwards directly
    def reverse_steps():
        for i in range(n - 3 * F - 1, -1, -1):
            for j in (3, 2, 1, 0):
                p = quarter_lo[j] + i
                if p < quarter_hi[j]:
                    yield j, p

    R = [RANS_LOW] * 4
    payload = bytearray()
    for j, p in reverse_steps():
        s = int(arr[p])
        ctx = int(arr[p - 1]) if p > quarter_lo[j] else 0
        f = int(norm[ctx][s])
        x = R[j]
        x_max = ((RANS_LOW >> TF_SHIFT) << 8) * f
        while x >= x_max:
            payload.append(x & 0xFF)
            x >>= 8
        R[j] = ((x // f) << TF_SHIFT) + (x % f) + int(cums[ctx][s])
    states = b"".join(struct.pack("<I", R[j]) for j in range(4))
    body = bytes(table) + states + bytes(reversed(payload))
    return b"\x01" + struct.pack("<II", len(body), n) + body


# ------------------------------------------------------------- blocks

def _decompress(method: int, data: bytes, raw_size: int) -> bytes:
    if method == M_RAW:
        return data
    if method in (M_GZIP, M_BZIP2, M_LZMA):
        import lzma

        try:
            if method == M_GZIP:
                return gzip.decompress(data)
            if method == M_BZIP2:
                import bz2

                return bz2.decompress(data)
            return lzma.decompress(data)
        except (OSError, ValueError, zlib.error, EOFError,
                lzma.LZMAError) as e:
            # stdlib decompressors raise their own error types on a
            # corrupt payload (LZMAError is not an OSError; truncated
            # bz2 raises a bare ValueError) — re-wrap with the
            # module's 'cram:' context
            raise ValueError(
                f"cram: corrupt block payload (method {method}: {e})"
            ) from e
    if method == M_RANS:
        return rans_decode(data)
    if method in (M_RANSNX16, M_ARITH, M_FQZCOMP, M_TOK3):
        if method == M_RANSNX16:
            from .rans_nx16 import decode as dec
        elif method == M_ARITH:
            from .arith import decode as dec
        elif method == M_FQZCOMP:
            from .fqzcomp import decode as dec
        else:
            from .tok3 import decode as dec
        try:
            return dec(data, raw_size)
        except ValueError as e:
            # the 3.1 codec layouts are pinned by in-repo encoder
            # twins (no htslib exists here to cross-validate, see
            # docs/cram.md): keep the actionable remedy a foreign
            # stream's parse failure used to get
            raise ValueError(
                f"cram: {e} — if this block came from another CRAM "
                "writer, its 3.1 codec layout may diverge from this "
                "clean-room implementation; re-encode with samtools "
                "view -O cram,version=3.0 (see docs/cram.md)"
            ) from e
    raise ValueError(f"cram: unsupported block compression method {method}")


@dataclass
class Block:
    method: int
    content_type: int
    content_id: int
    data: bytes  # uncompressed


@dataclass
class RawBlock:
    """One block as stored: compressed payload + frame, not decoded.

    The raw-access surface the device decode path needs — a container's
    blocks are collected first, then the entropy stage runs wherever
    the installed block decoder puts it (ops/rans_device.py ships
    these bytes compressed over the wire)."""

    method: int
    content_type: int
    content_id: int
    raw: bytes   # compressed payload as stored
    rsize: int   # declared uncompressed size


def read_block_raw(buf: memoryview, pos: int,
                   v2: bool = False) -> tuple[RawBlock, int]:
    """Parse one block's frame + CRC without decompressing."""
    start = pos
    method = buf[pos]
    ctype = buf[pos + 1]
    pos += 2
    cid, pos = read_itf8(buf, pos)
    csize, pos = read_itf8(buf, pos)
    rsize, pos = read_itf8(buf, pos)
    raw = bytes(buf[pos:pos + csize])
    pos += csize
    if not v2:  # CRAM 2.x blocks carry no CRC trailer
        want_crc = struct.unpack_from("<I", buf, pos)[0]
        # CRC covers the block's bytes exactly as stored (a spec-legal
        # non-minimal ITF8 must not be re-canonicalized before
        # checking)
        got_crc = zlib.crc32(bytes(buf[start:pos]))
        pos += 4
        if got_crc != want_crc:
            raise ValueError("cram: block CRC mismatch")
    return RawBlock(method, ctype, cid, raw, rsize), pos


def decode_raw_block(rb: RawBlock, data: bytes | None = None) -> Block:
    """RawBlock → Block, with the shared size validation. ``data``
    injects already-decoded bytes (the device decode path)."""
    if data is None:
        data = _decompress(rb.method, rb.raw, rb.rsize)
    if len(data) != rb.rsize:
        raise ValueError("cram: block size mismatch after decompression")
    return Block(rb.method, rb.content_type, rb.content_id, data)


def read_block(buf: memoryview, pos: int,
               v2: bool = False) -> tuple[Block, int]:
    rb, pos = read_block_raw(buf, pos, v2)
    return decode_raw_block(rb), pos


def write_block(method: int, ctype: int, cid: int, data: bytes,
                rans_order: int = 0, v2: bool = False,
                rans_stripe: int = 0) -> bytes:
    if method == M_RANSNX16:
        from .rans_nx16 import encode as nx16_encode

        # STRIPE only pays (and only exercises multi-lane framing)
        # past a few lanes' worth of bytes; tiny blocks stay plain
        comp = nx16_encode(data, order=rans_order if len(data) >= 16
                           else 0,
                           stripe=rans_stripe
                           if len(data) >= 16 * max(rans_stripe, 1)
                           else 0)
    elif method == M_ARITH:
        from .arith import encode as arith_encode

        comp = arith_encode(data, order=rans_order if len(data) >= 16
                            else 0)
    elif method == M_RANS and (rans_order == 0 or len(data) < 4):
        comp = rans_encode_0(data)
    elif method == M_RANS:
        comp = rans_encode_1(data)
    elif method == M_GZIP:
        comp = gzip.compress(data, 6)
    else:
        comp = data
    return _write_block_pre(method, ctype, cid, comp, len(data), v2)


def _write_block_pre(method: int, ctype: int, cid: int, comp: bytes,
                     raw_size: int, v2: bool = False) -> bytes:
    """Frame an already-compressed payload (write_block's tail, and
    the direct entry for the specialized codecs — tok3 names, fqzcomp
    qualities — which compress with record structure write_block
    cannot know)."""
    head = bytes([method, ctype]) + write_itf8(cid) + \
        write_itf8(len(comp)) + write_itf8(raw_size)
    if v2:  # CRAM 2.x blocks carry no CRC trailer
        return head + comp
    return head + comp + struct.pack("<I", zlib.crc32(head + comp))


# ------------------------------------------------------- encodings

E_NULL, E_EXTERNAL, E_GOLOMB, E_HUFFMAN = 0, 1, 2, 3
E_BYTE_ARRAY_LEN, E_BYTE_ARRAY_STOP, E_BETA = 4, 5, 6
E_SUBEXP, E_GOLOMB_RICE, E_GAMMA = 7, 8, 9


class BitReader:
    """MSB-first reader over the core block."""

    __slots__ = ("data", "byte", "bit")

    def __init__(self, data: bytes):
        self.data = data
        self.byte = 0
        self.bit = 0

    def read(self, n: int) -> int:
        v = 0
        for _ in range(n):
            b = (self.data[self.byte] >> (7 - self.bit)) & 1
            v = (v << 1) | b
            self.bit += 1
            if self.bit == 8:
                self.bit = 0
                self.byte += 1
        return v

    def read_unary(self) -> int:
        n = 0
        while True:
            b = (self.data[self.byte] >> (7 - self.bit)) & 1
            self.bit += 1
            if self.bit == 8:
                self.bit = 0
                self.byte += 1
            if b:
                return n
            n += 1


@dataclass
class Encoding:
    codec: int
    params: dict = field(default_factory=dict)

    @staticmethod
    def parse(buf: memoryview, pos: int) -> tuple["Encoding", int]:
        codec, pos = read_itf8(buf, pos)
        size, pos = read_itf8(buf, pos)
        end = pos + size
        p: dict = {}
        if codec == E_EXTERNAL:
            p["id"], pos = read_itf8(buf, pos)
        elif codec == E_HUFFMAN:
            n, pos = read_itf8(buf, pos)
            alphabet = []
            for _ in range(n):
                v, pos = read_itf8(buf, pos)
                alphabet.append(v)
            n2, pos = read_itf8(buf, pos)
            lens = []
            for _ in range(n2):
                v, pos = read_itf8(buf, pos)
                lens.append(v)
            p["alphabet"], p["lengths"] = alphabet, lens
        elif codec == E_BYTE_ARRAY_LEN:
            p["len_enc"], pos = Encoding.parse(buf, pos)
            p["val_enc"], pos = Encoding.parse(buf, pos)
        elif codec == E_BYTE_ARRAY_STOP:
            p["stop"] = buf[pos]
            pos += 1
            p["id"], pos = read_itf8(buf, pos)
        elif codec == E_BETA:
            p["offset"], pos = read_itf8(buf, pos)
            p["length"], pos = read_itf8(buf, pos)
        elif codec == E_GAMMA:
            p["offset"], pos = read_itf8(buf, pos)
        elif codec == E_NULL:
            pass
        else:
            raise ValueError(f"cram: unsupported codec id {codec}")
        return Encoding(codec, p), end

    def serialize(self) -> bytes:
        body = b""
        if self.codec == E_EXTERNAL:
            body = write_itf8(self.params["id"])
        elif self.codec == E_HUFFMAN:
            a, ls = self.params["alphabet"], self.params["lengths"]
            body = write_itf8(len(a)) + b"".join(write_itf8(x) for x in a)
            body += write_itf8(len(ls)) + b"".join(write_itf8(x) for x in ls)
        elif self.codec == E_BYTE_ARRAY_LEN:
            body = self.params["len_enc"].serialize() + \
                self.params["val_enc"].serialize()
        elif self.codec == E_BYTE_ARRAY_STOP:
            body = bytes([self.params["stop"]]) + \
                write_itf8(self.params["id"])
        elif self.codec == E_BETA:
            body = write_itf8(self.params["offset"]) + \
                write_itf8(self.params["length"])
        elif self.codec == E_GAMMA:
            body = write_itf8(self.params["offset"])
        return write_itf8(self.codec) + write_itf8(len(body)) + body


class BitWriter:
    """MSB-first writer (the BitReader's exact inverse) for the
    fixture writer's core-bit series."""

    __slots__ = ("out", "acc", "nbits")

    def __init__(self) -> None:
        self.out = bytearray()
        self.acc = 0
        self.nbits = 0

    def write(self, value: int, n: int) -> None:
        for i in range(n - 1, -1, -1):
            self.acc = (self.acc << 1) | ((value >> i) & 1)
            self.nbits += 1
            if self.nbits == 8:
                self.out.append(self.acc)
                self.acc = 0
                self.nbits = 0

    def finish(self) -> bytes:
        if self.nbits:
            self.out.append(self.acc << (8 - self.nbits))
            self.acc = 0
            self.nbits = 0
        return bytes(self.out)


def _huffman_lengths(values) -> tuple[list[int], list[int]]:
    """Canonical-Huffman code lengths for a value multiset (ascending
    alphabet; single-symbol alphabets get the spec's 0-bit code)."""
    import heapq
    import itertools
    from collections import Counter

    freq = Counter(values)
    if len(freq) == 1:
        return [next(iter(freq))], [0]
    cnt = itertools.count()
    heap = [(f, next(cnt), s) for s, f in freq.items()]
    heapq.heapify(heap)
    while len(heap) > 1:
        f1, _, n1 = heapq.heappop(heap)
        f2, _, n2 = heapq.heappop(heap)
        heapq.heappush(heap, (f1 + f2, next(cnt), (n1, n2)))
    lengths: dict[int, int] = {}

    def walk(node, depth):
        if isinstance(node, tuple):
            walk(node[0], depth + 1)
            walk(node[1], depth + 1)
        else:
            lengths[node] = depth

    walk(heap[0][2], 0)
    alphabet = sorted(lengths)
    return alphabet, [lengths[s] for s in alphabet]


def _canonical_codes(alphabet, lengths) -> dict[int, tuple[int, int]]:
    """symbol → (code, length), assigned exactly like the decoder's
    _build_huffman (sorted by (length, symbol))."""
    order = sorted(range(len(alphabet)),
                   key=lambda i: (lengths[i], alphabet[i]))
    codes: dict[int, tuple[int, int]] = {}
    code = 0
    prev = lengths[order[0]]
    for i in order:
        code <<= lengths[i] - prev
        prev = lengths[i]
        codes[alphabet[i]] = (code, lengths[i])
        code += 1
    return codes


class _ExternalStream:
    __slots__ = ("data", "pos")

    def __init__(self, data: bytes):
        self.data = memoryview(data)
        self.pos = 0

    def itf8(self) -> int:
        v, self.pos = read_itf8(self.data, self.pos)
        return v

    def byte(self) -> int:
        b = self.data[self.pos]
        self.pos += 1
        return b

    def until(self, stop: int) -> bytes:
        start = self.pos
        data = self.data
        p = self.pos
        n = len(data)
        while p < n and data[p] != stop:
            p += 1
        out = bytes(data[start:p])
        self.pos = p + 1  # skip the stop byte
        return out

    def take(self, n: int) -> bytes:
        out = bytes(self.data[self.pos:self.pos + n])
        self.pos += n
        return out


class Decoder:
    """One data series decoder bound to core/external streams."""

    def __init__(self, enc: Encoding, core: BitReader,
                 externals: dict[int, _ExternalStream]):
        self.enc = enc
        self.core = core
        self.ext = externals
        if enc.codec in (E_EXTERNAL, E_BYTE_ARRAY_STOP) \
                and enc.params["id"] not in externals:
            # validate at construction so a corrupt content id (which
            # nothing upstream catches in the CRC-less 2.x layout)
            # fails typed instead of KeyError-ing mid-record
            raise ValueError(
                f"cram: slice references missing external block "
                f"{enc.params['id']}")
        if enc.codec == E_HUFFMAN:
            self._build_huffman()
        elif enc.codec == E_BYTE_ARRAY_LEN:
            self.len_dec = Decoder(enc.params["len_enc"], core, externals)
            self.val_dec = Decoder(enc.params["val_enc"], core, externals)

    def _build_huffman(self):
        alphabet = self.enc.params["alphabet"]
        lengths = self.enc.params["lengths"]
        if len(alphabet) == 1:
            self.hf_single = alphabet[0]
            return
        self.hf_single = None
        # canonical assignment shared with the writer
        # (_canonical_codes): sort by (code length, symbol value) —
        # the spec/htslib tie-break; appearance order would swap
        # codes for equal-length symbols listed out of order
        codes = _canonical_codes(alphabet, lengths)
        self.hf_table = {(ln, code): sym
                         for sym, (code, ln) in codes.items()}
        self.hf_maxlen = max(lengths)

    def read_int(self) -> int:
        c = self.enc.codec
        if c == E_EXTERNAL:
            return self.ext[self.enc.params["id"]].itf8()
        if c == E_HUFFMAN:
            if self.hf_single is not None:
                return self.hf_single
            ln = 0
            code = 0
            while ln <= self.hf_maxlen:
                code = (code << 1) | self.core.read(1)
                ln += 1
                if (ln, code) in self.hf_table:
                    return self.hf_table[(ln, code)]
            raise ValueError("cram: bad huffman code")
        if c == E_BETA:
            return self.core.read(self.enc.params["length"]) - \
                self.enc.params["offset"]
        if c == E_GAMMA:
            n = self.core.read_unary()
            v = (1 << n) | (self.core.read(n) if n else 0)
            return v - self.enc.params["offset"]
        raise ValueError(f"cram: codec {c} cannot decode ints")

    def read_byte(self) -> int:
        c = self.enc.codec
        if c == E_EXTERNAL:
            return self.ext[self.enc.params["id"]].byte()
        return self.read_int() & 0xFF

    def read_bytes(self) -> bytes:
        c = self.enc.codec
        if c == E_BYTE_ARRAY_STOP:
            return self.ext[self.enc.params["id"]].until(
                self.enc.params["stop"]
            )
        if c == E_BYTE_ARRAY_LEN:
            n = self.len_dec.read_int()
            if self.val_dec.enc.codec == E_EXTERNAL:
                return self.val_dec.ext[
                    self.val_dec.enc.params["id"]
                ].take(n)
            return bytes(self.val_dec.read_byte() for _ in range(n))
        raise ValueError(f"cram: codec {c} cannot decode byte arrays")

    def read_bytes_n(self, n: int) -> bytes:
        """n bytes for fixed-length series (QS, unmapped bases)."""
        if self.enc.codec == E_EXTERNAL:
            return self.ext[self.enc.params["id"]].take(n)
        return bytes(self.read_byte() for _ in range(n))


# --------------------------------------------- compression header

# in-read length the feature consumes (query) / reference length
_Q_CONSUME = {ord("S"), ord("I"), ord("i")}
_R_CONSUME = {ord("D"), ord("N")}
# features that add a CIGAR op (break the single-M shape) even though
# they consume neither query nor reference
_STRUCTURAL = _Q_CONSUME | _R_CONSUME | {ord("H"), ord("P")}


@dataclass
class CompressionHeader:
    rn_included: bool = True
    ap_delta: bool = True
    ref_required: bool = True
    sub_matrix: bytes = b"\x00" * 5
    tag_dict: list[list[tuple[str, str]]] = field(default_factory=list)
    encodings: dict[str, Encoding] = field(default_factory=dict)
    tag_encodings: dict[int, Encoding] = field(default_factory=dict)

    @staticmethod
    def parse(data: bytes) -> "CompressionHeader":
        buf = memoryview(data)
        ch = CompressionHeader()
        pos = 0
        # preservation map
        _size, pos = read_itf8(buf, pos)
        nmap, pos = read_itf8(buf, pos)
        for _ in range(nmap):
            key = bytes(buf[pos:pos + 2]).decode()
            pos += 2
            if key == "RN":
                ch.rn_included = bool(buf[pos])
                pos += 1
            elif key == "AP":
                ch.ap_delta = bool(buf[pos])
                pos += 1
            elif key == "RR":
                ch.ref_required = bool(buf[pos])
                pos += 1
            elif key == "SM":
                ch.sub_matrix = bytes(buf[pos:pos + 5])
                pos += 5
            elif key == "TD":
                blob_len, pos = read_itf8(buf, pos)
                blob = bytes(buf[pos:pos + blob_len])
                pos += blob_len
                ch.tag_dict = []
                for line in blob.split(b"\x00")[:-1] if blob else []:
                    tags = []
                    for i in range(0, len(line), 3):
                        tags.append((line[i:i + 2].decode(),
                                     chr(line[i + 2])))
                    ch.tag_dict.append(tags)
                if not ch.tag_dict:
                    ch.tag_dict = [[]]
            else:
                raise ValueError(f"cram: unknown preservation key {key}")
        # data series encodings
        _size, pos = read_itf8(buf, pos)
        n, pos = read_itf8(buf, pos)
        for _ in range(n):
            key = bytes(buf[pos:pos + 2]).decode()
            pos += 2
            enc, pos = Encoding.parse(buf, pos)
            ch.encodings[key] = enc
        # tag encodings
        _size, pos = read_itf8(buf, pos)
        n, pos = read_itf8(buf, pos)
        for _ in range(n):
            key, pos = read_itf8(buf, pos)
            enc, pos = Encoding.parse(buf, pos)
            ch.tag_encodings[key] = enc
        return ch

    def serialize(self) -> bytes:
        pres = bytearray()
        entries = [
            (b"RN", bytes([1 if self.rn_included else 0])),
            (b"AP", bytes([1 if self.ap_delta else 0])),
            (b"RR", bytes([1 if self.ref_required else 0])),
            (b"SM", self.sub_matrix),
        ]
        blob = b""
        for line in self.tag_dict:
            for tag, typ in line:
                blob += tag.encode() + typ.encode()
            blob += b"\x00"
        entries.append((b"TD", write_itf8(len(blob)) + blob))
        body = write_itf8(len(entries))
        for k, v in entries:
            body += k + v
        out = write_itf8(len(body)) + body
        body = write_itf8(len(self.encodings))
        for k, enc in self.encodings.items():
            body += k.encode() + enc.serialize()
        out += write_itf8(len(body)) + body
        body = write_itf8(len(self.tag_encodings))
        for k, enc in self.tag_encodings.items():
            body += write_itf8(k) + enc.serialize()
        out += write_itf8(len(body)) + body
        return bytes(out)


# ----------------------------------------------------------- slices

@dataclass
class SliceHeader:
    ref_id: int
    start: int
    span: int
    n_records: int
    counter: int
    n_blocks: int
    content_ids: list[int]
    embedded_ref_id: int
    md5: bytes

    @staticmethod
    def parse(data: bytes, v2: bool = False) -> "SliceHeader":
        buf = memoryview(data)
        pos = 0
        ref_id, pos = read_itf8(buf, pos)
        start, pos = read_itf8(buf, pos)
        span, pos = read_itf8(buf, pos)
        nrec, pos = read_itf8(buf, pos)
        # ITF8 in 2.x, LTF8 from 3.0 (same as the container header)
        if v2:
            counter, pos = read_itf8(buf, pos)
        else:
            counter, pos = read_ltf8(buf, pos)
        nblocks, pos = read_itf8(buf, pos)
        ncids, pos = read_itf8(buf, pos)
        cids = []
        for _ in range(ncids):
            v, pos = read_itf8(buf, pos)
            cids.append(v)
        emb, pos = read_itf8(buf, pos)
        md5 = bytes(buf[pos:pos + 16])
        return SliceHeader(ref_id, start, span, nrec, counter, nblocks,
                           cids, emb, md5)

    def serialize(self, v2: bool = False) -> bytes:
        wc = write_itf8 if v2 else write_ltf8
        out = write_itf8(self.ref_id) + write_itf8(self.start) + \
            write_itf8(self.span) + write_itf8(self.n_records) + \
            wc(self.counter) + write_itf8(self.n_blocks) + \
            write_itf8(len(self.content_ids))
        for c in self.content_ids:
            out += write_itf8(c)
        out += write_itf8(self.embedded_ref_id) + self.md5
        return out


@dataclass
class CramRecord:
    bf: int
    cf: int
    ref_id: int
    read_len: int
    pos: int  # 1-based alignment position
    mapq: int
    mate_ref: int
    mate_pos: int
    tlen: int
    name: bytes
    features: list[tuple[int, int, int]]  # (code, in-read pos, length)

    @property
    def flag(self) -> int:
        f = self.bf
        return f

    def ref_end(self) -> int:
        """1-based exclusive-ish: pos + ref-consumed length."""
        q_only = sum(ln for c, _, ln in self.features if c in _Q_CONSUME)
        r_only = sum(ln for c, _, ln in self.features if c in _R_CONSUME)
        return self.pos + self.read_len - q_only + r_only

    def aligned_blocks(self) -> list[tuple[int, int]]:
        """0-based [start, end) M-run blocks (depth counts these)."""
        ref = self.pos - 1
        prev_q = 1
        blocks = []
        for code, fp, ln in sorted(self.features, key=lambda t: t[1]):
            if code in _Q_CONSUME:
                m = fp - prev_q
                if m > 0:
                    blocks.append((ref, ref + m))
                    ref += m
                prev_q = fp + ln
            elif code in _R_CONSUME:
                m = fp - prev_q
                if m > 0:
                    blocks.append((ref, ref + m))
                    ref += m
                ref += ln
                prev_q = fp
        m = self.read_len - prev_q + 1
        if m > 0:
            blocks.append((ref, ref + m))
        return blocks

    def single_m(self) -> bool:
        return not self.features


def decode_slice(comp: CompressionHeader, sl: SliceHeader,
                 core: bytes, externals: dict[int, bytes],
                 ) -> list[CramRecord]:
    br = BitReader(core)
    streams = {cid: _ExternalStream(d) for cid, d in externals.items()}

    decs: dict[str, Decoder] = {}

    def dec(key: str) -> Decoder:
        d = decs.get(key)
        if d is None:
            enc = comp.encodings.get(key)
            if enc is None:
                raise ValueError(f"cram: no encoding for series {key}")
            d = Decoder(enc, br, streams)
            decs[key] = d
        return d

    tag_decs: dict[int, Decoder] = {}
    records = []
    nf_links: list[int | None] = []
    prev_pos = sl.start
    for _ in range(sl.n_records):
        bf = dec("BF").read_int()
        cf = dec("CF").read_int()
        ref_id = sl.ref_id
        if sl.ref_id == -2:
            ref_id = dec("RI").read_int()
        rl = dec("RL").read_int()
        ap = dec("AP").read_int()
        if comp.ap_delta:
            pos = prev_pos + ap
            prev_pos = pos
        else:
            pos = ap
        dec("RG").read_int()
        name = b""
        if comp.rn_included:
            name = dec("RN").read_bytes()
        mate_ref, mate_pos, tlen = -1, -1, 0
        nf: int | None = None
        if cf & CF_DETACHED:
            mf = dec("MF").read_int()
            if not comp.rn_included:
                name = dec("RN").read_bytes()
            mate_ref = dec("NS").read_int()
            mate_pos = dec("NP").read_int()
            tlen = dec("TS").read_int()
            bf |= (BAM_MREVERSE if mf & MF_MATE_REVERSE else 0)
            bf |= (BAM_MUNMAP if mf & MF_MATE_UNMAPPED else 0)
        elif cf & CF_MATE_DOWNSTREAM:
            nf = dec("NF").read_int()
        tl = dec("TL").read_int()
        if not (0 <= tl < max(len(comp.tag_dict), 1)):
            # a bad index would silently desync every shared stream
            raise ValueError(f"cram: tag-line index {tl} out of range")
        if comp.tag_dict:
            for tag, typ in comp.tag_dict[tl]:
                key = (ord(tag[0]) << 16) | (ord(tag[1]) << 8) | ord(typ)
                td = tag_decs.get(key)
                if td is None:
                    enc = comp.tag_encodings.get(key)
                    if enc is None:
                        raise ValueError(f"cram: no tag encoding {tag}")
                    td = Decoder(enc, br, streams)
                    tag_decs[key] = td
                td.read_bytes()  # consume; values unused for depth
        features: list[tuple[int, int, int]] = []
        mapq = 0
        if not (bf & 0x4):  # mapped
            fn = dec("FN").read_int()
            fpos = 0
            for _ in range(fn):
                fc = dec("FC").read_byte()
                fpos += dec("FP").read_int()
                ln = 0
                if fc == ord("S"):
                    ln = len(dec("SC").read_bytes())
                elif fc == ord("I"):
                    ln = len(dec("IN").read_bytes())
                elif fc == ord("i"):
                    dec("BA").read_byte()
                    ln = 1
                elif fc == ord("D"):
                    ln = dec("DL").read_int()
                elif fc == ord("N"):
                    ln = dec("RS").read_int()
                elif fc == ord("H"):
                    dec("HC").read_int()
                elif fc == ord("P"):
                    dec("PD").read_int()
                elif fc == ord("X"):
                    dec("BS").read_byte()
                elif fc == ord("B"):
                    dec("BA").read_byte()
                    dec("QS").read_byte()
                elif fc == ord("Q"):
                    dec("QS").read_byte()
                elif fc == ord("b"):
                    dec("BB").read_bytes()
                elif fc == ord("q"):
                    dec("QQ").read_bytes()
                else:
                    raise ValueError(f"cram: unknown feature {chr(fc)}")
                if fc in _STRUCTURAL:
                    features.append((fc, fpos, ln))
            mapq = dec("MQ").read_int()
            if cf & CF_QS_STORED:
                dec("QS").read_bytes_n(rl)
        else:
            if not (cf & CF_NO_SEQ):
                dec("BA").read_bytes_n(rl)
            if cf & CF_QS_STORED:
                dec("QS").read_bytes_n(rl)
        records.append(CramRecord(bf, cf, ref_id, rl, pos, mapq,
                                  mate_ref, mate_pos, tlen, name,
                                  features))
        nf_links.append(nf)
    # resolve downstream mates (spec: mate = this + NF + 1, same slice)
    for i, nf in enumerate(nf_links):
        if nf is None:
            continue
        j = i + nf + 1
        if j >= len(records):
            continue
        a, b = records[i], records[j]
        for rec, other in ((a, b), (b, a)):
            rec.mate_ref = other.ref_id
            rec.mate_pos = other.pos
            if other.bf & 0x10:
                rec.bf |= BAM_MREVERSE
            if other.bf & 0x4:
                rec.bf |= BAM_MUNMAP
        # BAM-rule template length: outermost span, + on the leftmost
        lo = min(a.pos, b.pos)
        hi = max(a.ref_end(), b.ref_end())
        span = hi - lo
        if a.pos <= b.pos:
            a.tlen, b.tlen = span, -span
        else:
            a.tlen, b.tlen = -span, span
    return records


# -------------------------------------------------------- containers

@dataclass
class ContainerHeader:
    length: int  # total byte size of the container's blocks
    ref_id: int
    start: int
    span: int
    n_records: int
    counter: int
    n_bases: int
    n_blocks: int
    landmarks: list[int]

    @staticmethod
    def parse(buf: memoryview, pos: int,
              v2: bool = False) -> tuple["ContainerHeader", int]:
        (length,) = struct.unpack_from("<i", buf, pos)
        pos += 4
        ref_id, pos = read_itf8(buf, pos)
        start, pos = read_itf8(buf, pos)
        span, pos = read_itf8(buf, pos)
        nrec, pos = read_itf8(buf, pos)
        # the record counter widened to LTF8 in 3.0; 2.x stores ITF8
        if v2:
            counter, pos = read_itf8(buf, pos)
        else:
            counter, pos = read_ltf8(buf, pos)
        nbases, pos = read_ltf8(buf, pos)
        nblocks, pos = read_itf8(buf, pos)
        nland, pos = read_itf8(buf, pos)
        lands = []
        for _ in range(nland):
            v, pos = read_itf8(buf, pos)
            lands.append(v)
        if not v2:
            pos += 4  # header crc32 (v3 only)
        return ContainerHeader(length, ref_id, start, span, nrec, counter,
                               nbases, nblocks, lands), pos

    @staticmethod
    def build(length, ref_id, start, span, nrec, counter, nbases,
              nblocks, landmarks, v2: bool = False) -> bytes:
        wc = write_itf8 if v2 else write_ltf8
        body = write_itf8(ref_id) + write_itf8(start) + \
            write_itf8(span) + write_itf8(nrec) + wc(counter) + \
            write_ltf8(nbases) + write_itf8(nblocks) + \
            write_itf8(len(landmarks))
        for v in landmarks:
            body += write_itf8(v)
        head = struct.pack("<i", length) + body
        if v2:
            return head
        return head + struct.pack("<I", zlib.crc32(head))


def _container_blocks(buf: memoryview, pos: int, end: int,
                      v2: bool, block_decoder) -> list[Block]:
    """All of a container's blocks, decoded.

    Without a decoder this is the sequential read-and-inflate walk.
    With one (``--decode-device``), the frames are parsed first —
    still-compressed payloads — and the whole container's entropy
    decode runs as ONE batched call, so supported blocks share a
    single bucketed device dispatch instead of N host loops."""
    if block_decoder is None:
        blocks = []
        while pos < end:
            b, pos = read_block(buf, pos, v2)
            blocks.append(b)
        return blocks
    raws = []
    while pos < end:
        rb, pos = read_block_raw(buf, pos, v2)
        raws.append(rb)
    datas = block_decoder.decode_blocks(raws)
    return [decode_raw_block(rb, data=d) for rb, d in zip(raws, datas)]


def _container_records(buf: memoryview, pos: int,
                       hdr: ContainerHeader,
                       v2: bool = False,
                       block_decoder=None) -> list[CramRecord]:
    """Decode every record in the container starting at its first block."""
    end = pos + hdr.length
    try:
        blocks = iter(_container_blocks(buf, pos, end, v2,
                                        block_decoder))
        block = next(blocks, None)
        if block is None or block.content_type != CT_COMP_HEADER:
            raise ValueError("cram: expected compression header block")
        comp = CompressionHeader.parse(block.data)
        records: list[CramRecord] = []
        while True:
            sh_block = next(blocks, None)
            if sh_block is None:
                break
            if sh_block.content_type != CT_SLICE_HEADER:
                raise ValueError("cram: expected slice header block")
            sl = SliceHeader.parse(sh_block.data, v2)
            core = b""
            externals: dict[int, bytes] = {}
            for _ in range(sl.n_blocks):
                b = next(blocks, None)
                if b is None:
                    raise IndexError("slice block past container end")
                if b.content_type == CT_CORE:
                    core = b.data
                elif b.content_type == CT_EXTERNAL:
                    externals[b.content_id] = b.data
            records.extend(decode_slice(comp, sl, core, externals))
    except (IndexError, struct.error) as e:
        # truncated mid-container: raw memoryview/struct errors become
        # the module's clean error surface (missing external ids are
        # validated at Decoder construction, so a KeyError here would
        # be a genuine bug and must surface as one)
        raise ValueError(
            f"cram: truncated or corrupt container body at byte {pos}"
        ) from e
    return records


class CramFile:
    """Decoded-CRAM handle with the BAM-handle surface the depth tools
    use: ``.header`` (BamHeader), ``read_columns(tid, start, end)``,
    ``stream_columns()``. Region access uses the .crai when present
    (container offsets per (seq, start, span) — the same index
    indexcov's QC path already parses)."""

    native = False
    lazy = True
    is_cram = True

    def __init__(self, data, crai_path: str | None = None):
        from .bam import BamHeader

        self._buf = memoryview(data) if not isinstance(data, memoryview) \
            else data
        buf = self._buf
        if bytes(buf[:4]) != CRAM_MAGIC:
            raise ValueError("not a CRAM file (bad magic)")
        self.major, self.minor = buf[4], buf[5]
        if self.major not in (2, 3):
            raise ValueError(
                f"cram: unsupported major version {self.major} "
                "(2.x and 3.0/3.1 supported; re-encode with samtools)"
            )
        # 2.x shares the 3.0 container/slice layout minus the CRC32
        # trailers on container headers and blocks (the CRAM 2.1 spec
        # predates them); 3.1 adds block codecs, handled per block in
        # _decompress
        self._v2 = self.major == 2
        pos = 26  # magic + version + 20-byte file id
        try:
            hdr, pos = ContainerHeader.parse(buf, pos, self._v2)
            first_block, _ = read_block(buf, pos, self._v2)
        except (IndexError, struct.error) as e:
            # a file truncated inside the header container raises raw
            # memoryview/struct errors; surface the module's clean
            # error type like every other parse path
            raise ValueError(
                "cram: truncated or corrupt file header"
            ) from e
        if first_block.content_type != CT_FILE_HEADER:
            raise ValueError("cram: first container must hold SAM header")
        text = _sam_header_text(first_block.data)
        names, lens = [], []
        for line in text.splitlines():
            if line.startswith("@SQ"):
                nm, ln = None, 0
                for tok in line.split("\t")[1:]:
                    if tok.startswith("SN:"):
                        nm = tok[3:]
                    elif tok.startswith("LN:"):
                        ln = int(tok[3:])
                if nm is not None:
                    names.append(nm)
                    lens.append(ln)
        self.header = BamHeader(text, names, lens)
        import threading

        self._first_data_container = pos + hdr.length
        self._crai = None
        self._all_records = None  # no-.crai fallback decode cache
        self._cache_lock = threading.Lock()
        # pluggable per-container block decode (ops/rans_device.py's
        # DeviceBlockDecoder under --decode-device); None = host codecs
        self.block_decoder = None
        if crai_path:
            self._crai = _load_crai_entries(crai_path)

    def set_block_decoder(self, decoder) -> None:
        """Install a batch block decoder (``decode_blocks(raws) ->
        list[bytes]``) used for every container this handle decodes —
        byte-identical output is the decoder's contract."""
        self.block_decoder = decoder

    @classmethod
    def from_file(cls, path: str, lazy: bool = True) -> "CramFile":
        import mmap
        import os

        from . import remote

        crai = path + ".crai"
        if remote.is_remote(path):
            # stage the object once (block-cached ranged fetches);
            # the .crai sibling resolves through the same data plane
            data = remote.fetch_bytes(path)
            return cls(memoryview(data),
                       crai_path=crai if remote.exists(crai) else None)
        with open(path, "rb") as fh:
            mm = mmap.mmap(fh.fileno(), 0, access=mmap.ACCESS_READ)
        return cls(memoryview(mm),
                   crai_path=crai if os.path.exists(crai) else None)

    def _iter_containers(self, offset: int | None = None):
        buf = self._buf
        pos = offset if offset is not None else self._first_data_container
        n = len(buf)
        while pos + 4 <= n:
            try:
                hdr, body = ContainerHeader.parse(buf, pos, self._v2)
            except (IndexError, struct.error) as e:
                # memoryview reads past a truncated/corrupt container
                # raise raw slicing errors; surface the module's own
                # error type so CLIs print a clean "cram:" message
                raise ValueError(
                    f"cram: truncated or corrupt container at byte {pos}"
                ) from e
            if hdr.ref_id == -1 and hdr.n_records == 0:
                if hdr.n_blocks <= 1:
                    return  # EOF container
                pos = body + hdr.length
                continue  # unmapped-only container: skip (no positions)
            yield hdr, body
            pos = body + hdr.length

    def records(self, offset: int | None = None):
        for hdr, body in self._iter_containers(offset):
            yield from _container_records(self._buf, body, hdr,
                                          self._v2,
                                          self.block_decoder)

    def _region_offsets(self, tid: int, start: int, end: int):
        """Container offsets overlapping 0-based [start, end) from the
        .crai (whose alignment starts are 1-based)."""
        offs = []
        for (seq, s, span, c_off) in self._crai:
            if seq != tid or span <= 0:
                continue
            s0 = s - 1
            if s0 < end and s0 + span > start:
                offs.append(c_off)
        return sorted(set(offs))

    def read_columns(self, tid: int | None = None, start: int = 0,
                     end: int | None = None, voffset=None,
                     end_voffset=None):
        """Decode records into ReadColumns (BAM-handle-compatible).

        ``voffset``/``end_voffset`` are accepted for interface parity and
        ignored — CRAM random access goes through the .crai instead.
        """
        recs: list[CramRecord] = []
        e = end if end is not None else 1 << 60
        if tid is not None and self._crai is not None:
            seen = set()
            for off in self._region_offsets(tid, start, e):
                for hdr, body in self._iter_containers(off):
                    if hdr.ref_id not in (-2, tid) or hdr.start > e:
                        break
                    if body in seen:
                        break
                    seen.add(body)
                    recs.extend(_container_records(
                        self._buf, body, hdr, self._v2,
                        self.block_decoder))
                    break  # one container per crai offset
        else:
            # no .crai: decode the whole file ONCE and answer every
            # region from the cache (a sharded whole-genome run would
            # otherwise re-decode the file per region); shard threads
            # share the handle, so the fill is locked
            with self._cache_lock:
                if self._all_records is None:
                    if tid is not None:
                        _get_logger("cram").warning(
                            "no .crai alongside CRAM — region queries "
                            "fall back to one full-file decode held in "
                            "memory"
                        )
                    self._all_records = list(self.records())
            recs = self._all_records
        return _records_to_columns(recs, tid, start, e)

    def window_reduce(self, tid, start, end, w0, length, window,
                      depth_cap, min_mapq, flag_mask, voffset=None,
                      delta_scratch=None, **_ignored):
        """Fused decode + per-window depth sums for one region — the
        numpy equivalent of csrc/fastio.cpp::bam_window_reduce's dense
        path (M/=/X segments of records passing mapq/flag filters,
        clipped to [start, end) and [w0, w0+length), per-base depth
        capped at depth_cap, summed per window). Lets the cohort
        hybrid engine treat a CRAM handle like a native BAM handle:
        Python-orchestrated (the record decode already rides the C
        codec ports) but identical output.
        """
        del voffset  # CRAM random access rides the .crai
        if length % window:
            raise ValueError("length must be a multiple of window")
        cols = self.read_columns(tid=tid, start=start, end=end)
        wsums = np.zeros(length // window, dtype=np.int64)
        if cols.n_reads == 0:
            return wsums
        keep = ((cols.mapq.astype(np.int32) >= min_mapq)
                & ((cols.flag.astype(np.int32) & flag_mask) == 0))
        segk = keep[cols.seg_read]
        s = cols.seg_start[segk].astype(np.int64)
        e = cols.seg_end[segk].astype(np.int64)
        np.clip(s, start, end, out=s)
        np.clip(e, start, end, out=e)
        s -= w0
        e -= w0
        np.clip(s, 0, length, out=s)
        np.clip(e, 0, length, out=e)
        m = e > s
        if not m.any():
            return wsums
        delta = np.zeros(length + 1, dtype=np.int64)
        np.add.at(delta, s[m], 1)
        np.add.at(delta, e[m], -1)
        depth = np.cumsum(delta[:length])
        np.minimum(depth, depth_cap, out=depth)
        return depth.reshape(-1, window).sum(axis=1)

    def stream_columns(self, window_bytes: int = 0, chunk_records: int = 0):
        """Per-container column chunks (bounded by container size)."""
        for hdr, body in self._iter_containers():
            recs = _container_records(self._buf, body, hdr,
                                      self._v2, self.block_decoder)
            cols = _records_to_columns(recs, None, 0, 1 << 60)
            if cols.n_reads:
                yield cols


def _sam_header_text(data: bytes) -> str:
    # htslib prefixes the text with an int32 length; the spec allows the
    # raw text (possibly NUL-padded) as well — accept both
    if len(data) >= 4:
        (n,) = struct.unpack_from("<i", data, 0)
        if 0 <= n <= len(data) - 4:
            return data[4:4 + n].decode(errors="replace")
    return data.rstrip(b"\x00").decode(errors="replace")


def _load_crai_entries(path: str):
    import io as _pyio

    from . import remote

    entries = []
    if remote.is_remote(path):
        fh = _pyio.TextIOWrapper(gzip.GzipFile(
            fileobj=_pyio.BytesIO(remote.fetch_bytes(path))))
    else:
        fh = gzip.open(path, "rt")
    with fh:
        for line in fh:
            t = line.split("\t")
            if len(t) < 6:
                continue
            entries.append((int(t[0]), int(t[1]), int(t[2]), int(t[3])))
    return entries


def _records_to_columns(recs, tid, start, end):
    from .bam import ReadColumns

    tids, poss, ends, mapqs, flags, tlens, rlens = [], [], [], [], [], [], []
    mposs, singlem = [], []
    seg_t, seg_s, seg_e, seg_r = [], [], [], []
    n = 0
    for r in recs:
        if r.bf & 0x4:
            rpos, rend = r.pos - 1, r.pos - 1
        else:
            rpos, rend = r.pos - 1, r.ref_end() - 1
        if tid is not None:
            if r.ref_id != tid or rpos >= end or rend <= start:
                continue
        row = n
        n += 1
        tids.append(r.ref_id)
        poss.append(rpos)
        ends.append(rend)
        mapqs.append(r.mapq)
        flags.append(r.bf)
        tlens.append(r.tlen)
        rlens.append(r.read_len)
        mposs.append(r.mate_pos - 1 if r.mate_pos > 0 else -1)
        singlem.append(r.single_m() and not (r.bf & 0x4))
        if not (r.bf & 0x4):
            for bs, be in r.aligned_blocks():
                seg_t.append(r.ref_id)
                seg_s.append(bs)
                seg_e.append(be)
                seg_r.append(row)
    return ReadColumns(
        np.asarray(tids, dtype=np.int32),
        np.asarray(poss, dtype=np.int32),
        np.asarray(ends, dtype=np.int32),
        np.asarray(mapqs, dtype=np.uint8),
        np.asarray(flags, dtype=np.uint16),
        np.asarray(tlens, dtype=np.int32),
        np.asarray(rlens, dtype=np.int32),
        np.asarray(mposs, dtype=np.int32),
        np.asarray(singlem, dtype=bool),
        np.asarray(seg_t, dtype=np.int32),
        np.asarray(seg_s, dtype=np.int32),
        np.asarray(seg_e, dtype=np.int32),
        np.asarray(seg_r, dtype=np.int32),
    )


# -------------------------------------------------------------- writer

# EOF container (CRAM 3.0 spec appendix: fixed marker bytes)
EOF_CONTAINER = bytes([
    0x0f, 0x00, 0x00, 0x00, 0xff, 0xff, 0xff, 0xff, 0x0f, 0xe0,
    0x45, 0x4f, 0x46, 0x00, 0x00, 0x00, 0x00, 0x01, 0x00, 0x05,
    0xbd, 0xd9, 0x4f, 0x00, 0x01, 0x00, 0x06, 0x06, 0x01, 0x00,
    0x01, 0x00, 0x01, 0x00, 0xee, 0x63, 0x01, 0x4b,
])

# the 2.x EOF marker: same empty container (ref -1, start 0x454F46
# "EOF", one 6-byte raw compression-header block of empty maps) in the
# CRC-less 2.x layout with its ITF8 record counter — validated by
# exact byte comparison at open by other readers
EOF_CONTAINER_V2 = bytes([
    0x0b, 0x00, 0x00, 0x00,              # container length 11
    0xff, 0xff, 0xff, 0xff, 0x0f,        # ref id -1 (itf8)
    0xe0, 0x45, 0x4f, 0x46,              # start 0x454F46 "EOF"
    0x00, 0x00, 0x00, 0x00,              # span, nrec, counter, bases
    0x01, 0x00,                          # 1 block, 0 landmarks
    0x00, 0x01, 0x00, 0x06, 0x06,        # raw comp-header block, 6 bytes
    0x01, 0x00, 0x01, 0x00, 0x01, 0x00,  # empty preservation/maps
])

# external block content ids for the fixture writer's series
_W_IDS = {
    "BF": 1, "CF": 2, "RL": 3, "AP": 4, "RG": 5, "RN": 6, "MF": 7,
    "NS": 8, "NP": 9, "TS": 10, "TL": 11, "FN": 12, "FC": 13, "FP": 14,
    "DL": 15, "RS": 16, "HC": 17, "PD": 18, "SC": 19, "IN": 20,
    "BA": 21, "MQ": 22, "QS": 23, "BS": 24, "NF": 25, "RI": 26,
}


class CramWriter:
    """Minimal spec-conformant CRAM 3.0 writer for hermetic fixtures.

    One slice per container; every data series EXTERNAL in its own
    block (ITF8 ints / stop-byte name arrays); detached mate info; no
    tag values (one empty TD line). ``block_method`` picks the block
    compression (gzip default; rans exercises the rANS decoder
    round-trip). This is a test tool, not a production encoder — the
    production direction CRAM→columns is what the reader implements.
    """

    def __init__(self, fh, header_text: str, ref_names: list[str],
                 ref_lens: list[int], records_per_container: int = 10000,
                 block_method: int = M_GZIP, ap_delta: bool = True,
                 rans_order: int = 0, minor: int = 0, major: int = 3,
                 series_methods: dict[str, int] | None = None,
                 core_series: tuple = (), with_tags: bool = False,
                 rans_stripe: int = 0):
        if major not in (2, 3):
            raise ValueError("cram: writer supports major 2 and 3")
        self._fh = fh
        self.ref_names = list(ref_names)
        self._rpc = records_per_container
        self._method = block_method
        self._rans_order = rans_order
        self._rans_stripe = rans_stripe
        self._ap_delta = ap_delta
        self._v2 = major == 2
        # per-series block-method overrides, e.g. the htslib 3.1 shape
        # {"RN": M_TOK3, "QS": M_FQZCOMP}: RN switches to a \0 stop
        # byte and the tokeniser; QS compresses the per-record quality
        # payload through fqzcomp. Only combinations with a real
        # encoder are accepted — anything else would write a method
        # byte over a payload that codec cannot decode.
        general = {M_RAW, M_GZIP, M_RANS, M_RANSNX16, M_ARITH}
        if block_method not in general:
            raise ValueError(
                "cram: block_method must be a general-purpose codec "
                "(raw/gzip/rans4x8/rans-nx16/arith); use "
                "series_methods for RN:tok3 / QS:fqzcomp")
        self._series_methods = dict(series_methods or {})
        for k, m in self._series_methods.items():
            if m in general or (k == "RN" and m == M_TOK3) or \
                    (k == "QS" and m == M_FQZCOMP):
                continue
            raise ValueError(
                f"cram: no encoder for series {k!r} with method {m} "
                "(tok3 is RN-only, fqzcomp is QS-only)")
        # core-bit series: integer series coded as canonical HUFFMAN
        # bits in the CORE block (the layout real htslib CRAMs use for
        # BF/TL/MQ) instead of EXTERNAL ITF8 streams
        self._core_series = tuple(core_series)
        for k in self._core_series:
            if k not in ("BF", "RL", "MQ"):
                raise ValueError(
                    "cram: core_series supports BF/RL/MQ (the integer "
                    "series this fixture writer emits per record)")
        self._with_tags = with_tags
        self._pending: list[dict] = []
        self._counter = 0
        self._offsets: list[tuple[int, int, int, int, int]] = []
        fh.write(CRAM_MAGIC + bytes([major, minor])
                 + b"goleft-tpu-cram\x00\x00\x00\x00\x00")
        sq = "".join(
            f"@SQ\tSN:{n}\tLN:{ln}\n"
            for n, ln in zip(ref_names, ref_lens)
        )
        text = (header_text if "@SQ" in header_text
                else header_text + sq).encode()
        blob = struct.pack("<i", len(text)) + text
        block = write_block(M_RAW, CT_FILE_HEADER, 0, blob,
                            v2=self._v2)
        self._fh.write(ContainerHeader.build(
            len(block), 0, 0, 0, 0, 0, 0, 1, [0],
            v2=self._v2) + block)

    def write_record(self, tid: int, pos0: int,
                     cigar: list[tuple[int, int]], mapq: int = 60,
                     flag: int = 0, name: str = "r", mate_tid: int = -1,
                     mate_pos: int = -1, tlen: int = 0,
                     quals: bytes | None = None) -> None:
        """pos0 is 0-based (BamWriter-compatible); CRAM stores 1-based.
        ``quals`` (one byte per query base) stores the record's quality
        string (CF_QS_STORED) in the QS series."""
        if quals is not None:
            q_len = sum(ln for ln, op in cigar if op in (0, 1, 4, 7, 8))
            if len(quals) != q_len or not quals:
                raise ValueError(
                    "cram: quals must be non-empty and match the "
                    "query length")
        self._pending.append(dict(
            tid=tid, pos=pos0 + 1, cigar=cigar, mapq=mapq, flag=flag,
            name=name, mate_tid=mate_tid, mate_pos=mate_pos + 1,
            tlen=tlen, quals=quals,
        ))
        if len(self._pending) >= self._rpc or (
            len(self._pending) > 1
            and self._pending[-2]["tid"] != tid
        ):
            # flush everything before a tid change (single-ref slices)
            tail = []
            while self._pending and self._pending[-1]["tid"] != \
                    self._pending[0]["tid"]:
                tail.append(self._pending.pop())
            self._flush()
            self._pending = list(reversed(tail))

    def _flush(self) -> None:
        recs = self._pending
        if not recs:
            return
        self._pending = []
        ids = _W_IDS
        ints: dict[str, list[int]] = {k: [] for k in ids}
        rn_tok3 = self._series_methods.get("RN") == M_TOK3
        rn_stop = 0x00 if rn_tok3 else 0x09
        names = bytearray()
        name_list: list[bytes] = []
        qs_payload = bytearray()
        qs_lens: list[int] = []
        sc_bytes = bytearray()
        in_bytes = bytearray()
        ref_id = recs[0]["tid"]
        first_pos = recs[0]["pos"]
        prev = first_pos
        max_end = first_pos
        for r in recs:
            q_len = sum(ln for ln, op in r["cigar"]
                        if op in (0, 1, 4, 7, 8))  # M I S = X
            bf = r["flag"] & ~(BAM_MREVERSE | BAM_MUNMAP)
            cf = CF_DETACHED | CF_NO_SEQ
            if r.get("quals") is not None:
                cf |= CF_QS_STORED
                qs_payload += r["quals"]
                qs_lens.append(len(r["quals"]))
            ints["BF"].append(bf)
            ints["CF"].append(cf)
            ints["RL"].append(q_len)
            if self._ap_delta:
                ints["AP"].append(r["pos"] - prev)
                prev = r["pos"]
            else:
                ints["AP"].append(r["pos"])
            ints["RG"].append(-1)
            nm = r["name"].encode()
            names += nm + bytes([rn_stop])
            name_list.append(nm)
            mf = ((MF_MATE_REVERSE if r["flag"] & BAM_MREVERSE else 0)
                  | (MF_MATE_UNMAPPED if r["flag"] & BAM_MUNMAP else 0))
            ints["MF"].append(mf)
            ints["NS"].append(r["mate_tid"])
            ints["NP"].append(r["mate_pos"])
            ints["TS"].append(r["tlen"])
            ints["TL"].append(0)
            if not (r["flag"] & 0x4):
                feats = []
                qp = 1
                for ln, op in r["cigar"]:
                    if op == 0 or op == 7 or op == 8:  # M/=/X
                        qp += ln
                    elif op == 4:  # S
                        feats.append((ord("S"), qp, ln))
                        qp += ln
                    elif op == 1:  # I
                        feats.append((ord("I"), qp, ln))
                        qp += ln
                    elif op == 2:  # D
                        feats.append((ord("D"), qp, ln))
                    elif op == 3:  # N
                        feats.append((ord("N"), qp, ln))
                    elif op == 5:  # H
                        feats.append((ord("H"), qp, ln))
                    elif op == 6:  # P
                        feats.append((ord("P"), qp, ln))
                ints["FN"].append(len(feats))
                fprev = 0
                for code, fp, ln in feats:
                    ints["FC"].append(code)
                    ints["FP"].append(fp - fprev)
                    fprev = fp
                    if code == ord("S"):
                        sc_bytes += b"N" * ln + b"\x00"
                    elif code == ord("I"):
                        in_bytes += b"N" * ln + b"\x00"
                    elif code == ord("D"):
                        ints["DL"].append(ln)
                    elif code == ord("N"):
                        ints["RS"].append(ln)
                    elif code == ord("H"):
                        ints["HC"].append(ln)
                    elif code == ord("P"):
                        ints["PD"].append(ln)
                ints["MQ"].append(r["mapq"])
                ref_len = sum(ln for ln, op in r["cigar"]
                              if op in (0, 2, 3, 7, 8))
                max_end = max(max_end, r["pos"] + ref_len)
        span = max_end - first_pos

        comp = CompressionHeader(
            rn_included=True, ap_delta=self._ap_delta, ref_required=False,
            tag_dict=[[]],
        )
        tag_cid = max(ids.values()) + 1  # past every series block id
        if self._with_tags:
            # one NM:C tag per record through BYTE_ARRAY_LEN — the
            # nested-encoding shape real htslib CRAMs use for tag
            # values: length from a 0-bit single-symbol HUFFMAN (every
            # 'C' value is 1 byte), bytes from their own EXTERNAL
            # block
            comp.tag_dict = [[("NM", "C")]]
            key = (ord("N") << 16) | (ord("M") << 8) | ord("C")
            comp.tag_encodings[key] = Encoding(E_BYTE_ARRAY_LEN, {
                "len_enc": Encoding(E_HUFFMAN, {"alphabet": [1],
                                                "lengths": [0]}),
                "val_enc": Encoding(E_EXTERNAL, {"id": tag_cid}),
            })
        huff_codes: dict[str, dict[int, tuple[int, int]]] = {}
        for key, cid in ids.items():
            if key in self._core_series and ints[key]:
                alphabet, lengths = _huffman_lengths(ints[key])
                comp.encodings[key] = Encoding(
                    E_HUFFMAN, {"alphabet": alphabet,
                                "lengths": lengths})
                huff_codes[key] = _canonical_codes(alphabet, lengths)
            elif key == "RN":
                comp.encodings[key] = Encoding(
                    E_BYTE_ARRAY_STOP, {"stop": rn_stop, "id": cid})
            elif key in ("SC", "IN"):
                comp.encodings[key] = Encoding(
                    E_BYTE_ARRAY_STOP, {"stop": 0x00, "id": cid})
            else:
                comp.encodings[key] = Encoding(E_EXTERNAL, {"id": cid})

        # core bits, in the exact order decode_slice consumes them:
        # BF then RL per record, MQ only for mapped records
        core_bytes = b""
        if huff_codes:
            bw = BitWriter()
            mq_vals = iter(ints["MQ"])
            for i, r in enumerate(recs):
                per_rec = [("BF", ints["BF"][i]), ("RL", ints["RL"][i])]
                if not (r["flag"] & 0x4):
                    per_rec.append(("MQ", next(mq_vals)))
                for key, v in per_rec:
                    codes = huff_codes.get(key)
                    if codes is not None:
                        code, ln = codes[v]
                        bw.write(code, ln)
            core_bytes = bw.finish()

        ext_payload: dict[int, bytes] = {}
        for key, cid in ids.items():
            if key in huff_codes:
                continue  # series lives in the core block
            if key == "RN":
                ext_payload[cid] = bytes(names)
            elif key == "QS":
                ext_payload[cid] = bytes(qs_payload)
            elif key == "SC":
                ext_payload[cid] = bytes(sc_bytes)
            elif key == "IN":
                ext_payload[cid] = bytes(in_bytes)
            else:
                ext_payload[cid] = b"".join(
                    write_itf8(v) for v in ints[key]
                )
        if self._with_tags:
            # stand-in per-record NM value (any byte works — the
            # decoder consumes tag values for stream alignment only)
            ext_payload[tag_cid] = bytes(
                min(len(r["cigar"]), 255) for r in recs)
        used = [cid for cid, payload in ext_payload.items() if payload]
        key_of = {cid: key for key, cid in ids.items()}

        sl = SliceHeader(
            ref_id, first_pos, span, len(recs), self._counter,
            1 + len(used), list(used), -1, b"\x00" * 16,
        )
        blocks = write_block(M_RAW, CT_SLICE_HEADER, 0,
                             sl.serialize(v2=self._v2), v2=self._v2)
        blocks += write_block(M_RAW, CT_CORE, 0, core_bytes,
                              v2=self._v2)
        for cid in used:
            key = key_of.get(cid)  # None for the tag-value block
            method = self._series_methods.get(key, self._method)
            payload = ext_payload[cid]
            if method == M_TOK3 and key == "RN":
                from .tok3 import encode as tok3_encode

                comp_bytes = tok3_encode(name_list)
                blocks += _write_block_pre(M_TOK3, CT_EXTERNAL, cid,
                                           comp_bytes, len(payload),
                                           self._v2)
            elif method == M_FQZCOMP and key == "QS":
                from .fqzcomp import encode as fqz_encode

                comp_bytes = fqz_encode(qs_lens, bytes(payload))
                blocks += _write_block_pre(M_FQZCOMP, CT_EXTERNAL, cid,
                                           comp_bytes, len(payload),
                                           self._v2)
            else:
                blocks += write_block(method, CT_EXTERNAL, cid, payload,
                                      rans_order=self._rans_order,
                                      v2=self._v2,
                                      rans_stripe=self._rans_stripe)
        comp_block = write_block(M_RAW, CT_COMP_HEADER, 0,
                                 comp.serialize(), v2=self._v2)
        body = comp_block + blocks
        container_off = self._fh.tell()
        n_bases = sum(ints["RL"])
        self._fh.write(ContainerHeader.build(
            len(body), ref_id, first_pos, span, len(recs),
            self._counter, n_bases, 2 + len(used), [len(comp_block)],
            v2=self._v2,
        ))
        self._fh.write(body)
        self._offsets.append(
            (ref_id, first_pos, span, container_off, len(comp_block))
        )
        self._counter += len(recs)

    def close(self) -> None:
        self._flush()
        self._fh.write(EOF_CONTAINER_V2 if self._v2 else EOF_CONTAINER)

    def write_crai(self, path: str) -> None:
        """Companion .crai (gzipped 6-column TSV, spec appendix)."""
        with gzip.open(path, "wt") as fh:
            for (seq, start, span, c_off, slice_off) in self._offsets:
                fh.write(f"{seq}\t{start}\t{span}\t{c_off}\t"
                         f"{slice_off}\t0\n")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
