"""Strict streaming FASTQ reader: plain, gzip/BGZF, local or remote.

The read-mapping pipeline's input layer. Bytes come through the data
plane's :func:`~goleft_tpu.io.remote.source_io`, so ``http(s)://`` /
``s3://`` FASTQs read exactly like local paths (block-cached ranged
reads); gzip — including BGZF, which is concatenated gzip members —
is detected from magic bytes like utils/xopen does.

Parsing is deliberately strict 4-line FASTQ. Every malformed shape is
a :class:`FastqError` (a ``ValueError`` → classified PERMANENT by the
resilience RetryPolicy — retrying a corrupt file cannot help) with
the record number and offending line in the message, never a hang or
a silently-truncated iteration:

  - a record line missing at EOF → "truncated FASTQ record"
  - a sequence wrapped over multiple lines → rejected with a clear
    error (the '+' separator is how we detect it)
  - a '+' separator repeating a DIFFERENT header → rejected
    (repeating the same header is legal and accepted)
  - quality/sequence length mismatch → rejected
  - an empty file → rejected (a mapper fed zero bytes is a broken
    upstream, not an empty cohort)
  - CRLF line endings are accepted (both \\r\\n and \\n strip)
"""

from __future__ import annotations

import gzip
import io
from typing import Iterator, NamedTuple

from . import remote

#: bases the mapper accepts; anything else in a sequence line is
#: treated as corruption, not data
_SEQ_OK = frozenset(b"ACGTNacgtn" + bytes(range(ord("A"), ord("Z") + 1))
                    + bytes(range(ord("a"), ord("z") + 1)))


class FastqError(ValueError):
    """Malformed FASTQ — permanent under the RetryPolicy."""


class FastqRecord(NamedTuple):
    name: str
    seq: bytes
    qual: bytes


def _open_stream(path: str):
    """Binary line stream for ``path`` (gzip/BGZF auto-detected)."""
    raw = remote.source_io(path)
    buf = raw if isinstance(raw, io.BufferedReader) \
        else io.BufferedReader(raw)
    if buf.peek(2)[:2] == b"\x1f\x8b":
        return gzip.GzipFile(fileobj=buf), buf
    return buf, buf


class FastqReader:
    """Iterate :class:`FastqRecord` from a FASTQ path/URL.

    Usable as an iterator or a context manager; iteration raises
    :class:`FastqError` at the first malformed record (position
    included) and StopIteration cleanly at a well-formed EOF.
    """

    def __init__(self, path: str):
        self.path = path
        self._fh, self._raw = _open_stream(path)
        self._lineno = 0
        self.records = 0

    def close(self) -> None:
        try:
            self._fh.close()
        finally:
            if self._raw is not self._fh:
                self._raw.close()

    def __enter__(self) -> "FastqReader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _err(self, msg: str) -> FastqError:
        return FastqError(
            f"{self.path}: record {self.records + 1} "
            f"(line {self._lineno}): {msg}")

    def _line(self) -> bytes | None:
        ln = self._fh.readline()
        if not ln:
            return None
        self._lineno += 1
        return ln.rstrip(b"\r\n")

    def __iter__(self) -> Iterator[FastqRecord]:
        return self

    def __next__(self) -> FastqRecord:
        hdr = self._line()
        if hdr is None:
            if self.records == 0:
                raise FastqError(
                    f"{self.path}: empty FASTQ (zero records)")
            raise StopIteration
        if not hdr.startswith(b"@"):
            raise self._err(
                f"expected '@' header, got {hdr[:40]!r}")
        seq = self._line()
        if seq is None:
            raise self._err("truncated FASTQ record (no sequence)")
        if not seq or not all(b in _SEQ_OK for b in seq):
            raise self._err(
                f"invalid sequence line {seq[:40]!r}")
        sep = self._line()
        if sep is None:
            raise self._err("truncated FASTQ record (no '+' line)")
        if not sep.startswith(b"+"):
            if all(b in _SEQ_OK for b in sep):
                raise self._err(
                    "multi-line sequences are not supported "
                    "(expected '+' separator)")
            raise self._err(
                f"expected '+' separator, got {sep[:40]!r}")
        if len(sep) > 1 and sep[1:] != hdr[1:]:
            raise self._err(
                "'+' separator repeats a different header")
        qual = self._line()
        if qual is None:
            raise self._err("truncated FASTQ record (no quality)")
        if len(qual) != len(seq):
            raise self._err(
                f"quality length {len(qual)} != sequence length "
                f"{len(seq)}")
        self.records += 1
        name = hdr[1:].split()[0].decode("ascii", "replace") \
            if len(hdr) > 1 else ""
        return FastqRecord(name, seq, qual)


def read_fastq(path: str) -> list[FastqRecord]:
    """Whole-file convenience (tests, small inputs)."""
    with FastqReader(path) as r:
        return list(r)
