"""Adaptive arithmetic codec (CRAM 3.1 block method 6), clean-room.

CRAM 3.1's general-purpose range coder: an adaptive byte-wise
arithmetic coder with the same meta-transform family as rANS-Nx16.
Implemented from the CRAM 3.1 codecs specification (the reference
accepts 3.1 through htslib — covstats.go:229 smoove NewReader; this
module is the tpu-native rebuild's own implementation, validated by an
in-repo encoder/decoder pair + fuzzing like the Nx16 codec in
io/rans_nx16.py — no htslib binary exists in this environment for
cross-validation, so the layout below is pinned by documentation and
twins; see docs/cram.md).

Layout:

- flags byte: ORDER=0x01, EXT=0x04 (payload is bzip2, no modelling),
  STRIPE=0x08, NOSZ=0x10 (no stored size), CAT=0x20 (stored raw),
  RLE=0x40 (run lengths coded through dedicated run models),
  PACK=0x80
- sizes are uint7 varints (shared with rans_nx16)
- the coded stream proper starts with one byte holding the alphabet
  size (max symbol + 1; 0 encodes 256), sizing every adaptive model
- range coder: 32-bit range, carry-counting encoder (64-bit low,
  cache + FF-run), 5-byte decoder preload whose first byte is the
  cache priming byte; renormalizes a byte at a time while
  range < 2^24
- adaptive model: per-symbol frequencies starting at 1, +16 per
  update, halved (rounding up) when the total would reach 2^16-16,
  with the classic adjacent-swap keeping hot symbols near the front
  — encoder and decoder mutate identically, so state never needs to
  be transmitted
- order-1 keys a separate model on the previous output byte
  (initially 0)
- RLE: each literal is coded once through the byte model, then its
  repeat count through run models: first part keyed by the literal,
  continuation parts (a part of 255 means "more follows") by a
  shared continuation context
- PACK / STRIPE / CAT / NOSZ: identical framing to rans_nx16

Decode order for combined transforms: range-decode (with integrated
RLE) innermost, then PACK expansion — the exact inverse of the
encoder's PACK → model+RLE."""

from __future__ import annotations

from .rans_nx16 import (
    F_CAT,
    F_NOSZ,
    F_ORDER1,
    F_PACK,
    F_RLE,
    F_STRIPE,
    _pack,
    _unpack,
    read_uint7,
    write_uint7,
)

F_EXT = 0x04

STEP = 16
MAX_TOTAL = (1 << 16) - STEP
TOP = 1 << 24
MASK32 = 0xFFFFFFFF

# continuation context for run-length parts beyond the first
RUN_MORE = 256


# -------------------------------------------------------- range coder


class RangeEncoder:
    """Carry-counting range encoder (32-bit range, byte renorm)."""

    __slots__ = ("low", "range", "cache", "ffnum", "out")

    def __init__(self) -> None:
        self.low = 0
        self.range = MASK32
        self.cache = 0
        self.ffnum = 0
        self.out = bytearray()

    def _shift_low(self) -> None:
        low = self.low
        if low < 0xFF000000 or low > MASK32:
            carry = low >> 32
            self.out.append((self.cache + carry) & 0xFF)
            fill = (0xFF + carry) & 0xFF
            while self.ffnum:
                self.out.append(fill)
                self.ffnum -= 1
            self.cache = (low >> 24) & 0xFF
        else:
            self.ffnum += 1
        self.low = (low << 8) & MASK32

    def encode(self, cum: int, freq: int, total: int) -> None:
        r = self.range // total
        self.low += cum * r
        self.range = r * freq
        while self.range < TOP:
            self.range <<= 8
            self._shift_low()

    def finish(self) -> bytes:
        for _ in range(5):
            self._shift_low()
        return bytes(self.out)


class RangeDecoder:
    __slots__ = ("buf", "pos", "code", "range")

    def __init__(self, buf, pos: int = 0) -> None:
        self.buf = buf
        self.pos = pos
        self.code = 0
        self.range = MASK32
        n = len(buf)
        for _ in range(5):
            b = buf[self.pos] if self.pos < n else 0
            self.pos += 1
            self.code = ((self.code << 8) | b) & MASK32

    def get_freq(self, total: int) -> int:
        self.range //= total
        return self.code // self.range

    def decode(self, cum: int, freq: int) -> None:
        self.code -= cum * self.range
        self.range *= freq
        buf, n = self.buf, len(self.buf)
        while self.range < TOP:
            b = buf[self.pos] if self.pos < n else 0
            self.pos += 1
            self.code = ((self.code << 8) | b) & MASK32
            self.range <<= 8


# ----------------------------------------------------- adaptive model


class AdaptiveModel:
    """Symbol-frequency model updated identically by both sides."""

    __slots__ = ("syms", "freqs", "total")

    def __init__(self, nsym: int) -> None:
        self.syms = list(range(nsym))
        self.freqs = [1] * nsym
        self.total = nsym

    def _bump(self, i: int) -> None:
        freqs = self.freqs
        freqs[i] += STEP
        self.total += STEP
        if self.total > MAX_TOTAL:
            total = 0
            for j, f in enumerate(freqs):
                f -= f >> 1
                freqs[j] = f
                total += f
            self.total = total
        if i and freqs[i] > freqs[i - 1]:
            freqs[i], freqs[i - 1] = freqs[i - 1], freqs[i]
            syms = self.syms
            syms[i], syms[i - 1] = syms[i - 1], syms[i]

    def encode(self, rc: RangeEncoder, sym: int) -> None:
        syms = self.syms
        freqs = self.freqs
        acc = 0
        i = 0
        while syms[i] != sym:
            acc += freqs[i]
            i += 1
        rc.encode(acc, freqs[i], self.total)
        self._bump(i)

    def decode(self, rc: RangeDecoder) -> int:
        f = rc.get_freq(self.total)
        if f >= self.total:
            raise ValueError("arith: corrupt stream (freq out of range)")
        freqs = self.freqs
        acc = 0
        i = 0
        while acc + freqs[i] <= f:
            acc += freqs[i]
            i += 1
        rc.decode(acc, freqs[i])
        sym = self.syms[i]
        self._bump(i)
        return sym


# ------------------------------------------------------- coded bodies


def _model_nsym(header_byte: int) -> int:
    return header_byte if header_byte else 256


def _decode_body(buf, pos: int, out_len: int, order: int,
                 rle: bool) -> bytes:
    from . import native

    fast = native.arith_decode_body(buf, pos, out_len, order, rle)
    if fast is not None:
        return fast
    nsym = _model_nsym(buf[pos])
    pos += 1
    rc = RangeDecoder(buf, pos)
    out = bytearray(out_len)
    if order:
        models: dict[int, AdaptiveModel] = {}

        def byte_model(ctx: int) -> AdaptiveModel:
            m = models.get(ctx)
            if m is None:
                m = models[ctx] = AdaptiveModel(nsym)
            return m
    else:
        m0 = AdaptiveModel(nsym)

        def byte_model(ctx: int) -> AdaptiveModel:
            return m0

    if not rle:
        prev = 0
        for i in range(out_len):
            s = byte_model(prev).decode(rc)
            out[i] = s
            prev = s
        return bytes(out)

    run_models: dict[int, AdaptiveModel] = {}

    def run_model(ctx: int) -> AdaptiveModel:
        m = run_models.get(ctx)
        if m is None:
            m = run_models[ctx] = AdaptiveModel(256)
        return m

    i = 0
    prev = 0
    while i < out_len:
        s = byte_model(prev).decode(rc)
        prev = s
        run = 0
        ctx = s
        while True:
            part = run_model(ctx).decode(rc)
            run += part
            if part != 255:
                break
            if run > out_len:
                # a truncated stream zero-pads the range coder, which
                # can loop on the continuation symbol forever — bound
                # the run INSIDE the loop, not just after it
                raise ValueError("arith: run overflows declared size")
            ctx = RUN_MORE
        if i + run + 1 > out_len:
            raise ValueError("arith: run overflows declared size")
        for j in range(i, i + run + 1):
            out[j] = s
        i += run + 1
    return bytes(out)


def _encode_body(data: bytes, order: int, rle: bool) -> bytes:
    max_sym = max(data) if data else 0
    nsym = max_sym + 1
    rc = RangeEncoder()
    if order:
        models: dict[int, AdaptiveModel] = {}

        def byte_model(ctx: int) -> AdaptiveModel:
            m = models.get(ctx)
            if m is None:
                m = models[ctx] = AdaptiveModel(nsym)
            return m
    else:
        m0 = AdaptiveModel(nsym)

        def byte_model(ctx: int) -> AdaptiveModel:
            return m0

    if not rle:
        prev = 0
        for s in data:
            byte_model(prev).encode(rc, s)
            prev = s
    else:
        run_models: dict[int, AdaptiveModel] = {}

        def run_model(ctx: int) -> AdaptiveModel:
            m = run_models.get(ctx)
            if m is None:
                m = run_models[ctx] = AdaptiveModel(256)
            return m

        i = 0
        n = len(data)
        prev = 0
        while i < n:
            s = data[i]
            j = i + 1
            while j < n and data[j] == s:
                j += 1
            byte_model(prev).encode(rc, s)
            prev = s
            run = j - i - 1
            ctx = s
            while True:
                part = min(run, 255)
                run_model(ctx).encode(rc, part)
                run -= part
                if part != 255:
                    break
                ctx = RUN_MORE
            i = j
    return bytes([nsym & 0xFF]) + rc.finish()


# ----------------------------------------------------------- top level


def decode(data: bytes, expected_len: int | None = None) -> bytes:
    """Decode one adaptive-arithmetic stream (the full block payload)."""
    try:
        return _decode(data, expected_len, 0)
    except IndexError:
        # header/meta reads past the end of a truncated or corrupt
        # stream surface as the module's typed error, never a crash
        raise ValueError("arith: truncated stream") from None
    except OSError as e:  # bz2 EXT payload corruption
        raise ValueError(f"arith: corrupt EXT payload ({e})") from None


def _decode(data: bytes, expected_len: int | None,
            depth: int = 0) -> bytes:
    buf = memoryview(data)
    pos = 0
    flags = buf[pos]
    pos += 1
    if flags & F_NOSZ:
        if expected_len is None:
            raise ValueError("arith: NOSZ stream needs external size")
        out_len = expected_len
    else:
        out_len, pos = read_uint7(buf, pos)
        if expected_len is not None and out_len != expected_len:
            # checked BEFORE any allocation, same as rans_nx16: the
            # block header's raw size is authoritative
            raise ValueError(
                f"arith: stored size {out_len} != declared block "
                f"size {expected_len}"
            )
    if flags & F_STRIPE:
        if depth:
            # the spec's composition never nests STRIPE; a crafted
            # chain of stripe headers must not turn into unbounded
            # recursion
            raise ValueError("arith: nested STRIPE stream")
        n_lanes = buf[pos]
        pos += 1
        if n_lanes == 0 and out_len > 0:
            raise ValueError("arith: stripe stream with 0 lanes")
        clens = []
        for _ in range(n_lanes):
            c, pos = read_uint7(buf, pos)
            clens.append(c)
        lanes = []
        for j in range(n_lanes):
            lane_len = (out_len - j + n_lanes - 1) // n_lanes
            lanes.append(_decode(bytes(buf[pos:pos + clens[j]]),
                                 lane_len, depth + 1))
            pos += clens[j]
        out = bytearray(out_len)
        for j, lane in enumerate(lanes):
            out[j::n_lanes] = lane
        return bytes(out)

    pack_map = None
    final_len = out_len
    if flags & F_PACK:
        nsym = buf[pos]
        pos += 1
        pack_map = [buf[pos + k] for k in range(nsym)]
        pos += nsym
        out_len, pos = read_uint7(buf, pos)  # packed byte count

    if flags & F_CAT:
        payload = bytes(buf[pos:pos + out_len])
        if len(payload) != out_len:
            raise ValueError("arith: truncated CAT payload")
    elif flags & F_EXT:
        import bz2

        payload = bz2.decompress(bytes(buf[pos:]))
        if len(payload) != out_len:
            raise ValueError("arith: EXT payload length mismatch")
    elif out_len == 0:
        payload = b""
    else:
        payload = _decode_body(buf, pos, out_len, flags & F_ORDER1,
                               bool(flags & F_RLE))

    if pack_map is not None:
        payload = _unpack(payload, pack_map, final_len)
    if len(payload) != final_len:
        raise ValueError("arith: output length mismatch")
    return payload


def encode(data: bytes, order: int = 0, use_rle: bool = False,
           use_pack: bool = False, stripe: int = 0,
           ext: bool = False) -> bytes:
    """Encode (fixture writer + fuzz twin for the decoder). Transforms
    apply PACK → model(+RLE), the exact inverse of decode's expansion
    order; tiny or degenerate bodies store CAT."""
    if stripe:
        lanes = [data[j::stripe] for j in range(stripe)]
        subs = [encode(ln, order=order, use_rle=use_rle) for ln in lanes]
        out = bytearray([F_STRIPE])
        out += write_uint7(len(data))
        out.append(stripe)
        for s in subs:
            out += write_uint7(len(s))
        for s in subs:
            out += s
        return bytes(out)
    flags = order & 1
    body = data
    meta = bytearray()
    final_len = len(data)
    if use_pack and body:
        res = _pack(body)
        if res is not None and len(res[0]) < len(body):
            packed, pmap = res
            flags |= F_PACK
            meta += bytes([len(pmap)]) + bytes(pmap)
            meta += write_uint7(len(packed))
            body = packed
    if ext and body:
        import bz2

        comp = bz2.compress(bytes(body))
        if len(comp) < len(body):
            flags |= F_EXT
            payload = comp
        else:
            flags |= F_CAT
            payload = bytes(body)
    elif len(body) < 16 or len(set(body)) <= 1 and not use_rle:
        flags |= F_CAT
        payload = bytes(body)
    else:
        if use_rle:
            flags |= F_RLE
        payload = _encode_body(bytes(body), flags & F_ORDER1,
                               bool(flags & F_RLE))
        if len(payload) >= len(body):
            flags &= ~(F_RLE | F_ORDER1)
            flags |= F_CAT
            payload = bytes(body)
    return bytes([flags]) + write_uint7(final_len) + bytes(meta) \
        + payload
