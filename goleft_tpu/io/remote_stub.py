"""A stdlib HTTP object store for testing the remote data plane.

Just enough of an S3/htsget-shaped server to exercise every contract
:mod:`goleft_tpu.io.remote` depends on, with zero dependencies:

  - ``HEAD /name`` → 200 + ``Content-Length`` + ``ETag``
  - ``GET /name`` with ``Range: bytes=a-b`` → 206 + ``Content-Range``
    (or 200 full-body without a Range header)
  - strong ETags derived from content (sha256 prefix), so a mutated
    object *is* a new identity
  - deterministic fault injection: ``fail(name, times=N, status=S)``
    makes the next N requests for that object answer ``S`` — 503 for
    transient-retry legs, 403 for permanent ones
  - deterministic drift: ``flip_after(name, n, new_data)`` swaps the
    object's content (and therefore its ETag) once ``n`` requests
    have touched it — the mid-run ETag-drift scenario, no timing
    races
  - ``ignore_range(name)`` answers 200 full-body to Range requests
    (a server that ignores Range is legal per RFC 7233; the client
    must still produce correct bytes)

:class:`StubServer` is the harness: a context manager that binds a
loopback port and yields URLs. Used by the unit tests, the
``dataplane-smoke`` e2e and the ``remote_fetch`` bench entry; run
directly it serves a directory (the smoke's subprocess mode)::

    python -m goleft_tpu.io.remote_stub [--dir D] [--port P]
"""

from __future__ import annotations

import argparse
import hashlib
import http.server
import os
import re
import sys
import threading

_RANGE_RE = re.compile(r"bytes=(\d+)-(\d*)$")


def _etag(data: bytes) -> str:
    return '"' + hashlib.sha256(data).hexdigest()[:16] + '"'


class ObjectStore:
    """The in-memory bucket: named blobs + per-name behaviors."""

    def __init__(self):
        self._lock = threading.Lock()
        self._objects: dict = {}
        self._faults: dict = {}
        self._flips: dict = {}
        self._ignore_range: set = set()
        self.request_counts: dict = {}

    def put(self, name: str, data: bytes) -> None:
        with self._lock:
            self._objects[name] = bytes(data)

    def delete(self, name: str) -> None:
        with self._lock:
            self._objects.pop(name, None)

    def etag(self, name: str) -> str:
        with self._lock:
            return _etag(self._objects[name])

    def fail(self, name: str, times: int = 1,
             status: int = 503) -> None:
        """The next ``times`` requests touching ``name`` answer
        ``status`` (then behavior reverts)."""
        with self._lock:
            self._faults[name] = [times, status]

    def flip_after(self, name: str, n: int, new_data: bytes) -> None:
        """Swap ``name``'s content (→ new ETag) once its request
        count reaches ``n`` — deterministic mid-run drift."""
        with self._lock:
            self._flips[name] = [n, bytes(new_data)]

    def ignore_range(self, name: str) -> None:
        with self._lock:
            self._ignore_range.add(name)

    # ---- the handler's one entry point ----

    def serve(self, name: str):
        """(status, data-or-None, etag, ranged) for one request —
        applies fault/flip bookkeeping under the lock."""
        with self._lock:
            count = self.request_counts.get(name, 0) + 1
            self.request_counts[name] = count
            fault = self._faults.get(name)
            if fault is not None and fault[0] > 0:
                fault[0] -= 1
                if fault[0] <= 0:
                    del self._faults[name]
                return fault[1], None, "", False
            flip = self._flips.get(name)
            if flip is not None and count >= flip[0]:
                self._objects[name] = flip[1]
                del self._flips[name]
            data = self._objects.get(name)
            if data is None:
                return 404, None, "", False
            return (200, data, _etag(data),
                    name not in self._ignore_range)


class _Handler(http.server.BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    store: ObjectStore = None  # bound per-server subclass

    def log_message(self, *a):  # quiet: tests read stdout
        pass

    def _name(self) -> str:
        return self.path.lstrip("/").split("?", 1)[0]

    def _answer(self, head_only: bool) -> None:
        status, data, etag, ranged = self.store.serve(self._name())
        if data is None:
            self.send_response(status)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        rng = self.headers.get("Range")
        m = _RANGE_RE.match(rng.strip()) if rng and ranged else None
        if not head_only and m:
            start = int(m.group(1))
            stop = (int(m.group(2)) + 1) if m.group(2) else len(data)
            stop = min(stop, len(data))
            if start >= len(data):
                self.send_response(416)
                self.send_header("Content-Range",
                                 f"bytes */{len(data)}")
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            body = data[start:stop]
            self.send_response(206)
            self.send_header(
                "Content-Range",
                f"bytes {start}-{stop - 1}/{len(data)}")
        else:
            body = data
            self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.send_header("ETag", etag)
        self.send_header("Accept-Ranges", "bytes")
        self.end_headers()
        if not head_only:
            self.wfile.write(body)

    def do_GET(self):
        self._answer(head_only=False)

    def do_HEAD(self):
        self._answer(head_only=True)


class StubServer:
    """Loopback object store harness::

        with StubServer() as srv:
            url = srv.put("a.bam", data)   # http://127.0.0.1:PORT/a.bam
    """

    def __init__(self, store: ObjectStore | None = None,
                 port: int = 0):
        self.store = store if store is not None else ObjectStore()
        handler = type("_BoundHandler", (_Handler,),
                       {"store": self.store})
        self._httpd = http.server.ThreadingHTTPServer(
            ("127.0.0.1", port), handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def put(self, name: str, data: bytes) -> str:
        self.store.put(name, data)
        return f"{self.url}/{name}"

    def start(self) -> "StubServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=10)

    def __enter__(self) -> "StubServer":
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


def serve_directory(directory: str, port: int = 0,
                    announce=True) -> StubServer:
    """Load every file under ``directory`` (flat) into a store and
    serve it — the smoke's subprocess mode."""
    store = ObjectStore()
    for name in sorted(os.listdir(directory)):
        p = os.path.join(directory, name)
        if os.path.isfile(p):
            with open(p, "rb") as fh:
                store.put(name, fh.read())
    srv = StubServer(store, port=port).start()
    if announce:
        print(f"remote-stub listening on {srv.url}", flush=True)
    return srv


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="stdlib HTTP object store (test harness)")
    ap.add_argument("--dir", required=True,
                    help="directory whose files become objects")
    ap.add_argument("--port", type=int, default=0)
    a = ap.parse_args(argv)
    srv = serve_directory(a.dir, port=a.port)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
