"""BGZF codec, clean-room from the SAM/BAM specification (section 4.1).

BGZF is a series of gzip members, each with an extra subfield ("BC") carrying
the total compressed block size minus one; blocks hold at most 65536 bytes of
uncompressed payload. The stream ends with a fixed 28-byte empty block.

This replaces what the reference gets from the vendored biogo/hts bgzf
package (SURVEY.md §2.4, used at indexcov/indexcov.go:26-34 for bed.gz
output and BAM reading). Virtual offsets are ``coffset << 16 | uoffset``
exactly as in BAI/virtual-file-offset semantics.

A native C++ fast path (csrc/fastio.cpp) is used for whole-file inflation
when available; this module is the portable fallback and the writer.
"""

from __future__ import annotations

import struct
import zlib
from typing import BinaryIO

from ..resilience import faults as _faults

# Fixed empty final block from the SAM spec (magic EOF marker).
BGZF_EOF = bytes(
    [
        0x1F, 0x8B, 0x08, 0x04, 0x00, 0x00, 0x00, 0x00, 0x00, 0xFF,
        0x06, 0x00, 0x42, 0x43, 0x02, 0x00, 0x1B, 0x00, 0x03, 0x00,
        0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00,
    ]
)

MAX_BLOCK_SIZE = 0x10000  # 65536 uncompressed bytes per block
# Leave headroom for the gzip wrapper so a worst-case incompressible block
# still fits in the u16 BSIZE field.
WRITE_CHUNK = 0xFF00


def _parse_block_header(buf: bytes, off: int) -> tuple[int, int]:
    """Return (bsize, xlen) for the gzip member starting at ``off``.

    bsize is the total compressed size of the member (BC subfield + 1).
    """
    if buf[off : off + 2] != b"\x1f\x8b":
        raise ValueError(f"bgzf: bad gzip magic at offset {off}")
    flg = buf[off + 3]
    if not flg & 4:  # FEXTRA
        raise ValueError("bgzf: gzip member without FEXTRA (not BGZF)")
    (xlen,) = struct.unpack_from("<H", buf, off + 10)
    xoff = off + 12
    xend = xoff + xlen
    while xoff < xend:
        si1, si2, slen = struct.unpack_from("<BBH", buf, xoff)
        if si1 == 0x42 and si2 == 0x43 and slen == 2:
            (bsize_minus1,) = struct.unpack_from("<H", buf, xoff + 4)
            return bsize_minus1 + 1, xlen
        xoff += 4 + slen
    raise ValueError("bgzf: no BC subfield in gzip extra")


def bgzf_decompress(data: bytes) -> bytes:
    """Inflate an entire in-memory BGZF stream to one bytes object.

    Two passes: the headers are walked first (each block's ISIZE
    trailer is at a known offset, so the exact output size is the sum
    of trailers — O(#blocks), no inflation), then every block inflates
    directly into ONE preallocated buffer through memoryview slices.
    The previous accumulate-then-join held every block's bytes object
    alive simultaneously and paid a second full-size copy at the join
    — real alloc churn on multi-GB whole-file fallbacks."""
    n = len(data)
    spans = []
    off = 0
    total = 0
    while off < n:
        bsize, xlen = _parse_block_header(data, off)
        crc, isize = struct.unpack_from("<II", data, off + bsize - 8)
        spans.append((off, bsize, xlen, crc, isize, total))
        total += isize
        off += bsize
    out = bytearray(total)
    view = memoryview(out)
    for off, bsize, xlen, crc, isize, w in spans:
        _faults.maybe_fail("bgzf", off)
        cdata_off = off + 12 + xlen
        cdata_len = bsize - 12 - xlen - 8  # minus header and crc32+isize
        raw = zlib.decompress(
            data[cdata_off : cdata_off + cdata_len], wbits=-15
        )
        if len(raw) != isize:
            raise ValueError("bgzf: ISIZE mismatch")
        if zlib.crc32(raw) & 0xFFFFFFFF != crc:
            raise ValueError("bgzf: CRC mismatch (corrupt block)")
        view[w : w + isize] = raw
    return bytes(out)


class BgzfReader:
    """Random-access BGZF reader over an in-memory compressed stream.

    Supports sequential ``read`` and ``seek_virtual(voffset)`` where
    voffset = compressed_offset << 16 | within_block_offset.
    """

    def __init__(self, data: bytes):
        self._data = data
        self._coffset = 0  # compressed offset of current block
        self._block = b""
        self._uoffset = 0  # position within current inflated block
        self._next_coffset = 0
        self._load_block(0)

    @classmethod
    def from_file(cls, path: str) -> "BgzfReader":
        from . import remote

        if remote.is_remote(path):
            return cls(remote.fetch_bytes(path))
        with open(path, "rb") as fh:
            return cls(fh.read())

    def _load_block(self, coffset: int) -> None:
        _faults.maybe_fail("bgzf", coffset)
        if coffset >= len(self._data):
            self._coffset = coffset
            self._block = b""
            self._uoffset = 0
            self._next_coffset = coffset
            return
        bsize, xlen = _parse_block_header(self._data, coffset)
        cdata_off = coffset + 12 + xlen
        cdata_len = bsize - 12 - xlen - 8
        self._block = zlib.decompress(
            self._data[cdata_off : cdata_off + cdata_len], wbits=-15
        )
        (crc,) = struct.unpack_from("<I", self._data, coffset + bsize - 8)
        if zlib.crc32(self._block) & 0xFFFFFFFF != crc:
            raise ValueError("bgzf: CRC mismatch (corrupt block)")
        self._coffset = coffset
        self._next_coffset = coffset + bsize
        self._uoffset = 0

    def seek_virtual(self, voffset: int) -> None:
        coffset = voffset >> 16
        uoffset = voffset & 0xFFFF
        if coffset != self._coffset or not self._block:
            self._load_block(coffset)
        self._uoffset = uoffset

    def tell_virtual(self) -> int:
        return (self._coffset << 16) | self._uoffset

    @property
    def eof(self) -> bool:
        return self._uoffset >= len(self._block) and self._next_coffset >= len(
            self._data
        )

    def read(self, n: int) -> bytes:
        out = bytearray()
        while n > 0:
            if self._uoffset >= len(self._block):
                if self._next_coffset >= len(self._data):
                    break
                self._load_block(self._next_coffset)
                if not self._block:
                    break
                continue
            take = min(n, len(self._block) - self._uoffset)
            out += self._block[self._uoffset : self._uoffset + take]
            self._uoffset += take
            n -= take
        return bytes(out)


class BgzfWriter:
    """Streaming BGZF writer (used for .bam fixtures and bed.gz outputs).

    ``block_size`` caps uncompressed bytes per block — small blocks give
    test fixtures realistic multi-block-per-tile BAI linear indexes.
    """

    def __init__(self, fh: BinaryIO, level: int = 6,
                 block_size: int = WRITE_CHUNK):
        self._fh = fh
        self._level = level
        self._chunk = min(block_size, WRITE_CHUNK)
        self._buf = bytearray()

    def write(self, data: bytes) -> None:
        self._buf += data
        while len(self._buf) >= self._chunk:
            self._flush_block(self._chunk)

    def _flush_block(self, n: int) -> None:
        chunk = bytes(self._buf[:n])
        del self._buf[:n]
        # native libdeflate block compression is 2-4x zlib — the bed.gz
        # writer was ~1.1s of indexcov's whole-genome wall. Decompressed
        # content is identical either way; only compressed bytes differ.
        from . import native

        blob = native.bgzf_deflate_block(chunk, self._level)
        if blob is not None:
            self._fh.write(blob)
            return
        co = zlib.compressobj(self._level, zlib.DEFLATED, -15)
        cdata = co.compress(chunk) + co.flush()
        crc = zlib.crc32(chunk) & 0xFFFFFFFF
        bsize = len(cdata) + 12 + 6 + 8  # header(12) + extra(6) + crc/isize(8)
        header = struct.pack(
            "<BBBBIBBHBBHH",
            0x1F, 0x8B, 8, 4,  # magic, deflate, FEXTRA
            0, 0, 0xFF,  # mtime, xfl, os
            6,  # xlen
            0x42, 0x43, 2,  # BC subfield
            bsize - 1,
        )
        self._fh.write(header + cdata + struct.pack("<II", crc, len(chunk)))

    def close(self) -> None:
        while self._buf:
            self._flush_block(min(len(self._buf), self._chunk))
        self._fh.write(BGZF_EOF)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
