"""Name-tokeniser codec (CRAM 3.1 block method 8), clean-room.

CRAM 3.1's read-name codec: each name is split into typed tokens
(alpha runs, digit runs with or without leading zeros, single chars)
and coded as a diff against an earlier name — identical tokens become
MATCH, numeric tokens with a small increment become a delta, whole
repeats become DUP. Token fields fan out into per-(position, field)
byte streams, each compressed independently with rANS-Nx16 or the
adaptive arithmetic coder. Implemented from the CRAM 3.1 codecs
specification's structure (the reference accepts 3.1 through htslib —
covstats.go:229 smoove NewReader); as with the other 3.1 codecs there
is no htslib binary in this environment to cross-validate against, so
the layout below is pinned by documentation + an in-repo encoder twin
with fuzzing (docs/cram.md).

Token types::

    TYPE=0 ALPHA=1 CHAR=2 DIGITS0=3 DZLEN=4 DUP=5 DIFF=6
    DIGITS=7 DDELTA=8 DDELTA0=9 MATCH=10 NOP=11 END=12

Layout:

- byte 0: flags — bit0 ARITH (streams use io/arith.py instead of
  rANS-Nx16), bit1 NEWLINE (names joined with '\\n' instead of '\\0')
- uint7 decoded byte length, uint7 name count
- a sequence of stream chunks, each ``[token position byte]
  [field-type byte] [uint7 compressed length] [compressed stream]``,
  in ascending (position, field) order:
  - position 0 / TYPE: one byte per name — DUP (whole-name repeat) or
    DIFF (diff follows); its distance stream (u32-le per name) tells
    how many names back the template is (0 ⇒ the previous name)
  - position t / TYPE: the token type each diffed name has at t
  - ALPHA: '\\0'-terminated strings; CHAR: single bytes; DIGITS /
    DIGITS0: u32-le values (DIGITS0 zero-padded to the DZLEN byte);
    DDELTA / DDELTA0: u8 increments over the template name's value at
    the same position (DDELTA0 keeps the template's zero-padded
    width); MATCH/END/NOP carry no payload
"""

from __future__ import annotations

import struct

from .rans_nx16 import read_uint7, write_uint7

F_ARITH = 0x01
F_NEWLINE = 0x02

(T_TYPE, T_ALPHA, T_CHAR, T_DIGITS0, T_DZLEN, T_DUP, T_DIFF,
 T_DIGITS, T_DDELTA, T_DDELTA0, T_MATCH, T_NOP, T_END) = range(13)

_MAX_TOKEN_VAL = (1 << 32) - 1


def _compress_stream(data: bytes, use_arith: bool) -> bytes:
    if use_arith:
        from .arith import encode
    else:
        from .rans_nx16 import encode
    if len(data) < 64:
        return encode(data, order=0)
    # token streams are often near-constant (all-DIFF type bytes,
    # zero distances, +1 deltas): let RLE compete with plain entropy
    # coding and keep the smaller stream
    best = encode(data, order=1)
    for kw in ({"order": 0}, {"order": 0, "use_rle": True},
               {"order": 1, "use_rle": True}):
        cand = encode(data, **kw)
        if len(cand) < len(best):
            best = cand
    return best


def _decompress_stream(data: bytes, use_arith: bool) -> bytes:
    if use_arith:
        from .arith import decode

        return decode(data)
    from .rans_nx16 import decode

    return decode(data)


# ---------------------------------------------------------- tokenizer


def _tokenize(name: bytes) -> list[tuple[int, bytes]]:
    """Split a name into (type, text) tokens: digit runs (DIGITS0 when
    zero-padded or too long for u32), alpha runs, single chars."""
    toks: list[tuple[int, bytes]] = []
    i = 0
    n = len(name)
    while i < n:
        c = name[i]
        if 0x30 <= c <= 0x39:
            j = i
            while j < n and 0x30 <= name[j] <= 0x39:
                j += 1
            run = name[i:j]
            if (run[0] == 0x30 and len(run) > 1) or \
                    int(run) > _MAX_TOKEN_VAL:
                toks.append((T_DIGITS0, run))
            else:
                toks.append((T_DIGITS, run))
            i = j
        elif (0x41 <= c <= 0x5A) or (0x61 <= c <= 0x7A):
            j = i
            while j < n and ((0x41 <= name[j] <= 0x5A)
                             or (0x61 <= name[j] <= 0x7A)):
                j += 1
            toks.append((T_ALPHA, name[i:j]))
            i = j
        else:
            toks.append((T_CHAR, name[i:i + 1]))
            i += 1
    return toks


class _Streams:
    """(position, field) → bytearray, created on demand."""

    def __init__(self) -> None:
        self.d: dict[tuple[int, int], bytearray] = {}

    def get(self, pos: int, field: int) -> bytearray:
        key = (pos, field)
        b = self.d.get(key)
        if b is None:
            b = self.d[key] = bytearray()
        return b


# ----------------------------------------------------------- encoding


def encode(names: list[bytes], use_arith: bool = False,
           newline_sep: bool = False) -> bytes:
    """Encode a list of read names (fixture writer + fuzz twin)."""
    st = _Streams()
    prev_toks: list[list[tuple[int, bytes]]] = []
    for n_idx, name in enumerate(names):
        toks = _tokenize(name)
        if n_idx and toks == prev_toks[n_idx - 1] \
                and name == names[n_idx - 1]:
            st.get(0, T_TYPE).append(T_DUP)
            st.get(0, T_DUP).extend(struct.pack("<I", 0))
            prev_toks.append(toks)
            continue
        st.get(0, T_TYPE).append(T_DIFF)
        st.get(0, T_DIFF).extend(struct.pack("<I", 0))
        tmpl = prev_toks[n_idx - 1] if n_idx else []
        for t, (typ, text) in enumerate(toks, start=1):
            ttyp, ttext = tmpl[t - 1] if t - 1 < len(tmpl) \
                else (None, b"")
            if ttyp == typ and ttext == text:
                st.get(t, T_TYPE).append(T_MATCH)
                continue
            if typ == T_DIGITS and ttyp == T_DIGITS:
                delta = int(text) - int(ttext)
                if 0 <= delta <= 255:
                    st.get(t, T_TYPE).append(T_DDELTA)
                    st.get(t, T_DDELTA).append(delta)
                    continue
            if typ == T_DIGITS0 and ttyp == T_DIGITS0 \
                    and len(text) == len(ttext) \
                    and int(text) <= _MAX_TOKEN_VAL:
                delta = int(text) - int(ttext)
                if 0 <= delta <= 255:
                    st.get(t, T_TYPE).append(T_DDELTA0)
                    st.get(t, T_DDELTA0).append(delta)
                    continue
            st.get(t, T_TYPE).append(typ)
            if typ == T_ALPHA:
                st.get(t, T_ALPHA).extend(text + b"\x00")
            elif typ == T_CHAR:
                st.get(t, T_CHAR).extend(text)
            elif typ == T_DIGITS:
                st.get(t, T_DIGITS).extend(struct.pack("<I", int(text)))
            else:  # T_DIGITS0
                if int(text) > _MAX_TOKEN_VAL:
                    # too wide for the u32 payload: store as ALPHA,
                    # and remember the degraded type so later names
                    # diff against what the decoder will reconstruct
                    st.get(t, T_TYPE)[-1] = T_ALPHA
                    st.get(t, T_ALPHA).extend(text + b"\x00")
                    toks[t - 1] = (T_ALPHA, text)
                else:
                    st.get(t, T_DIGITS0).extend(struct.pack("<I", int(text)))
                    st.get(t, T_DZLEN).append(len(text))
        st.get(len(toks) + 1, T_TYPE).append(T_END)
        prev_toks.append(toks)

    ulen = sum(len(n) + 1 for n in names)
    flags = (F_ARITH if use_arith else 0) \
        | (F_NEWLINE if newline_sep else 0)
    out = bytearray([flags])
    out += write_uint7(ulen)
    out += write_uint7(len(names))
    for (pos, field) in sorted(st.d):
        comp = _compress_stream(bytes(st.d[(pos, field)]), use_arith)
        out.append(pos)
        out.append(field)
        out += write_uint7(len(comp))
        out += comp
    return bytes(out)


# ----------------------------------------------------------- decoding


class _Reader:
    __slots__ = ("data", "pos")

    def __init__(self, data: bytes) -> None:
        self.data = data
        self.pos = 0

    def byte(self) -> int:
        if self.pos >= len(self.data):
            raise ValueError("tok3: stream underrun")
        b = self.data[self.pos]
        self.pos += 1
        return b

    def u32(self) -> int:
        if self.pos + 4 > len(self.data):
            raise ValueError("tok3: stream underrun")
        v = struct.unpack_from("<I", self.data, self.pos)[0]
        self.pos += 4
        return v

    def cstr(self) -> bytes:
        end = self.data.find(b"\x00", self.pos)
        if end < 0:
            raise ValueError("tok3: unterminated string")
        s = self.data[self.pos:end]
        self.pos = end + 1
        return s


def decode(data: bytes, expected_len: int | None = None) -> bytes:
    try:
        return _decode(data, expected_len)
    except (IndexError, struct.error):
        # header/stream reads past the end of a truncated or corrupt
        # stream (including inside an inner rANS stream) surface as
        # the module's typed error, never a crash
        raise ValueError("tok3: truncated stream") from None


def _decode(data: bytes, expected_len: int | None) -> bytes:
    buf = memoryview(data)
    if len(buf) < 3:
        raise ValueError("tok3: truncated stream")
    flags = buf[0]
    use_arith = bool(flags & F_ARITH)
    sep = b"\n" if flags & F_NEWLINE else b"\x00"
    pos = 1
    ulen, pos = read_uint7(buf, pos)
    n_names, pos = read_uint7(buf, pos)
    if expected_len is not None and ulen != expected_len:
        raise ValueError(
            f"tok3: stored size {ulen} != declared block size "
            f"{expected_len}"
        )
    raw_streams: dict[tuple[int, int], bytes] = {}
    while pos < len(buf):
        p = buf[pos]
        f = buf[pos + 1]
        pos += 2
        clen, pos = read_uint7(buf, pos)
        if pos + clen > len(buf):
            raise ValueError("tok3: truncated stream chunk")
        raw = _decompress_stream(bytes(buf[pos:pos + clen]), use_arith)
        raw_streams[(p, f)] = raw
        pos += clen

    from . import native

    fast = native.tok3_assemble(raw_streams, n_names, sep[0], ulen)
    if fast is not None:
        return fast

    streams = {k: _Reader(v) for k, v in raw_streams.items()}

    def stream(p: int, f: int) -> _Reader:
        r = streams.get((p, f))
        if r is None:
            raise ValueError(f"tok3: missing stream ({p},{f})")
        return r

    names: list[bytes] = []
    toks_per_name: list[list[tuple[int, bytes]]] = []
    for n_idx in range(n_names):
        t0 = stream(0, T_TYPE).byte()
        if t0 == T_DUP:
            dist = stream(0, T_DUP).u32()
            src = n_idx - 1 - dist
            if not 0 <= src < n_idx:
                raise ValueError("tok3: DUP distance out of range")
            names.append(names[src])
            toks_per_name.append(toks_per_name[src])
            continue
        if t0 != T_DIFF:
            raise ValueError("tok3: name must start with DUP or DIFF")
        dist = stream(0, T_DIFF).u32()
        src = n_idx - 1 - dist
        if n_idx and not 0 <= src < n_idx:
            raise ValueError("tok3: DIFF distance out of range")
        tmpl = toks_per_name[src] if n_idx else []
        toks: list[tuple[int, bytes]] = []
        t = 1
        while True:
            typ = stream(t, T_TYPE).byte()
            if typ == T_END:
                break
            if typ == T_NOP:
                t += 1
                continue
            ttyp, ttext = tmpl[t - 1] if t - 1 < len(tmpl) \
                else (None, b"")
            if typ == T_MATCH:
                if ttyp is None:
                    raise ValueError("tok3: MATCH without template")
                toks.append((ttyp, ttext))
            elif typ == T_ALPHA:
                toks.append((T_ALPHA, stream(t, T_ALPHA).cstr()))
            elif typ == T_CHAR:
                toks.append((T_CHAR,
                             bytes([stream(t, T_CHAR).byte()])))
            elif typ == T_DIGITS:
                v = stream(t, T_DIGITS).u32()
                toks.append((T_DIGITS, str(v).encode()))
            elif typ == T_DIGITS0:
                v = stream(t, T_DIGITS0).u32()
                z = stream(t, T_DZLEN).byte()
                s = str(v).encode().rjust(z, b"0")
                if len(s) != z:
                    raise ValueError("tok3: DIGITS0 width mismatch")
                toks.append((T_DIGITS0, s))
            elif typ == T_DDELTA:
                if ttyp not in (T_DIGITS, T_DIGITS0):
                    raise ValueError("tok3: DDELTA without digits")
                d = stream(t, T_DDELTA).byte()
                toks.append((T_DIGITS,
                             str(int(ttext) + d).encode()))
            elif typ == T_DDELTA0:
                if ttyp not in (T_DIGITS, T_DIGITS0):
                    raise ValueError("tok3: DDELTA0 without digits")
                d = stream(t, T_DDELTA0).byte()
                s = str(int(ttext) + d).encode().rjust(len(ttext),
                                                       b"0")
                if len(s) != len(ttext):
                    raise ValueError("tok3: DDELTA0 overflow")
                toks.append((T_DIGITS0, s))
            else:
                raise ValueError(f"tok3: unknown token type {typ}")
            t += 1
        names.append(b"".join(tx for _, tx in toks))
        toks_per_name.append(toks)

    out = sep.join(names) + sep if names else b""
    if len(out) != ulen:
        raise ValueError("tok3: output length mismatch")
    return out
