"""Object-store data plane end-to-end: the ``make dataplane-smoke``
body.

The same hermetic cohorts every other smoke builds, staged twice —
once on the local filesystem and once in a loopback
:mod:`~goleft_tpu.io.remote_stub` object store — and driven through
real subprocess tiers, proving ``https://`` inputs are a drop-in for
paths at every layer:

  1. **CLI byte-identity**: ``cohortdepth`` (plain, and with
     ``--prefetch-depth``/``--decode-device`` composing), ``depth``
     and ``indexcov`` produce byte-identical output over stub-remote
     URLs vs local paths.
  2. **fetch fault site**: an injected transient fault
     (``GOLEFT_TPU_FAULTS=fetch:...``) is retried to byte-identical
     output; a PERMANENT failure (404'd object) quarantines only the
     affected sample — the cohort completes degraded with the
     standard exit-3 contract.
  3. **staleness**: the object flipping contents mid-run (new ETag)
     is detected as a stale input and quarantined — never silently
     mixed into the matrix.
  4. **serve parity**: a real serve worker returns byte-identical
     ``matrix_tsv`` for local paths vs URLs (``decode_device``
     composing).
  5. **cache replication failover**: two real fleets with DISTINCT
     ``--shared-cache`` dirs behind a federation with
     ``--cache-sync-interval``; after one warm request the entry
     replicates to the idle fleet, the home fleet is SIGKILLed, and
     the survivor serves the SAME request byte-identically from the
     replicated entry with ``serve_device_passes_total == 0`` —
     failover is cache replay, not recompute.

Run directly::

    python -m goleft_tpu.io.dataplane_smoke
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request


def _run(args, env, timeout_s=240.0, expect_rc=0):
    rc = subprocess.run(
        [sys.executable, "-m", "goleft_tpu", *args], env=env,
        timeout=timeout_s, capture_output=True, text=True)
    if expect_rc is not None and rc.returncode != expect_rc:
        raise RuntimeError(
            f"goleft-tpu {' '.join(args[:1])} exited "
            f"{rc.returncode}, want {expect_rc}:\n{rc.stderr}")
    return rc


def _stage(srv, paths: list[str], prefix: str = "") -> list[str]:
    """Upload files into the stub store under their basenames
    (optionally namespaced by ``prefix/``); returns the URLs in the
    same order."""
    urls = []
    for p in paths:
        name = (prefix + "/" if prefix else "") + os.path.basename(p)
        with open(p, "rb") as fh:
            urls.append(srv.put(name, fh.read()))
    return urls


def _leg_cli_identity(d, crams, fai, cram_urls, fai_url, env,
                      verbose):
    base = ["cohortdepth", "--fai", fai, "-w", "500", *crams]
    local = _run(base, env).stdout
    rem = ["cohortdepth", "--fai", fai_url, "-w", "500", *cram_urls]
    if _run(rem, env).stdout != local:
        raise RuntimeError("cohortdepth over URLs != local paths")
    composed = ["cohortdepth", "--fai", fai_url, "-w", "500",
                "--prefetch-depth", "2", "--decode-device",
                *cram_urls]
    if _run(composed, env).stdout != local:
        raise RuntimeError("cohortdepth --prefetch-depth "
                           "--decode-device over URLs != local")
    if verbose:
        rows = local.count("\n") - 1
        print("dataplane-smoke: cohortdepth byte-identical over "
              f"URLs, prefetch+device composing ({rows} windows)")
    return local


def _leg_cli_depth_indexcov(d, bams, fai2, bed, bam_urls, fai2_url,
                            env, verbose):
    pl = os.path.join(d, "dl")
    pr = os.path.join(d, "dr")
    _run(["depth", "--prefix", pl, "-b", bed, "-w", "100", bams[0]],
         env)
    _run(["depth", "--prefix", pr, "-b", bed, "-w", "100",
          bam_urls[0]], env)
    for suffix in (".depth.bed", ".callable.bed"):
        with open(pl + suffix, "rb") as fl, \
                open(pr + suffix, "rb") as fr:
            if fl.read() != fr.read():
                raise RuntimeError(
                    f"depth {suffix} over a URL != local")
    outs = []
    for tag, inputs, f in (("L", bams, fai2),
                           ("R", bam_urls, fai2_url)):
        od = os.path.join(d, tag, "ix")
        os.makedirs(od)
        _run(["indexcov", "-d", od, "--fai", f, "--no-html",
              *inputs], env)
        outs.append(od)
    files = sorted(os.listdir(outs[0]))
    if files != sorted(os.listdir(outs[1])) or not files:
        raise RuntimeError("indexcov output sets differ")
    for name in files:
        with open(os.path.join(outs[0], name), "rb") as fl, \
                open(os.path.join(outs[1], name), "rb") as fr:
            if fl.read() != fr.read():
                raise RuntimeError(
                    f"indexcov {name} over URLs != local")
    if verbose:
        print("dataplane-smoke: depth + indexcov byte-identical "
              f"over URLs ({len(files)} indexcov artifacts)")


def _leg_fetch_faults(srv, crams, local_out, cram_urls, fai_url,
                      env, verbose):
    # transient: one injected failure at the fetch site is retried
    # through the same RetryPolicy every dispatch boundary uses
    fenv = dict(env, GOLEFT_TPU_FAULTS="fetch:after=2:transient")
    rem = ["cohortdepth", "--fai", fai_url, "-w", "500", *cram_urls]
    if _run(rem, fenv).stdout != local_out:
        raise RuntimeError(
            "transient fetch fault not retried to identical bytes")
    # permanent: one object 404s — ONLY that sample quarantines, the
    # cohort completes degraded under the standard exit-3 contract
    victim = os.path.basename(crams[0])
    srv.store.delete(victim)
    try:
        rc = _run(rem, env, expect_rc=3)
    finally:
        with open(crams[0], "rb") as fh:
            srv.store.put(victim, fh.read())
    if "quarantined" not in rc.stderr:
        raise RuntimeError(
            f"exit-3 run carried no quarantine summary: {rc.stderr}")
    if not rc.stdout.startswith("#chrom"):
        raise RuntimeError("degraded cohort wrote no partial matrix")
    for other in crams[1:]:
        sample = os.path.basename(other)[:-5]  # crN.cram -> crN
        if sample not in rc.stdout.splitlines()[0]:
            raise RuntimeError(
                f"healthy sample {sample} missing from the degraded "
                "matrix header")
    if verbose:
        print("dataplane-smoke: transient fetch fault retried to "
              "identical bytes; 404'd object quarantined only its "
              "own sample (exit 3)")


def _leg_stale_detection(srv, crams, cram_urls, fai_url, env,
                         verbose):
    victim = os.path.basename(crams[0])
    with open(crams[0], "rb") as fh:
        original = fh.read()
    # the next request pins the identity (HEAD); the flip lands
    # before the first ranged GET, so the pinned ETag can never match
    # again (the threshold is RELATIVE — earlier legs already counted
    # requests against this name)
    seen = srv.store.request_counts.get(victim, 0)
    srv.store.flip_after(victim, seen + 2, original + b"\x00drifted")
    try:
        rc = _run(["cohortdepth", "--fai", fai_url, "-w", "500",
                   *cram_urls], env, expect_rc=3)
    finally:
        srv.store.put(victim, original)
    blob = (rc.stderr + rc.stdout).lower()
    if "stale" not in blob:
        raise RuntimeError(
            "mid-run ETag drift was not surfaced as a stale input:\n"
            + rc.stderr)
    if verbose:
        print("dataplane-smoke: mid-run ETag drift detected as "
              "stale-input and quarantined — never silently mixed")


def _leg_serve_parity(crams, fai, cram_urls, fai_url, env, local_out,
                      verbose):
    from ..fleet.federation_smoke import _kill, _post, _spawn

    proc = None
    try:
        proc, url = _spawn(["serve", "--port", "0", "--no-warmup"],
                           env)
        code, a = _post(url + "/v1/cohortdepth",
                        {"bams": crams, "fai": fai, "window": 500,
                         "decode_device": True})
        if code != 200:
            raise RuntimeError(f"serve local cohortdepth: {code} {a}")
        code, b = _post(url + "/v1/cohortdepth",
                        {"bams": cram_urls, "fai": fai_url,
                         "window": 500, "decode_device": True})
        if code != 200:
            raise RuntimeError(f"serve URL cohortdepth: {code} {b}")
        if a["matrix_tsv"] != b["matrix_tsv"] \
                or a["matrix_tsv"] != local_out:
            raise RuntimeError(
                "serve matrix over URLs != local paths / CLI bytes")
    finally:
        _kill(proc)
    if verbose:
        print("dataplane-smoke: serve worker byte-identical over "
              "URLs (decode_device composing, == CLI bytes)")


def _prom_counter(prom: str, name: str) -> float:
    for line in prom.splitlines():
        if line.startswith(name + " "):
            return float(line.split()[1])
    return 0.0


def _leg_federation_cache_failover(d, cram_urls, fai_url, env,
                                   local_out, verbose):
    from ..fleet.federation_smoke import (
        _get_json, _kill, _post, _spawn, _wait_until,
    )

    fleets: dict[str, dict] = {}
    fed = None
    try:
        for i in range(2):
            cache_dir = os.path.join(d, f"cache{i}")  # DISTINCT dirs
            proc, url = _spawn(
                ["fleet", "--port", "0", "--workers", "1",
                 "--poll-interval-s", "0.3", "--down-after", "1",
                 "--supervise-interval-s", "0.1",
                 "--shared-cache", cache_dir,
                 "--worker-args=--no-warmup"], env)
            url = url.rstrip("/")
            slots = _get_json(url + "/metrics")["supervisor"]["slots"]
            fleets[url] = {"proc": proc, "cache_dir": cache_dir,
                           "worker_url": slots[0]["url"],
                           "worker_pid": slots[0]["pid"]}
        fed, fed_url = _spawn(
            ["federation", "--port", "0",
             *[a for u in fleets for a in ("--fleet", u)],
             "--poll-interval-s", "0.3", "--down-after", "1",
             "--cache-sync-interval", "0.5"], env)

        def fleets_up():
            try:
                return _get_json(
                    fed_url + "/healthz")["fleets_up"] == 2
            except Exception:  # noqa: BLE001 — 503 while settling
                return False

        _wait_until(fleets_up, 120.0, "both fleets up")
        req = {"bams": cram_urls, "fai": fai_url, "window": 500,
               "tenant": "alice"}
        home_url = _post(fed_url + "/fleet/plan",
                         {"kind": "cohortdepth",
                          **req})[1]["candidates"][0].rstrip("/")
        survivor_url = next(u for u in fleets if u != home_url)
        code, warm = _post(fed_url + "/v1/cohortdepth", req,
                           timeout_s=300.0)
        if code != 200 or warm["matrix_tsv"] != local_out:
            raise RuntimeError(
                f"warm federation request not byte-identical ({code})")

        def replicated():
            try:
                body = _get_json(survivor_url + "/fleet/cache/")
                return len(body["entries"]) >= 1
            except Exception:  # noqa: BLE001 — not yet
                return False

        _wait_until(replicated, 60.0,
                    "cachesync to replicate onto the idle fleet")
        fleets[home_url]["proc"].kill()
        fleets[home_url]["proc"].wait(timeout=30)

        def home_down():
            try:
                return _get_json(
                    fed_url + "/healthz")["fleets_up"] == 1
            except Exception:  # noqa: BLE001 — poll raced the kill
                return False

        _wait_until(home_down, 60.0, "federation to mark the home "
                                     "fleet down")
        code, cold = _post(fed_url + "/v1/cohortdepth", req,
                           timeout_s=300.0)
        if code != 200 or cold["matrix_tsv"] != local_out:
            raise RuntimeError(
                "survivor's failover response not byte-identical "
                f"({code})")
        if not cold.get("cached"):
            raise RuntimeError(
                "failover response was not a replicated-cache hit")
        wreq = urllib.request.Request(
            fleets[survivor_url]["worker_url"]
            + "/metrics?format=prom",
            headers={"Accept": "text/plain"})
        with urllib.request.urlopen(wreq, timeout=30) as r:
            prom = r.read().decode()
        passes = _prom_counter(prom, "serve_device_passes_total")
        if passes != 0:
            raise RuntimeError(
                f"survivor recomputed on the device "
                f"(serve_device_passes_total={passes:g}) despite the "
                "replicated cache")
        fedm = _get_json(fed_url + "/metrics")["counters"]
        if fedm.get("cachesync.entries_replicated_total", 0) < 1:
            raise RuntimeError("cachesync counters never moved")
        if verbose:
            print("dataplane-smoke: home fleet SIGKILLed — survivor "
                  "served byte-identically from the REPLICATED cache "
                  "(0 device passes, "
                  f"{fedm['cachesync.entries_replicated_total']:g} "
                  "entries replicated)")
    finally:
        _kill(fed)
        for rec in fleets.values():
            _kill(rec["proc"])
        for rec in fleets.values():
            # the SIGKILLed fleet's worker is orphaned — reap by pid
            try:
                os.kill(rec["worker_pid"], signal.SIGKILL)
            except (OSError, ProcessLookupError):
                pass


def run_smoke(timeout_s: float = 900.0, verbose: bool = True) -> int:
    """Returns 0 on success; raises on any failed leg."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",     # CI has no accelerator
               GOLEFT_TPU_PROBE="0",    # don't pay a probe timeout
               # cache replication is authenticated (pushes carry an
               # HMAC keyed by the shared fleet secret); the fleets
               # and the federation all inherit this env
               GOLEFT_TPU_FLEET_SECRET="dataplane-smoke")
    env.pop("GOLEFT_TPU_FAULTS", None)  # hermetic (leg 2 adds it)
    from ..ops.decode_smoke import make_cram_cohort
    from ..resilience.smoke import _make_cohort
    from .remote_stub import StubServer

    t0 = time.monotonic()
    with tempfile.TemporaryDirectory(prefix="goleft_dp_") as d, \
            StubServer() as srv:
        dc = os.path.join(d, "cramset")
        db = os.path.join(d, "bamset")
        os.makedirs(dc)
        os.makedirs(db)
        crams, fai = make_cram_cohort(dc)
        cram_urls = _stage(srv, crams)
        for c in crams:
            _stage(srv, [c + ".crai"])
        fai_url = _stage(srv, [fai])[0]
        bams, fai2, bed = _make_cohort(db, ref_len=20_000)
        bam_urls = _stage(srv, bams, prefix="bamset")
        for b in bams:
            _stage(srv, [b + ".bai"], prefix="bamset")
        fai2_url = _stage(srv, [fai2], prefix="bamset")[0]

        local_out = _leg_cli_identity(d, crams, fai, cram_urls,
                                      fai_url, env, verbose)
        _leg_cli_depth_indexcov(d, bams, fai2, bed, bam_urls,
                                fai2_url, env, verbose)
        _leg_fetch_faults(srv, crams, local_out, cram_urls, fai_url,
                          env, verbose)
        _leg_stale_detection(srv, crams, cram_urls, fai_url, env,
                             verbose)
        _leg_serve_parity(crams, fai, cram_urls, fai_url, env,
                          local_out, verbose)
        _leg_federation_cache_failover(d, cram_urls, fai_url, env,
                                       local_out, verbose)
        if time.monotonic() - t0 > timeout_s:
            raise RuntimeError(
                f"dataplane-smoke exceeded its {timeout_s:g}s budget")
    if verbose:
        print(f"dataplane-smoke: PASS ({time.monotonic() - t0:.1f}s)")
    return 0


if __name__ == "__main__":
    sys.exit(run_smoke())
