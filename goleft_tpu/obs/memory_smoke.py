"""End-to-end memory-plane leak sentinel: ``make memory-smoke``.

Three legs, because the memory plane's promises span three layers:

  1. **no leak under steady work**: an in-process sampler watches >= 3
     sampling windows while allocate/free rounds churn — RSS stays
     bounded (the allocator gives mmap'd blocks back), and a device
     buffer attributed to a family via the ``observe()`` seam returns
     that family's live bytes to 0 once the buffer dies.
  2. **pressure sheds and recovers**: a real serve daemon with an
     armed band takes a deliberate numpy hog, trips to ``pressure``,
     503s a POST admission with ``retry_after_s``, then recovers below
     the low water mark when the hog is freed and admits again — the
     two-sided hysteresis, observed through real HTTP.
  3. **the supervisor recycles a runaway**: a subprocess
     ``goleft-tpu fleet`` with ``--mem-recycle-mb`` far below the
     worker's baseline drains and recycles it, and the
     ``memory_recycle`` event is visible through the real
     ``goleft-tpu fleet events --json`` CLI (journal replay).

Run directly::

    python -m goleft_tpu.obs.memory_smoke
"""

from __future__ import annotations

import gc
import json
import os
import signal
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

import numpy as np

HOG_BYTES = 256 * 1024 * 1024
ROUND_BYTES = 32 * 1024 * 1024
RSS_SLACK_BYTES = 96 * 1024 * 1024


def _wait_until(pred, timeout_s: float, what: str,
                interval_s: float = 0.1):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(interval_s)
    raise RuntimeError(f"timed out waiting for {what}")


def _get_json(url: str, timeout_s: float = 30.0) -> dict:
    req = urllib.request.Request(
        url, headers={"Accept": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout_s) as r:
        return json.loads(r.read().decode())


def _post_json(url: str, body: dict,
               timeout_s: float = 30.0) -> tuple[int, dict]:
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode() or "{}")


def _leg_bounded_and_device_baseline(verbose):
    """Leg 1: RSS bounded across allocate/free rounds over >= 3
    sampling windows; a family's device bytes return to 0 when its
    buffer dies."""
    from .metrics import MetricsRegistry
    from .memplane import MemorySampler, get_tracker, quick_rss

    reg = MetricsRegistry()
    sampler = MemorySampler(interval_s=0.05, registry=reg).start()
    try:
        # warm the allocator once so the baseline includes the pool
        np.ones(ROUND_BYTES // 8).sum()
        baseline = quick_rss()
        for _ in range(5):
            block = np.ones(ROUND_BYTES // 8)
            block.sum()
            del block
        _wait_until(
            lambda: reg.counter("memory.samples_total").value >= 3,
            30.0, ">= 3 memory sampling windows")
        rss = quick_rss()
        if rss > baseline + RSS_SLACK_BYTES:
            raise RuntimeError(
                f"RSS leaked across allocate/free rounds: "
                f"{baseline} -> {rss} bytes")
        snap = sampler.snapshot()
        if snap["gauges"]["memory.rss_bytes"] <= 0:
            raise RuntimeError(f"host sampling returned no RSS: "
                               f"{snap['host']}")

        # device attribution round-trip through the observe() seam
        import jax

        tracker = get_tracker()
        payload = np.arange(512 * 1024, dtype=np.float32)  # 2MB
        with tracker.observe("memsmoke"):
            buf = jax.device_put(payload)
            buf.block_until_ready()
        doc = tracker.device_doc()
        got = doc["by_family"].get("memsmoke", 0)
        if got < payload.nbytes:
            raise RuntimeError(
                f"device attribution missed the smoke buffer: "
                f"memsmoke={got} < {payload.nbytes} "
                f"(families: {doc['by_family']})")
        del buf
        gc.collect()
        after = tracker.device_doc()["by_family"].get("memsmoke", 0)
        if after != 0:
            raise RuntimeError(
                f"device family bytes did not return to baseline "
                f"after the buffer died: memsmoke={after}")
        if verbose:
            print("memory-smoke: RSS bounded over "
                  f"{reg.counter('memory.samples_total').value} "
                  f"windows (+{rss - baseline} bytes); device family "
                  f"attribution {got} bytes -> 0 at baseline")
    finally:
        sampler.close()


def _leg_pressure_shed_and_recover(verbose):
    """Leg 2: a deliberate hog trips the band, POST admissions shed
    503 + retry_after_s, freeing the hog recovers admission."""
    from ..serve.server import ServeApp, ServerThread
    from .memplane import quick_rss

    app = ServeApp(batch_window_s=0.0, max_batch=1,
                   mem_sample_interval_s=0.02)
    with ServerThread(app) as url:
        _get_json(url + "/debug/memory")  # settle the daemon
        rss0 = quick_rss()
        # arm the band relative to the settled process: the hog is
        # 2x the headroom, so the trip and the recovery are both
        # deterministic
        ctl = app.memplane.pressure
        ctl.low_water_bytes = rss0 + HOG_BYTES // 4
        ctl.high_water_bytes = rss0 + HOG_BYTES // 2

        hog = np.ones(HOG_BYTES // 8)  # touched -> resident
        try:
            _wait_until(
                lambda: _get_json(url + "/debug/memory")
                ["pressure"]["state"] == "pressure",
                30.0, "the pressure band to trip")
            code, body = _post_json(url + "/v1/depth", {})
            if code != 503 or "retry_after_s" not in body:
                raise RuntimeError(
                    f"hogged worker admitted a POST: {code} {body}")
        finally:
            del hog
        gc.collect()
        _wait_until(
            lambda: _get_json(url + "/debug/memory")
            ["pressure"]["state"] == "ok",
            60.0, "RSS to recover below the low water mark")
        code, body = _post_json(url + "/v1/depth", {})
        if code == 503:
            raise RuntimeError(
                f"recovered worker still shedding: {code} {body}")
        snap = _get_json(url + "/debug/memory")
        sheds = snap["counters"]["memory.sheds_total"]
        if sheds < 1:
            raise RuntimeError(
                f"memory.sheds_total never incremented: {sheds}")
        if verbose:
            print("memory-smoke: pressure tripped -> 503 with "
                  f"retry_after_s, recovered -> {code} "
                  f"(sheds={sheds})")


def _leg_supervisor_recycle(verbose):
    """Leg 3: a fleet with --mem-recycle-mb below the worker's
    baseline recycles it; the memory_recycle event survives into the
    journal and the real events CLI."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", GOLEFT_TPU_PROBE="0")
    env.pop("GOLEFT_TPU_FAULTS", None)
    cap_mb = 64.0  # far below any live worker's baseline
    with tempfile.TemporaryDirectory(prefix="goleft_memsmk_") as d:
        journal = os.path.join(d, "events.jsonl")
        router = subprocess.Popen(
            [sys.executable, "-m", "goleft_tpu", "fleet",
             "--port", "0", "--workers", "1",
             "--poll-interval-s", "0.3", "--down-after", "1",
             "--supervise-interval-s", "0.2",
             "--hang-timeout-s", "10", "--restart-limit", "8",
             "--mem-recycle-mb", str(cap_mb),
             "--events-journal", journal,
             "--worker-args=--no-warmup"],
            stdout=subprocess.PIPE, text=True, env=env)
        try:
            line = router.stdout.readline()
            if "listening on " not in line:
                raise RuntimeError(f"router never announced: {line!r}")
            url = line.rsplit("listening on ", 1)[1].strip()

            def _recycled() -> bool:
                try:
                    m = _get_json(url + "/metrics")
                except Exception:  # noqa: BLE001 — mid-drain 503s
                    return False
                return m["counters"].get(
                    "memory.recycles_total", 0) >= 1
            _wait_until(_recycled, 180.0,
                        "the supervisor to recycle the worker")

            cp = subprocess.run(
                [sys.executable, "-m", "goleft_tpu", "fleet",
                 "events", "--journal", journal,
                 "--type", "memory_recycle", "--json"],
                capture_output=True, text=True, timeout=120)
            if cp.returncode != 0:
                raise RuntimeError(
                    f"fleet events failed rc={cp.returncode}: "
                    f"{cp.stderr[-500:]}")
            doc = json.loads(cp.stdout)
            evs = [e for e in doc.get("events") or []
                   if e.get("type") == "memory_recycle"]
            if not evs:
                raise RuntimeError(
                    f"no memory_recycle event in the journal: {doc}")
            ev = evs[0]
            if ev.get("rss_bytes", 0) <= ev.get("cap_bytes", 1 << 62):
                raise RuntimeError(
                    f"recycle event does not show rss over cap: {ev}")
            if verbose:
                print("memory-smoke: supervisor recycled worker at "
                      f"rss={ev['rss_bytes']} > cap={ev['cap_bytes']} "
                      f"({len(evs)} event(s) via fleet events --json)")
        finally:
            if router.poll() is None:
                router.send_signal(signal.SIGTERM)
                try:
                    router.wait(timeout=60)
                except subprocess.TimeoutExpired:
                    router.kill()
                    router.wait(timeout=10)
            if router.stdout is not None:
                router.stdout.close()


def run_smoke(timeout_s: float = 600.0, verbose: bool = True) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    t0 = time.monotonic()
    _leg_bounded_and_device_baseline(verbose)
    _leg_pressure_shed_and_recover(verbose)
    _leg_supervisor_recycle(verbose)
    if time.monotonic() - t0 > timeout_s:
        raise RuntimeError(
            f"memory-smoke exceeded its {timeout_s:g}s budget")
    if verbose:
        print(f"memory-smoke: PASS ({time.monotonic() - t0:.1f}s)")
    return 0


if __name__ == "__main__":
    sys.exit(run_smoke())
