"""End-to-end fleet observability smoke: the ``make fleet-obs-smoke`` body.

Real subprocess daemons all the way down — one ``goleft-tpu fleet``
router process SUPERVISING two real serve workers (three OS
processes), because the whole point of the fleet plane is evidence
that crosses process boundaries:

  1. **one request, one stitched trace**: a depth request through the
     router with a client-minted ``x-goleft-trace`` id yields ONE
     stitched tree from ``GET /fleet/trace/<id>`` containing spans
     from >= 2 processes — the router's ``fleet.request``/
     ``fleet.forward`` spans parenting the worker's ``request.depth``
     → ``plan.step.depth`` → ``batch.depth`` →
     ``serve.depth.dispatch`` chain — and the Perfetto export carries
     distinct process tracks. The ``goleft-tpu trace`` CLI renders the
     same tree (subprocess, proving registration).
  2. **fleet counters are worker sums**: after a burst of requests,
     ``/fleet/metrics`` counters equal the arithmetic sum of the live
     workers' own ``/metrics`` counters, in JSON and in the
     Prometheus encoding.
  3. **lifecycle events are durable and queryable**: a worker
     SIGKILLed mid-fleet produces death → backoff → restart events
     visible in ``goleft-tpu fleet events --json`` (the fsync'd
     events.jsonl) and in the router ``/metrics`` ``fleet.events``
     block, while the fleet heals itself.

Run directly::

    python -m goleft_tpu.obs.fleet_smoke
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request


def _wait_until(pred, timeout_s: float, what: str,
                interval_s: float = 0.1):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(interval_s)
    raise RuntimeError(f"timed out waiting for {what}")


def _get_json(url: str, timeout_s: float = 30.0) -> dict:
    req = urllib.request.Request(
        url, headers={"Accept": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout_s) as r:
        return json.loads(r.read().decode())


def _walk(node):
    yield node
    for c in node.get("children", ()):
        yield from _walk(c)


def _leg_stitched_trace(router_url, bam, fai, d, verbose):
    from ..serve.client import ServeClient

    client = ServeClient(router_url, timeout_s=120.0, retries=2,
                         retry_cap_s=2.0, trace=True)
    r = client.depth(bam, fai=fai, window=200)
    if not r.get("depth_bed"):
        raise RuntimeError("routed depth request returned no bed")
    tid = client.last_trace_id
    if not tid:
        raise RuntimeError("client minted no trace id")
    doc = client.fleet_trace(tid)
    if doc["trace_id"] != tid:
        raise RuntimeError("stitched trace id mismatch")
    if len(doc["processes"]) < 2:
        raise RuntimeError(
            f"stitched trace spans {len(doc['processes'])} "
            f"process(es), want >= 2: {sorted(doc['processes'])}")
    names = {n["name"]: n.get("process") for n in _walk(doc["tree"])}
    for want in ("fleet.request.depth", "fleet.forward.depth",
                 "request.depth", "plan.step.depth", "batch.depth",
                 "serve.depth.dispatch"):
        if want not in names:
            raise RuntimeError(
                f"stitched trace is missing the {want!r} span "
                f"(has: {sorted(names)})")
    if not any(str(p).startswith("worker:")
               for p in names.values()):
        raise RuntimeError("no span attributed to a worker process")
    # graft shape: the worker request tree sits UNDER the router's
    # forward span, and the device dispatch under the batch tree
    tree = doc["tree"]
    fwd = next(n for n in _walk(tree)
               if n["name"] == "fleet.forward.depth")
    if not any(c["name"] == "request.depth"
               for c in fwd["children"]):
        raise RuntimeError(
            "worker request tree not grafted under the router "
            "forward span")
    # Perfetto export: distinct process tracks, loadable shape
    perf = doc["perfetto"]
    procs = [e["args"]["name"] for e in perf["traceEvents"]
             if e.get("ph") == "M"
             and e.get("name") == "process_name"]
    if len(procs) < 2:
        raise RuntimeError(
            f"Perfetto export has {len(procs)} process track(s), "
            "want >= 2")
    if not any(e.get("ph") == "X" for e in perf["traceEvents"]):
        raise RuntimeError("Perfetto export has no complete events")
    # the CLI renders the same tree (subprocess: registration proof)
    out = os.path.join(d, "trace.perfetto.json")
    cp = subprocess.run(
        [sys.executable, "-m", "goleft_tpu", "trace", tid,
         "--router", router_url, "--perfetto", out],
        capture_output=True, text=True, timeout=120)
    if cp.returncode != 0:
        raise RuntimeError(
            f"goleft-tpu trace failed rc={cp.returncode}: "
            f"{cp.stderr[-500:]}")
    if "serve.depth.dispatch" not in cp.stdout \
            or "fleet.forward.depth" not in cp.stdout:
        raise RuntimeError("goleft-tpu trace output missing spans")
    with open(out) as fh:
        if not json.load(fh).get("traceEvents"):
            raise RuntimeError("--perfetto wrote an empty export")
    if verbose:
        print("fleet-obs-smoke: one request -> ONE stitched trace "
              f"across {len(doc['processes'])} processes (router "
              "forward -> worker request -> plan step -> device "
              "dispatch), Perfetto tracks distinct, CLI renders it")
    return tid


def _leg_counter_rollup(router_url, bams, fai, poll_s, verbose):
    from ..serve.client import ServeClient

    client = ServeClient(router_url, timeout_s=120.0, retries=2,
                         retry_cap_s=2.0)
    for i, bam in enumerate(bams):
        client.depth(bam, fai=fai, window=190 + i)
    worker_urls = sorted(_get_json(router_url + "/metrics")
                         ["workers"])
    if len(worker_urls) < 2:
        raise RuntimeError(f"fleet has {len(worker_urls)} worker(s)")
    # let every worker's NEXT jittered scrape land
    time.sleep(2 * poll_s + 0.5)

    def sums_match():
        fleet = _get_json(router_url + "/fleet/metrics")
        per = [_get_json(u + "/metrics") for u in worker_urls]
        want = sum(p["counters"].get("requests_total.depth", 0)
                   for p in per)
        got = fleet["counters"].get("requests_total.depth", 0)
        return want > 0 and got == want, want, got

    _wait_until(lambda: sums_match()[0], 30.0,
                "fleet counters to equal the worker sum")
    _ok, want, _got = sums_match()
    # same number through the Prometheus encoding
    req = urllib.request.Request(
        router_url + "/fleet/metrics?format=prom",
        headers={"Accept": "text/plain"})
    with urllib.request.urlopen(req, timeout=30) as r:
        prom = r.read().decode()
    needle = f"fleet_worker_requests_total_depth {want}"
    if needle not in prom:
        raise RuntimeError(
            f"prometheus rollup missing {needle!r}")
    if "fleet_slo_burn_rate" not in prom:
        raise RuntimeError("prometheus rollup missing burn gauges")
    if verbose:
        print("fleet-obs-smoke: /fleet/metrics counters == "
              f"sum over {len(worker_urls)} live workers "
              f"(requests_total.depth = {want}), both encodings")


def _leg_events_journal(router_url, journal, verbose):
    snap = _get_json(router_url + "/metrics")
    slots = snap["supervisor"]["slots"]
    victim = next(s for s in slots if s["state"] == "healthy")
    os.kill(victim["pid"], signal.SIGKILL)

    def restarted():
        m = _get_json(router_url + "/metrics")
        return m["counters"].get("fleet.restarts_total", 0) >= 1 \
            and m["supervisor"]["capacity"] >= 2
    _wait_until(restarted, 180.0, "supervisor to heal the SIGKILL")
    # the events CLI replays the fsync'd journal (subprocess)
    cp = subprocess.run(
        [sys.executable, "-m", "goleft_tpu", "fleet", "events",
         "--journal", journal, "--json"],
        capture_output=True, text=True, timeout=60)
    if cp.returncode != 0:
        raise RuntimeError(
            f"fleet events failed rc={cp.returncode}: "
            f"{cp.stderr[-500:]}")
    doc = json.loads(cp.stdout)
    if doc["schema"] != "goleft-tpu.fleet-events/1":
        raise RuntimeError("fleet events --json schema drifted")
    types = [e["type"] for e in doc["events"]]
    for want in ("spawn", "death", "backoff", "restart"):
        if want not in types:
            raise RuntimeError(
                f"events journal missing {want!r} (has {types})")
    if not types.index("death") < types.index("restart"):
        raise RuntimeError("event order broken (death !< restart)")
    death = next(e for e in doc["events"] if e["type"] == "death")
    if death.get("slot") != victim["index"] \
            or death.get("pid") != victim["pid"]:
        raise RuntimeError("death event lost slot/pid identity")
    # and the same story in the router /metrics fleet.events block
    m = _get_json(router_url + "/metrics")
    block = m.get("fleet.events") or {}
    recent = [e["type"] for e in block.get("recent", [])]
    if "restart" not in recent:
        raise RuntimeError(
            f"/metrics fleet.events block missing restart: {recent}")
    if m["counters"].get("fleet.events_total.death", 0) < 1:
        raise RuntimeError("fleet.events_total.death not counted")
    if verbose:
        print("fleet-obs-smoke: SIGKILLed worker -> death/backoff/"
              "restart replayable from events.jsonl (fleet events "
              "--json schema-stable) and visible in /metrics")


def run_smoke(timeout_s: float = 600.0, verbose: bool = True) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",     # CI has no accelerator
               GOLEFT_TPU_PROBE="0")    # don't pay a probe timeout
    env.pop("GOLEFT_TPU_FAULTS", None)  # hermetic
    from ..resilience.smoke import _make_cohort

    t0 = time.monotonic()
    poll_s = 0.3
    with tempfile.TemporaryDirectory(prefix="goleft_fobs_") as d:
        bams, fai, _bed = _make_cohort(d, ref_len=20_000)
        journal = os.path.join(d, "events.jsonl")
        router = subprocess.Popen(
            [sys.executable, "-m", "goleft_tpu", "fleet",
             "--port", "0", "--workers", "2",
             "--events-journal", journal,
             "--poll-interval-s", str(poll_s),
             "--down-after", "1",
             "--supervise-interval-s", "0.1",
             "--hang-timeout-s", "2", "--restart-limit", "8",
             "--worker-args=--no-warmup"],
            stdout=subprocess.PIPE, text=True, env=env)
        try:
            line = router.stdout.readline()
            if "listening on " not in line:
                raise RuntimeError(
                    f"router never announced: {line!r}")
            url = line.rsplit("listening on ", 1)[1].strip()

            def _healthy() -> int:
                try:
                    return _get_json(url + "/healthz").get(
                        "healthy", 0)
                except Exception:  # noqa: BLE001 — 503 while degraded
                    return -1

            _wait_until(lambda: _healthy() == 2, 120.0,
                        "both workers healthy")
            _leg_stitched_trace(url, bams[0], fai, d, verbose)
            _leg_counter_rollup(url, bams, fai, poll_s, verbose)
            _leg_events_journal(url, journal, verbose)
        finally:
            if router.poll() is None:
                router.send_signal(signal.SIGTERM)
                try:
                    router.wait(timeout=60)
                except subprocess.TimeoutExpired:
                    router.kill()
                    router.wait(timeout=10)
            if router.stdout is not None:
                router.stdout.close()
        if time.monotonic() - t0 > timeout_s:
            raise RuntimeError(
                f"fleet-obs-smoke exceeded its {timeout_s:g}s budget")
    if verbose:
        print(f"fleet-obs-smoke: PASS "
              f"({time.monotonic() - t0:.1f}s)")
    return 0


if __name__ == "__main__":
    sys.exit(run_smoke())
