"""Observability smoke: the ``make obs-smoke`` body.

Runs a REAL ``goleft-tpu depth`` subprocess with ``--trace-out`` and
``--metrics-out`` on a fabricated fixture, then validates both
artifacts: the trace must be Chrome-trace-event JSON (the exact schema
Perfetto loads — ph/ts/dur/pid/tid on every span event) containing the
run's root and stage spans, and the manifest must parse with every
required provenance key (obs/manifest.py::REQUIRED_KEYS) and a backend
block naming a platform. Run directly::

    python -m goleft_tpu.obs.smoke
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile


def _make_fixture(d: str, n_reads: int = 400,
                  ref_len: int = 20_000) -> tuple[str, str]:
    """(bam, fai): a tiny coordinate-sorted BAM + matching .fai
    (the serve smoke's hermetic-fixture approach)."""
    import numpy as np

    from ..io.bai import build_bai, write_bai
    from ..io.bam import BamWriter

    rng = np.random.default_rng(11)
    starts = np.sort(rng.integers(0, ref_len - 100, size=n_reads))
    bam = os.path.join(d, "obs.bam")
    with open(bam, "wb") as fh:
        with BamWriter(
            fh, "@HD\tVN:1.6\tSO:coordinate\n@SQ\tSN:chr1\tLN:"
            f"{ref_len}\n@RG\tID:r\tSM:obs\n", ["chr1"], [ref_len],
            level=1,
        ) as w:
            for i, s in enumerate(starts):
                w.write_record(0, int(s), [(100, 0)], mapq=60,
                               name=f"r{i}")
    write_bai(build_bai(bam), bam + ".bai")
    fai = os.path.join(d, "ref.fa.fai")
    with open(fai, "w") as fh:
        fh.write(f"chr1\t{ref_len}\t6\t60\t61\n")
    return bam, fai


def validate_trace(path: str) -> dict:
    """Parse + schema-check a ``--trace-out`` artifact; returns the
    document. Raises on anything Perfetto would choke on."""
    with open(path) as fh:
        doc = json.load(fh)
    events = doc.get("traceEvents")
    if not isinstance(events, list) or not events:
        raise ValueError(f"{path}: no traceEvents")
    span_events = [e for e in events if e.get("ph") == "X"]
    if not span_events:
        raise ValueError(f"{path}: no complete ('X') span events")
    for e in span_events:
        missing = {"name", "ph", "ts", "dur", "pid", "tid"} - set(e)
        if missing:
            raise ValueError(
                f"{path}: span event missing {sorted(missing)}: {e}")
        if not (isinstance(e["ts"], (int, float))
                and isinstance(e["dur"], (int, float))
                and e["dur"] >= 0):
            raise ValueError(f"{path}: bad ts/dur in {e}")
    return doc


def run_smoke(timeout_s: float = 180.0, verbose: bool = True) -> int:
    """Returns 0 on success; raises on any failed step."""
    from .manifest import load_manifest

    env = dict(os.environ,
               JAX_PLATFORMS="cpu",     # CI has no accelerator;
               GOLEFT_TPU_PROBE="0")    # don't pay a probe timeout
    with tempfile.TemporaryDirectory(prefix="goleft_obs_") as d:
        bam, fai = _make_fixture(d)
        trace_p = os.path.join(d, "trace.json")
        manifest_p = os.path.join(d, "run.json")
        cmd = [sys.executable, "-m", "goleft_tpu", "depth",
               "--trace-out", trace_p, "--metrics-out", manifest_p,
               "--prefix", os.path.join(d, "out"), "-r",
               os.path.join(d, "ref.fa"), bam]
        rc = subprocess.run(cmd, env=env, timeout=timeout_s,
                            capture_output=True, text=True)
        if rc.returncode != 0:
            raise RuntimeError(
                f"depth run failed ({rc.returncode}):\n{rc.stderr}")
        if not os.path.exists(os.path.join(d, "out.depth.bed")):
            raise RuntimeError("depth produced no output bed")

        doc = validate_trace(trace_p)
        names = {e["name"] for e in doc["traceEvents"]
                 if e.get("ph") == "X"}
        for want in ("run.depth", "host-decode", "device-compute"):
            if want not in names:
                raise RuntimeError(
                    f"trace is missing the {want!r} span "
                    f"(has: {sorted(names)[:12]}...)")
        if verbose:
            n = sum(1 for e in doc["traceEvents"]
                    if e.get("ph") == "X")
            print(f"obs-smoke: trace ok ({n} spans, "
                  f"{len(names)} distinct)")

        man = load_manifest(manifest_p)
        backend = man["backend"]
        if "error" not in backend:
            for key in ("platform", "device_kind", "device_count"):
                if key not in backend:
                    raise RuntimeError(
                        f"manifest backend block missing {key!r}")
        if not man["spans"]:
            raise RuntimeError("manifest has no span summary")
        if "host-decode" not in man["spans"]:
            raise RuntimeError(
                "manifest span summary is missing the pipeline "
                f"stages (has {sorted(man['spans'])[:12]})")
        if verbose:
            print(f"obs-smoke: manifest ok (platform="
                  f"{backend.get('platform', 'n/a')}, "
                  f"{len(man['spans'])} span names, "
                  f"{len(man['metrics']['counters'])} counters)")
            print("obs-smoke: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(run_smoke())
