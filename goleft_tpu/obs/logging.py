"""Consistent ``goleft-tpu.*`` logger naming + one CLI-level config.

Every module logs under ``goleft-tpu.<area>`` via :func:`get_logger`
(the dotted hierarchy hangs off one root, so the CLI's ``--log-level``
/ ``-v`` flag configures the whole tree at once and third-party
loggers — jax's included — stay untouched).
"""

from __future__ import annotations

import logging
import sys

ROOT = "goleft-tpu"

_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
}


def get_logger(area: str = "") -> logging.Logger:
    """``get_logger("serve")`` → the ``goleft-tpu.serve`` logger."""
    return logging.getLogger(f"{ROOT}.{area}" if area else ROOT)


def parse_level(spec: str) -> int:
    try:
        return _LEVELS[spec.strip().lower()]
    except KeyError:
        raise ValueError(
            f"unknown log level {spec!r} (choose from "
            f"{'/'.join(_LEVELS)})")


def configure(level: int | str = logging.WARNING) -> logging.Logger:
    """Install (once) a stderr handler with a uniform format on the
    ``goleft-tpu`` root and set its level. Idempotent: repeat calls
    only adjust the level, so tests and nested CLI invocations cannot
    stack handlers."""
    if isinstance(level, str):
        level = parse_level(level)
    root = logging.getLogger(ROOT)
    if not any(getattr(h, "_goleft_cli", False)
               for h in root.handlers):
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(logging.Formatter(
            "%(asctime)s %(name)s %(levelname)s: %(message)s",
            datefmt="%H:%M:%S"))
        h._goleft_cli = True
        root.addHandler(h)
        # propagation stays ON: having a handler here already stops
        # logging.lastResort from double-printing, and test harnesses
        # (pytest caplog) capture via root-logger propagation
    root.setLevel(level)
    return root
