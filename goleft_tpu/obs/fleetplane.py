"""Fleet observability plane: one telemetry story across processes.

PRs 3-4 gave a single process spans, a metrics registry and a flight
recorder; PRs 9-10 split a request's life across a router and a
supervised worker fleet. This module is the glue that makes the fleet
observable as ONE system, in three stdlib-only pieces shared by the
router (jax-free) and the serve workers:

  - **trace context propagation**: the ``x-goleft-trace`` header (a
    W3C-traceparent-style ``<trace_id>;<parent_span_id>`` pair) minted
    by the client or the router and forwarded on every proxied
    request. The worker's ``request.<kind>`` root adopts the remote
    trace id (``Tracer.trace(trace_id=...)``) and records the remote
    parent span id as the ``remote_parent`` attribute — span ids stay
    process-local, so adoption never aliases a foreign id into the
    local parent chain.
  - **cross-process trace stitching**: :func:`stitch_trace` takes the
    router's flight record for a trace id plus each worker's matching
    records (``/debug/flight?trace_id=``) and rebuilds the Dapper-style
    request tree: worker ``request.*`` trees graft under the router
    ``fleet.forward.*`` span named by their ``remote_parent``, and
    worker ``batch.*`` trees (which run on the dispatcher thread under
    their own trace) graft under the plan-step span recorded in their
    ``parent_trace``/``parent_span`` link attributes.
    :func:`perfetto_export` renders the same records as Chrome
    trace-event JSON with one process track per OS process.
    Cross-process timestamps align via each record's wall-clock root
    ``ts`` (millisecond precision — good enough to read a request's
    shape, not to measure a syscall).
  - **metrics rollup**: :func:`merge_worker_metrics` folds the polled
    per-worker ``/metrics`` bodies into one fleet view — counters
    summed, gauges kept per-worker plus min/max/sum, histogram
    summaries merged (counts and sums exactly; quantiles as
    count-weighted means of the per-worker quantiles, which is an
    APPROXIMATION — quantiles are not mergeable from summaries, see
    docs/observability.md) — and computes the fleet SLO burn-rate
    gauges (``fleet.slo.burn_rate.<endpoint>``) the supervisor's
    autoscaler consumes.
"""

from __future__ import annotations

import datetime
import hashlib
import itertools
import os
import time

#: the cross-process trace header (request AND response)
TRACE_HEADER = "x-goleft-trace"

#: longest trace id accepted from the wire (the flight ring keys on
#: it; an unbounded attacker-chosen string must not become one)
MAX_TRACE_ID = 128

_mint_seq = itertools.count(1)


def format_trace_header(trace_id: str, span_id: int | None = None) -> str:
    """``<trace_id>`` or ``<trace_id>;<parent_span_id>``."""
    if span_id is None:
        return trace_id
    return f"{trace_id};{span_id}"


def parse_trace_header(value: str | None) -> tuple[str, int | None] | None:
    """(trace_id, parent_span_id|None), or None for absent/garbage.

    The header crosses a trust boundary (any client can send one), so
    parsing is strict: bounded length, printable non-space id, integer
    span. A bad header degrades to "no header" — propagation is an
    observability feature and must never 400 a request.
    """
    if not value:
        return None
    head, _, tail = value.strip().partition(";")
    if not head or len(head) > MAX_TRACE_ID \
            or any(c.isspace() or not c.isprintable() for c in head):
        return None
    span_id: int | None = None
    if tail:
        try:
            span_id = int(tail.strip())
        except ValueError:
            return None
    return head, span_id


def mint_trace_id(component: str = "cli") -> str:
    """A fleet-unique trace id for a process WITHOUT a tracer (the
    stdlib client): ``serve-<component>-<pid>-<ms>-<n>``. The
    ``serve-`` prefix is what the workers' flight recorders watch, so
    a client-minted trace is retained end to end."""
    return (f"serve-{component}-{os.getpid()}-"
            f"{int(time.time() * 1000)}-{next(_mint_seq)}")


def poll_jitter_frac(name: str, seed: int = 0) -> float:
    """Deterministic per-worker scrape offset in [0, 1) — the
    RetryPolicy jitter trick applied to the poller's schedule, so N
    workers spread across the poll interval instead of being scraped
    in one tick burst. Same (name, seed), same offset, every process."""
    h = hashlib.sha256(f"{seed}:{name}".encode()).digest()
    return int.from_bytes(h[:8], "big") / 2.0 ** 64


# ---------------------------------------------------------------------------
# metrics rollup
# ---------------------------------------------------------------------------

#: scalar top-level fields of a worker /metrics body treated as gauges
GAUGE_FIELDS = ("queue_depth", "queue_age_s", "uptime_s")

#: histogram-summary keys merged as count-weighted means (approximate)
_QUANTILE_KEYS = ("p50", "p95", "p99")


def _merge_counter_maps(maps: list[dict]) -> dict:
    out: dict[str, int] = {}
    for m in maps:
        for k, v in m.items():
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            out[k] = out.get(k, 0) + v
    return {k: out[k] for k in sorted(out)}


def merge_histogram_summaries(summaries: list[dict],
                              windows: list[list] | None = None) \
        -> dict:
    """One merged summary from per-worker summaries produced by
    :meth:`~goleft_tpu.obs.metrics.Histogram.summary`.

    ``count`` and ``sum`` merge exactly (they are additive); ``max``
    is the max of maxes (exact). Quantiles come in two grades:

      - **exact** — when ``windows`` carries every live worker's
        bounded raw observation window (the ``latency_windows``
        block workers ship in /metrics), the merged quantiles are
        computed over the CONCATENATED windows: the same windowed
        estimator a single worker uses, applied to the union, so the
        fleet p99 is exactly what one process holding all the samples
        would report (``quantile_source: "exact"``).
      - **approximate** — without raw windows the quantiles fall back
        to count-weighted means of the per-worker quantiles, which is
        an approximation (true quantiles cannot be recovered from
        summaries; ``quantile_source: "approximate"``).
    """
    live = [(i, s) for i, s in enumerate(summaries)
            if s and s.get("count")]
    if not live:
        return {"count": 0}
    total = sum(s.get("count", 0) for _, s in live)
    out: dict = {"count": total}
    sums = [s["sum"] for _, s in live if isinstance(s.get("sum"),
                                                    (int, float))]
    if sums:
        out["sum"] = round(sum(sums), 4)
    maxes = [s["max"] for _, s in live if isinstance(s.get("max"),
                                                     (int, float))]
    if maxes:
        out["max"] = round(max(maxes), 6)
    wins = None
    if windows is not None:
        wins = [windows[i] if i < len(windows) else None
                for i, _ in live]
        if not all(isinstance(w, (list, tuple)) and w for w in wins):
            wins = None  # any live worker missing its window → fall
            # back for the whole merge (a mixed exact/approx answer
            # would claim precision it doesn't have)
    if wins is not None:
        from ..utils.profiling import percentiles

        merged = percentiles(
            [float(v) for w in wins for v in w
             if isinstance(v, (int, float))])
        for q in _QUANTILE_KEYS:
            if merged.get(q) is not None:
                out[q] = round(merged[q], 6)
        if merged.get("max") is not None:
            out["max"] = round(merged["max"], 6)
        out["quantile_source"] = "exact"
        return out
    for q in _QUANTILE_KEYS:
        pairs = [(s.get("count", 0), s[q]) for _, s in live
                 if isinstance(s.get(q), (int, float))]
        w = sum(c for c, _ in pairs)
        if pairs and w > 0:
            out[q] = round(sum(c * v for c, v in pairs) / w, 6)
    out["quantile_source"] = "approximate"
    return out


def merge_worker_metrics(snaps: dict[str, dict],
                         error_budget: float = 0.01) -> dict:
    """Fold per-worker ``/metrics`` JSON bodies into the fleet view.

    ``snaps`` maps a stable worker label (the router uses the port) to
    the worker's last polled metrics body. Returns::

        {"workers": N,
         "counters": {...summed...},
         "batch_size_hist": {...summed per bucket...},
         "gauges": {name: {"min","max","sum","workers":{label: v}}},
         "histograms": {name: merged summary},
         "slo": {"error_rate", "availability", "window_requests",
                 "p99_latency_ratio": {ep: worst},
                 "burn_rate": {ep: rate}, "burn_rate_max": rate,
                 "error_budget": budget},
         "quantile_note": "..."}

    Merge rules: counters sum; gauges keep per-worker values plus
    min/max/sum; histograms merge via
    :func:`merge_histogram_summaries`; the SLO block's error rate is
    the window-request-weighted mean, p99 ratios take the WORST worker
    (the one a new request might land on), and the burn rate per
    endpoint is ``max(p99_ratio, error_rate / error_budget)`` — above
    1.0 the fleet is burning its budget faster than it earns it, the
    autoscaler's scale-up trigger.
    """
    labels = sorted(snaps)
    out: dict = {
        "workers": len(labels),
        "counters": _merge_counter_maps(
            [snaps[w].get("counters") or {} for w in labels]),
        "batch_size_hist": _merge_counter_maps(
            [snaps[w].get("batch_size_hist") or {} for w in labels]),
        "gauges": {},
        "histograms": {},
        "quantile_note": ("histogram quantiles are EXACT (computed "
                          "over the workers' concatenated raw "
                          "latency windows) when every live worker "
                          "ships its window, else count-weighted "
                          "means of per-worker summaries "
                          "(approximate); counts and sums are exact "
                          "either way — see each merged summary's "
                          "quantile_source"),
    }
    for gname in GAUGE_FIELDS:
        per = {w: snaps[w][gname] for w in labels
               if isinstance(snaps[w].get(gname), (int, float))
               and not isinstance(snaps[w].get(gname), bool)}
        if not per:
            continue
        vals = list(per.values())
        out["gauges"][gname] = {
            "min": round(min(vals), 4), "max": round(max(vals), 4),
            "sum": round(sum(vals), 4), "workers": per,
        }
    hist_names = sorted({n for w in labels
                         for n in (snaps[w].get("latency_s") or {})})
    for name in hist_names:
        out["histograms"][f"latency_s.{name}"] = \
            merge_histogram_summaries(
                [(snaps[w].get("latency_s") or {}).get(name) or {}
                 for w in labels],
                windows=[(snaps[w].get("latency_windows") or {})
                         .get(name) for w in labels])
    out["slo"] = _merge_slo(
        [snaps[w].get("slo") or {} for w in labels], error_budget)
    return out


def _merge_slo(slos: list[dict], error_budget: float) -> dict:
    live = [s for s in slos if s]
    weights = [(s.get("window_requests") or 0, s.get("error_rate"))
               for s in live]
    w_total = sum(w for w, er in weights if isinstance(er, (int, float)))
    if w_total > 0:
        error_rate = sum(w * er for w, er in weights
                         if isinstance(er, (int, float))) / w_total
    else:
        # no windowed traffic anywhere: idle fleet, zero burn
        error_rate = 0.0
    ratios: dict[str, float] = {}
    for s in live:
        for ep, r in (s.get("p99_latency_ratio") or {}).items():
            if isinstance(r, (int, float)):
                ratios[ep] = max(ratios.get(ep, 0.0), r)
    budget = max(error_budget, 1e-9)
    err_burn = error_rate / budget
    burn = {ep: round(max(r, err_burn), 4)
            for ep, r in sorted(ratios.items())}
    burn_max = max(burn.values(), default=round(err_burn, 4))
    return {
        "error_rate": round(error_rate, 6),
        "availability": round(1.0 - error_rate, 6),
        "window_requests": sum(s.get("window_requests") or 0
                               for s in live),
        "p99_latency_ratio": {ep: round(r, 4)
                              for ep, r in sorted(ratios.items())},
        "error_budget": error_budget,
        "burn_rate": burn,
        "burn_rate_max": round(burn_max, 4),
        "tenants": merge_tenant_slos(
            [s.get("tenants") or {} for s in live], budget),
    }


def merge_tenant_slos(blocks: list[dict],
                      error_budget: float) -> dict:
    """Fold per-source ``tenants`` SLO blocks (the per-tenant
    dimension workers — and, one level up, fleets — publish) into one
    view with a burn rate per tenant.

    Error rates merge request-weighted; p99 ratios take the WORST
    source; ``burn_rate`` is ``max(p99_ratio, error_rate / budget)``
    — the same definition as the endpoint burn, scoped to one
    tenant. This is the gauge the federation's tenant-scoped shed is
    driven by (``federation.tenant.burn_rate.<tenant>``)."""
    budget = max(error_budget, 1e-9)
    agg: dict[str, dict] = {}
    for block in blocks:
        for tenant, rec in sorted((block or {}).items()):
            a = agg.setdefault(tenant, {"n": 0, "err_w": 0.0,
                                        "p99": 0.0})
            n = rec.get("window_requests") or 0
            er = rec.get("error_rate")
            if isinstance(er, (int, float)) and n:
                a["n"] += n
                a["err_w"] += n * er
            r = rec.get("p99_latency_ratio")
            if isinstance(r, (int, float)):
                a["p99"] = max(a["p99"], r)
    out: dict = {}
    for tenant, a in sorted(agg.items()):
        er = (a["err_w"] / a["n"]) if a["n"] else 0.0
        out[tenant] = {
            "window_requests": a["n"],
            "error_rate": round(er, 6),
            "p99_latency_ratio": round(a["p99"], 4),
            "burn_rate": round(max(a["p99"], er / budget), 4),
        }
    return out


def rollup_registry_snapshot(merged: dict) -> dict:
    """Flatten a :func:`merge_worker_metrics` result into the
    registry-snapshot shape :func:`goleft_tpu.obs.prometheus.render`
    consumes — one snapshot, two encodings, same numbers.

    Counters keep their worker-side names under ``fleet.worker.``;
    per-worker gauge values become ``fleet.worker.<name>.w.<label>``
    alongside ``.min/.max/.sum`` (the text exposition has no labels in
    this renderer, so the label rides the name); merged histograms
    render as summaries under ``fleet.worker.latency_s.*``; the SLO
    block lands as ``fleet.slo.*`` gauges.
    """
    counters = {f"fleet.worker.{n}": v
                for n, v in merged.get("counters", {}).items()}
    for size, v in merged.get("batch_size_hist", {}).items():
        counters[f"fleet.worker.batch_size.{size}"] = v
    gauges: dict[str, float] = {
        "fleet.workers_reporting": merged.get("workers", 0)}
    for name, rec in merged.get("gauges", {}).items():
        for stat in ("min", "max", "sum"):
            gauges[f"fleet.worker.{name}.{stat}"] = rec[stat]
        for label, v in sorted(rec.get("workers", {}).items()):
            gauges[f"fleet.worker.{name}.w.{label}"] = round(v, 4)
    slo = merged.get("slo") or {}
    for k in ("error_rate", "availability", "window_requests",
              "burn_rate_max"):
        if isinstance(slo.get(k), (int, float)):
            gauges[f"fleet.slo.{k}"] = slo[k]
    for ep, r in (slo.get("burn_rate") or {}).items():
        gauges[f"fleet.slo.burn_rate.{ep}"] = r
    for ep, r in (slo.get("p99_latency_ratio") or {}).items():
        gauges[f"fleet.slo.p99_latency_ratio.{ep}"] = r
    for tenant, rec in (slo.get("tenants") or {}).items():
        if isinstance(rec.get("burn_rate"), (int, float)):
            gauges[f"fleet.slo.tenant.burn_rate.{tenant}"] = \
                rec["burn_rate"]
    hists = {f"fleet.worker.{n}": s
             for n, s in merged.get("histograms", {}).items()
             if s.get("count")}
    return {"counters": counters, "gauges": gauges,
            "histograms": hists}


# ---------------------------------------------------------------------------
# cross-process trace stitching
# ---------------------------------------------------------------------------

def record_epoch(rec: dict) -> float | None:
    """Epoch seconds of a flight record's root (its ``ts`` ISO stamp),
    None when absent/garbled — the cross-process alignment anchor."""
    ts = rec.get("ts")
    if not ts:
        return None
    try:
        return datetime.datetime.fromisoformat(ts).timestamp()
    except ValueError:
        return None


def _walk(node: dict):
    yield node
    for c in node.get("children", ()):
        yield from _walk(c)


def _shift(node: dict, delta_ms: float) -> None:
    for n in _walk(node):
        n["start_ms"] = round(n.get("start_ms", 0.0) + delta_ms, 3)


def _annotate(node: dict, process: str) -> None:
    for n in _walk(node):
        n["process"] = process


def _find_span(root: dict, span_id) -> dict | None:
    if span_id is None:
        return None
    for n in _walk(root):
        if n.get("span_id") == span_id:
            return n
    return None


def stitch_trace(trace_id: str, router_records: list[dict],
                 worker_records: dict[str, list[dict]],
                 clock_offsets: dict[str, float] | None = None) \
        -> dict | None:
    """One stitched cross-process tree for ``trace_id``.

    ``router_records``: the router's own flight records matching the
    id (newest first); ``worker_records``: per-worker-url lists pulled
    from ``/debug/flight?trace_id=``. ``clock_offsets`` optionally
    maps a worker url to its estimated wall-clock offset in seconds
    (positive = that worker's clock runs AHEAD of the router's — the
    poller's midpoint handshake estimate); a record's epoch is
    corrected by it before rebasing, so cross-HOST skew does not
    shear the stitched timeline. Returns None when NOBODY has the
    trace. Grafting:

      - the router's ``fleet.request.*`` tree is the stitched root
        (synthesized when the router ring already evicted it but a
        worker still holds the tree);
      - a worker ``request.*`` tree attaches under the router span
        whose ``span_id`` equals the tree's ``remote_parent`` attr
        (the forward span that carried it), else under the root;
      - a worker ``batch.*`` tree (its own trace, linked by
        ``parent_trace``/``parent_span`` attrs) attaches under the
        span of that worker's request tree whose ``span_id`` equals
        ``parent_span`` — the plan step that submitted the work.

    Every node gains a ``process`` label; ``start_ms`` is rebased onto
    the stitched root's clock via each record's wall-clock ``ts``.
    """
    import copy

    root = None
    for rec in router_records:
        if rec.get("trace_id") == trace_id:
            root = copy.deepcopy(rec)
            break
    have_workers = any(worker_records.get(u) for u in worker_records)
    if root is None and not have_workers:
        return None
    if root is None:
        root = {"name": f"trace.{trace_id}", "trace_id": trace_id,
                "category": "synthetic", "start_ms": 0.0,
                "duration_ms": 0.0, "children": [],
                "synthesized": True}
    _annotate(root, "router")
    root_epoch = record_epoch(root)
    processes: dict[str, dict] = {
        "router": {"pid": root.get("pid"), "spans": sum(
            1 for _ in _walk(root))}}

    for url in sorted(worker_records):
        recs = worker_records[url] or []
        label = f"worker:{url.rsplit(':', 1)[-1]}"
        req_roots: list[dict] = []
        batches: list[dict] = []
        for rec in recs:
            rec = copy.deepcopy(rec)
            if rec.get("trace_id") == trace_id:
                req_roots.append(rec)
            elif (rec.get("attrs") or {}).get("parent_trace") \
                    == trace_id:
                batches.append(rec)
        if not req_roots and not batches:
            continue
        off = float((clock_offsets or {}).get(url) or 0.0)
        n_spans = 0
        for rec in req_roots + batches:
            _annotate(rec, label)
            n_spans += sum(1 for _ in _walk(rec))
            ep = record_epoch(rec)
            if root_epoch is not None and ep is not None:
                _shift(rec, (ep - off - root_epoch) * 1e3
                       - rec.get("start_ms", 0.0))
        processes[label] = {
            "pid": (req_roots + batches)[0].get("pid"),
            "spans": n_spans}
        for rec in req_roots:
            remote = (rec.get("attrs") or {}).get("remote_parent")
            parent = _find_span(root, remote) or root
            parent["children"].append(rec)
        for rec in batches:
            pspan = (rec.get("attrs") or {}).get("parent_span")
            parent = None
            for req in req_roots:
                parent = _find_span(req, pspan)
                if parent is not None:
                    break
            if parent is None:
                parent = req_roots[0] if req_roots else root
            parent["children"].append(rec)
    for n in _walk(root):
        n["children"].sort(key=lambda c: c.get("start_ms", 0.0))
    return {
        "trace_id": trace_id,
        "processes": processes,
        "span_count": sum(p["spans"] for p in processes.values()),
        "tree": root,
    }


def stitch_federation(trace_id: str, fed_records: list[dict],
                      fleet_docs: dict[str, dict | None],
                      clock_offsets: dict[str, float] | None = None) \
        -> dict | None:
    """Compose ONE federation-wide tree from per-fleet stitched docs.

    The graft rules are :func:`stitch_trace`'s applied one level up —
    a federation hop is just one more ``remote_parent`` level:

      - the federation router's ``federation.request.*`` flight record
        is the stitched root (synthesized when its ring already
        evicted the trace but a fleet still holds it);
      - each fleet's stitched document (the ``GET /fleet/trace/<id>``
        answer — its own router + worker tree) grafts under the
        federation ``federation.forward.*`` span whose ``span_id``
        equals the fleet tree's ``remote_parent`` attr (the forward
        that carried the request), else under the root;
      - process labels are namespaced ``fleet:<port>/<process>`` so
        two fleets' routers (both "router" locally) stay distinct
        tracks in the Perfetto export;
      - ``start_ms`` rebases via each fleet root's wall ``ts``,
        corrected by the federation poller's per-fleet clock offset
        (the same midpoint handshake the fleet router applies to its
        workers).

    Returns the same shape as :func:`stitch_trace` — ``format_tree``
    and :func:`perfetto_export` consume it unchanged. None when no
    process holds the trace.
    """
    import copy

    root = None
    for rec in fed_records:
        if rec.get("trace_id") == trace_id:
            root = copy.deepcopy(rec)
            break
    have_fleets = any(d for d in fleet_docs.values())
    if root is None and not have_fleets:
        return None
    if root is None:
        root = {"name": f"trace.{trace_id}", "trace_id": trace_id,
                "category": "synthetic", "start_ms": 0.0,
                "duration_ms": 0.0, "children": [],
                "synthesized": True}
    _annotate(root, "federation")
    root_epoch = record_epoch(root)
    processes: dict[str, dict] = {
        "federation": {"pid": root.get("pid"), "spans": sum(
            1 for _ in _walk(root))}}
    for url in sorted(fleet_docs):
        doc = fleet_docs[url]
        if not doc or not doc.get("tree"):
            continue
        label = f"fleet:{url.rsplit(':', 1)[-1]}"
        tree = copy.deepcopy(doc["tree"])
        for n in _walk(tree):
            n["process"] = f"{label}/{n.get('process', '?')}"
        off = float((clock_offsets or {}).get(url) or 0.0)
        ep = record_epoch(tree)
        if root_epoch is not None and ep is not None:
            _shift(tree, (ep - off - root_epoch) * 1e3
                   - tree.get("start_ms", 0.0))
        remote = (tree.get("attrs") or {}).get("remote_parent")
        parent = _find_span(root, remote) or root
        parent.setdefault("children", []).append(tree)
        for pname, pinfo in sorted((doc.get("processes")
                                    or {}).items()):
            processes[f"{label}/{pname}"] = dict(pinfo)
    for n in _walk(root):
        n.setdefault("children", []).sort(
            key=lambda c: c.get("start_ms", 0.0))
    return {
        "trace_id": trace_id,
        "processes": processes,
        "span_count": sum(p.get("spans", 0)
                          for p in processes.values()),
        "tree": root,
    }


def perfetto_export(trace_id: str,
                    stitched: dict) -> dict:
    """A :func:`stitch_trace` result as Chrome trace-event JSON with
    one PROCESS TRACK per OS process (router + each worker), loadable
    in Perfetto. Timestamps are the stitched tree's rebased clock
    (absolute epoch µs when the root carried a wall stamp)."""
    tree = stitched["tree"]
    base_epoch = record_epoch(tree) or 0.0
    base_us = base_epoch * 1e6
    pids: dict[str, int] = {}
    tids: dict[tuple, int] = {}
    events: list[dict] = []
    meta: list[dict] = []
    for n in _walk(tree):
        proc = n.get("process", "router")
        if proc not in pids:
            pid = n.get("pid") or (100000 + len(pids))
            # two processes can recycle a pid across restarts: keep
            # tracks distinct by falling back to a synthetic id
            if pid in pids.values():
                pid = 100000 + len(pids)
            pids[proc] = pid
            meta.append({"name": "process_name", "ph": "M",
                         "pid": pid, "tid": 0,
                         "args": {"name": proc}})
        pid = pids[proc]
        tkey = (proc, n.get("thread", ""))
        if tkey not in tids:
            tids[tkey] = len(tids) + 1
            meta.append({"name": "thread_name", "ph": "M",
                         "pid": pid, "tid": tids[tkey],
                         "args": {"name": n.get("thread", "") or
                                  f"thread-{tids[tkey]}"}})
        args = {"trace_id": trace_id, "process": proc}
        if n.get("span_id") is not None:
            args["span_id"] = n["span_id"]
        for k, v in (n.get("attrs") or {}).items():
            args.setdefault(k, v)
        events.append({
            "name": n["name"], "cat": n.get("category") or "span",
            "ph": "X",
            "ts": round(base_us + n.get("start_ms", 0.0) * 1e3, 3),
            "dur": round(n.get("duration_ms", 0.0) * 1e3, 3),
            "pid": pid, "tid": tids[tkey], "args": args,
        })
    events.sort(key=lambda e: e["ts"])
    return {
        "traceEvents": meta + events,
        "displayTimeUnit": "ms",
        "otherData": {"producer": "goleft-tpu fleetplane",
                      "trace_id": trace_id,
                      "processes": sorted(pids)},
    }


def format_tree(stitched: dict, width: int = 78) -> str:
    """Human-readable stitched tree (the ``goleft-tpu trace`` body):
    one line per span — indent, name, duration, process."""
    lines = [f"trace {stitched['trace_id']} — "
             f"{stitched['span_count']} span(s), "
             f"{len(stitched['processes'])} process(es)"]
    for proc in sorted(stitched["processes"]):
        info = stitched["processes"][proc]
        lines.append(f"  process {proc}: pid={info.get('pid')} "
                     f"spans={info['spans']}")

    def _fmt(node: dict, depth: int) -> None:
        pad = "  " * depth
        dur = node.get("duration_ms", 0.0)
        head = f"{pad}{node['name']}"
        tail = f"{dur:9.3f}ms  [{node.get('process', '?')}]"
        gap = max(1, width - len(head) - len(tail))
        lines.append(head + " " * gap + tail)
        for c in node.get("children", ()):
            _fmt(c, depth + 1)

    lines.append("")
    _fmt(stitched["tree"], 0)
    return "\n".join(lines)
