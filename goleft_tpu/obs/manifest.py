"""The run manifest: ``--metrics-out run.json``.

One JSON document per invocation tying together what three artifacts
used to carry separately: the environment it ran in, the backend it
actually dispatched to (obs/provenance.py — the same fields
``bench.py`` pins into its device entries), the span totals of where
the wall clock went, and the full metrics-registry snapshot. The
bench ingests this file directly instead of re-deriving provenance;
a run whose manifest says ``"platform": "cpu"`` can never be mistaken
for device evidence.
"""

from __future__ import annotations

import datetime
import json
import os

from .metrics import MetricsRegistry, get_registry
from .provenance import backend_provenance, env_provenance
from .tracing import Tracer, get_tracer

#: keys every manifest must carry — validated by the obs smoke and by
#: bench-side ingestion (a manifest missing one of these is not run
#: evidence)
REQUIRED_KEYS = ("schema", "ts", "argv", "env", "backend", "spans",
                 "metrics", "trace_id")

SCHEMA = "goleft-tpu.run-manifest/1"


def build_manifest(tracer: Tracer | None = None,
                   registry: MetricsRegistry | None = None,
                   trace_id: str | None = None,
                   argv: list[str] | None = None,
                   extra: dict | None = None) -> dict:
    tracer = tracer if tracer is not None else get_tracer()
    registry = registry if registry is not None else get_registry()
    doc = {
        "schema": SCHEMA,
        "ts": datetime.datetime.now(datetime.timezone.utc)
                .isoformat(timespec="seconds"),
        "argv": list(argv) if argv is not None else None,
        "env": env_provenance(),
        "backend": backend_provenance(),
        "spans": tracer.summary(trace_id=trace_id),
        "spans_dropped": tracer.spans_dropped,
        "metrics": registry.snapshot(),
        "trace_id": trace_id,
    }
    if extra:
        doc.update(extra)
    return doc


def write_manifest(path: str, **kw) -> dict:
    doc = build_manifest(**kw)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as fh:
        json.dump(doc, fh, indent=1, sort_keys=False)
        fh.write("\n")
    os.replace(tmp, path)
    return doc


def load_manifest(path: str) -> dict:
    """Parse + validate a manifest (the bench's ingestion entry): the
    REQUIRED_KEYS must be present and the backend block must carry
    either provenance fields or an explicit error."""
    with open(path) as fh:
        doc = json.load(fh)
    missing = [k for k in REQUIRED_KEYS if k not in doc]
    if missing:
        raise ValueError(f"manifest {path}: missing keys {missing}")
    backend = doc["backend"]
    if "error" not in backend and "platform" not in backend:
        raise ValueError(
            f"manifest {path}: backend block has neither platform "
            "nor error")
    return doc
