"""The run manifest: ``--metrics-out run.json``.

One JSON document per invocation tying together what three artifacts
used to carry separately: the environment it ran in, the backend it
actually dispatched to (obs/provenance.py — the same fields
``bench.py`` pins into its device entries), the span totals of where
the wall clock went, and the full metrics-registry snapshot. The
bench ingests this file directly instead of re-deriving provenance;
a run whose manifest says ``"platform": "cpu"`` can never be mistaken
for device evidence.
"""

from __future__ import annotations

import datetime
import json
import os

from .metrics import MetricsRegistry, get_registry
from .provenance import backend_provenance, env_provenance
from .tracing import Tracer, get_tracer

#: keys every manifest must carry — validated by the obs smoke and by
#: bench-side ingestion (a manifest missing one of these is not run
#: evidence)
REQUIRED_KEYS = ("schema", "ts", "argv", "env", "backend", "spans",
                 "metrics", "trace_id")

#: current writer version. Minor bumps (1.x) ADD fields and must stay
#: readable by every 1.* consumer (the perf ledger ingests manifests
#: from many rounds); a major bump means the REQUIRED_KEYS contract
#: itself changed and old readers must refuse loudly.
SCHEMA_PREFIX = "goleft-tpu.run-manifest/"
SCHEMA_MAJOR = 1
SCHEMA = f"{SCHEMA_PREFIX}1.3"

#: subsystem-contributed manifest sections (1.2): name -> provider().
#: A provider returning None omits its section; a raising provider
#: degrades to an error stub — manifest writing must never fail the
#: run it is documenting. The resilience subsystem registers its
#: quarantine/checkpoint block here.
_SECTIONS: dict = {}


def register_section(name: str, provider) -> None:
    if name in REQUIRED_KEYS:
        raise ValueError(f"cannot shadow required manifest key {name!r}")
    _SECTIONS[name] = provider


def parse_schema_version(schema: str) -> tuple[int, int]:
    """``goleft-tpu.run-manifest/1.2`` -> (1, 2); a bare ``/1`` means
    (1, 0). Raises ValueError on anything else."""
    if not isinstance(schema, str) \
            or not schema.startswith(SCHEMA_PREFIX):
        raise ValueError(f"not a run-manifest schema id: {schema!r}")
    ver = schema[len(SCHEMA_PREFIX):]
    major, _, minor = ver.partition(".")
    try:
        return int(major), int(minor) if minor else 0
    except ValueError:
        raise ValueError(
            f"unparseable run-manifest version: {schema!r}") from None


def build_manifest(tracer: Tracer | None = None,
                   registry: MetricsRegistry | None = None,
                   trace_id: str | None = None,
                   argv: list[str] | None = None,
                   extra: dict | None = None) -> dict:
    tracer = tracer if tracer is not None else get_tracer()
    registry = registry if registry is not None else get_registry()
    doc = {
        "schema": SCHEMA,
        "ts": datetime.datetime.now(datetime.timezone.utc)
                .isoformat(timespec="seconds"),
        "argv": list(argv) if argv is not None else None,
        "env": env_provenance(),
        "backend": backend_provenance(),
        "spans": tracer.summary(trace_id=trace_id),
        # the span summary is only as complete as the ring: the drop
        # count (and a plain truncation flag, added in 1.1) ride next
        # to it so a partial summary is self-describing
        "spans_dropped": tracer.spans_dropped,
        "spans_truncated": tracer.spans_dropped > 0,
        "metrics": registry.snapshot(),
        "trace_id": trace_id,
    }
    for name in sorted(_SECTIONS):
        try:
            section = _SECTIONS[name]()
        except Exception as e:  # noqa: BLE001 — never fail the run
            section = {"error": repr(e)}
        if section is not None:
            doc[name] = section
    if extra:
        doc.update(extra)
    return doc


def write_manifest(path: str, **kw) -> dict:
    doc = build_manifest(**kw)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as fh:
        json.dump(doc, fh, indent=1, sort_keys=False)
        fh.write("\n")
    os.replace(tmp, path)
    return doc


def load_manifest(path: str) -> dict:
    """Parse + validate a manifest (the bench's and the perf ledger's
    ingestion entry): the REQUIRED_KEYS must be present and the
    backend block must carry either provenance fields or an explicit
    error.

    Schema policy: any ``goleft-tpu.run-manifest/1.x`` revision loads
    (minor revisions only add fields — ledger ingestion must survive
    manifests written by future rounds); a different major is rejected
    with a clear error instead of being half-parsed.
    """
    with open(path) as fh:
        doc = json.load(fh)
    missing = [k for k in REQUIRED_KEYS if k not in doc]
    if missing:
        raise ValueError(f"manifest {path}: missing keys {missing}")
    major, _minor = parse_schema_version(doc["schema"])
    if major != SCHEMA_MAJOR:
        raise ValueError(
            f"manifest {path}: unsupported schema major version "
            f"{major} ({doc['schema']!r}); this reader supports "
            f"{SCHEMA_MAJOR}.x — upgrade goleft-tpu to read it")
    backend = doc["backend"]
    if "error" not in backend and "platform" not in backend:
        raise ValueError(
            f"manifest {path}: backend block has neither platform "
            "nor error")
    return doc
