"""The regression sentinel: noise-aware trend gating over the ledger.

Consumes ``PERF_LEDGER.jsonl`` (obs/ledger.py) and answers, per
(entry, metric) series, the question no snapshot can: *is the newest
round's number better, worse, noise, or not even evidence?*

Classification contract (the perf ``check`` gate and the table-driven
tests pin these):

  - ``stale-evidence`` — the newest record is a carryover/pin (its
    ``stale`` flag is set, e.g. a ``device_lastgood`` block in a
    probe-failed round). It is flagged, never compared: stale numbers
    can neither regress nor improve, they are facts about an earlier
    round. This includes the host-vs-device mismatch case: a device
    claim has NO fresh measurement behind it this round.
  - ``regressed`` / ``improved`` — the relative delta against the
    baseline exceeds the noise threshold in the metric's bad / good
    direction.
  - ``flat`` — within the threshold.
  - ``new`` — no provenance-compatible prior rounds to compare against
    (including a fresh device number after host-only rounds: device
    compares only against device).
  - ``info`` — the metric has no regression semantics (``vs_baseline``
    ratios whose denominator is re-measured per round, config echoes,
    the numpy baseline itself).

Baseline = median of the provenance-matched, non-stale prior rounds;
noise threshold = max(relative floor, ``mad_k`` × relative MAD of
those priors) — so a series that historically wobbles ±30% needs more
than 30%-ish movement to alarm, while a stable series trips at the
floor. Provenance matching: device records compare only against
device records; host records against host (records with no platform
claim are treated as host-side — every unpinned bench entry predates
per-entry pinning and ran on the host suite).
"""

from __future__ import annotations

import math
import statistics

#: default relative-delta floor below which movement is noise
DEFAULT_FLOOR = 0.20
#: how many relative MADs of historical wobble the delta must exceed
DEFAULT_MAD_K = 3.0

#: substrings deciding metric direction; first match wins, checked
#: info -> lower -> higher so e.g. ``numpy_kernel_gbases_per_sec``
#: stays informational even though it looks like a throughput
_INFO_PAT = ("vs_baseline", "numpy_", "baseline", "ratio",
             "spans_dropped", "calls", "count", "counters.",
             "gauges.", "overhead", "threaded_over_serial")
_LOWER_PAT = ("seconds", "latency", "_ms", "wall")
_HIGHER_PAT = ("per_sec", "per_chip", "throughput", "speedup",
               "samples_per_sec", "efficiency", "hit_rate",
               "req_per_s", "gbases", "mb_per_s", "per_second")


def metric_direction(entry: str, metric: str) -> str | None:
    """'higher' | 'lower' | None (no regression semantics). A bare
    ``value`` metric takes its direction from its entry name (the
    headline records)."""
    name = f"{entry}.{metric}" if metric == "value" else metric
    low = name.lower()
    if any(p in low for p in _INFO_PAT):
        return None
    if any(p in low for p in _LOWER_PAT):
        return "lower"
    if any(p in low for p in _HIGHER_PAT):
        return "higher"
    return None


def provenance_compatible(current: str, prior: str) -> bool:
    """Device evidence only ever compares against device evidence;
    host (and legacy unpinned = host-suite) records compare among
    themselves. The asymmetric case this exists for: a device claim
    must never be judged against a host baseline (or vice versa) —
    that comparison produced three rounds of phantom 'regressions'
    and 'speedups' before per-entry pinning."""
    if current == "device" or prior == "device":
        return current == prior == "device"
    return True  # host/unknown pool together (host-suite reality)


def _series(records: list[dict]) -> dict:
    """{(entry, metric): [(round, value, provenance, stale)]} over the
    numeric-round records, round-ordered."""
    out: dict[tuple, list] = {}
    for rec in records:
        rnd = rec.get("round")
        if not isinstance(rnd, int):
            continue  # pins / unround manifests trend nowhere
        for metric, value in (rec.get("metrics") or {}).items():
            out.setdefault((rec["entry"], metric), []).append(
                (rnd, float(value), rec.get("provenance", "unknown"),
                 bool(rec.get("stale"))))
    for vals in out.values():
        vals.sort(key=lambda t: t[0])
    return out


def classify_series(points: list, entry: str, metric: str,
                    floor: float = DEFAULT_FLOOR,
                    mad_k: float = DEFAULT_MAD_K) -> dict:
    """Classify the NEWEST point of one (entry, metric) series against
    its provenance-matched history. ``points`` is the round-ordered
    [(round, value, provenance, stale)] list."""
    rnd, value, prov, stale = points[-1]
    history = [v for r, v, p, s in points[:-1]
               if r < rnd and not s
               and provenance_compatible(prov, p)]
    out = {
        "entry": entry, "metric": metric, "round": rnd,
        "value": value, "provenance": prov,
        "history": [v for r, v, _, _ in points[:-1] if r < rnd],
        "baseline": None, "delta": None, "threshold": None,
        "direction": metric_direction(entry, metric),
    }
    if stale:
        out["status"] = "stale-evidence"
        return out
    if out["direction"] is None:
        out["status"] = "info"
        return out
    if not history:
        out["status"] = "new"
        return out
    baseline = statistics.median(history)
    out["baseline"] = baseline
    if baseline == 0:
        out["status"] = "new"  # nothing meaningful to scale against
        return out
    rel_mad = (statistics.median(
        [abs(v - baseline) for v in history]) / abs(baseline)
        if len(history) > 1 else 0.0)
    threshold = max(floor, mad_k * rel_mad)
    delta = (value - baseline) / abs(baseline)
    out["delta"] = round(delta, 4)
    out["threshold"] = round(threshold, 4)
    worse = -delta if out["direction"] == "higher" else delta
    if worse > threshold:
        out["status"] = "regressed"
    elif -worse > threshold:
        out["status"] = "improved"
    else:
        out["status"] = "flat"
    return out


def analyze(records: list[dict], floor: float = DEFAULT_FLOOR,
            mad_k: float = DEFAULT_MAD_K) -> dict:
    """Full sentinel pass over ledger records.

    Returns {round, results: [classification...], counts,
    device_evidence_gap}: ``results`` classifies every (entry, metric)
    present in the NEWEST numeric round; ``device_evidence_gap`` is
    True when that round's device-provenance claims are backed ONLY by
    carryover data (every device record stale) — the ROADMAP gap as a
    machine-readable bit.
    """
    series = _series(records)
    rounds = {pt[0] for pts in series.values() for pt in pts}
    if not rounds:
        return {"round": None, "results": [], "counts": {},
                "device_evidence_gap": False}
    newest = max(rounds)
    results = []
    for (entry, metric), pts in sorted(series.items()):
        if pts[-1][0] != newest:
            continue  # entry didn't run in the newest round
        results.append(classify_series(pts, entry, metric,
                                       floor=floor, mad_k=mad_k))
    counts: dict[str, int] = {}
    for r in results:
        counts[r["status"]] = counts.get(r["status"], 0) + 1
    device_pts = [r for r in results if r["provenance"] == "device"]
    gap = bool(device_pts) and all(
        r["status"] == "stale-evidence" for r in device_pts)
    return {"round": newest, "results": results, "counts": counts,
            "device_evidence_gap": gap}


# ---- rendering ----

_SPARK = "▁▂▃▄▅▆▇█"


def sparkline(values: list[float]) -> str:
    """Unicode mini-trend of a series (empty string for <1 point)."""
    vals = [v for v in values if isinstance(v, (int, float))
            and math.isfinite(v)]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    if hi == lo:
        return _SPARK[3] * len(vals)
    return "".join(
        _SPARK[min(len(_SPARK) - 1,
                   int((v - lo) / (hi - lo) * (len(_SPARK) - 1)))]
        for v in vals)


_STATUS_ORDER = ("regressed", "stale-evidence", "new", "improved",
                 "flat", "info")


def render_report(analysis: dict, show_info: bool = False) -> str:
    """The ``perf report`` table: per-entry sparkline trend rows,
    worst news first."""
    results = [r for r in analysis["results"]
               if show_info or r["status"] != "info"]
    if not results:
        return "perf: ledger has no classifiable series"
    results.sort(key=lambda r: (_STATUS_ORDER.index(r["status"]),
                                r["entry"], r["metric"]))
    name_w = max(len(f"{r['entry']}.{r['metric']}")
                 for r in results)
    name_w = min(max(name_w, 20), 58)
    lines = [f"round r{analysis['round']:02d} vs provenance-matched "
             "history (median baseline, MAD-scaled threshold)", ""]
    hdr = (f"{'entry.metric':<{name_w}} {'trend':<8} "
           f"{'latest':>10} {'baseline':>10} {'delta':>8} "
           f"{'thresh':>7}  status")
    lines += [hdr, "-" * len(hdr)]
    for r in results:
        name = f"{r['entry']}.{r['metric']}"
        if len(name) > name_w:
            name = name[:name_w - 1] + "…"
        spark = sparkline(r["history"] + [r["value"]])
        delta = (f"{r['delta']:+.1%}" if r["delta"] is not None
                 else "-")
        thresh = (f"{r['threshold']:.0%}"
                  if r["threshold"] is not None else "-")
        base = (f"{r['baseline']:.4g}"
                if r["baseline"] is not None else "-")
        lines.append(
            f"{name:<{name_w}} {spark:<8} {r['value']:>10.4g} "
            f"{base:>10} {delta:>8} {thresh:>7}  {r['status']}")
    counts = analysis["counts"]
    lines += ["", "summary: " + ", ".join(
        f"{counts[s]} {s}" for s in _STATUS_ORDER if s in counts)]
    if analysis["device_evidence_gap"]:
        lines.append(
            "device-evidence gap: every device-provenance claim in "
            "this round is carryover (stale) — no fresh on-chip "
            "measurement backs it (run bench.py on the chip host; "
            "see ROADMAP)")
    return "\n".join(lines)


def check(analysis: dict, strict: bool = False
          ) -> tuple[int, list[str]]:
    """The gate: (exit_code, failure_lines). Nonzero on any
    regression; with ``strict`` also on a device-evidence gap (device
    claims backed only by carryover)."""
    failures = []
    for r in analysis["results"]:
        if r["status"] == "regressed":
            failures.append(
                f"REGRESSED {r['entry']}.{r['metric']}: "
                f"{r['value']:.4g} vs baseline {r['baseline']:.4g} "
                f"({r['delta']:+.1%}, threshold "
                f"{r['threshold']:.0%}, {r['provenance']})")
    if strict and analysis["device_evidence_gap"]:
        failures.append(
            "STALE-EVIDENCE device claims are backed only by "
            "carryover data (no fresh device measurement this round)")
    return (1 if failures else 0), failures
