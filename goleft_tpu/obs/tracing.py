"""Run-scoped hierarchical tracing: the one span model every path uses.

Every CLI invocation and every serve request runs under a *trace* — a
string id grouping all the spans that invocation caused, across every
thread it touched. A *span* is one named wall-clock interval with
attributes and a parent: the CLI's run span parents the shard spans,
a shard's decode span parents nothing further, the serve batcher's
batch span parents the executors' decode/compute/format stages.

Design constraints (why this is not a logging framework):

  - recording must be cheap enough for the hot paths that already use
    ``StageTimer`` (one perf_counter pair + one lock-guarded append);
  - spans cross threads: the prefetch producers and the serve
    dispatcher record work on behalf of a consumer/request that lives
    on another thread, so the ambient context is thread-local but
    explicitly *portable* (:meth:`Tracer.capture` /
    :meth:`Tracer.attach`);
  - the buffer is bounded: a long-lived serve daemon must not grow
    per-request state, so the span ring drops oldest-first and counts
    what it dropped (``spans_dropped``);
  - export is Chrome trace-event JSON (the ``traceEvents`` array
    format) so ``--trace-out`` artifacts load directly in Perfetto /
    chrome://tracing next to the XLA profiler's own dumps.

Stdlib-only; jax never imports here (device attributes are the
caller's business — see obs/provenance.py).
"""

from __future__ import annotations

import contextlib
import itertools
import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field

# perf_counter gives monotonic durations; the offset maps them onto the
# epoch so exported timestamps line up across processes (and with the
# jax profiler's traces, which use epoch-based clocks)
_EPOCH_OFFSET = time.time() - time.perf_counter()


@dataclass
class Span:
    """One finished (or in-flight) named interval."""

    name: str
    span_id: int
    parent_id: int | None
    trace_id: str
    t0: float  # perf_counter seconds
    t1: float | None = None
    attrs: dict = field(default_factory=dict)
    thread_id: int = 0
    thread_name: str = ""
    category: str = ""

    def duration(self) -> float:
        return (self.t1 if self.t1 is not None
                else time.perf_counter()) - self.t0


class _Context(threading.local):
    """Per-thread ambient state: the active trace id and span stack."""

    def __init__(self):
        self.trace_id: str | None = None
        self.stack: list[Span] = []


@dataclass(frozen=True)
class SpanContext:
    """A portable snapshot of (trace, parent span) — what a worker
    thread attaches to record on behalf of the thread that captured
    it."""

    trace_id: str | None
    parent_id: int | None


class Tracer:
    """Process-wide span recorder with a bounded ring buffer.

    One instance (:data:`TRACER`) serves the whole process; tests may
    build private ones. All methods are thread-safe; the ambient
    context (current trace + span stack) is thread-local.
    """

    def __init__(self, max_spans: int = 100_000):
        self._spans: deque[Span] = deque(maxlen=max_spans)
        self.spans_dropped = 0
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._trace_ids = itertools.count(1)
        self._ctx = _Context()
        # completed-span listeners (the serve flight recorder): a plain
        # tuple read without the lock — empty for every process that
        # never registers one, so the hot path pays one truth test
        self._listeners: tuple = ()
        # --trace-out / GOLEFT_TPU_DEVICE_EVENTS=1 turn on per-dispatch
        # device fencing (obs.dispatch): off by default so the async
        # dispatch pipelines keep their overlap when nobody is looking
        self.device_events = bool(
            os.environ.get("GOLEFT_TPU_DEVICE_EVENTS"))
        # when the memory plane arms it (obs.memplane.MemorySampler.
        # start), a zero-arg callable returning current RSS bytes:
        # every span then carries mem_delta_bytes / mem_peak_bytes
        # attributes (manifest 1.3). None — the default — keeps spans
        # byte-identical to every earlier round: the Perfetto goldens
        # of unsampled runs must not change.
        self.mem_probe = None
        # thread ident -> trace id for threads currently inside
        # trace(): the sampling profiler reads this to tag stacks
        # taken during a traced request with that request's id
        self._active_traces: dict[int, str] = {}

    # ---- trace scoping ----

    def new_trace_id(self, kind: str = "run") -> str:
        return f"{kind}-{os.getpid()}-{next(self._trace_ids)}"

    @contextlib.contextmanager
    def trace(self, name: str, kind: str = "run",
              trace_id: str | None = None,
              remote_parent: int | None = None, **attrs):
        """Run-scoped root: sets this thread's trace id and opens the
        root span; yields the root :class:`Span` (its ``trace_id`` is
        the invocation's id).

        ``trace_id``/``remote_parent`` adopt a REMOTE context (the
        ``x-goleft-trace`` header): the root joins the caller's trace
        instead of minting one, and the foreign parent span id is
        recorded as the ``remote_parent`` attribute — NOT as
        ``parent_id``, which stays process-local (a foreign id in the
        local parent chain could alias a local span; the fleet
        stitcher resolves ``remote_parent`` against the remote
        process's tree instead)."""
        prev = self._ctx.trace_id
        self._ctx.trace_id = trace_id if trace_id \
            else self.new_trace_id(kind)
        if remote_parent is not None:
            attrs = dict(attrs, remote_parent=remote_parent)
        ident = threading.get_ident()
        with self._lock:
            self._active_traces[ident] = self._ctx.trace_id
        try:
            with self.span(name, **attrs) as root:
                yield root
        finally:
            with self._lock:
                if prev is not None:
                    self._active_traces[ident] = prev
                else:
                    self._active_traces.pop(ident, None)
            self._ctx.trace_id = prev

    def current_trace_id(self) -> str | None:
        return self._ctx.trace_id

    def active_traces(self) -> dict[int, str]:
        """Snapshot of {thread ident: trace id} for every thread
        currently inside :meth:`trace` — how the sampling profiler
        ties a stack sample back to the request it interrupted."""
        with self._lock:
            return dict(self._active_traces)

    # ---- span recording ----

    @contextlib.contextmanager
    def span(self, name: str, category: str = "", **attrs):
        """Open a child of this thread's innermost open span (or a
        trace root when the stack is empty)."""
        th = threading.current_thread()
        parent = self._ctx.stack[-1] if self._ctx.stack else None
        # captured once: close() may disarm the probe mid-span, and a
        # delta needs both readings from the same probe
        probe = self.mem_probe
        rss0 = probe() if probe is not None else 0
        sp = Span(
            name=name,
            span_id=next(self._ids),
            parent_id=parent.span_id if parent is not None else None,
            trace_id=self._ctx.trace_id or f"proc-{os.getpid()}",
            t0=time.perf_counter(),
            attrs=dict(attrs) if attrs else {},
            thread_id=th.ident or 0,
            thread_name=th.name,
            category=category,
        )
        self._ctx.stack.append(sp)
        try:
            yield sp
        finally:
            sp.t1 = time.perf_counter()
            if probe is not None:
                rss1 = probe()
                # boundary-observed: delta across the span, peak of
                # the two readings (a spike inside the span shows in
                # the sampler's rss_peak gauge, not here)
                sp.attrs["mem_delta_bytes"] = rss1 - rss0
                sp.attrs["mem_peak_bytes"] = max(rss0, rss1)
            self._ctx.stack.pop()
            with self._lock:
                if len(self._spans) == self._spans.maxlen:
                    self.spans_dropped += 1
                self._spans.append(sp)
            for cb in self._listeners:
                try:
                    cb(sp)
                except Exception:  # noqa: BLE001 — a broken listener
                    pass           # must never fail the traced work

    def record_span(self, name: str, t0: float, t1: float,
                    category: str = "", **attrs) -> Span:
        """Record an already-measured interval as a completed span.

        The compile observatory discovers a compile only after the
        fact (cache-size delta / jax log record at observation exit),
        so it cannot open a ``with span()`` around it; this records
        the measured [t0, t1] perf_counter window post hoc, parented
        to this thread's innermost open span — the compile lands
        inside the device stage that triggered it in the flight tree.
        """
        th = threading.current_thread()
        parent = self._ctx.stack[-1] if self._ctx.stack else None
        sp = Span(
            name=name,
            span_id=next(self._ids),
            parent_id=parent.span_id if parent is not None else None,
            trace_id=self._ctx.trace_id or f"proc-{os.getpid()}",
            t0=t0,
            t1=t1,
            attrs=dict(attrs) if attrs else {},
            thread_id=th.ident or 0,
            thread_name=th.name,
            category=category,
        )
        with self._lock:
            if len(self._spans) == self._spans.maxlen:
                self.spans_dropped += 1
            self._spans.append(sp)
        for cb in self._listeners:
            try:
                cb(sp)
            except Exception:  # noqa: BLE001 — a broken listener
                pass           # must never fail the recorded work
        return sp

    # ---- completed-span listeners ----

    def add_listener(self, cb) -> None:
        """Register ``cb(span)`` to run after every span completes
        (outside the ring lock, on the recording thread)."""
        with self._lock:
            if cb not in self._listeners:
                self._listeners = self._listeners + (cb,)

    def remove_listener(self, cb) -> None:
        # equality, not identity: a bound method is a fresh object at
        # every attribute access, but compares equal to itself
        with self._lock:
            self._listeners = tuple(
                c for c in self._listeners if c != cb)

    # ---- cross-thread propagation ----

    def capture(self) -> SpanContext:
        """Snapshot this thread's (trace, innermost span) for a worker
        thread to attach — how prefetch producers and the serve
        dispatcher parent their spans under the submitting request."""
        parent = self._ctx.stack[-1] if self._ctx.stack else None
        return SpanContext(
            trace_id=self._ctx.trace_id,
            parent_id=parent.span_id if parent is not None else None)

    @contextlib.contextmanager
    def attach(self, ctx: SpanContext | None):
        """Adopt a captured context on the current thread: spans
        recorded inside parent under ``ctx`` (a synthetic stack entry
        carries the foreign parent id)."""
        if ctx is None:
            yield
            return
        prev_trace = self._ctx.trace_id
        pushed = False
        if ctx.trace_id is not None:
            self._ctx.trace_id = ctx.trace_id
        if ctx.parent_id is not None and not self._ctx.stack:
            # a placeholder open span carrying only identity: children
            # parent to it, it is never itself recorded
            self._ctx.stack.append(Span(
                name="<attached>", span_id=ctx.parent_id,
                parent_id=None,
                trace_id=ctx.trace_id or f"proc-{os.getpid()}",
                t0=time.perf_counter()))
            pushed = True
        try:
            yield
        finally:
            if pushed:
                self._ctx.stack.pop()
            self._ctx.trace_id = prev_trace

    # ---- inspection / export ----

    def snapshot(self) -> list[Span]:
        with self._lock:
            return list(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self.spans_dropped = 0

    def summary(self, trace_id: str | None = None) -> dict:
        """{name: {seconds, calls}} totals over the buffered spans —
        the manifest's spans block (StageTimer.as_dict's shape, so the
        bench can ingest either)."""
        out: dict[str, dict] = {}
        for sp in self.snapshot():
            if trace_id is not None and sp.trace_id != trace_id:
                continue
            rec = out.setdefault(sp.name, {"seconds": 0.0, "calls": 0})
            rec["seconds"] += sp.duration()
            rec["calls"] += 1
        return {k: {"seconds": round(v["seconds"], 4),
                    "calls": v["calls"]}
                for k, v in sorted(out.items())}

    def to_chrome_trace(self, trace_id: str | None = None,
                        epoch_offset: float | None = None) -> dict:
        """The Chrome trace-event JSON object (Perfetto-loadable).

        Spans become ``ph: "X"`` complete events (ts/dur in
        microseconds); per-thread ``thread_name`` metadata events name
        the rows. ``trace_id`` filters to one invocation's spans (a
        serve daemon's ring holds many); attributes land in ``args``.
        """
        off = _EPOCH_OFFSET if epoch_offset is None else epoch_offset
        pid = os.getpid()
        events = []
        threads: dict[int, str] = {}
        for sp in self.snapshot():
            if trace_id is not None and sp.trace_id != trace_id:
                continue
            threads.setdefault(sp.thread_id, sp.thread_name)
            args = {"trace_id": sp.trace_id, "span_id": sp.span_id}
            if sp.parent_id is not None:
                args["parent_id"] = sp.parent_id
            args.update(sp.attrs)
            events.append({
                "name": sp.name,
                "cat": sp.category or "span",
                "ph": "X",
                "ts": round((sp.t0 + off) * 1e6, 3),
                "dur": round(sp.duration() * 1e6, 3),
                "pid": pid,
                "tid": sp.thread_id,
                "args": args,
            })
        events.sort(key=lambda e: e["ts"])
        meta = [{
            "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
            "args": {"name": nm or f"thread-{tid}"},
        } for tid, nm in sorted(threads.items())]
        # truncation is part of the evidence: a metadata event carries
        # the ring's drop count INSIDE traceEvents (Perfetto surfaces
        # event args; otherData is not reachable from the UI), so a
        # short trace says it is short instead of looking complete
        meta.append({
            "name": "spans_dropped", "ph": "M", "pid": pid, "tid": 0,
            "args": {"spans_dropped": self.spans_dropped},
        })
        return {
            "traceEvents": meta + events,
            "displayTimeUnit": "ms",
            "otherData": {"producer": "goleft-tpu obs",
                          "spans_dropped": self.spans_dropped},
        }

    def write_chrome_trace(self, path: str,
                           trace_id: str | None = None) -> None:
        doc = self.to_chrome_trace(trace_id=trace_id)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as fh:
            json.dump(doc, fh)
        os.replace(tmp, path)


#: the process-wide tracer every module records into
TRACER = Tracer()


def get_tracer() -> Tracer:
    return TRACER
