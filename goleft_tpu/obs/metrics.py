"""Process-wide metrics registry: counters, gauges, histograms.

One :data:`REGISTRY` instance is shared by every pipeline in the
process — the serve daemon's request counters, the prefetch staging
pipeline's byte counters, the ResultCache's hit/miss/eviction tallies
and the CLI's compile-cache deltas all land in the same namespace, so
one ``snapshot()`` (the ``--metrics-out`` manifest's registry block,
and the serve daemon's /metrics body) is the whole process's counter
evidence. Serve tests construct private registries for isolation.

Histograms share :func:`goleft_tpu.utils.profiling.percentiles` with
the bench, so a latency summary means the same thing in /metrics, the
run manifest and ``serve_throughput``.

Snapshot determinism: ``snapshot()`` sorts every name and rounds
consistently, so two snapshots of identical state serialize to
identical JSON bytes (pinned by tests/test_obs.py).
"""

from __future__ import annotations

import threading
from collections import deque


class Counter:
    """Monotonic int counter."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._value


class Gauge:
    """Last-write-wins instantaneous value."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = v

    def max(self, v: float) -> None:
        """Keep the high-water mark (queue depths, batch widths)."""
        with self._lock:
            if v > self._value:
                self._value = v

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Bounded observation buffer summarized via the shared
    ``percentiles`` (p50/p95/p99/max). ``count`` tracks ALL
    observations ever seen; only the last ``maxlen`` contribute to the
    percentile estimate (a long-lived daemon must not grow
    per-request state)."""

    __slots__ = ("name", "_vals", "_count", "_sum", "_lock")

    def __init__(self, name: str, maxlen: int = 4096):
        self.name = name
        self._vals: deque = deque(maxlen=maxlen)
        self._count = 0
        self._sum = 0.0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        with self._lock:
            self._vals.append(float(v))
            self._count += 1
            self._sum += float(v)

    def summary(self) -> dict:
        from ..utils.profiling import percentiles

        with self._lock:
            vals = list(self._vals)
            count, total = self._count, self._sum
        out = percentiles(vals)
        out["count"] = count  # all-time, not just the window
        if vals:
            out["sum"] = round(total, 4)
        return out

    def window(self) -> list[float]:
        """The bounded raw observation window (most recent ``maxlen``
        values, oldest first) — what the fleet rollup concatenates to
        compute EXACT merged quantiles instead of the count-weighted
        approximation summaries force on it. Rounded to µs-ish
        precision so shipping a window over /metrics stays cheap."""
        with self._lock:
            vals = list(self._vals)
        return [round(v, 6) for v in vals]


class MetricsRegistry:
    """Thread-safe name → instrument registry (get-or-create)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._hists: dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name)
            return c

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name)
            return g

    def histogram(self, name: str, maxlen: int = 4096) -> Histogram:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram(name, maxlen)
            return h

    def counters(self, prefix: str = "") -> dict[str, int]:
        """{name: value} for counters under ``prefix`` (sorted, the
        prefix stripped) — how ServeMetrics renders its legacy keys."""
        with self._lock:
            items = list(self._counters.items())
        return {n[len(prefix):]: c.value
                for n, c in sorted(items) if n.startswith(prefix)}

    def histograms(self, prefix: str = "") -> dict[str, dict]:
        with self._lock:
            items = list(self._hists.items())
        return {n[len(prefix):]: h.summary()
                for n, h in sorted(items) if n.startswith(prefix)}

    def histogram_windows(self, prefix: str = "") -> dict[str, list]:
        """{name: bounded raw window} per histogram under ``prefix`` —
        the worker-side half of the fleet's exact-quantile merge."""
        with self._lock:
            items = list(self._hists.items())
        return {n[len(prefix):]: h.window()
                for n, h in sorted(items) if n.startswith(prefix)}

    def snapshot(self) -> dict:
        """Deterministic full snapshot: sorted names, stable rounding.
        Zero-valued instruments are included — existence is evidence
        (a counter at 0 says the path was instrumented and idle)."""
        with self._lock:
            counters = sorted(self._counters.items())
            gauges = sorted(self._gauges.items())
            hists = sorted(self._hists.items())
        return {
            "counters": {n: c.value for n, c in counters},
            "gauges": {n: round(g.value, 4) for n, g in gauges},
            "histograms": {n: h.summary() for n, h in hists},
        }

    def reset(self) -> None:
        """Drop every instrument (tests only)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()


#: the process-wide registry (CLI pipelines, prefetch, caches, serve
#: daemon); tests and embedded apps may construct private ones
REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return REGISTRY
