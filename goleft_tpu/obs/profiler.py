"""Stdlib-only sampling profiler: where the host CPU time goes.

Span boundaries say a worker spent 1.8s in ``host-decode``; they
cannot say whether that was inflate, CRC, numpy windowing or lock
wait. This sampler fills that gap without a single dependency:
``sys._current_frames()`` at a fixed rate on a supervised background
thread, every thread's stack collapsed to the flamegraph-standard
semicolon form (root first, frames keyed ``module:func:line``) and
aggregated into a bounded counter table.

Design points:

  - **off by default** (``hz=0``): a profiler nobody asked for costs
    literally nothing — no thread, no samples;
  - **bounded**: the distinct-stack table caps at ``max_stacks``;
    beyond it new stacks are dropped and counted
    (``profiler.stacks_dropped_total``) rather than growing a
    long-lived daemon — the hot stacks a flamegraph is for are by
    definition already in the table;
  - **deterministic aggregation**: the frame key is
    ``module:func:line`` — no addresses, no ids — so two samples of
    the same code point always merge, across threads and (at the
    fleet rollup) across processes;
  - **cheap**: per-(code, line) key strings are memoized, so steady-
    state sampling is a dict walk — the pinned overhead test bounds
    100 Hz at <= 2% of wall on the depth pipeline;
  - **trace-linked**: a sample taken while a thread is inside a
    traced request (``Tracer.active_traces()``) tags that trace id,
    so a flamegraph window can be tied back to its stitched trace;
  - **supervised**: the sampler thread is joined by :meth:`close`
    (the thr-unjoined contract every serve daemon thread follows).

The worker surface is ``GET /debug/profile?seconds=N`` — a collect-
then-respond window over the continuously-sampling table (delta of
two snapshots) — and the router merges windows stack-wise at
``GET /fleet/profile`` (:func:`merge_profiles`: exact arithmetic
sums, the PR-13 rollup discipline).
"""

from __future__ import annotations

import sys
import threading
import time

from .metrics import get_registry

#: response/document schema for /debug/profile and /fleet/profile
PROFILE_SCHEMA = "goleft-tpu.profile/1"

#: hard ceiling on one collect window (the HTTP surface clamps to it:
#: a typo'd ?seconds= must not pin a handler thread for an hour)
MAX_WINDOW_S = 120.0

#: bounded memo: (code object, lineno) -> "module:func:line". Cleared
#: wholesale past the cap — code objects are long-lived, the memo is
#: what makes steady-state sampling a dict walk
_KEY_MEMO_CAP = 8192

#: frames deeper than this are truncated with a sentinel — a runaway
#: recursion must not make one sample O(recursion limit)
MAX_DEPTH = 64


def collapse_frame(frame, memo: dict | None = None,
                   max_depth: int = MAX_DEPTH) -> str:
    """One thread's stack as the collapsed-flamegraph line body:
    root-first ``module:func:line`` frames joined by ``;``."""
    parts: list[str] = []
    depth = 0
    while frame is not None and depth < max_depth:
        code = frame.f_code
        lineno = frame.f_lineno
        key = None
        mk = (code, lineno)
        if memo is not None:
            key = memo.get(mk)
        if key is None:
            mod = frame.f_globals.get("__name__", "?")
            key = f"{mod}:{code.co_name}:{lineno}"
            if memo is not None:
                if len(memo) >= _KEY_MEMO_CAP:
                    memo.clear()
                memo[mk] = key
        parts.append(key)
        frame = frame.f_back
        depth += 1
    if frame is not None:
        parts.append("~truncated~")
    parts.reverse()
    return ";".join(parts)


class SamplingProfiler:
    """Background wall-clock sampler over ``sys._current_frames()``.

    ``clock`` and ``frames_provider`` are injectable (tests pin the
    collapsed output and the table bounds deterministically without a
    real thread); production uses the defaults. ``registry=None``
    publishes into the process registry."""

    def __init__(self, hz: float = 0.0, max_stacks: int = 4096,
                 registry=None, tracer=None, clock=None,
                 frames_provider=None):
        if hz < 0:
            raise ValueError(f"profile hz must be >= 0 (got {hz})")
        self.hz = float(hz)
        self.max_stacks = int(max_stacks)
        self._registry = registry
        self._tracer = tracer
        self._clock = clock if clock is not None else time.monotonic
        self._frames = frames_provider \
            if frames_provider is not None else sys._current_frames
        self._lock = threading.Lock()
        self._stacks: dict[str, int] = {}
        self._trace_ids: dict[str, int] = {}
        self._samples_total = 0
        self._stacks_dropped = 0
        self._memo: dict = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def _reg(self):
        return self._registry if self._registry is not None \
            else get_registry()

    @property
    def enabled(self) -> bool:
        return self.hz > 0

    # ---- lifecycle ----

    def start(self) -> "SamplingProfiler":
        """Spawn the sampler thread (no-op when disabled). Daemon +
        joined-on-close: it must never block interpreter exit, and
        close() joins it so drain leaves no thread mutating the
        table."""
        if not self.enabled or self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="goleft-profiler")
        self._thread.start()
        return self

    def close(self) -> None:
        """Stop and join the sampler (idempotent)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def _run(self) -> None:
        interval = 1.0 / self.hz
        while not self._stop.wait(interval):
            self._sample_once()

    # ---- sampling ----

    def _sample_once(self) -> int:
        """Take one sample of every thread but our own; returns the
        number of stacks recorded (the overhead test drives this
        directly)."""
        me = threading.get_ident()
        frames = self._frames()
        active = {}
        if self._tracer is not None:
            active = self._tracer.active_traces()
        collapsed = []
        for tid, frame in frames.items():
            if tid == me:
                continue
            collapsed.append((collapse_frame(frame, self._memo),
                              active.get(tid)))
        dropped = 0
        with self._lock:
            self._samples_total += 1
            for stack, trace_id in collapsed:
                cnt = self._stacks.get(stack)
                if cnt is None:
                    if len(self._stacks) >= self.max_stacks:
                        dropped += 1
                        continue
                    self._stacks[stack] = 1
                else:
                    self._stacks[stack] = cnt + 1
                if trace_id is not None \
                        and len(self._trace_ids) < 256:
                    self._trace_ids[trace_id] = \
                        self._trace_ids.get(trace_id, 0) + 1
            self._stacks_dropped += dropped
        reg = self._reg()
        reg.counter("profiler.samples_total").inc()
        if dropped:
            reg.counter("profiler.stacks_dropped_total").inc(dropped)
        return len(collapsed)

    # ---- snapshots / windows ----

    def snapshot(self) -> dict:
        """The cumulative table (sorted stacks: deterministic
        serialization, same discipline as the metrics registry)."""
        with self._lock:
            stacks = dict(sorted(self._stacks.items()))
            traces = dict(sorted(self._trace_ids.items()))
            return {
                "schema": PROFILE_SCHEMA,
                "enabled": self.enabled,
                "hz": self.hz,
                "samples_total": self._samples_total,
                "stacks_dropped": self._stacks_dropped,
                "stacks": stacks,
                "trace_ids": traces,
            }

    def collect(self, seconds: float) -> dict:
        """Collect-then-respond: the delta the window accumulated —
        what ``GET /debug/profile?seconds=N`` returns. Disabled
        profiler -> an honest empty document (enabled: false), never
        an error: the fleet rollup must merge mixed fleets."""
        seconds = max(0.0, min(float(seconds), MAX_WINDOW_S))
        if not self.enabled:
            return self.snapshot()
        before = self.snapshot()
        deadline = self._clock() + seconds
        while self._clock() < deadline:
            if self._stop.wait(min(0.05, seconds)):
                break
        after = self.snapshot()
        return diff_profiles(before, after)


def diff_profiles(before: dict, after: dict) -> dict:
    """after - before, stack-wise (a window over the cumulative
    table). Counts are clamped at zero defensively — the table only
    grows, so a negative delta would mean a reset mid-window."""
    stacks = {}
    for k, v in after["stacks"].items():
        d = v - before["stacks"].get(k, 0)
        if d > 0:
            stacks[k] = d
    traces = {}
    for k, v in after["trace_ids"].items():
        d = v - before["trace_ids"].get(k, 0)
        if d > 0:
            traces[k] = d
    return {
        "schema": PROFILE_SCHEMA,
        "enabled": after["enabled"],
        "hz": after["hz"],
        "samples_total": max(
            0, after["samples_total"] - before["samples_total"]),
        "stacks_dropped": max(
            0, after["stacks_dropped"] - before["stacks_dropped"]),
        "stacks": dict(sorted(stacks.items())),
        "trace_ids": dict(sorted(traces.items())),
    }


def merge_profiles(bodies: list[dict]) -> dict:
    """Stack-wise counter merge across workers: exact arithmetic sums
    (the PR-13 metrics-rollup discipline — pinned by test to equal
    the sum of the inputs), sample/drop totals summed, trace ids
    unioned. ``per_worker`` is the caller's to attach."""
    stacks: dict[str, int] = {}
    traces: dict[str, int] = {}
    samples = dropped = 0
    hz = 0.0
    enabled = False
    for b in bodies:
        if not isinstance(b, dict) or "stacks" not in b:
            continue
        enabled = enabled or bool(b.get("enabled"))
        hz = max(hz, float(b.get("hz") or 0.0))
        samples += int(b.get("samples_total") or 0)
        dropped += int(b.get("stacks_dropped") or 0)
        for k, v in b["stacks"].items():
            stacks[k] = stacks.get(k, 0) + int(v)
        for k, v in (b.get("trace_ids") or {}).items():
            traces[k] = traces.get(k, 0) + int(v)
    return {
        "schema": PROFILE_SCHEMA,
        "enabled": enabled,
        "hz": hz,
        "samples_total": samples,
        "stacks_dropped": dropped,
        "stacks": dict(sorted(stacks.items())),
        "trace_ids": dict(sorted(traces.items())),
    }


def to_collapsed(doc: dict) -> str:
    """The flamegraph-compatible collapsed text form: one
    ``stack count`` line per distinct stack, sorted — feed it
    straight to flamegraph.pl / speedscope / inferno."""
    lines = [f"{stack} {count}"
             for stack, count in sorted(doc["stacks"].items())]
    return "\n".join(lines) + ("\n" if lines else "")
