"""Structured fleet event journal: lifecycle transitions, durable.

The supervisor's state machine (spawn, death, backoff, hang-kill,
quarantine, scale up/down, drain) is the fleet's incident narrative —
and until now it lived only in log lines. This module writes it as an
fsync'd append-only ``events.jsonl`` using the checkpoint journal's
exact durability protocol (one JSON object per line, flush + fsync per
append, torn-tail-tolerant replay via
:func:`~goleft_tpu.resilience.checkpoint.iter_journal_lines`), so the
sequence of events survives a SIGKILLed supervisor and is replayable
after restart: a torn final line — the only artifact a crash
mid-append can leave — is skipped, everything before it is intact.

One record per event, schema-stable (``goleft-tpu.fleet-event/1``)::

    {"schema": "goleft-tpu.fleet-event/1", "t": <epoch seconds>,
     "ts": "<UTC ISO8601>", "type": "<spawn|death|backoff|hang_kill|
     quarantine|scale_up|scale_down|drain|restart>",
     "slot": <int|null>, "worker": "<url|null>", "pid": <int|null>,
     "trace_id": "<id|null>", ...free-form detail fields}

Query via :func:`read_events` (the ``goleft-tpu fleet events`` body)
with ``--since/--slot/--type`` filters; the router surfaces a bounded
``fleet.events`` block (per-type counts + the most recent events) in
its ``/metrics`` body.
"""

from __future__ import annotations

import datetime
import json
import os
import threading
import time
from collections import deque

SCHEMA = "goleft-tpu.fleet-event/1"

#: the canonical journal filename under a fleet's state directory
EVENTS_NAME = "events.jsonl"

#: event types the supervisor emits (free-form types are allowed —
#: the reader filters by string equality — but these are the contract)
EVENT_TYPES = ("spawn", "restart", "death", "backoff", "hang_kill",
               "quarantine", "scale_up", "scale_down", "drain",
               "spawn_failure", "stop", "memory_recycle")


class EventJournal:
    """Append-only, fsync-per-append event sink.

    Opens in append mode — a restarted supervisor CONTINUES the same
    journal (the whole point: the incident narrative spans restarts).
    A torn tail left by a crash is the reader's business
    (:func:`read_events` tolerates it); appends after one are fine —
    each record is its own line, so one garbled line never corrupts
    its neighbors. Thread-safe; close() is idempotent.
    """

    def __init__(self, path: str):
        self.path = path
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._fh = open(path, "a")
        # a torn tail has no trailing newline: start our first append
        # on a fresh line so the reader sees ONE garbled line, not a
        # torn fragment fused to a valid record
        if self._fh.tell() > 0:
            with open(path, "rb") as rf:
                rf.seek(-1, os.SEEK_END)
                if rf.read(1) != b"\n":
                    self._fh.write("\n")
        self._lock = threading.Lock()

    def append(self, type: str, *, slot: int | None = None,
               worker: str | None = None, pid: int | None = None,
               trace_id: str | None = None, **detail) -> dict:
        """Durably append one event; returns the record written."""
        now = time.time()
        rec = {
            "schema": SCHEMA,
            "t": round(now, 3),
            "ts": datetime.datetime.fromtimestamp(
                now, datetime.timezone.utc)
            .isoformat(timespec="milliseconds"),
            "type": type,
            "slot": slot,
            "worker": worker,
            "pid": pid,
            "trace_id": trace_id,
        }
        rec.update(detail)
        line = json.dumps(rec, sort_keys=True) + "\n"
        with self._lock:
            if self._fh.closed:
                return rec  # racing a close(): drop, never crash
            self._fh.write(line)
            self._fh.flush()
            os.fsync(self._fh.fileno())
        return rec

    def close(self) -> None:
        with self._lock:
            if not self._fh.closed:
                self._fh.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def read_events(path: str, since: float | None = None,
                slot: int | None = None,
                type: str | None = None) -> list[dict]:
    """Replay ``events.jsonl`` (torn tail tolerated — the checkpoint
    journal's reader), filtered: ``since`` is an epoch-seconds lower
    bound on ``t``, ``slot``/``type`` match exactly. Records come
    back in journal (= chronological) order."""
    from ..resilience.checkpoint import iter_journal_lines

    out = []
    # stop_on_torn=False: a restarted supervisor appends PAST the torn
    # line its predecessor's crash left — skip the fragment, keep the
    # rest of the narrative
    for rec in iter_journal_lines(path, stop_on_torn=False):
        if not isinstance(rec, dict):
            continue
        if since is not None and (rec.get("t") or 0) < since:
            continue
        if slot is not None and rec.get("slot") != slot:
            continue
        if type is not None and rec.get("type") != type:
            continue
        out.append(rec)
    return out


def parse_since(value: str) -> float:
    """``--since`` grammar: epoch seconds (``1723400000``), a relative
    window (``30s``/``15m``/``2h``/``1d`` ago), or an ISO8601 stamp —
    returns the epoch-seconds lower bound."""
    value = value.strip()
    if value and value[-1] in "smhd":
        try:
            n = float(value[:-1])
        except ValueError:
            raise ValueError(f"bad --since window: {value!r}")
        mult = {"s": 1, "m": 60, "h": 3600, "d": 86400}[value[-1]]
        return time.time() - n * mult
    try:
        return float(value)
    except ValueError:
        pass
    try:
        dt = datetime.datetime.fromisoformat(value)
    except ValueError:
        raise ValueError(
            f"bad --since value: {value!r} (want epoch seconds, "
            "a relative window like 15m, or ISO8601)")
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=datetime.timezone.utc)
    return dt.timestamp()


class EventLog:
    """The supervisor-facing fan-out: every event goes to the durable
    journal (when configured), a bounded in-memory recent ring (the
    router's ``fleet.events`` /metrics block) and per-type counters in
    the metrics registry (``fleet.events_total.<type>``)."""

    def __init__(self, journal: EventJournal | None = None,
                 registry=None, recent: int = 64):
        self.journal = journal
        self.registry = registry
        self._recent: deque[dict] = deque(maxlen=recent)
        self._lock = threading.Lock()

    def emit(self, type: str, **fields) -> None:
        if self.journal is not None:
            rec = self.journal.append(type, **fields)
        else:
            rec = {"schema": SCHEMA, "t": round(time.time(), 3),
                   "type": type, **fields}
        if self.registry is not None:
            self.registry.counter(f"fleet.events_total.{type}").inc()
        with self._lock:
            self._recent.append(rec)

    def block(self) -> dict:
        """The ``fleet.events`` /metrics block: per-type counts over
        this process's lifetime + the newest events (newest first)."""
        with self._lock:
            recent = list(self._recent)[::-1]
        counts: dict[str, int] = {}
        for r in recent:
            counts[r.get("type", "?")] = \
                counts.get(r.get("type", "?"), 0) + 1
        return {
            "journal": self.journal.path if self.journal else None,
            "recent": recent[:16],
            "recent_counts": dict(sorted(counts.items())),
        }

    def close(self) -> None:
        if self.journal is not None:
            self.journal.close()
