"""The compile observatory: every jit cache miss is a recorded event.

Until now the only compile evidence in the tree was the bench's
ad-hoc log handler — serve workers re-jitted their whole bucketed
program portfolio on every restart and nobody could say what it cost
or which signatures were hot. This module makes compilation a
first-class, mergeable signal:

  - :class:`CompileTracker` (one per process, :data:`TRACKER`) is fed
    by the existing dispatch seams — ``obs.InstrumentedDispatch``,
    the pairhmm/rANS bucket dispatches, the serve executors' device
    stage (``plan/executor.py run_device_step``) — through
    :meth:`CompileTracker.observe`, a context manager around one
    dispatch;
  - a miss is detected two independent ways: a ``_cache_size()``
    delta on the wrapped jit (exact, when the seam holds the jit
    object) and the ``jax_log_compiles`` WARNING records ("Compiling
    <name> with global shapes..." from jax._src.interpreters.pxla)
    attributed to the innermost active observation on the emitting
    thread — jax compiles synchronously on the dispatching thread, so
    thread-local attribution is sound. A compile seen by both
    detectors is counted once (``max``, not sum);
  - every miss becomes a structured :class:`CompileEvent` (program
    family, bucket signature, backend, wall duration, pid, trigger
    context), flows into the registry
    (``compile.events_total.<family>``,
    ``compile.seconds_total.<family>``, gauge
    ``compile.signatures_live``), and is recorded post-hoc as an
    ``xla.compile.<family>`` span nested inside whatever span was
    open at the dispatch — so stitched traces and flight trees show
    compile storms inline;
  - the accumulated (family, signature, backend) table is the
    **warmup manifest** (``goleft-tpu.warmup-manifest/1``): hot
    signatures ranked by hit count x compile cost, written atomically
    (tmp + fsync + rename) and merged-on-update — the exact artifact
    the ROADMAP "Elastic warm-start" item pre-compiles from. Served
    live at ``GET /debug/compiles``; exported/merged by ``goleft-tpu
    warmup export``.

The log hook is installed lazily by the first ``observe()`` that runs
with jax already imported (never imports jax itself — the jax-free
router/fleet processes import this module); ``GOLEFT_TPU_NO_COMPILE_
HOOK=1`` keeps jax logging untouched, degrading detection to the
cache-delta path. A "Compiling" record with no active observation is
still recorded (family ``unattributed``) — the observatory is
process-wide, not seam-wide.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass, field

from .metrics import get_registry
from .tracing import get_tracer

#: warmup-manifest schema id. /1 is the first version; a consumer
#: (the future warm-start path) must reject other majors loudly.
WARMUP_SCHEMA = "goleft-tpu.warmup-manifest/1"

#: bounded structured-event ring (a long-lived serve daemon must not
#: grow per-compile state; compiles are rare after warmup anyway)
MAX_EVENTS = 512

#: bounded distinct-signature table — same spirit as the rANS
#: MAX_BUCKET_SIGNATURES cap: beyond this the long tail is dropped
#: (and counted), never the process's memory
MAX_SIGNATURES = 1024


def family_of_dispatch(name: str) -> str:
    """Map a dispatch-span name onto its program family:
    ``serve.depth.dispatch`` -> ``depth``; anything else (a jit's own
    name like ``shard_depth_pipeline_cls_packed``) passes through."""
    fam = name
    if fam.startswith("serve."):
        fam = fam[len("serve."):]
    if fam.endswith(".dispatch"):
        fam = fam[:-len(".dispatch")]
    return fam


def canonical_signature(sig) -> str:
    """One stable string per bucket signature: JSON with tuples
    lowered to lists, sorted keys — the content key the warmup
    manifest and the merge are keyed by. ``None`` -> "" (a seam with
    no bucket geometry, e.g. a wrapped jit observed only by name)."""
    if sig is None:
        return ""
    if isinstance(sig, str):
        return sig

    def lower(x):
        if isinstance(x, (list, tuple)):
            return [lower(v) for v in x]
        if isinstance(x, dict):
            return {str(k): lower(v) for k, v in sorted(x.items())}
        if isinstance(x, (int, float, bool)) or x is None:
            return x
        return str(x)

    return json.dumps(lower(sig), sort_keys=True,
                      separators=(",", ":"))


@dataclass
class CompileEvent:
    """One detected jit cache miss (one observation window may carry
    several compiles — ``compiles`` counts them; the wall duration is
    the observation's, which a cold dispatch is dominated by)."""

    family: str
    signature: str
    backend: str
    duration_s: float
    compiles: int
    pid: int
    trigger: str
    ts: float  # epoch seconds
    names: tuple = ()  # jit names from the log detector, bounded

    def to_dict(self) -> dict:
        return {
            "family": self.family, "signature": self.signature,
            "backend": self.backend,
            "duration_s": round(self.duration_s, 6),
            "compiles": self.compiles, "pid": self.pid,
            "trigger": self.trigger, "ts": round(self.ts, 3),
            "names": list(self.names),
        }


class _Observation:
    """The thread-local record of one in-flight observe() window."""

    __slots__ = ("family", "signature", "trigger", "log_names")

    def __init__(self, family: str, signature: str, trigger: str):
        self.family = family
        self.signature = signature
        self.trigger = trigger
        self.log_names: list[str] = []


class _ObsStack(threading.local):
    def __init__(self):
        self.stack: list[_Observation] = []


class CompileTracker:
    """Process-wide compile accounting: the observe() seam, the
    structured event ring, the (family, signature, backend) stats
    table behind /debug/compiles and the warmup manifest."""

    def __init__(self, registry=None, tracer=None):
        self._lock = threading.Lock()
        self._ctx = _ObsStack()
        self._events: deque[CompileEvent] = deque(maxlen=MAX_EVENTS)
        # (family, signature, backend) -> {hits, compiles, seconds}
        self._stats: dict[tuple, dict] = {}
        self.events_total = 0
        self.compiles_total = 0
        self.signatures_dropped = 0
        self._registry = registry
        self._tracer = tracer
        self._backend: str | None = None
        # count_compiles() windows: name lists the log hook feeds
        self._windows: list[list[str]] = []

    # the registry/tracer default to the process-wide singletons but
    # resolve lazily so a test tracker can inject private ones
    def _reg(self):
        return self._registry if self._registry is not None \
            else get_registry()

    def _trc(self):
        return self._tracer if self._tracer is not None \
            else get_tracer()

    # ---- backend provenance (cached once; jax is loaded by the time
    # a compile can happen) ----

    def _backend_name(self) -> str:
        if self._backend is None:
            if "jax" not in sys.modules:
                return ""  # not cached: jax may load later
            try:
                import jax

                self._backend = jax.devices()[0].platform
            except Exception:  # noqa: BLE001 — provenance must never
                self._backend = "unknown"  # fail the dispatch
        return self._backend

    # ---- the observe() seam ----

    @contextlib.contextmanager
    def observe(self, family: str, signature=None, cache_size_fn=None,
                trigger: str = ""):
        """Wrap ONE dispatch: always counts a hit for (family,
        signature); when a compile is detected (cache-size delta
        and/or attributed log records), records the CompileEvent, the
        registry counters and the nested ``xla.compile.<family>``
        span. Exceptions pass through untouched — a failed dispatch
        that compiled first still cost the compile."""
        ensure_log_hook()
        ob = _Observation(family, canonical_signature(signature),
                          trigger or family)
        size0 = None
        if cache_size_fn is not None:
            try:
                size0 = int(cache_size_fn())
            except Exception:  # noqa: BLE001 — private-ish jax API
                size0 = None
        self._ctx.stack.append(ob)
        t0 = time.perf_counter()
        try:
            yield ob
        finally:
            t1 = time.perf_counter()
            self._ctx.stack.pop()
            delta = 0
            if size0 is not None:
                try:
                    delta = max(0, int(cache_size_fn()) - size0)
                except Exception:  # noqa: BLE001 — same API caveat
                    delta = 0
            # one compile seen by both detectors is ONE compile
            n = max(delta, len(ob.log_names))
            self._record(ob, n, t0, t1)

    def _record(self, ob: _Observation, n: int, t0: float,
                t1: float) -> None:
        key = (ob.family, ob.signature, self._backend_name())
        wall = t1 - t0
        with self._lock:
            rec = self._stats.get(key)
            if rec is None:
                if len(self._stats) >= MAX_SIGNATURES:
                    self.signatures_dropped += 1
                    if n == 0:
                        return
                    # a COMPILING signature always lands (evict
                    # nothing: compiles are the signal; the cap
                    # protects against hit-only cardinality)
                self._stats[key] = rec = {
                    "hits": 0, "compiles": 0, "compile_seconds": 0.0}
            rec["hits"] += 1
            if n:
                rec["compiles"] += n
                rec["compile_seconds"] += wall
                self.events_total += 1
                self.compiles_total += n
                ev = CompileEvent(
                    family=ob.family, signature=ob.signature,
                    backend=key[2], duration_s=wall, compiles=n,
                    pid=os.getpid(), trigger=ob.trigger,
                    ts=time.time(), names=tuple(ob.log_names[:8]))
                self._events.append(ev)
                live = sum(1 for r in self._stats.values()
                           if r["compiles"] > 0)
        if not n:
            return
        reg = self._reg()
        reg.counter(f"compile.events_total.{ob.family}").inc(n)
        reg.counter(f"compile.seconds_total.{ob.family}").inc(
            round(wall, 6))
        reg.gauge("compile.signatures_live").set(live)
        # the post-hoc span: parented under whatever span is open on
        # this thread RIGHT NOW — observe() runs inside the device
        # dispatch span, so flight trees and stitched traces show the
        # compile nested where the time actually went
        self._trc().record_span(
            f"xla.compile.{ob.family}", t0, t1, category="compile",
            family=ob.family, signature=ob.signature,
            compiles=n, backend=key[2], trigger=ob.trigger)

    # ---- the log-hook feed ----

    def _on_compile_log(self, name: str) -> None:
        """One ``jax_log_compiles`` WARNING record: attribute it to
        the emitting thread's innermost observation, or record it
        unattributed — the observatory misses nothing either way."""
        self._reg().counter("xla.compiles_total").inc()
        with self._lock:
            for w in self._windows:
                w.append(name)
        stack = self._ctx.stack
        if stack:
            stack[-1].log_names.append(name)
            return
        # no seam around this compile (warmup pass, a direct jit):
        # synthesize a zero-length observation so it still lands in
        # the stats/events/counters
        ob = _Observation("unattributed", "", name)
        ob.log_names.append(name)
        t = time.perf_counter()
        self._record(ob, 1, t, t)

    # ---- bench windows ----

    @contextlib.contextmanager
    def window(self):
        """Collect every compile-log name recorded while the window
        is open (the bench's ``_count_compiles`` contract: ``.names``
        on the yielded handle)."""
        names: list[str] = []

        class _Handle:
            pass

        h = _Handle()
        h.names = names
        with self._lock:
            self._windows.append(names)
        try:
            yield h
        finally:
            with self._lock:
                self._windows.remove(names)

    # ---- inspection / export ----

    def stats(self) -> dict[tuple, dict]:
        with self._lock:
            return {k: dict(v) for k, v in self._stats.items()}

    def recent_events(self, n: int = 64) -> list[dict]:
        with self._lock:
            evs = list(self._events)[-n:]
        return [e.to_dict() for e in evs]

    def to_doc(self) -> dict:
        """The ``GET /debug/compiles`` body: the ranked warmup
        manifest plus the recent structured events and totals."""
        doc = build_warmup_manifest(self.stats())
        with self._lock:
            doc.update(
                events_total=self.events_total,
                compiles_total=self.compiles_total,
                signatures_dropped=self.signatures_dropped,
                pid=os.getpid(),
            )
        doc["events"] = self.recent_events()
        return doc

    def manifest_section(self) -> dict | None:
        """The run manifest's ``compiles`` block (omitted when the
        run never compiled anything — most warm-path invocations)."""
        stats = self.stats()
        if not any(r["compiles"] for r in stats.values()):
            return None
        top = build_warmup_manifest(stats)["signatures"][:16]
        with self._lock:
            return {
                "events_total": self.events_total,
                "compiles_total": self.compiles_total,
                "seconds_total": round(
                    sum(r["compile_seconds"]
                        for r in stats.values()), 4),
                "signatures": top,
            }

    def reset(self) -> None:
        """Tests only."""
        with self._lock:
            self._events.clear()
            self._stats.clear()
            self.events_total = 0
            self.compiles_total = 0
            self.signatures_dropped = 0


#: the process-wide tracker every dispatch seam feeds
TRACKER = CompileTracker()


def get_tracker() -> CompileTracker:
    return TRACKER


@contextlib.contextmanager
def observe(family: str, signature=None, cache_size_fn=None,
            trigger: str = ""):
    """Module-level convenience over :data:`TRACKER`."""
    with TRACKER.observe(family, signature=signature,
                         cache_size_fn=cache_size_fn,
                         trigger=trigger) as ob:
        yield ob


# ------------------------------------------------- jax log-hook plumbing

class _JaxCompileLogHandler(logging.Handler):
    """The jax_log_compiles WARNING feed ("Compiling <name> with
    global shapes..." from jax._src.interpreters.pxla). Fragile by
    nature — a jax upgrade can rename logger or message — which is
    why every seam that can also passes ``cache_size_fn`` and the
    bench keeps its independent jit-cache cross-check."""

    def __init__(self, tracker: CompileTracker):
        super().__init__(level=logging.WARNING)
        self._tracker = tracker

    def emit(self, record):
        msg = record.getMessage()
        if msg.startswith("Compiling "):
            name = msg.split(" with ")[0][len("Compiling "):]
            self._tracker._on_compile_log(name)


_HOOK_LOCK = threading.Lock()
_HOOK: _JaxCompileLogHandler | None = None


def ensure_log_hook() -> bool:
    """Install the process-wide compile-log hook once jax is loaded.

    Never imports jax itself (jax-free routers call observe()-guarded
    paths too); a no-op until ``jax`` appears in sys.modules, then:
    ``jax_log_compiles=True``, a WARNING handler on logger "jax" with
    ``propagate=False`` (count quietly, don't spray stderr), and the
    ``jax._src.dispatch`` logger disabled (jax_log_compiles also
    elevates its per-op "Finished tracing/MLIR/XLA" chatter).
    ``GOLEFT_TPU_NO_COMPILE_HOOK=1`` opts out entirely."""
    global _HOOK
    if _HOOK is not None:
        return True
    if os.environ.get("GOLEFT_TPU_NO_COMPILE_HOOK"):
        return False
    if "jax" not in sys.modules:
        return False
    with _HOOK_LOCK:
        if _HOOK is not None:
            return True
        import jax

        try:
            jax.config.update("jax_log_compiles", True)
        except Exception:  # noqa: BLE001 — config drift: degrade to
            return False   # the cache-delta detector only
        lg = logging.getLogger("jax")
        if lg.level > logging.WARNING or lg.level == logging.NOTSET:
            lg.setLevel(logging.WARNING)
        lg.propagate = False
        h = _JaxCompileLogHandler(TRACKER)
        lg.addHandler(h)
        # jax's logging_config pins its own stderr StreamHandler
        # directly on logger "jax", so propagate=False alone still
        # sprays "Compiling fn with global shapes..." per cache miss;
        # drop exactly that handler (plain StreamHandler -> stderr),
        # leaving any user-attached file/custom handlers alone
        for other in list(lg.handlers):
            if other is not h \
                    and type(other) is logging.StreamHandler \
                    and getattr(other, "stream", None) is sys.stderr:
                lg.removeHandler(other)
        logging.getLogger("jax._src.dispatch").disabled = True
        _HOOK = h
    return True


@contextlib.contextmanager
def count_compiles():
    """The bench's compile window (bench.py ``_count_compiles``): a
    handle whose ``.names`` lists every jit name the log hook saw
    while the window was open. Imports jax (the bench already has)
    so the hook is live before the window starts."""
    import jax  # noqa: F401 — force the module into sys.modules

    ensure_log_hook()
    with TRACKER.window() as h:
        yield h


# ---------------------------------------------------- warmup manifest

def _rank_key(entry: dict):
    # hot first: hits x compile cost, compile count and hits as
    # tiebreakers, then the content key for full determinism
    return (-entry["hits"] * entry["compile_seconds"],
            -entry["compiles"], -entry["hits"],
            entry["family"], entry["signature"], entry["backend"])


def build_warmup_manifest(stats: dict[tuple, dict]) -> dict:
    """Rank a tracker stats table into the warmup-manifest document.
    Hit-only entries (never compiled in this process) are kept — a
    restarted worker WILL pay them — but rank below anything with a
    measured compile cost at equal hits."""
    sigs = []
    for (family, signature, backend), rec in stats.items():
        sigs.append({
            "family": family, "signature": signature,
            "backend": backend, "hits": int(rec["hits"]),
            "compiles": int(rec["compiles"]),
            "compile_seconds": round(
                float(rec["compile_seconds"]), 6),
        })
    sigs.sort(key=_rank_key)
    for i, s in enumerate(sigs):
        s["rank"] = i + 1
    return {"schema": WARMUP_SCHEMA, "signatures": sigs}


def validate_warmup_manifest(doc: dict) -> dict:
    """Schema-check a warmup manifest (load + merge + the smoke all
    go through here). Raises ValueError with a precise message."""
    if not isinstance(doc, dict):
        raise ValueError("warmup manifest: not a JSON object")
    if doc.get("schema") != WARMUP_SCHEMA:
        raise ValueError(
            f"warmup manifest: schema {doc.get('schema')!r}, want "
            f"{WARMUP_SCHEMA!r}")
    sigs = doc.get("signatures")
    if not isinstance(sigs, list):
        raise ValueError("warmup manifest: 'signatures' must be a "
                         "list")
    for s in sigs:
        if not isinstance(s, dict):
            raise ValueError("warmup manifest: signature entries "
                             "must be objects")
        for k, typ in (("family", str), ("signature", str),
                       ("backend", str), ("hits", int),
                       ("compiles", int),
                       ("compile_seconds", (int, float))):
            if not isinstance(s.get(k), typ) \
                    or isinstance(s.get(k), bool):
                raise ValueError(
                    f"warmup manifest: entry missing/bad {k!r}: "
                    f"{s.get(k)!r}")
        if s["hits"] < 0 or s["compiles"] < 0 \
                or s["compile_seconds"] < 0:
            raise ValueError(
                "warmup manifest: negative tallies are impossible "
                f"(entry {s['family']}/{s['signature']})")
    return doc


def merge_warmup_docs(*docs: dict) -> dict:
    """Merge-on-update: sum hits/compiles/seconds per (family,
    signature, backend) key and re-rank — every tally in the merge is
    >= its value in any input (monotonicity, pinned by test), so
    repeated exports only ever sharpen the manifest."""
    acc: dict[tuple, dict] = {}
    for doc in docs:
        validate_warmup_manifest(doc)
        for s in doc["signatures"]:
            key = (s["family"], s["signature"], s["backend"])
            rec = acc.setdefault(key, {
                "hits": 0, "compiles": 0, "compile_seconds": 0.0})
            rec["hits"] += s["hits"]
            rec["compiles"] += s["compiles"]
            rec["compile_seconds"] += s["compile_seconds"]
    return build_warmup_manifest(acc)


def save_warmup_manifest(path: str, doc: dict) -> dict:
    """Atomic + durable write (tmp, fsync, rename): a SIGKILL at any
    instant leaves either the previous manifest or the new one —
    never a torn document. When ``path`` already holds a valid
    manifest the new doc is MERGED into it first (merge-on-update);
    an unreadable existing file is replaced, not crashed on."""
    validate_warmup_manifest(doc)
    try:
        doc = merge_warmup_docs(load_warmup_manifest(path), doc)
    except (OSError, ValueError):
        pass  # no/invalid predecessor: this doc IS the manifest
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    return doc


def load_warmup_manifest(path: str) -> dict:
    with open(path) as fh:
        return validate_warmup_manifest(json.load(fh))


# the run manifest's `compiles` section: a run that compiled anything
# documents what and how long (None -> omitted for warm runs)
from .manifest import register_section  # noqa: E402 — import cycle
# guard: manifest.py imports only metrics/provenance/tracing

register_section("compiles", lambda: TRACKER.manifest_section())
