"""Backend/platform provenance: the ONE place that answers "what ran
this" — shared by the run manifest, the device-event spans and the
bench artifacts, so their platform/device fields can never drift apart
(the ROADMAP's device-evidence gap was exactly three instruments
answering that question separately).
"""

from __future__ import annotations

import threading

_lock = threading.Lock()
_cached: dict | None = None


def backend_provenance(refresh: bool = False) -> dict:
    """{platform, device, device_kind, device_count, jax} for the live
    backend — or an ``{"error": ...}`` record when no backend comes up
    (provenance must never crash the run it describes).

    Cached after the first successful look: the answer cannot change
    within a process, and the hot device-span path reads it per
    dispatch. NOTE: calling this initializes the jax backend — CLI
    paths only reach it after device_guard bring-up.
    """
    global _cached
    with _lock:
        if _cached is not None and not refresh:
            return dict(_cached)
    try:
        import jax

        devs = jax.devices()
        rec = {
            "platform": devs[0].platform,
            "device": str(devs[0]),
            "device_kind": devs[0].device_kind,
            "device_count": len(devs),
            "jax": jax.__version__,
        }
    except Exception as e:  # noqa: BLE001 — degrade, don't crash
        return {"error": repr(e)}
    with _lock:
        _cached = rec
    return dict(rec)


def device_span_attrs() -> dict:
    """The attribute set every device-event span carries: backend,
    platform and device kind (the honest-evidence contract — a span
    that says 'compute' without saying on WHAT is how stale chip
    numbers survive three rounds)."""
    prov = backend_provenance()
    if "error" in prov:
        return {"platform": "unavailable"}
    return {"platform": prov["platform"],
            "device_kind": prov["device_kind"],
            "device_count": prov["device_count"]}


def env_provenance() -> dict:
    """Host/environment block for the run manifest."""
    import os
    import platform as _platform
    import sys

    try:
        cores = len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        cores = os.cpu_count()
    rec = {
        "python": sys.version.split()[0],
        "machine": _platform.machine(),
        "node": _platform.node(),
        "effective_cores": cores,
        "pid": os.getpid(),
    }
    knobs = {k: v for k, v in os.environ.items()
             if k.startswith(("GOLEFT_TPU_", "JAX_PLATFORM"))}
    if knobs:
        rec["env_knobs"] = knobs
    return rec
