"""goleft_tpu.obs — the unified tracing & metrics subsystem.

One observability layer for every execution path (CLI one-shot,
prefetched cohort, warm serve batch):

  - :mod:`~goleft_tpu.obs.tracing` — run-scoped hierarchical spans
    with cross-thread propagation + Chrome/Perfetto export
    (``--trace-out``)
  - :mod:`~goleft_tpu.obs.metrics` — the process-wide registry of
    counters/gauges/histograms (``--metrics-out``, serve /metrics)
  - :mod:`~goleft_tpu.obs.provenance` — the one backend/platform
    answer the manifest, the device spans and the bench all share
  - :mod:`~goleft_tpu.obs.manifest` — the per-run evidence document
  - :mod:`~goleft_tpu.obs.logging` — ``goleft-tpu.*`` logger tree +
    the CLI's ``--log-level`` config
  - :mod:`~goleft_tpu.obs.ledger` / :mod:`~goleft_tpu.obs.sentinel` —
    the longitudinal perf ledger (``PERF_LEDGER.jsonl``) and the
    regression sentinel behind ``goleft-tpu perf``
  - :mod:`~goleft_tpu.obs.prometheus` — text-exposition rendering of
    a registry snapshot (the serve daemon's ``/metrics?format=prom``)

Import is jax-free and cheap (the CLI touches this before backend
bring-up); anything needing jax resolves it lazily per call.
"""

from __future__ import annotations

import contextlib

from .logging import configure as configure_logging, get_logger
from .metrics import (  # noqa: F401 — public API
    Counter, Gauge, Histogram, MetricsRegistry, REGISTRY, get_registry,
)
from .provenance import (  # noqa: F401
    backend_provenance, device_span_attrs, env_provenance,
)
from .tracing import (  # noqa: F401
    Span, SpanContext, TRACER, Tracer, get_tracer,
)

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "REGISTRY",
    "Span", "SpanContext", "TRACER", "Tracer",
    "backend_provenance", "configure_logging", "capture", "attach",
    "device_span", "device_span_attrs", "dispatch", "env_provenance",
    "get_logger", "get_registry", "get_tracer", "span", "trace",
]


# ---- ambient-tracer conveniences (the process tracer) ----

def span(name: str, category: str = "", **attrs):
    """Context manager: a span on the process tracer."""
    return TRACER.span(name, category=category, **attrs)


def trace(name: str, kind: str = "run", trace_id: str | None = None,
          remote_parent: int | None = None, **attrs):
    """Context manager: a run-scoped root span + fresh trace id (or an
    ADOPTED one — ``trace_id``/``remote_parent`` attach the remote
    context a forwarded ``x-goleft-trace`` header carries)."""
    return TRACER.trace(name, kind=kind, trace_id=trace_id,
                        remote_parent=remote_parent, **attrs)


def capture() -> "SpanContext":
    return TRACER.capture()


def attach(ctx: "SpanContext | None"):
    return TRACER.attach(ctx)


# ---- device-event instrumentation ----

def device_events_enabled() -> bool:
    return TRACER.device_events


def set_device_events(enabled: bool) -> None:
    """Turn per-dispatch fencing on/off (the CLI's ``--trace-out``
    sets it; GOLEFT_TPU_DEVICE_EVENTS=1 preseeds it)."""
    TRACER.device_events = bool(enabled)


def device_span(name: str, **attrs):
    """A span carrying the backend/platform/device-kind attribute set
    — for dispatch sites that already synchronize (np.asarray fetches
    etc.), where no extra fence is needed for the time to be honest."""
    return TRACER.span(name, category="device",
                       **device_span_attrs(), **attrs)


def _under_jit_trace() -> bool:
    """True when called during jax tracing (vmap/jit of a wrapped
    dispatch): instrumenting there would record compile-time as device
    time and bake a host callback into the program."""
    try:
        import jax

        return not jax.core.trace_state_clean()
    except Exception:  # noqa: BLE001 — jax version drift: stay safe
        return False


def dispatch(name: str, fn, *args, **kwargs):
    """Run ``fn(*args, **kwargs)`` as an honest device event.

    When device events are off (the default) this is a plain call —
    async dispatch keeps its pipelining. When on (``--trace-out`` /
    GOLEFT_TPU_DEVICE_EVENTS=1), the call is wrapped in a span with
    backend/platform/device-kind attributes and fenced with
    ``block_until_ready`` so the span's duration is the dispatch's
    device time, not the microseconds of enqueueing it.
    """
    if not TRACER.device_events or _under_jit_trace():
        return fn(*args, **kwargs)
    import jax

    with device_span(f"device.{name}", fenced=True):
        out = fn(*args, **kwargs)
        jax.block_until_ready(out)
    return out


class InstrumentedDispatch:
    """Transparent proxy over a jitted callable: ``__call__`` routes
    through :func:`dispatch`; every other attribute (``_cache_size``,
    ``lower``, …) forwards to the wrapped function, so compile-cache
    cross-checks and AOT tooling keep working."""

    def __init__(self, fn, name: str):
        self.__wrapped__ = fn
        self._obs_name = name
        self.__name__ = name
        self.__doc__ = getattr(fn, "__doc__", None)

    def __call__(self, *args, **kwargs):
        if _under_jit_trace():
            return self.__wrapped__(*args, **kwargs)
        from .compiles import TRACKER, family_of_dispatch
        from .memplane import TRACKER as MEM_TRACKER

        family = family_of_dispatch(self._obs_name)
        cache_size = getattr(self.__wrapped__, "_cache_size", None)
        # the memory plane shares this seam: buffers born during the
        # dispatch are attributed to its family (a bare yield until a
        # sampler arms the tracker)
        with TRACKER.observe(family,
                             cache_size_fn=cache_size,
                             trigger="dispatch"), \
                MEM_TRACKER.observe(family):
            return dispatch(self._obs_name, self.__wrapped__,
                            *args, **kwargs)

    def __getattr__(self, item):
        return getattr(self.__wrapped__, item)

    def __repr__(self):
        return f"InstrumentedDispatch({self.__wrapped__!r})"


@contextlib.contextmanager
def maybe_span(enabled: bool, name: str, **attrs):
    """span() when ``enabled``, else a no-op — for call sites whose
    instrumentation is conditional on a flag they already hold."""
    if not enabled:
        yield None
        return
    with span(name, **attrs) as sp:
        yield sp
