"""The memory plane: where the bytes go, host and device, mergeable.

The fleet can see time (trace stitching, the compile observatory, the
sampling profiler) but until now was blind to space: a worker that
OOM'd just died and got respawned with zero evidence of what grew.
This module makes memory a first-class, mergeable signal with the
same shape as the profiler:

  - **host collection is stdlib-only**: ``/proc/self/statm`` for RSS
    (one small read — cheap enough for per-span deltas),
    ``/proc/self/status`` ``VmHWM`` for the process high-water mark,
    ``/proc/self/smaps_rollup`` ``Pss`` when present; optional
    ``tracemalloc`` top-N allocation sites behind ``--mem-trace``;
  - **device accounting rides the existing dispatch seams**:
    :meth:`MemoryTracker.observe` wraps the same dispatches the
    compile observatory instruments (``obs.InstrumentedDispatch``,
    plan ``run_device_step``) and attributes every ``jax.live_arrays``
    buffer that APPEARED during the dispatch to that dispatch's
    family. A later scan drops attributions whose buffer died, so
    ``memory.device_live_bytes.<family>`` is live bytes, not a
    monotonic tally — it returns to baseline when the buffers do.
    jax is never imported here (the jax-free router/fleet processes
    import this module); everything device-side is gated on
    ``"jax" in sys.modules``;
  - **pressure is a two-sided hysteresis band** (the autoscaler's
    recover-below pattern): above ``high_water_bytes`` the controller
    trips and the serve daemon sheds best-effort admissions with 503 +
    ``retry_after_s``; it recovers only at/below ``low_water_bytes``,
    so a worker hovering at the cap doesn't flap. The prefetch
    staging pipeline reads the same state to clamp its depth, and the
    supervisor drains-and-recycles a worker past its hard cap
    (``memory_recycle`` in the event journal) instead of waiting for
    the kernel OOM killer;
  - **off costs nothing**: ``interval_s=0`` spawns no thread; the
    on-demand ``snapshot()`` behind ``GET /debug/memory`` still
    works, so the fleet surface never 404s on a worker that wasn't
    started with sampling.

The worker surface is ``GET /debug/memory``; the router merges bodies
at ``GET /fleet/memory`` (:func:`merge_memory`: counters as exact
arithmetic sums — the PR-13 rollup discipline, pinned by test in both
the JSON and Prometheus encodings — gauges as per-worker min/max/sum)
and the federation passes it through one level up. ``goleft-tpu
memory`` renders either view.
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading
import time

from .metrics import get_registry
from .tracing import get_tracer

#: response/document schema for /debug/memory and /fleet/memory
MEMORY_SCHEMA = "goleft-tpu.memory/1"

#: bounded per-family attribution table — same spirit as the compile
#: observatory's MAX_SIGNATURES cap: cardinality must never become
#: the leak the plane exists to catch
MAX_FAMILIES = 256

#: bounded live-buffer attribution table (ids of device arrays whose
#: birth we witnessed); beyond it new buffers go unattributed and are
#: counted, never stored
MAX_TRACKED_BUFFERS = 65536

#: tracemalloc top-N table size when --mem-trace is on
TRACE_TOP_N = 20

_PAGE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def read_host_memory(pss: bool = True) -> dict:
    """Current host memory of THIS process, stdlib-only.

    ``rss_bytes`` comes from ``/proc/self/statm`` (resident pages ×
    page size — one 32-byte read, cheap enough to run per span);
    ``rss_peak_bytes`` from ``/proc/self/status`` ``VmHWM`` (the
    kernel's process-lifetime high-water mark); ``pss_bytes`` from
    ``/proc/self/smaps_rollup`` when the kernel provides it (0
    otherwise). ``pss=False`` skips the rollup read — the kernel
    walks every VMA to answer it (~1.5ms on a loaded process, ~50×
    the rest of this function combined), so the periodic sampling
    tick passes False and only on-demand snapshots pay for Pss. On a
    platform without procfs every field is 0 and ``source`` says so —
    an honest empty, never an error, because the fleet rollup must
    merge mixed fleets."""
    out = {"rss_bytes": 0, "rss_peak_bytes": 0, "pss_bytes": 0,
           "source": "procfs"}
    try:
        with open("/proc/self/statm") as fh:
            out["rss_bytes"] = int(fh.read().split()[1]) * _PAGE
    except (OSError, IndexError, ValueError):
        out["source"] = "unavailable"
        return out
    try:
        with open("/proc/self/status") as fh:
            for line in fh:
                if line.startswith("VmHWM:"):
                    out["rss_peak_bytes"] = \
                        int(line.split()[1]) * 1024
                    break
    except (OSError, IndexError, ValueError):
        pass
    if not pss:
        return out
    try:
        with open("/proc/self/smaps_rollup") as fh:
            for line in fh:
                if line.startswith("Pss:"):
                    out["pss_bytes"] = int(line.split()[1]) * 1024
                    break
    except (OSError, IndexError, ValueError):
        pass
    return out


def quick_rss() -> int:
    """Just the resident byte count (the per-span delta probe)."""
    try:
        with open("/proc/self/statm") as fh:
            return int(fh.read().split()[1]) * _PAGE
    except (OSError, IndexError, ValueError):
        return 0


class PressureController:
    """Two-sided hysteresis over host RSS: trip above ``high``,
    recover only at/below ``low`` (the autoscaler band pattern — a
    worker hovering at the cap must not flap between shedding and
    admitting). ``high=0`` disables the controller entirely."""

    def __init__(self, high_water_bytes: int = 0,
                 low_water_bytes: int = 0,
                 retry_after_s: float = 1.0):
        if high_water_bytes and low_water_bytes > high_water_bytes:
            raise ValueError(
                f"memory pressure band inverted: low water "
                f"{low_water_bytes} > high water {high_water_bytes}")
        self.high_water_bytes = int(high_water_bytes)
        self.low_water_bytes = int(low_water_bytes) \
            or int(high_water_bytes * 0.8)
        self.retry_after_s = float(retry_after_s)
        self._tripped = False
        self._lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        return self.high_water_bytes > 0

    def update(self, rss_bytes: int) -> str:
        """Feed one RSS observation; returns the (possibly new)
        state, ``"ok"`` or ``"pressure"``."""
        if not self.enabled:
            return "ok"
        with self._lock:
            if self._tripped:
                if rss_bytes <= self.low_water_bytes:
                    self._tripped = False
            elif rss_bytes > self.high_water_bytes:
                self._tripped = True
            return "pressure" if self._tripped else "ok"

    @property
    def state(self) -> str:
        with self._lock:
            return "pressure" if self._tripped else "ok"

    def should_shed(self) -> bool:
        with self._lock:
            return self._tripped

    def to_dict(self) -> dict:
        return {
            "state": self.state,
            "high_water_bytes": self.high_water_bytes,
            "low_water_bytes": (self.low_water_bytes
                                if self.enabled else 0),
            "retry_after_s": self.retry_after_s,
        }


class MemoryTracker:
    """Process-wide device-buffer attribution: the observe() seam.

    Mirrors the compile observatory's design — a thread-local-free
    table fed by the dispatch seams, lazily jax-aware, singleton per
    process (:data:`TRACKER`). A buffer is attributed to the family
    of the dispatch during which it first appeared in
    ``jax.live_arrays()``; attributions die with their buffers at the
    next scan."""

    def __init__(self, registry=None):
        self._lock = threading.Lock()
        # id(array) -> (family, nbytes); ids of DEAD arrays are
        # pruned on every scan, so the table tracks live bytes
        self._attr: dict[int, tuple] = {}
        self._families: set[str] = set()
        self.buffers_dropped = 0
        self._registry = registry
        # off by default costs nothing: until an enabled
        # MemorySampler arms the tracker, observe() is a bare yield —
        # no live_arrays() walk on the dispatch hot path
        self.armed = False

    def _reg(self):
        return self._registry if self._registry is not None \
            else get_registry()

    @staticmethod
    def _live_arrays():
        if "jax" not in sys.modules:
            return []
        try:
            import jax

            return jax.live_arrays()
        except Exception:  # noqa: BLE001 — accounting must never
            return []      # fail the dispatch

    @staticmethod
    def _nbytes(a) -> int:
        try:
            return int(a.nbytes)
        except Exception:  # noqa: BLE001 — deleted/donated buffer
            return 0

    @staticmethod
    def _device_of(a) -> str:
        try:
            (dev,) = a.devices()
            return str(dev)
        except Exception:  # noqa: BLE001 — sharded or deleted
            return "sharded"

    @contextlib.contextmanager
    def observe(self, family: str):
        """Wrap ONE dispatch: buffers live after but not before are
        the family's. Exceptions pass through — a failed dispatch
        that allocated first still holds the bytes. A bare yield
        until armed (the dispatch hot path must not pay for a plane
        nobody started)."""
        if not self.armed:
            yield
            return
        before = {id(a) for a in self._live_arrays()}
        try:
            yield
        finally:
            born = [(id(a), self._nbytes(a))
                    for a in self._live_arrays()
                    if id(a) not in before]
            if born:
                with self._lock:
                    if len(self._families) < MAX_FAMILIES:
                        self._families.add(family)
                    for bid, nb in born:
                        if len(self._attr) >= MAX_TRACKED_BUFFERS:
                            self.buffers_dropped += len(born)
                            break
                        self._attr[bid] = (family, nb)

    def device_doc(self) -> dict:
        """Scan live arrays, prune dead attributions, return
        {total_bytes, by_device, by_family} (sorted keys —
        deterministic serialization) and publish the family gauges.
        A family whose buffers all died reports 0 (the leak
        sentinel's "returned to baseline" check reads exactly
        this)."""
        live = self._live_arrays()
        by_device: dict[str, int] = {}
        live_ids: dict[int, int] = {}
        total = 0
        for a in live:
            nb = self._nbytes(a)
            total += nb
            live_ids[id(a)] = nb
            dev = self._device_of(a)
            by_device[dev] = by_device.get(dev, 0) + nb
        by_family: dict[str, int] = {}
        with self._lock:
            self._attr = {bid: (fam, live_ids[bid])
                          for bid, (fam, _) in self._attr.items()
                          if bid in live_ids}
            for fam in self._families:
                by_family[fam] = 0
            for fam, nb in self._attr.values():
                by_family[fam] = by_family.get(fam, 0) + nb
            dropped = self.buffers_dropped
        reg = self._reg()
        reg.gauge("memory.device_live_bytes_total").set(total)
        for fam, nb in by_family.items():
            reg.gauge(f"memory.device_live_bytes.{fam}").set(nb)
        return {
            "total_bytes": total,
            "by_device": dict(sorted(by_device.items())),
            "by_family": dict(sorted(by_family.items())),
            "buffers_dropped": dropped,
        }


#: the process singleton the dispatch seams feed
TRACKER = MemoryTracker()


def get_tracker() -> MemoryTracker:
    return TRACKER


class MemorySampler:
    """The per-process memory observatory behind ``/debug/memory``.

    ``interval_s=0`` (the default) spawns no thread — a sampler
    nobody asked for costs literally nothing; ``snapshot()`` still
    answers on demand. ``high_water_bytes`` arms the pressure
    controller. ``trace_top > 0`` starts ``tracemalloc`` and ships
    the top-N allocation sites in every snapshot (``--mem-trace``:
    real overhead, opt-in only). ``clock`` is injectable for tests;
    ``registry=None`` publishes into the process registry."""

    def __init__(self, interval_s: float = 0.0, registry=None,
                 tracer=None, high_water_bytes: int = 0,
                 low_water_bytes: int = 0, trace_top: int = 0,
                 tracker: MemoryTracker | None = None, clock=None):
        if interval_s < 0:
            raise ValueError(
                f"memory sample interval must be >= 0 "
                f"(got {interval_s})")
        self.interval_s = float(interval_s)
        self.trace_top = int(trace_top)
        self._registry = registry
        self._tracer = tracer
        self._tracker = tracker if tracker is not None else TRACKER
        self._clock = clock if clock is not None else time.monotonic
        self.pressure = PressureController(
            high_water_bytes=high_water_bytes,
            low_water_bytes=low_water_bytes)
        self._lock = threading.Lock()
        self._samples_total = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._trace_started = False

    def _reg(self):
        return self._registry if self._registry is not None \
            else get_registry()

    @property
    def enabled(self) -> bool:
        return self.interval_s > 0

    # ---- lifecycle ----

    def start(self) -> "MemorySampler":
        """Spawn the sampler thread (no-op when disabled). Daemon +
        joined-on-close, the thr-unjoined contract every serve daemon
        thread follows. Arms the per-span memory probe on the tracer
        so flight trees carry byte deltas alongside wall time —
        exactly while a sampler is running, so the Perfetto goldens
        of unsampled runs stay byte-stable."""
        if self.trace_top > 0 and not self._trace_started:
            import tracemalloc

            if not tracemalloc.is_tracing():
                tracemalloc.start()
                self._trace_started = True
        trc = self._tracer if self._tracer is not None \
            else get_tracer()
        if self.enabled:
            trc.mem_probe = quick_rss
            # arm family attribution process-wide (never disarmed: a
            # process that asked for the plane once keeps it — the
            # table is bounded and scans are per-dispatch only)
            self._tracker.armed = True
        if not self.enabled or self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="goleft-memplane")
        self._thread.start()
        return self

    def close(self) -> None:
        """Stop and join the sampler; disarm the span probe
        (idempotent)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        trc = self._tracer if self._tracer is not None \
            else get_tracer()
        if getattr(trc, "mem_probe", None) is quick_rss:
            trc.mem_probe = None
        if self._trace_started:
            import tracemalloc

            tracemalloc.stop()
            self._trace_started = False

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.sample_once()

    # ---- sampling ----

    def sample_once(self, pss: bool = False) -> dict:
        """Take one sample: host RSS/peak into the gauges, a device
        live-buffer scan, one pressure-band evaluation. Returns the
        host dict (the overhead bench drives this directly). The
        periodic tick skips the expensive smaps_rollup Pss read —
        see :func:`read_host_memory`."""
        host = read_host_memory(pss=pss)
        reg = self._reg()
        reg.gauge("memory.rss_bytes").set(host["rss_bytes"])
        reg.gauge("memory.rss_peak_bytes").set(host["rss_peak_bytes"])
        state = self.pressure.update(host["rss_bytes"])
        reg.gauge("memory.pressure_state").set(
            1.0 if state == "pressure" else 0.0)
        self._tracker.device_doc()
        with self._lock:
            self._samples_total += 1
        reg.counter("memory.samples_total").inc()
        return host

    def _tracemalloc_top(self) -> list[dict]:
        if self.trace_top <= 0:
            return []
        import tracemalloc

        if not tracemalloc.is_tracing():
            return []
        snap = tracemalloc.take_snapshot()
        stats = snap.statistics("lineno")
        out = []
        for st in stats[: self.trace_top]:
            fr = st.traceback[0] if st.traceback else None
            out.append({
                "site": (f"{fr.filename}:{fr.lineno}" if fr
                         else "?"),
                "size_bytes": int(st.size),
                "count": int(st.count),
            })
        return out

    def snapshot(self) -> dict:
        """The full on-demand document behind ``GET /debug/memory``
        (always answers, sampler thread or not). ``counters`` and
        ``gauges`` blocks carry the registry names verbatim so the
        fleet merge is a mechanical sum over the same namespace the
        /metrics body exposes."""
        host = self.sample_once(pss=True)
        device = self._tracker.device_doc()
        reg = self._reg()
        with self._lock:
            samples = self._samples_total
        doc = {
            "schema": MEMORY_SCHEMA,
            "enabled": self.enabled,
            "interval_s": self.interval_s,
            "pid": os.getpid(),
            "host": host,
            "device": device,
            "pressure": self.pressure.to_dict(),
            "counters": {
                "memory.samples_total": samples,
                "memory.sheds_total":
                    reg.counter("memory.sheds_total").value,
            },
            "gauges": {
                "memory.rss_bytes": host["rss_bytes"],
                "memory.rss_peak_bytes": host["rss_peak_bytes"],
                "memory.device_live_bytes_total":
                    device["total_bytes"],
                "memory.pressure_state":
                    1.0 if self.pressure.state == "pressure"
                    else 0.0,
            },
        }
        top = self._tracemalloc_top()
        if top:
            doc["tracemalloc_top"] = top
        return doc

    def manifest_section(self) -> dict | None:
        """The run manifest's ``memory`` block: the final host/device
        picture. ``None`` (section omitted, zero side effects) when
        the process never sampled, isn't sampling, and holds no
        device attribution — a run that never looked at memory writes
        the same manifest it always did."""
        with self._lock:
            sampled = self._samples_total > 0
        if not self.enabled and not sampled \
                and not self._tracker._attr:
            return None
        return {
            "host": read_host_memory(),
            "device": self._tracker.device_doc(),
            "pressure": self.pressure.to_dict(),
        }


#: the process singleton behind the CLI manifest section; serve
#: daemons build their own (private registry, flag-driven bands)
SAMPLER = MemorySampler()


def under_pressure() -> bool:
    """Is ANY armed controller in this process tripped? The prefetch
    staging pipeline polls this to clamp its depth to 1 while the
    band is high — backpressure without a config plumb-through."""
    return _armed_controller_tripped()


_CONTROLLERS: list = []  # weakly-ordered: serve app registers its own
_CONTROLLERS_LOCK = threading.Lock()


def register_controller(ctl: PressureController) -> None:
    """Make a controller visible to :func:`under_pressure` (the serve
    daemon registers its flag-armed one at startup)."""
    with _CONTROLLERS_LOCK:
        if ctl not in _CONTROLLERS:
            _CONTROLLERS.append(ctl)


def unregister_controller(ctl: PressureController) -> None:
    with _CONTROLLERS_LOCK:
        if ctl in _CONTROLLERS:
            _CONTROLLERS.remove(ctl)


def _armed_controller_tripped() -> bool:
    with _CONTROLLERS_LOCK:
        ctls = list(_CONTROLLERS)
    return any(c.should_shed() for c in ctls)


# ---- fleet merge ----


def merge_memory(bodies: list[dict]) -> dict:
    """Merge worker ``/debug/memory`` bodies the PR-13 way: counters
    as EXACT arithmetic sums (pinned by test to equal the sum of the
    inputs, in both the JSON and prom encodings), gauges as
    per-worker {min, max, sum}, device family bytes summed
    family-wise. Non-dict bodies are skipped (a worker mid-restart
    must not poison the merge); ``per_worker`` is the caller's to
    attach."""
    counters: dict[str, int] = {}
    gauges: dict[str, dict] = {}
    by_family: dict[str, int] = {}
    workers = 0
    in_pressure = 0
    enabled = False
    for b in bodies:
        if not isinstance(b, dict) or "host" not in b:
            continue
        workers += 1
        enabled = enabled or bool(b.get("enabled"))
        if (b.get("pressure") or {}).get("state") == "pressure":
            in_pressure += 1
        for k, v in (b.get("counters") or {}).items():
            counters[k] = counters.get(k, 0) + int(v)
        for k, v in (b.get("gauges") or {}).items():
            g = gauges.get(k)
            v = float(v)
            if g is None:
                gauges[k] = {"min": v, "max": v, "sum": v}
            else:
                g["min"] = min(g["min"], v)
                g["max"] = max(g["max"], v)
                g["sum"] = g["sum"] + v
        fams = ((b.get("device") or {}).get("by_family") or {})
        for fam, nb in fams.items():
            by_family[fam] = by_family.get(fam, 0) + int(nb)
    return {
        "schema": MEMORY_SCHEMA,
        "workers": workers,
        "enabled": enabled,
        "workers_in_pressure": in_pressure,
        "counters": dict(sorted(counters.items())),
        "gauges": {k: {m: gauges[k][m] for m in ("min", "max",
                                                 "sum")}
                   for k in sorted(gauges)},
        "device_by_family": dict(sorted(by_family.items())),
    }


def merge_merged_memory(bodies: list[dict]) -> dict:
    """Merge already-merged ``/fleet/memory`` documents one tier up
    (the federation over its fleets): counter sums stay exact sums,
    gauge aggregates compose as min-of-mins / max-of-maxes /
    sum-of-sums, worker tallies and family bytes add. Composition is
    associative by construction — the federation's numbers equal a
    flat merge over every worker."""
    counters: dict[str, int] = {}
    gauges: dict[str, dict] = {}
    by_family: dict[str, int] = {}
    workers = 0
    in_pressure = 0
    enabled = False
    for b in bodies:
        if not isinstance(b, dict) or "counters" not in b:
            continue
        workers += int(b.get("workers") or 0)
        in_pressure += int(b.get("workers_in_pressure") or 0)
        enabled = enabled or bool(b.get("enabled"))
        for k, v in (b.get("counters") or {}).items():
            counters[k] = counters.get(k, 0) + int(v)
        for k, agg in (b.get("gauges") or {}).items():
            g = gauges.get(k)
            if g is None:
                gauges[k] = {m: float(agg[m])
                             for m in ("min", "max", "sum")}
            else:
                g["min"] = min(g["min"], float(agg["min"]))
                g["max"] = max(g["max"], float(agg["max"]))
                g["sum"] = g["sum"] + float(agg["sum"])
        for fam, nb in (b.get("device_by_family") or {}).items():
            by_family[fam] = by_family.get(fam, 0) + int(nb)
    return {
        "schema": MEMORY_SCHEMA,
        "workers": workers,
        "enabled": enabled,
        "workers_in_pressure": in_pressure,
        "counters": dict(sorted(counters.items())),
        "gauges": {k: gauges[k] for k in sorted(gauges)},
        "device_by_family": dict(sorted(by_family.items())),
    }


def flatten_merged(merged: dict) -> dict:
    """A merged /fleet/memory document as a registry-style snapshot
    {counters, gauges} for ``obs.prometheus.render`` — counter names
    ride verbatim (the prom body's ``memory_*_total`` lines ARE the
    exact sums), gauges flatten to ``<name>.min/.max/.sum``."""
    counters = dict(merged.get("counters") or {})
    gauges: dict[str, float] = {
        "memory.fleet_workers": merged.get("workers", 0),
        "memory.fleet_workers_in_pressure":
            merged.get("workers_in_pressure", 0),
    }
    for k, agg in (merged.get("gauges") or {}).items():
        for m in ("min", "max", "sum"):
            gauges[f"{k}.{m}"] = agg[m]
    for fam, nb in (merged.get("device_by_family") or {}).items():
        gauges[f"memory.device_live_bytes.{fam}.sum"] = nb
    return {"counters": dict(sorted(counters.items())),
            "gauges": dict(sorted(gauges.items())),
            "histograms": {}}


# ---- chunk auto-sizing (the cohortscan consumer) ----


def auto_chunk_samples(per_sample_bytes: int, budget_bytes: int,
                       n_samples: int, minimum: int = 8,
                       maximum: int = 4096) -> int:
    """Size a cohort chunk so one chunk's matrices fit the budget:
    ``budget / per_sample`` clamped to [minimum, min(maximum,
    n_samples)]. A zero/unknown per-sample measurement falls back to
    the maximum (no evidence → no constraint)."""
    if per_sample_bytes <= 0 or budget_bytes <= 0:
        return min(maximum, max(minimum, n_samples))
    fit = budget_bytes // per_sample_bytes
    return int(max(minimum, min(maximum, n_samples, fit)))


# the process sampler contributes the manifest's `memory` section
# (1.3); its provider returns None — section omitted, manifest
# unchanged from earlier rounds — for any run that never sampled
from .manifest import register_section  # noqa: E402 — see compiles.py

register_section("memory", lambda: SAMPLER.manifest_section())
