"""Prometheus text exposition (format 0.0.4) over the metrics registry.

The serve daemon's /metrics has always been a JSON document (and stays
one, byte-for-byte — scripts and tests pin it); this module renders
the SAME :meth:`MetricsRegistry.snapshot` as the plain-text format a
Prometheus scraper ingests, so pointing a scrape job at
``/metrics?format=prom`` (or sending ``Accept: text/plain``) needs no
sidecar exporter. One snapshot, two encodings — the numbers cannot
disagree.

Mapping:

  - counters  -> ``# TYPE <name> counter`` + one sample
  - gauges    -> ``# TYPE <name> gauge``
  - histograms (bounded-window summaries) -> a Prometheus *summary*:
    ``<name>{quantile="0.5"}`` per recorded percentile plus
    ``<name>_sum`` / ``<name>_count`` (count is all-time, matching the
    JSON body)

Names are sanitized to the Prometheus grammar
(``[a-zA-Z_:][a-zA-Z0-9_:]*`` — the registry's dotted names become
underscored); every emitted family carries ``# HELP``/``# TYPE``.
Stdlib-only, no client library.
"""

from __future__ import annotations

import re

#: the content type a 0.0.4 text exposition must be served under
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_BAD = re.compile(r"[^a-zA-Z0-9_:]")

#: percentile keys in a histogram summary -> Prometheus quantile label
_QUANTILES = {"p50": "0.5", "p95": "0.95", "p99": "0.99",
              "max": "1"}


def sanitize_name(name: str) -> str:
    """Registry name -> legal Prometheus metric name (dots and every
    other illegal byte become ``_``; a leading digit is prefixed)."""
    out = _BAD.sub("_", name)
    if not _NAME_OK.match(out):
        out = "_" + out
    return out


def _fmt(v) -> str:
    # Prometheus floats: plain repr is fine, but ints stay ints so
    # counter samples read naturally
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    return repr(float(v))


def render(snapshot: dict, prefix: str = "",
           help_text: dict | None = None) -> str:
    """Render a ``MetricsRegistry.snapshot()`` dict as exposition text.

    ``prefix`` is prepended to every metric name (after sanitizing);
    ``help_text`` optionally maps ORIGINAL registry names to HELP
    strings. Deterministic: sorted names, one trailing newline.
    """
    help_text = help_text or {}
    lines: list[str] = []

    def emit(orig: str, kind: str, samples: list[tuple[str, object]]):
        name = sanitize_name(prefix + orig)
        hlp = help_text.get(orig, f"goleft-tpu metric {orig}")
        lines.append(f"# HELP {name} {hlp}")
        lines.append(f"# TYPE {name} {kind}")
        for suffix_or_labels, v in samples:
            lines.append(f"{name}{suffix_or_labels} {_fmt(v)}")

    for n, v in sorted(snapshot.get("counters", {}).items()):
        emit(n, "counter", [("", v)])
    for n, v in sorted(snapshot.get("gauges", {}).items()):
        emit(n, "gauge", [("", v)])
    for n, summ in sorted(snapshot.get("histograms", {}).items()):
        samples = [(f'{{quantile="{q}"}}', summ[pk])
                   for pk, q in _QUANTILES.items() if pk in summ]
        if "sum" in summ:
            samples.append(("_sum", summ["sum"]))
        samples.append(("_count", summ.get("count", 0)))
        emit(n, "summary", samples)
    return "\n".join(lines) + "\n"
