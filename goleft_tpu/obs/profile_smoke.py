"""End-to-end compile-observatory + profiler smoke: ``make profile-smoke``.

Real subprocess daemons — one ``goleft-tpu fleet`` router supervising
one real serve worker started with ``--profile-hz 50`` — because the
whole point of PR 18 is that "where did the time go" survives process
boundaries:

  1. **the profiler sees real work**: while traced depth requests
     flow, ``GET /fleet/profile?seconds=N`` returns a non-empty merged
     profile whose stacks include a ``goleft_tpu`` frame (the worker
     sampled its own serving threads and the router merged the
     window).
  2. **the compile observatory caught the cold dispatch**: the
     worker's ``GET /debug/compiles`` carries >= 1 depth-family
     signature with a compile tally (the worker runs ``--no-warmup``,
     so the first request's dispatch IS the cache miss).
  3. **the warmup manifest round-trips through the real CLI**:
     ``goleft-tpu warmup export`` (subprocess) writes a manifest that
     ``validate_warmup_manifest`` accepts, whose top signature is the
     depth family the run actually hammered.
  4. **the manifest predicts the restart miss**: the sole worker is
     SIGKILLed, the supervisor restarts it, and the fresh worker's
     ``/debug/compiles`` shows NO depth compile for the exported top
     signature — exactly the cold start a prewarmer would spend the
     manifest preventing (this leg is the control for leg 5).
  5. **the prewarmer prevents it**: a second fleet starts with
     ``--warmup <manifest>`` forwarded to its worker; before ANY
     request the worker's ``/debug/compiles`` already holds the top
     signature compiled (trigger ``warmstart``), and after replaying
     the same depth traffic its compile tally has NOT grown while its
     hits have — the restarted-worker cold miss of leg 4, eliminated.

Run directly::

    python -m goleft_tpu.obs.profile_smoke
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request


def _wait_until(pred, timeout_s: float, what: str,
                interval_s: float = 0.1):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(interval_s)
    raise RuntimeError(f"timed out waiting for {what}")


def _get_json(url: str, timeout_s: float = 30.0) -> dict:
    req = urllib.request.Request(
        url, headers={"Accept": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout_s) as r:
        return json.loads(r.read().decode())


def _worker_urls(router_url: str) -> list[str]:
    return sorted(_get_json(router_url + "/metrics")["workers"])


def _leg_profile_window(router_url, bam, fai, verbose):
    from ..serve.client import ServeClient

    client = ServeClient(router_url, timeout_s=120.0, retries=2,
                         retry_cap_s=2.0, trace=True)
    # first (cold) request compiles the depth program on the worker
    r = client.depth(bam, fai=fai, window=200)
    if not r.get("depth_bed"):
        raise RuntimeError("routed depth request returned no bed")

    # keep the worker busy while the profile window is open
    stop = threading.Event()

    def hammer():
        i = 0
        while not stop.is_set():
            i += 1
            try:
                client.depth(bam, fai=fai, window=200 + (i % 3))
            except Exception:  # noqa: BLE001 — load, not correctness
                if stop.is_set():
                    return
                time.sleep(0.1)

    t = threading.Thread(target=hammer, name="smoke-hammer")
    t.start()
    try:
        doc = _get_json(router_url + "/fleet/profile?seconds=2",
                        timeout_s=60.0)
        # the CLI renders the same merged window as flamegraph
        # collapsed format (subprocess: proves registration too)
        cp = subprocess.run(
            [sys.executable, "-m", "goleft_tpu", "profile",
             "--router", router_url, "--seconds", "1",
             "--collapsed", "-"],
            capture_output=True, text=True, timeout=120)
    finally:
        stop.set()
        t.join(timeout=60)
    if cp.returncode != 0:
        raise RuntimeError(
            f"goleft-tpu profile failed rc={cp.returncode}: "
            f"{cp.stderr[-500:]}")
    lines = [ln for ln in cp.stdout.splitlines() if ln]
    if not lines or not all(
            ln.rsplit(" ", 1)[-1].isdigit() for ln in lines):
        raise RuntimeError(
            "profile --collapsed output is not 'stack count' lines: "
            f"{lines[:3]}")
    if doc.get("schema") != "goleft-tpu.profile/1":
        raise RuntimeError(f"profile schema drifted: {doc.get('schema')!r}")
    if not doc.get("enabled"):
        raise RuntimeError(
            "--profile-hz 50 worker reported profiling disabled")
    if doc.get("samples_total", 0) < 1 or not doc.get("stacks"):
        raise RuntimeError(
            f"merged /fleet/profile window is empty: "
            f"samples={doc.get('samples_total')} "
            f"stacks={len(doc.get('stacks') or {})}")
    if not any("goleft_tpu" in s for s in doc["stacks"]):
        raise RuntimeError(
            "no goleft_tpu frame in the merged profile stacks")
    per = doc.get("per_worker") or {}
    if not any(w.get("samples_total", 0) > 0 for w in per.values()
               if isinstance(w, dict)):
        raise RuntimeError(f"per_worker attribution empty: {per}")
    if verbose:
        print("profile-smoke: /fleet/profile merged "
              f"{doc['samples_total']} samples over "
              f"{len(doc['stacks'])} stacks (goleft_tpu frames "
              "present) while depth requests flowed")


def _leg_compile_observatory(router_url, verbose):
    (worker_url,) = _worker_urls(router_url)
    doc = _get_json(worker_url + "/debug/compiles")
    if doc.get("schema") != "goleft-tpu.warmup-manifest/1":
        raise RuntimeError(
            f"/debug/compiles schema drifted: {doc.get('schema')!r}")
    depth = [s for s in doc.get("signatures") or []
             if s["family"] == "depth" and s["compiles"] >= 1]
    if not depth:
        raise RuntimeError(
            "no depth-family compile in /debug/compiles after a cold "
            f"request (families: "
            f"{sorted({s['family'] for s in doc.get('signatures') or []})})")
    if doc.get("compiles_total", 0) < 1:
        raise RuntimeError("compiles_total never incremented")
    if not any(e.get("family") == "depth"
               for e in doc.get("events") or []):
        raise RuntimeError("no depth CompileEvent in the event ring")
    if verbose:
        print("profile-smoke: /debug/compiles shows "
              f"{len(depth)} depth-family signature(s), "
              f"compiles_total={doc['compiles_total']}")
    return doc


def _leg_warmup_export(router_url, d, verbose):
    from .compiles import load_warmup_manifest

    (worker_url,) = _worker_urls(router_url)
    out = os.path.join(d, "warmup-manifest.json")
    cp = subprocess.run(
        [sys.executable, "-m", "goleft_tpu", "warmup", "export",
         "--url", worker_url, "--out", out],
        capture_output=True, text=True, timeout=120)
    if cp.returncode != 0:
        raise RuntimeError(
            f"warmup export failed rc={cp.returncode}: "
            f"{cp.stderr[-500:]}")
    manifest = load_warmup_manifest(out)  # validates or raises
    if not manifest["signatures"]:
        raise RuntimeError("exported manifest has no signatures")
    top = manifest["signatures"][0]
    # the run's hot bucket IS the top-ranked signature
    if top["family"] != "depth" or top["compiles"] < 1:
        raise RuntimeError(
            f"top manifest signature is not the hot depth bucket: "
            f"{top}")
    if verbose:
        print("profile-smoke: warmup export wrote a valid manifest, "
              f"top signature depth/{top['signature']} "
              f"(hits={top['hits']}, "
              f"compile_seconds={top['compile_seconds']:.2f})")
    return top


def _leg_restart_would_miss(router_url, top, verbose):
    snap = _get_json(router_url + "/metrics")
    victim = next(s for s in snap["supervisor"]["slots"]
                  if s["state"] == "healthy")
    os.kill(victim["pid"], signal.SIGKILL)

    def healed():
        try:
            m = _get_json(router_url + "/metrics")
        except Exception:  # noqa: BLE001 — router mid-heal
            return False
        return m["counters"].get("fleet.restarts_total", 0) >= 1 \
            and m["supervisor"]["capacity"] >= 1
    _wait_until(healed, 180.0, "supervisor to restart the worker")
    (worker_url,) = _worker_urls(router_url)

    def fresh_doc():
        try:
            return _get_json(worker_url + "/debug/compiles")
        except Exception:  # noqa: BLE001 — worker still warming
            return None
    _wait_until(lambda: fresh_doc() is not None, 60.0,
                "restarted worker /debug/compiles")
    doc = fresh_doc()
    hits = [s for s in doc.get("signatures") or []
            if s["family"] == top["family"]
            and s["signature"] == top["signature"]
            and s["compiles"] >= 1]
    if hits:
        raise RuntimeError(
            "restarted worker already holds the exported top "
            f"signature — the cold-miss prediction is vacuous: {hits}")
    if verbose:
        print("profile-smoke: restarted worker has no compile for "
              f"{top['family']}/{top['signature']} — the exported "
              "manifest predicts exactly this cold miss")


def _find_sig(doc: dict, top: dict) -> dict | None:
    for s in doc.get("signatures") or []:
        if s["family"] == top["family"] \
                and s["signature"] == top["signature"]:
            return s
    return None


def _leg_prewarm_no_cold_miss(manifest_path, top, bam, fai, env,
                              verbose):
    """A fresh fleet started with --warmup holds the top signature
    compiled BEFORE any request, and real traffic then hits it warm
    (compiles flat, hits growing) — leg 4's cold miss, eliminated."""
    from ..serve.client import ServeClient

    router = subprocess.Popen(
        [sys.executable, "-m", "goleft_tpu", "fleet",
         "--port", "0", "--workers", "1",
         "--poll-interval-s", "0.3", "--down-after", "1",
         "--supervise-interval-s", "0.1",
         "--hang-timeout-s", "5", "--restart-limit", "8",
         "--warmup", manifest_path,
         "--worker-args=--no-warmup"],
        stdout=subprocess.PIPE, text=True, env=env)
    try:
        line = router.stdout.readline()
        if "listening on " not in line:
            raise RuntimeError(
                f"prewarm router never announced: {line!r}")
        url = line.rsplit("listening on ", 1)[1].strip()

        def _healthy() -> int:
            try:
                return _get_json(url + "/healthz").get("healthy", 0)
            except Exception:  # noqa: BLE001 — 503 while degraded
                return -1

        _wait_until(lambda: _healthy() == 1, 180.0,
                    "the prewarmed worker healthy")
        (worker_url,) = _worker_urls(url)
        before = _find_sig(
            _get_json(worker_url + "/debug/compiles"), top)
        # the whole point: compiled at startup, before ANY request
        if before is None or before["compiles"] < 1:
            raise RuntimeError(
                "prewarmed worker does not hold the top signature "
                f"before traffic: {before} (want "
                f"{top['family']}/{top['signature']} compiled)")
        client = ServeClient(url, timeout_s=120.0, retries=2,
                             retry_cap_s=2.0)
        # replay the exact traffic shape that minted the signature
        for w in (200, 201, 202):
            r = client.depth(bam, fai=fai, window=w)
            if not r.get("depth_bed"):
                raise RuntimeError(
                    "prewarmed depth request returned no bed")
        after = _find_sig(
            _get_json(worker_url + "/debug/compiles"), top)
        if after["compiles"] != before["compiles"]:
            raise RuntimeError(
                "prewarmed worker COLD-MISSED the top signature: "
                f"compiles {before['compiles']} -> "
                f"{after['compiles']}")
        if after["hits"] <= before["hits"]:
            raise RuntimeError(
                "replayed traffic never hit the prewarmed "
                f"signature (hits {before['hits']} -> "
                f"{after['hits']}) — the no-cold-miss assertion "
                "would be vacuous")
        if verbose:
            print("profile-smoke: --warmup worker held "
                  f"{top['family']}/{top['signature']} compiled "
                  "before any request and served "
                  f"{after['hits'] - before['hits']} warm hit(s) "
                  "with zero new compiles — the leg-4 cold miss, "
                  "eliminated")
    finally:
        if router.poll() is None:
            router.send_signal(signal.SIGTERM)
            try:
                router.wait(timeout=60)
            except subprocess.TimeoutExpired:
                router.kill()
                router.wait(timeout=10)
        if router.stdout is not None:
            router.stdout.close()


def run_smoke(timeout_s: float = 600.0, verbose: bool = True) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",     # CI has no accelerator
               GOLEFT_TPU_PROBE="0")    # don't pay a probe timeout
    env.pop("GOLEFT_TPU_FAULTS", None)  # hermetic
    from ..resilience.smoke import _make_cohort

    t0 = time.monotonic()
    with tempfile.TemporaryDirectory(prefix="goleft_prof_") as d:
        bams, fai, _bed = _make_cohort(d, ref_len=20_000)
        router = subprocess.Popen(
            [sys.executable, "-m", "goleft_tpu", "fleet",
             "--port", "0", "--workers", "1",
             "--poll-interval-s", "0.3", "--down-after", "1",
             "--supervise-interval-s", "0.1",
             "--hang-timeout-s", "5", "--restart-limit", "8",
             "--worker-args=--no-warmup --profile-hz 50"],
            stdout=subprocess.PIPE, text=True, env=env)
        try:
            line = router.stdout.readline()
            if "listening on " not in line:
                raise RuntimeError(f"router never announced: {line!r}")
            url = line.rsplit("listening on ", 1)[1].strip()

            def _healthy() -> int:
                try:
                    return _get_json(url + "/healthz").get(
                        "healthy", 0)
                except Exception:  # noqa: BLE001 — 503 while degraded
                    return -1

            _wait_until(lambda: _healthy() == 1, 120.0,
                        "the worker healthy")
            _leg_profile_window(url, bams[0], fai, verbose)
            _leg_compile_observatory(url, verbose)
            top = _leg_warmup_export(url, d, verbose)
            _leg_restart_would_miss(url, top, verbose)
        finally:
            if router.poll() is None:
                router.send_signal(signal.SIGTERM)
                try:
                    router.wait(timeout=60)
                except subprocess.TimeoutExpired:
                    router.kill()
                    router.wait(timeout=10)
            if router.stdout is not None:
                router.stdout.close()
        # leg 5 runs on its own fleet (started WITH --warmup), after
        # the control fleet is fully torn down
        _leg_prewarm_no_cold_miss(
            os.path.join(d, "warmup-manifest.json"), top, bams[0],
            fai, env, verbose)
        if time.monotonic() - t0 > timeout_s:
            raise RuntimeError(
                f"profile-smoke exceeded its {timeout_s:g}s budget")
    if verbose:
        print(f"profile-smoke: PASS ({time.monotonic() - t0:.1f}s)")
    return 0


if __name__ == "__main__":
    sys.exit(run_smoke())
