"""The performance ledger: bench history as a normalized time series.

The repo's perf evidence has always been write-only snapshots: five
``BENCH_r*.json`` round artifacts (the driver's captured stdout tail +
final headline JSON line), a ``BENCH_lastgood.json`` device pin, and —
since PR 3 — per-run manifests. Nothing relates them, so the
ROADMAP's top open item (stale round-2 chip numbers riding through
rounds 3-5 as if they were fresh) could only be caught by a human
reading tails. This module converts all of it into ONE normalized,
append-only ``PERF_LEDGER.jsonl``: one record per bench entry per
round, each carrying

  - the round id (``r03`` / ``lastgood`` / ``live-<ts>`` /
    ``manifest``) and, for driver rounds, the integer round number
    the sentinel trends over,
  - the entry's OWN platform claim and its normalized provenance
    class (``host`` / ``device`` / ``unknown``) — the per-entry
    pinning PR 1 introduced is what makes class-matched baselines
    possible,
  - a ``stale`` carryover flag: an entry that claims device platform
    in a round whose probe failed, or that arrived inside a
    ``device_lastgood`` block, is *evidence about the past*, never a
    fresh measurement,
  - the numeric metrics themselves, flattened to dotted keys.

Parsing is deliberately forgiving: round tails are TRUNCATED stdout
(the first line is usually cut mid-dict), so any line that doesn't
parse is skipped — what survives is real, what didn't survive was
never evidence. The sentinel (obs/sentinel.py) consumes the ledger;
``goleft-tpu perf`` is the CLI over both.
"""

from __future__ import annotations

import ast
import datetime
import json
import os
import re

LEDGER_SCHEMA = "goleft-tpu.perf-ledger/1"
DEFAULT_LEDGER = "PERF_LEDGER.jsonl"

#: tail lines shaped like ``entry_name: {python dict repr}`` — how the
#: bench's incremental _merge_details echoes each entry as it lands
_ENTRY_LINE = re.compile(
    r"^([A-Za-z_][A-Za-z0-9_]*): (\{.*\})\s*$")

_ROUND_FILE = re.compile(r"BENCH_r(\d+)\.json$")

#: metric keys that are configuration/identity, not measurements
_CONFIG_KEYS = frozenset({
    "samples", "ref_bp", "coverage", "read_len", "window", "iters",
    "shard_bp", "threads", "chromosomes", "tiles", "windows", "n",
    "rc", "effective_cores", "genome_gb", "decode_threads_used",
    "optimal_threads", "timeout_s", "level", "seed",
    "kernel_shard_bp", "kernel_coverage", "kernel_read_len",
    "kernel_iters", "payload_mb",
})


def classify_platform(platform) -> str:
    """Normalize an entry's platform claim to a provenance class.

    ``host``/``cpu``-prefixed claims (including the bench's annotated
    forms like ``"host (decode+reduce is pure host work)"`` and
    ``"cpu (host-only mode)"``) are host evidence; a missing or
    ``unavailable`` claim is ``unknown``; anything else (tpu, gpu,
    axon, ...) is a device claim.
    """
    if not platform or not isinstance(platform, str):
        return "unknown"
    p = platform.strip().lower()
    if p.startswith(("host", "cpu")):
        return "host"
    if p.startswith(("unavailable", "unknown", "n/a")):
        return "unknown"
    return "device"


def numeric_metrics(d: dict, prefix: str = "",
                    max_depth: int = 3) -> dict:
    """Flatten a bench entry's numeric leaves to {dotted_key: float},
    skipping configuration keys, bools, and anything non-numeric."""
    out: dict[str, float] = {}
    if max_depth < 0 or not isinstance(d, dict):
        return out
    for k, v in d.items():
        if not isinstance(k, str) or k in _CONFIG_KEYS:
            continue
        key = f"{prefix}{k}"
        if isinstance(v, bool):
            continue
        if isinstance(v, (int, float)):
            out[key] = float(v)
        elif isinstance(v, dict):
            out.update(numeric_metrics(v, f"{key}.", max_depth - 1))
    return out


def make_record(*, source: str, round_label: str, entry: str,
                kind: str, metrics: dict,
                round_num: int | None = None,
                platform: str | None = None, stale: bool = False,
                stale_reason: str | None = None,
                ts: str | None = None, extra: dict | None = None
                ) -> dict:
    rec = {
        "schema": LEDGER_SCHEMA,
        "source": source,
        "round": round_num,
        "round_label": round_label,
        "entry": entry,
        "kind": kind,
        "platform": platform,
        "provenance": classify_platform(platform),
        "stale": bool(stale),
        "stale_reason": stale_reason,
        "metrics": {k: round(float(v), 6)
                    for k, v in sorted(metrics.items())},
        "ts": ts,
    }
    if extra:
        rec.update(extra)
    return rec


def _tail_entries(tail: str) -> dict:
    """{entry_name: dict} for every parseable ``name: {...}`` tail
    line (python dict reprs — the bench echoes entries via repr)."""
    out: dict[str, dict] = {}
    for line in (tail or "").splitlines():
        m = _ENTRY_LINE.match(line)
        if not m:
            continue
        try:
            val = ast.literal_eval(m.group(2))
        except (ValueError, SyntaxError, MemoryError,
                RecursionError):
            continue  # truncated / not a literal — not evidence
        if isinstance(val, dict):
            out[m.group(1)] = val
    return out


def _probe_failed(tail: str, entries: dict) -> bool:
    """Did this round run without a usable accelerator? Derived from
    the bench's own loud markers, not inferred from silence."""
    if "accelerator unusable" in (tail or ""):
        return True
    probe = entries.get("device_probe")
    if isinstance(probe, dict):
        attempts = probe.get("attempts")
        if isinstance(attempts, list) and attempts:
            return not any(a.get("ok") for a in attempts
                           if isinstance(a, dict))
    return False


def parse_round_file(path: str) -> list[dict]:
    """One committed ``BENCH_rNN.json`` driver artifact -> records.

    Produces a record per parseable tail entry plus one for the final
    headline JSON line (``parsed``). Stale derivation: entries inside
    a ``device_lastgood`` block are carryover by construction; any
    other entry whose own platform claims a device in a round whose
    probe failed cannot have been measured this round.
    """
    with open(path) as fh:
        doc = json.load(fh)
    source = os.path.basename(path)
    m = _ROUND_FILE.search(source)
    round_num = int(m.group(1)) if m else int(doc.get("n", 0)) or None
    label = f"r{round_num:02d}" if round_num is not None else source
    tail = doc.get("tail") or ""
    entries = _tail_entries(tail)
    failed = _probe_failed(tail, entries)
    records: list[dict] = []

    for name, val in entries.items():
        if name == "device_probe":
            continue  # probe attempts are provenance, not metrics
        if name == "device_lastgood":
            prov = val.get("provenance") or {}
            for sub_name, sub in (val.get("entries") or {}).items():
                if not isinstance(sub, dict):
                    continue
                records.append(make_record(
                    source=source, round_label=label, entry=sub_name,
                    kind="carryover", round_num=round_num,
                    platform=sub.get("platform")
                    or prov.get("platform"),
                    stale=True,
                    stale_reason="device_lastgood carryover: probe "
                                 "failed this round; values were "
                                 "measured in an earlier round",
                    metrics=numeric_metrics(sub), ts=prov.get("ts")))
            continue
        plat = val.get("platform")
        stale = failed and classify_platform(plat) == "device"
        records.append(make_record(
            source=source, round_label=label, entry=name,
            kind="bench", round_num=round_num, platform=plat,
            stale=stale,
            stale_reason=("entry claims device platform but this "
                          "round's probe failed — carryover"
                          if stale else None),
            metrics=numeric_metrics(val)))

    parsed = doc.get("parsed")
    if isinstance(parsed, dict) and "metric" in parsed:
        records.extend(_headline_records(parsed, source, label,
                                         round_num, failed))
    return records


def _headline_records(parsed: dict, source: str, label: str,
                      round_num: int | None,
                      probe_failed: bool) -> list[dict]:
    """The driver headline (the bench's final stdout JSON line).

    The cohort e2e headline is host work by definition (decode+reduce
    never touches the device — bench.py pins exactly that into the
    cohort entry's platform field); other headline metrics take their
    platform from their own config block. Kernel numbers embedded in
    the config ride as their own record so the device series is
    continuous across rounds where the suite reshuffled.
    """
    metric = str(parsed["metric"])
    config = parsed.get("config") or {}
    if metric.startswith("cohort_depth_e2e"):
        plat = "host (decode+reduce is pure host work)"
    else:
        plat = config.get("platform")
    metrics = {"value": parsed.get("value", 0.0)}
    if isinstance(parsed.get("vs_baseline"), (int, float)):
        metrics["vs_baseline"] = parsed["vs_baseline"]
    out = [make_record(
        source=source, round_label=label, entry=metric,
        kind="headline", round_num=round_num, platform=plat,
        stale=probe_failed and classify_platform(plat) == "device",
        stale_reason=("headline claims device platform but this "
                      "round's probe failed — carryover"
                      if probe_failed
                      and classify_platform(plat) == "device"
                      else None),
        metrics=metrics)]
    kern = {k: v for k, v in config.items()
            if k.startswith("kernel_") and isinstance(v, (int, float))
            and not isinstance(v, bool) and k not in _CONFIG_KEYS
            and not k.endswith(("_shard_bp", "_coverage", "_read_len",
                                "_iters"))}
    if kern:
        kplat = config.get("platform")
        stale = probe_failed and classify_platform(kplat) == "device"
        out.append(make_record(
            source=source, round_label=label, entry="device_kernels",
            kind="headline", round_num=round_num, platform=kplat,
            stale=stale,
            stale_reason=("kernel numbers claim device platform but "
                          "this round's probe failed — carryover"
                          if stale else None),
            metrics=kern))
    return out


def parse_lastgood(path: str) -> list[dict]:
    """``BENCH_lastgood.json`` -> pin records (round ``lastgood``).

    A pin is by definition evidence about a PAST round (the most
    recent real device run); it never participates in round-over-round
    trending, but ingesting it keeps the device claim's backing data
    inside the ledger where ``perf check --strict`` can see it.
    """
    with open(path) as fh:
        doc = json.load(fh)
    prov = doc.get("provenance") or {}
    records = []
    for name, entry in (doc.get("entries") or {}).items():
        if not isinstance(entry, dict):
            continue
        records.append(make_record(
            source=os.path.basename(path), round_label="lastgood",
            entry=name, kind="pin",
            platform=entry.get("platform") or prov.get("platform"),
            stale=True,
            stale_reason="lastgood pin: most recent recorded device "
                         "numbers, not a fresh measurement",
            metrics=numeric_metrics(entry), ts=prov.get("ts")))
    return records


def parse_manifest(path: str, round_num: int | None = None) -> list[dict]:
    """A PR-3 run manifest -> one record (span seconds + counters),
    carrying the manifest's own backend provenance. Schema-validated
    via obs.manifest.load_manifest (accepts any 1.x minor)."""
    from .manifest import load_manifest

    doc = load_manifest(path)
    backend = doc.get("backend") or {}
    metrics: dict[str, float] = {}
    for name, rec in (doc.get("spans") or {}).items():
        if isinstance(rec, dict) and "seconds" in rec:
            metrics[f"spans.{name}.seconds"] = rec["seconds"]
    snap = doc.get("metrics") or {}
    for name, v in (snap.get("counters") or {}).items():
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            metrics[f"counters.{name}"] = v
    for name, v in (snap.get("gauges") or {}).items():
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            metrics[f"gauges.{name}"] = v
    if "spans_dropped" in doc:
        metrics["spans_dropped"] = doc["spans_dropped"]
    cmd = doc.get("command") or "run"
    label = (f"r{round_num:02d}" if round_num is not None
             else "manifest")
    return [make_record(
        source=os.path.basename(path), round_label=label,
        entry=f"manifest.{cmd}", kind="manifest",
        round_num=round_num, platform=backend.get("platform"),
        stale="error" in backend,
        stale_reason=(f"backend unavailable: {backend.get('error')}"
                      if "error" in backend else None),
        metrics=metrics, ts=doc.get("ts"))]


def live_run_records(details: dict, headline: dict | None,
                     source: str = "bench.py") -> list[dict]:
    """Records for a bench run that JUST completed in this process —
    how ``python bench.py`` auto-appends itself to the ledger. The
    round label is ``live-<utc ts>``; entries reuse the same per-entry
    platform pinning the committed artifacts carry."""
    ts = datetime.datetime.now(datetime.timezone.utc) \
        .isoformat(timespec="seconds")
    label = f"live-{ts}"
    records = []
    for name, val in (details or {}).items():
        if not isinstance(val, dict) or name == "device_probe":
            continue
        if name == "device_lastgood":
            prov = val.get("provenance") or {}
            for sub_name, sub in (val.get("entries") or {}).items():
                if isinstance(sub, dict):
                    records.append(make_record(
                        source=source, round_label=label,
                        entry=sub_name, kind="carryover",
                        platform=sub.get("platform")
                        or prov.get("platform"), stale=True,
                        stale_reason="device_lastgood carryover",
                        metrics=numeric_metrics(sub), ts=ts))
            continue
        records.append(make_record(
            source=source, round_label=label, entry=name,
            kind="live", platform=val.get("platform"),
            metrics=numeric_metrics(val), ts=ts))
    if isinstance(headline, dict) and "metric" in headline:
        for rec in _headline_records(headline, source, label, None,
                                     probe_failed=False):
            rec["kind"] = "live"
            rec["ts"] = ts
            records.append(rec)
    return records


# ---- ledger file I/O ----


def read_ledger(path: str) -> list[dict]:
    records = []
    with open(path) as fh:
        for i, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                raise ValueError(
                    f"{path}:{i}: corrupt ledger line: {e}") from None
            if not isinstance(rec, dict):
                raise ValueError(f"{path}:{i}: record is not an object")
            records.append(rec)
    return records


def append_records(path: str, records: list[dict]) -> None:
    """Append-only write: one sorted-key JSON object per line, atomic
    against torn lines (single write per record, flushed once)."""
    if not records:
        return
    with open(path, "a") as fh:
        for rec in records:
            fh.write(json.dumps(rec, sort_keys=True) + "\n")


def record_key(rec: dict) -> tuple:
    """Identity for dedup: the same entry of the same round from the
    same source is the same evidence, however often ingested."""
    return (rec.get("source"), rec.get("round_label"),
            rec.get("entry"))


def discover_sources(root: str = ".") -> dict:
    """{kind: [paths]} of the committed artifacts under ``root``."""
    rounds = sorted(
        os.path.join(root, f) for f in os.listdir(root)
        if _ROUND_FILE.search(f))
    lastgood = os.path.join(root, "BENCH_lastgood.json")
    return {
        "rounds": rounds,
        "lastgood": [lastgood] if os.path.exists(lastgood) else [],
    }


def ingest(root: str = ".", ledger_path: str | None = None,
           manifests: list[str] | tuple = (),
           rebuild: bool = False) -> tuple[int, int]:
    """Ingest every discoverable artifact into the ledger.

    Append-only with dedup: records whose (source, round, entry)
    identity is already in the ledger are skipped, so re-running
    ``perf ingest`` is idempotent. ``rebuild=True`` starts from an
    empty file (the committed artifacts are the source of truth; the
    ledger is a derived view). Returns (records_added, total).
    """
    ledger_path = ledger_path or os.path.join(root, DEFAULT_LEDGER)
    srcs = discover_sources(root)
    fresh: list[dict] = []
    for p in srcs["rounds"]:
        fresh.extend(parse_round_file(p))
    for p in srcs["lastgood"]:
        fresh.extend(parse_lastgood(p))
    for p in manifests:
        fresh.extend(parse_manifest(p))
    if rebuild and os.path.exists(ledger_path):
        os.remove(ledger_path)
    existing = (read_ledger(ledger_path)
                if os.path.exists(ledger_path) else [])
    seen = {record_key(r) for r in existing}
    new = []
    for rec in fresh:
        k = record_key(rec)
        if k not in seen:
            seen.add(k)
            new.append(rec)
    append_records(ledger_path, new)
    return len(new), len(existing) + len(new)
