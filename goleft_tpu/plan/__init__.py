"""goleft_tpu.plan — the one plan-then-execute layer.

Before this package the repo had three parallel dispatch paths — the
cold CLI pipelines, ``run_prefetched_cohort`` and the serve executors —
each hand-composing its own slice of the resilience stack: the CLI got
checkpoint/resume and quarantine, prefetch got retry, serve got fault
injection and nothing else. A serve request could neither checkpoint
nor quarantine, and the retry loop lived in three shapes.

Now every entry point lowers its work into :class:`~goleft_tpu.plan.core.Step`
values — content-keyed units of work — and ONE
:class:`~goleft_tpu.plan.executor.Executor` runs them with the full
composition applied uniformly, in a fixed order:

    quarantine short-circuit → checkpoint resume → result-cache lookup
    → [fault site → span → fn]  under the RetryPolicy
    → quarantine on exhaustion → cache put → checkpoint commit

  - :mod:`~goleft_tpu.plan.core` — ``Step`` / ``Plan`` / ``StepOutcome``
  - :mod:`~goleft_tpu.plan.executor` — the ``Executor`` plus
    ``execute_task`` (the shard-scheduler facade, moved here from
    resilience/policy.py)
  - :mod:`~goleft_tpu.plan.lint` — the ``make plan-lint`` body: fails
    when any module outside this package calls ``execute_task`` or
    ``policy.call`` directly, so the three-path split can't silently
    regrow

Lowered call sites (the inventory the lint protects):

  - ``parallel/scheduler.py`` ``run_sharded`` / ``iter_prefetched`` →
    ``execute_task``
  - ``commands/cohortdepth.py`` per-sample decode/reduce and the
    per-region checkpoint/fault boundary → sample / region Steps
  - ``commands/indexcov.py`` per-chromosome QC → chromosome Steps
  - ``parallel/prefetch.py`` ``run_prefetched_cohort`` per-chunk
    commit → chunk Steps
  - ``ops/pairhmm.py`` per-bucket wavefront dispatch → bucket Steps
  - ``serve/executors.py`` every device dispatch → device Steps
    (transient device faults are now retried inside the batch instead
    of failing every coalesced neighbor)

Import is jax-free and cheap.
"""

from __future__ import annotations

from .core import Plan, Step, StepOutcome  # noqa: F401
from .executor import Executor, execute_task, run_device_step  # noqa: F401

__all__ = ["Executor", "Plan", "Step", "StepOutcome", "execute_task",
           "run_device_step"]
