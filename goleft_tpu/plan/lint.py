"""plan-lint: the dispatch-path-split regression gate.

The tentpole refactor's value is that there is ONE place retry/
checkpoint/quarantine compose (plan/executor.py). This check fails CI
(``make plan-lint``) when any module outside ``goleft_tpu/plan/``
grows a direct call to the retry machinery again:

  - ``execute_task(...)`` — the scheduler facade must be reached
    through the plan package
  - ``<policy>.call(...)`` — a raw RetryPolicy attempt loop
  - ``RetriesExhausted`` handling paired with a hand-rolled retry
    ``while True:`` loop is caught by the two patterns above (the loop
    needs one of them to retry)

Definitions inside ``goleft_tpu/plan/`` and the test tree are exempt;
``# plan-lint: ok`` on the offending line grants an explicit waiver
(none exist today — a waiver should be a reviewed decision).

Run: ``python -m goleft_tpu.plan.lint [root]`` — exits 1 with one
line per violation.
"""

from __future__ import annotations

import os
import re
import sys

#: pattern → why it is banned outside goleft_tpu/plan/
BANNED = [
    (re.compile(r"\bexecute_task\s*\("),
     "call execute_task via goleft_tpu.plan (Executor/Step)"),
    (re.compile(r"\bpolicy\s*\.\s*call\s*\("),
     "raw RetryPolicy.call loop — lower the work into a plan Step"),
    (re.compile(r"\bRetryPolicy\s*\([^)]*\)\s*\.\s*call\s*\("),
     "raw RetryPolicy.call loop — lower the work into a plan Step"),
]

WAIVER = "# plan-lint: ok"


def check_tree(root: str) -> list[str]:
    """Return one 'path:line: message' string per violation under
    ``root`` (the goleft_tpu package directory)."""
    violations: list[str] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", "plan")]
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            with open(path, encoding="utf-8") as fh:
                for lineno, line in enumerate(fh, 1):
                    if WAIVER in line:
                        continue
                    stripped = line.lstrip()
                    if stripped.startswith("#"):
                        continue
                    for patt, why in BANNED:
                        if patt.search(line):
                            rel = os.path.relpath(path,
                                                  os.path.dirname(root))
                            violations.append(
                                f"{rel}:{lineno}: {why}\n"
                                f"    {line.rstrip()}")
                            break
    return violations


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    root = argv[0] if argv else os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    violations = check_tree(root)
    if violations:
        print(f"plan-lint: {len(violations)} direct retry-layer "
              "call(s) outside goleft_tpu/plan/ — lower them into "
              "plan Steps (docs/resilience.md):", file=sys.stderr)
        for v in violations:
            print(v, file=sys.stderr)
        return 1
    print("plan-lint: ok — all dispatch paths lower through "
          "goleft_tpu/plan/")
    return 0


if __name__ == "__main__":
    sys.exit(main())
