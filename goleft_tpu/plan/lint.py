"""plan-lint: the dispatch-path-split gate (now a shim).

The original grep implementation lived here through PR 7; the check is
now the ``plan-boundary`` rule of the AST analyzer
(:mod:`goleft_tpu.analysis.rules.plan_boundary`), which resolves call
names through each module's import table — ``from goleft_tpu.plan
.executor import execute_task as et`` can no longer dodge the gate,
and a method merely *named* ``call`` no longer false-positives.

This module keeps the two public contracts:

  - ``python -m goleft_tpu.plan.lint [root]`` — same exit codes and
    one-violation-per-line stderr report (``make plan-lint`` is now
    ``goleft-tpu lint --only plan-boundary``, the same rule)
  - ``check_tree(root) -> [str]`` — the API tests/test_plan.py pins

``# plan-lint: ok`` on a line still waives it (waivers.py maps the
historical marker onto the ``plan-boundary`` rule id).
"""

from __future__ import annotations

import os
import sys


def check_tree(root: str) -> list[str]:
    """Return one 'path:line: message' string per violation under
    ``root`` (the goleft_tpu package directory)."""
    from ..analysis.engine import run_analysis

    result = run_analysis(root, only=["plan-boundary"])
    out = []
    for f in result.findings:
        out.append(f"{f.path}:{f.line}: {f.message}\n    {f.snippet}")
    return out


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    root = argv[0] if argv else os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    violations = check_tree(root)
    if violations:
        print(f"plan-lint: {len(violations)} direct retry-layer "
              "call(s) outside goleft_tpu/plan/ — lower them into "
              "plan Steps (docs/resilience.md):", file=sys.stderr)
        for v in violations:
            print(v, file=sys.stderr)
        return 1
    print("plan-lint: ok — all dispatch paths lower through "
          "goleft_tpu/plan/")
    return 0


if __name__ == "__main__":
    sys.exit(main())
