"""The one Step executor: retry × quarantine × checkpoint × faults × spans.

Every dispatch path — CLI shard schedulers, the prefetched cohort
pipeline, the per-chromosome indexcov loop, the pair-HMM bucket
dispatch, the serve executors — runs its Steps through
:meth:`Executor.run_step`, so the composition order is defined once:

    1. quarantine short-circuit (an already-quarantined key degrades
       to its fallback with zero work)
    2. checkpoint resume (every key committed → restore, no fault
       site, no retry, counted in ``checkpoint.shards_resumed_total``)
    3. result-cache lookup (I/O failures never fail the step —
       ``result_cache.io_errors_total``)
    4. the attempt loop under the RetryPolicy: each attempt fires the
       step's fault-injection site, then runs ``fn`` inside the step's
       span (a device-event span for device steps)
    5. on exhaustion: quarantine + fallback when the step carries a
       quarantine identity, else the failure lands in the outcome
    6. cache put, then checkpoint commit (one journal commit per step)

``execute_task`` is the shard-scheduler facade (moved here from
resilience/policy.py): same (key, thunk, cache, policy) →
``ShardResult`` contract both scheduler paths have used since PR 5.
``run_device_step`` is the serve executors' facade: one coalesced
device dispatch as a retried Step, so a transient device/tunnel fault
costs one backoff instead of failing the whole batch.
"""

from __future__ import annotations

import threading
from typing import Any

from ..obs import get_registry
from ..resilience import faults
from ..resilience.policy import (
    DEFAULT_POLICY, RetriesExhausted, RetryPolicy,
)
from .core import Plan, Step, StepOutcome


class _InflightEntry:
    __slots__ = ("event", "outcome")

    def __init__(self):
        self.event = threading.Event()
        self.outcome: StepOutcome | None = None


class InflightSteps:
    """Cross-request in-flight step table: the dedup machinery.

    Two concurrent Steps carrying the same content key (and
    ``dedup=True``) share ONE execution: the first arrival is the
    *leader* and computes; every later arrival is a *follower* that
    waits on the leader's outcome and reuses its value — one device
    pass serves all of them. Content keys make this safe: the key
    pins every input's identity (``file_key`` = path+size+mtime_ns)
    plus the canonical parameters, so "same key" means "same bytes
    out".

    Failures are NOT shared: a follower whose leader errored (or
    vanished past ``wait_s``) computes independently — dedup is an
    optimization, never a correlated-failure amplifier.

    The process-wide instance is :data:`INFLIGHT`; executors use it by
    default so dedup spans every Executor in the process (the serve
    executors construct one per dispatch).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._inflight: dict = {}

    def join(self, key) -> tuple[_InflightEntry, bool]:
        """(entry, is_leader). The leader MUST eventually
        :meth:`settle` its entry (use try/finally)."""
        with self._lock:
            entry = self._inflight.get(key)
            if entry is None:
                entry = self._inflight[key] = _InflightEntry()
                return entry, True
            return entry, False

    def settle(self, key, entry: _InflightEntry,
               outcome: StepOutcome | None) -> None:
        with self._lock:
            # pop only our own entry: a follower that timed out and
            # re-led must not have its fresh entry evicted by the
            # stale leader settling late
            if self._inflight.get(key) is entry:
                del self._inflight[key]
        entry.outcome = outcome
        entry.event.set()

    def depth(self) -> int:
        with self._lock:
            return len(self._inflight)


#: the process-wide in-flight table (one dedup domain per process)
INFLIGHT = InflightSteps()

#: how long a follower waits on its leader before giving up and
#: computing independently — generous (a wedged leader is the
#: watchdog's business, not the follower's), but bounded so a leaked
#: leader cannot wedge every future identical request
DEDUP_WAIT_S = 600.0


class Executor:
    """Runs Steps under one (policy, quarantine, checkpoint, cache)
    composition. All collaborators optional: a bare ``Executor()``
    just calls the thunk — entry points construct one unconditionally
    and the resilience features engage exactly when their objects are
    wired, which is what makes the lowering transparent."""

    def __init__(self, policy: RetryPolicy | None = None,
                 quarantine=None, checkpoint=None, cache=None,
                 inflight: InflightSteps | None = None):
        self.policy = policy
        self.quarantine = quarantine
        self.checkpoint = checkpoint
        self.cache = cache
        # dedup domain: the process-wide table unless a test injects
        # its own — steps only participate when they set dedup=True
        self.inflight = inflight if inflight is not None else INFLIGHT

    # ---- the composition ----

    def run_step(self, step: Step) -> StepOutcome:
        q = self.quarantine
        if q is not None and step.quarantine_key is not None \
                and step.quarantine_key in q:
            return StepOutcome(
                step.key, quarantined=True,
                value=step.fallback() if step.fallback else None)

        ck = self.checkpoint
        ck_keys = step.ck_keys() if ck is not None else []
        if ck_keys and step.resumable \
                and all(ck.has(k) for k in ck_keys):
            vals = [ck.get(k) for k in ck_keys]
            value = step.restore(vals) if step.restore is not None \
                else vals[0] if step.checkpoint_key is not None \
                else vals
            return StepOutcome(step.key, value=value, resumed=True)

        reg = get_registry()
        if self.cache is not None and step.cacheable:
            try:
                hit = self.cache.get(step.key)
            except Exception:  # noqa: BLE001 — cache must not fail steps
                reg.counter("result_cache.io_errors_total").inc()
                hit = None
            if hit is not None:
                return StepOutcome(step.key, value=hit, from_cache=True)

        def attempt():
            if step.site:
                faults.maybe_fail(step.site, step.key)
            with self._span(step):
                return step.fn()

        def compute() -> StepOutcome:
            policy = step.policy if step.policy is not None \
                else self.policy
            if policy is None or not step.retry:
                # resilience layer off (or a no-retry boundary step):
                # run raw — errors propagate to the caller, exactly
                # the pre-plan behavior of the unguarded paths
                return StepOutcome(step.key, value=attempt())
            try:
                value, attempts = policy.call(step.key, attempt)
            except RetriesExhausted as rx:
                if q is not None and step.quarantine_key is not None:
                    q.add(step.quarantine_key, step.quarantine_name,
                          step.quarantine_source, rx.cause,
                          rx.attempts, rx.classification)
                    return StepOutcome(
                        step.key, quarantined=True,
                        attempts=rx.attempts,
                        classification=rx.classification,
                        value=step.fallback() if step.fallback
                        else None)
                return StepOutcome(step.key, error=rx.cause,
                                   retries_exhausted=rx,
                                   attempts=rx.attempts,
                                   classification=rx.classification)
            return StepOutcome(step.key, value=value,
                               attempts=attempts)

        if step.dedup:
            outcome = self._run_deduped(step, compute, reg)
        else:
            outcome = compute()

        if outcome.error is None and not outcome.quarantined \
                and not outcome.deduped:
            # persistence is the leader's job: a follower's value is
            # already covered by the execution it joined
            if self.cache is not None and step.cacheable:
                try:
                    self.cache.put(step.key, outcome.value)
                except Exception:  # noqa: BLE001 — cache must not fail steps
                    reg.counter("result_cache.io_errors_total").inc()
            if ck_keys:
                items = step.commit(outcome.value) \
                    if step.commit is not None \
                    else [(ck_keys[0], outcome.value)]
                ck.put_many(items)
        return outcome

    def _run_deduped(self, step: Step, compute, reg) -> StepOutcome:
        """Leader-or-follower execution through the in-flight table.

        Exceptions escaping ``compute()`` (the no-policy raw path)
        still settle the entry — a follower never waits on a leader
        that already died."""
        entry, leader = self.inflight.join(step.key)
        if leader:
            outcome = None
            try:
                outcome = compute()
            finally:
                self.inflight.settle(step.key, entry, outcome)
            return outcome
        reg.counter("plan.steps_deduped_total").inc()
        shared = entry.outcome if entry.event.wait(DEDUP_WAIT_S) \
            else None
        if shared is not None and shared.error is None \
                and not shared.quarantined:
            return StepOutcome(step.key, value=shared.value,
                               deduped=True)
        # leader failed / was quarantined / timed out: compute
        # independently — failures are never shared
        reg.counter("plan.dedup_fallbacks_total").inc()
        return compute()

    def run(self, step: Step):
        """run_step, raising the failure (the exhausted attempt's
        original cause) instead of returning it — the call shape for
        entry points that want plain values."""
        return self.run_step(step).value_or_raise()

    def execute(self, plan: Plan):
        """Run a whole Plan, yielding one StepOutcome per Step in
        order (lazy: a generator, so streaming consumers overlap)."""
        for step in plan:
            yield self.run_step(step)

    # ---- span plumbing ----

    @staticmethod
    def _span(step: Step):
        import contextlib

        if step.span is None:
            return contextlib.nullcontext()
        from .. import obs

        if step.device:
            return obs.device_span(step.span, **step.attrs)
        return obs.span(step.span, **step.attrs)


def execute_task(key, thunk, cache=None,
                 policy: RetryPolicy | None = None):
    """Cache-lookup + retry for one shard task: the ONE helper behind
    ``run_sharded`` and ``iter_prefetched``.

    Returns a ``parallel.scheduler.ShardResult``; failures come back
    with ``.error`` set (shard isolation — the caller decides whether
    to raise). Cache I/O failures never fail the task: a computed
    value beats a broken cache (counted in
    ``result_cache.io_errors_total``).
    """
    from ..parallel.scheduler import ShardResult

    ex = Executor(policy=policy if policy is not None
                  else DEFAULT_POLICY, cache=cache)
    out = ex.run_step(Step(key=key, fn=thunk, site="shard",
                           cacheable=cache is not None))
    return ShardResult(key, out.value, error=out.error,
                       attempts=out.attempts,
                       from_cache=out.from_cache)


def run_device_step(name: str, fn, *, key=None, metrics=None,
                    policy: RetryPolicy | None = None,
                    retry: bool = True, dedup: bool = False,
                    count_passes: bool = False, signature=None,
                    **attrs):
    """One coalesced serve device dispatch as a Step.

    The serve executors' dispatch boundary: the shared ``compute``
    stage wall-clock PLUS a device-event span carrying backend/
    platform attributes, with the ``device`` fault site fired per
    attempt — so an injected (or real) transient device fault is
    retried under the policy instead of failing every request that
    shared the batch. The wrapped ``fn`` fetches its results to host
    numpy before returning, so the span already fences on the device
    work. Raises the original failure on exhaustion (the batcher's
    bisect-and-retry isolation takes it from there).

    ``dedup=True`` (with a content-identity ``key``) routes the step
    through the process-wide in-flight table: a concurrent dispatch of
    the same key joins the running pass instead of re-executing —
    cross-request step dedup (``plan.steps_deduped_total``).
    ``count_passes=True`` moves the executors'
    ``device_passes_total`` accounting here, where a deduped dispatch
    is visibly NOT a pass: only a genuinely executed step increments
    it — the honesty the fleet smoke's one-pass assertion rests on.
    """
    import contextlib

    from ..obs.compiles import TRACKER, family_of_dispatch

    def staged():
        if metrics is None:
            cm = contextlib.nullcontext()
        else:
            cm = metrics.timer.stage("compute")
        # the compile observation runs INSIDE the device span the
        # Executor opens around this fn, so a jit miss surfaced here
        # lands as a nested xla.compile.<family> span in flight trees.
        # ``signature`` (program geometry) makes the observation
        # warmstart-actionable: the warmup manifest records it and
        # serve --warmup can recreate the compile before admission.
        with cm, TRACKER.observe(family_of_dispatch(name),
                                 signature=signature, trigger=name):
            return fn()

    ex = Executor(policy=policy if policy is not None
                  else DEFAULT_POLICY)
    out = ex.run_step(Step(key=key if key is not None else (name,),
                           fn=staged, site="device", retry=retry,
                           dedup=dedup, span=name, device=True,
                           attrs=attrs))
    if count_passes and metrics is not None and not out.deduped \
            and out.error is None:
        metrics.inc("device_passes_total")
    return out.value_or_raise()
