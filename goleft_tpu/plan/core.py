"""Plan/Step data model: content-keyed units of work.

A :class:`Step` is the smallest schedulable unit every entry point
lowers to: a thunk plus the metadata the
:class:`~goleft_tpu.plan.executor.Executor` needs to apply the
resilience stack uniformly — a content-identity key, a fault-injection
site, optional checkpoint keys (with commit/restore adapters for
multi-key shards), optional quarantine identity and fallback. The data
model is deliberately passive: a Step never executes itself, so the
composition order (quarantine → resume → cache → retry → commit) is
defined in exactly one place.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Sequence


@dataclass
class Step:
    """One content-keyed unit of work.

    ``key`` is the step's identity everywhere: the retry policy's
    deterministic-jitter seed, the fault site's logged key, the
    result-cache key (when ``cacheable``). Callers build it from
    content identity (``parallel.scheduler.file_key`` of each input +
    canonical params) so a stale input invalidates only its own steps.

    ``checkpoint_key``/``checkpoint_keys`` make the step resumable: a
    step whose every key is committed in the executor's store is
    *restored* (``restore(values)``; default: the single value) with no
    fault site, no retry, no ``fn``. After a fresh compute, ``commit``
    maps the value to ``(key, block)`` pairs persisted in ONE journal
    commit (default: ``[(checkpoint_key, value)]``).

    ``quarantine_key`` opts the step into graceful degradation: a step
    of an already-quarantined key short-circuits to ``fallback()``, and
    a permanently-failing step quarantines itself and degrades to
    ``fallback()`` instead of erroring (matching cohortdepth's
    per-sample contract).

    ``retry=False`` runs ``fn`` outside the retry policy (errors
    propagate raw) while keeping the fault site and checkpoint
    behavior — the region-advance steps, whose inner work carries its
    own per-sample policy.

    ``dedup=True`` opts the step into the executor's in-flight step
    table: a concurrent step with the SAME key joins the running
    execution instead of launching its own (one device pass serves
    both callers). Only safe — and only meaningful — for steps whose
    key is full content identity and whose value is a pure function of
    it; callers must treat the shared value as read-only.
    """

    key: tuple
    fn: Callable[[], Any]
    name: str = ""
    site: str | None = None
    retry: bool = True
    policy: Any = None             # RetryPolicy override (else executor's)
    cacheable: bool = False
    checkpoint_key: tuple | None = None
    checkpoint_keys: Sequence[tuple] | None = None
    resumable: bool = True         # False: commit-only (no store skip —
    #                                order-dependent steps whose resume
    #                                is a caller-level prefix decision)
    restore: Callable[[list], Any] | None = None
    commit: Callable[[Any], Sequence[tuple]] | None = None
    quarantine_key: Any = None
    quarantine_name: str = ""
    quarantine_source: str = ""
    fallback: Callable[[], Any] | None = None
    span: str | None = None        # obs span name (None: no extra span)
    device: bool = False           # span is a device-event span
    dedup: bool = False            # share one in-flight execution per key
    attrs: dict = field(default_factory=dict)

    def ck_keys(self) -> list[tuple]:
        """The step's checkpoint keys, normalized to a list."""
        if self.checkpoint_keys is not None:
            return list(self.checkpoint_keys)
        if self.checkpoint_key is not None:
            return [self.checkpoint_key]
        return []


@dataclass
class StepOutcome:
    """What running one Step produced (the executor never raises for
    policy-managed failures — the caller decides, via :meth:`value_or_raise`
    or by inspecting ``error``)."""

    key: tuple
    value: Any = None
    error: BaseException | None = None
    retries_exhausted: BaseException | None = None  # the RetriesExhausted
    attempts: int = 1
    classification: str = ""
    from_cache: bool = False
    resumed: bool = False
    quarantined: bool = False
    deduped: bool = False  # value shared from a concurrent execution

    @property
    def ok(self) -> bool:
        return self.error is None

    def value_or_raise(self):
        if self.error is not None:
            raise self.error
        return self.value


@dataclass
class Plan:
    """An ordered sequence of Steps plus the request-level metadata
    (entry-point kind, canonical params) — what an entry point lowers
    its whole invocation into before anything executes."""

    kind: str
    steps: list[Step] = field(default_factory=list)
    meta: dict = field(default_factory=dict)

    def add(self, step: Step) -> Step:
        self.steps.append(step)
        return step

    def __len__(self) -> int:
        return len(self.steps)

    def __iter__(self):
        return iter(self.steps)
