"""Streaming cross-sample normalization: exact two-pass form.

The monolithic ``normalize_across_samples`` is a scan over the bin
axis where bin j's cohort mean mixes the *already processed* column
j-1 — a feedback loop that looks like it needs the whole cohort
resident. It does not. Two observations make an exact streaming split
possible (full derivation in docs/cohort.md):

1. Given the per-bin scalars ``(m[j], skip[j])``, the finalize step is
   **per-sample elementwise**: each sample's output row depends only on
   its own raw row and the scalar sequence. Elementwise f32 lanes are
   independent of the batch they ride in, so applying the finalize to
   any sample chunk reproduces exactly the rows the monolithic run
   would produce.
2. The scalars themselves depend on the cohort only through *sums over
   samples*, and the smoothing recurrence is linear with branch
   membership decided purely by ``(sample_length, j)``. Summing the
   recurrence over every sample of one length class therefore closes:
   a per-class f64 carry of the last three processed-column sums plus
   per-class raw column sums reproduce the sequence ``(m[j], skip[j])``
   without ever materializing a processed matrix.

:class:`NormStats` is the pass-1 accumulator. Its state is O(classes ×
bins) — independent of cohort size — and accumulation is strictly
sequential per class, which is what makes it invariant under any
contiguous chunking of the sample axis (the "merge" of two adjacent
chunks' statistics is literally continuing the accumulation; there is
no floating-point partial-sum reassociation anywhere).

``apply_normalization`` is the pass-2 device kernel. The monolithic
``ops.indexcov_ops.normalize_across_samples`` now lowers onto these
same two passes, so chunked == monolithic is true by construction, and
the property test pins it byte-for-byte across chunk sizes.
"""

from __future__ import annotations

import hashlib

import jax
import jax.numpy as jnp
import numpy as np


class NormStats:
    """Chunk-invariant cross-sample normalization statistics.

    Feed sample chunks **in cohort order** via :meth:`accumulate`;
    then :meth:`finalize` yields the per-bin ``(m, skip)`` scalar
    sequences that drive :func:`apply_normalization`. State is one
    f64 raw-column-sum vector and a counter per distinct sample
    length ("length class") — a few KB per chromosome regardless of
    cohort size.
    """

    def __init__(self):
        # length -> [sample_count, f64 raw column sums (length,)]
        self._cls: dict[int, list] = {}
        self.n_samples = 0

    def accumulate(self, depths: np.ndarray, lengths: np.ndarray) -> None:
        """Add one sample chunk. ``depths`` is (chunk, width) f32 with
        zero padding past each sample's ``lengths[i]`` bins."""
        depths = np.asarray(depths)
        lengths = np.asarray(lengths)
        if depths.shape[0] != lengths.shape[0]:
            raise ValueError(
                f"cohort: {depths.shape[0]} depth rows vs "
                f"{lengths.shape[0]} lengths")
        for i in range(len(lengths)):
            ln = int(lengths[i])
            self.n_samples += 1
            if ln <= 0:
                continue
            ent = self._cls.get(ln)
            if ent is None:
                ent = self._cls[ln] = [0, np.zeros(ln, np.float64)]
            ent[0] += 1
            # one sequential f64 add per sample: the accumulation order
            # is the cohort order, never a chunk-shaped reduction tree,
            # so any contiguous chunking yields bit-identical sums
            ent[1] += depths[i, :ln].astype(np.float64)

    def finalize(self, n_bins: int) -> tuple[np.ndarray, np.ndarray]:
        """Per-bin scalars: (m (n_bins,) f32, skip (n_bins,) bool).

        Replays the reference's f64 neighborhood-mean recurrence
        (indexcov.go:549-597) on the class aggregates. Bins past every
        sample's end get ``skip=True`` (the monolithic scan reaches the
        same state: its sample count drops below the 3n-4 floor).
        """
        n_total = self.n_samples
        m_out = np.zeros(n_bins, np.float32)
        skip_out = np.ones(n_bins, bool)
        if not self._cls:
            return m_out, skip_out
        lens = np.array(sorted(self._cls), np.int64)
        cnts = np.array([self._cls[int(ln)][0] for ln in lens], np.float64)
        max_len = int(lens[-1])
        # pad class sums 3 past the longest class: bin j's smoothing
        # reads raw columns j+1..j+3
        rs = np.zeros((len(lens), max_len + 3), np.float64)
        for r, ln in enumerate(lens):
            rs[r, :ln] = self._cls[int(ln)][1]
        carry = np.zeros((len(lens), 3), np.float64)  # Σ out at j-3..j-1
        thresh = 3 * n_total - 4
        for j in range(min(n_bins, max_len)):
            alive = lens > j        # class still has a live column
            has_next = lens > j + 1
            # the padded rows are zero past each class's end, so the
            # raw-sum terms need no masking; the carry does (a class
            # whose last bin was j-1 contributes nothing at j)
            m_sum = float(rs[:, j].sum()) + float(rs[:, j + 1].sum())
            if j > 0:
                m_sum += float(np.where(alive, carry[:, 2], 0.0).sum())
            n1 = float(cnts[alive].sum())
            n = int(n1) + (int(n1) if j > 0 else 0) \
                + int(cnts[has_next].sum())
            m_acc = m_sum / max(n, 1)
            skip = (n < thresh) or (m_acc < 0.1)
            mj = np.float32(m_acc)
            m_out[j] = mj
            skip_out[j] = skip
            if skip:
                out_sum = rs[:, j]
            else:
                # the per-sample finalize divides by the f32-rounded m
                # — mirror that here so the aggregate tracks the lane
                # arithmetic as closely as f64 allows
                m64 = np.float64(mj)
                scaled = rs[:, j] / m64
                smooth = alive & (lens > j + 3) & (j > 2)
                smoothed = (
                    carry[:, 0] + carry[:, 1] + carry[:, 2] + scaled
                    + rs[:, j + 1] / m64 + rs[:, j + 2] / m64
                    + rs[:, j + 3] / m64
                ) / 7.0
                out_sum = np.where(smooth, smoothed, scaled)
            shifted = np.stack([carry[:, 1], carry[:, 2], out_sum], axis=1)
            carry = np.where(alive[:, None], shifted, carry)
        return m_out, skip_out

    def scalars_digest(self, n_bins: int) -> str:
        """Content digest of the finalized scalars — what checkpoint
        keys bind when the QC input is the *normalized* matrix, so a
        cohort-composition change invalidates exactly the shards whose
        normalization actually moved."""
        m, skip = self.finalize(n_bins)
        h = hashlib.sha256()
        h.update(m.tobytes())
        h.update(np.packbits(skip).tobytes())
        return h.hexdigest()[:16]


@jax.jit
def apply_normalization(
    depths: jax.Array, lengths: jax.Array,
    m_all: jax.Array, skip_all: jax.Array,
) -> jax.Array:
    """Pass-2 finalize: normalize + 7-tap smooth one sample chunk given
    the global per-bin scalars.

    Elementwise per sample lane — a chunk's output rows are exactly the
    rows the monolithic run produces for those samples. ``depths`` is
    (chunk, n_bins) with ``n_bins == len(m_all)``.
    """
    n_chunk, n_bins = depths.shape
    lengths = lengths.astype(jnp.int32)
    raw = depths
    pad = jnp.zeros((n_chunk, 3), raw.dtype)
    raw_p = jnp.concatenate([raw, pad], axis=1)

    def step(prev3, xs):
        j, m, skip = xs
        col = raw[:, j]
        valid_j = lengths > j
        scaled = jnp.where(valid_j, col / m, col)
        do_smooth = valid_j & (j > 2) & (j < lengths - 3)
        smoothed = (
            prev3[:, 0] + prev3[:, 1] + prev3[:, 2] + scaled
            + raw_p[:, j + 1] / m + raw_p[:, j + 2] / m
            + raw_p[:, j + 3] / m
        ) / 7.0
        out = jnp.where(do_smooth, smoothed, scaled)
        out = jnp.where(skip, col, out)
        new_carry = jnp.concatenate([prev3[:, 1:], out[:, None]], axis=1)
        return new_carry, out

    init = jnp.zeros((n_chunk, 3), raw.dtype)
    xs = (jnp.arange(n_bins, dtype=jnp.int32), m_all, skip_all)
    _, cols = jax.lax.scan(step, init, xs)
    return cols.T


def normalize_across_samples_chunked(
    chunks: list[tuple[np.ndarray, np.ndarray]], n_bins: int | None = None,
) -> list[np.ndarray]:
    """Convenience wrapper over the two passes for an in-memory list of
    ``(depths_chunk, lengths_chunk)`` pairs in cohort order.

    Peak memory is O(chunk × bins) beyond the class statistics. Returns
    one processed f32 array per chunk; hstacking them equals the
    monolithic ``normalize_across_samples`` byte-for-byte. Cohorts
    under 5 samples pass through unchanged (the reference's floor).
    """
    if n_bins is None:
        n_bins = max((np.asarray(d).shape[1] for d, _ in chunks),
                     default=0)
    total = sum(np.asarray(d).shape[0] for d, _ in chunks)
    if total < 5:
        return [np.asarray(d, np.float32) for d, _ in chunks]
    stats = NormStats()
    for depths, lengths in chunks:
        stats.accumulate(_pad_to(np.asarray(depths, np.float32), n_bins),
                         lengths)
    m, skip = stats.finalize(n_bins)
    out = []
    for depths, lengths in chunks:
        d = _pad_to(np.asarray(depths, np.float32), n_bins)
        out.append(np.asarray(apply_normalization(
            d, np.asarray(lengths, np.int32), m, skip)))
    return out


def _pad_to(mat: np.ndarray, n_bins: int) -> np.ndarray:
    """Zero-pad a chunk to the shared bin width (padding columns are
    masked everywhere downstream; outputs at real bins are unaffected
    because the scalars depend only on class data, never on width)."""
    if mat.shape[1] == n_bins:
        return mat
    if mat.shape[1] > n_bins:
        raise ValueError(
            f"cohort: chunk width {mat.shape[1]} exceeds n_bins {n_bins}")
    out = np.zeros((mat.shape[0], n_bins), mat.dtype)
    out[:, :mat.shape[1]] = mat
    return out
