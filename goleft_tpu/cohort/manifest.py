"""The cohort manifest: ``goleft-tpu.cohort-manifest/1``.

The manifest is the cohort plane's commit record: one JSON document
per output directory naming every sample by **content identity** —
``parallel.scheduler.file_key`` of the index file actually read
(path + size + mtime_ns locally; the ETag/Last-Modified/size tuple of
``io.remote.remote_file_key`` for ``https://``/``s3://`` inputs) —
plus the canonical scan parameters and the run's QC-compute counters.

Invalidation is two-layered and strictly content-keyed:

- The per-(sample, chromosome) checkpoint blocks embed the sample's
  own identity key, so a changed ETag (or a rewritten .bai) stops
  matching ONLY its own blocks; every other sample resumes. The
  manifest never has to *decide* invalidation — the store's key
  lookup is the decision.
- The manifest records what the previous committed run looked like, so
  an incremental re-run can report exactly which samples are new /
  changed / unchanged (the diff the append-k acceptance counter is
  asserted against), and refuse a silent parameter drift (changed
  params → every block is a miss anyway; the manifest makes it loud).

Schema (docs/cohort.md#manifest):

.. code-block:: json

    {"format": "goleft-tpu.cohort-manifest/1",
     "params": {"sex": "X,Y", "exclude": "...", "chrom": "",
                "extra_normalize": false, "tile": 16384},
     "samples": [{"path": "...", "name": "...", "key": [..]}],
     "counters": {"chrom_qc_computed_total": 0,
                  "chrom_qc_resumed_total": 0}}
"""

from __future__ import annotations

import json
import os

FORMAT = "goleft-tpu.cohort-manifest/1"


class CohortManifest:
    def __init__(self, params: dict, samples: list[dict],
                 counters: dict | None = None):
        self.params = params
        self.samples = samples
        self.counters = dict(counters or {})

    # ---- (de)serialization ----

    @classmethod
    def load(cls, path: str) -> "CohortManifest":
        with open(path) as f:
            doc = json.load(f)
        if doc.get("format") != FORMAT:
            raise ValueError(
                f"cohort: {path}: not a {FORMAT} document "
                f"(format={doc.get('format')!r})")
        return cls(doc["params"], doc["samples"], doc.get("counters"))

    def save(self, path: str) -> None:
        doc = {
            "format": FORMAT,
            "params": self.params,
            "samples": self.samples,
            "counters": {k: self.counters[k]
                         for k in sorted(self.counters)},
        }
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)  # atomic: a torn write never commits

    # ---- the incremental diff ----

    def diff(self, samples: list[dict]) -> dict:
        """Classify the *current* sample list against this committed
        manifest: ``{"new": [...], "changed": [...], "unchanged":
        [...], "removed": [...]}`` (lists of paths, current order).

        Identity is the path; content is the key — a sample whose path
        is known but whose key moved (ETag drift, rewritten index) is
        *changed*, and its checkpoint blocks are already unreachable
        because the key is part of every block's name.
        """
        committed = {s["path"]: _norm_key(s["key"])
                     for s in self.samples}
        out = {"new": [], "changed": [], "unchanged": [], "removed": []}
        seen = set()
        for s in samples:
            seen.add(s["path"])
            if s["path"] not in committed:
                out["new"].append(s["path"])
            elif committed[s["path"]] != _norm_key(s["key"]):
                out["changed"].append(s["path"])
            else:
                out["unchanged"].append(s["path"])
        out["removed"] = [p for p in sorted(committed) if p not in seen]
        return out


def _norm_key(key):
    """JSON round-trips tuples as lists; canonicalize for comparison."""
    return json.loads(json.dumps(key))
