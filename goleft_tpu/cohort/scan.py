"""The chunked, incremental cohort-scan engine behind ``cohortscan``.

``run_cohortscan`` produces byte-identical bed.gz/.roc/.ped artifacts
to one-shot ``run_indexcov`` on the same inputs, while holding at most
one sample chunk's matrix in memory and recomputing only what changed
across runs. The pipeline:

1. **Chunk pass (host)** — per sample chunk in cohort order: parse the
   .bai/.crai (local path or ranged-read URL, exactly indexcov's
   ``SampleIndex``), spill each chromosome's raw depth rows to an
   .npy file under the checkpoint directory, and feed the
   :class:`~goleft_tpu.cohort.streaming.NormStats` accumulator when
   ``--extranormalize`` is on. Peak memory: O(chunk × bins).
2. **Scalars** — finalize the per-bin normalization scalars per
   chromosome (exact, chunk-invariant — docs/cohort.md).
3. **Emit pass (device + host)** — per chromosome, per chunk:
   normalize the chunk against the global scalars, run the fused
   ``chrom_qc`` kernel for exactly the samples whose content-keyed
   checkpoint block is missing (one batched dispatch per chunk,
   per-sample blocks committed individually), then stream bed.gz
   blocks by gathering (samples × 2048-bin) column slices from the
   chunk spills. The per-sample QC dispatch passes ``longest=0`` so
   the stored block is **cohort-independent**; the missing-tail-bin
   counts (an additive integer) are corrected on host against the
   cohort's longest sample — the same exact-delta trick the serve
   IndexcovExecutor uses.
4. **Finalize** — ROC/ped assembly from the per-sample blocks, PCA
   (oracle under ``pca_exact_max`` samples for byte-parity, sharded
   power iteration above), manifest commit.

Incrementality falls out of the content keys: every per-(sample,
chromosome) block's name embeds the sample's own ``file_key`` /
``remote_file_key``, so appending k samples to a committed cohort
computes exactly k × chromosomes QC blocks (counter-verified by the
biobank smoke), and an ETag drift invalidates exactly its own sample.
"""

from __future__ import annotations

import concurrent.futures as cf
import os
import re
import shutil

import numpy as np

from ..commands import indexcov as ic
from ..io.bgzf import BgzfWriter
from ..obs import get_registry
from ..obs.logging import get_logger
from ..ops import indexcov_ops as ops
from .manifest import CohortManifest
from .streaming import NormStats, apply_normalization

log = get_logger("cohortscan")

#: bump to invalidate every per-sample QC block (layout change)
SCHEMA = 1
BED_BLOCK = 2048
#: above this sample count the PCA switches from the byte-parity
#: oracle (full-matrix SVD) to the sharded power iteration
PCA_EXACT_MAX = 4096


def _row_bucket(n: int) -> int:
    """Next power-of-two row count ≥ n: bounds the (rows, width)
    compile-signature space of the per-chunk QC dispatch the same way
    ``_width_bucket`` bounds the bin axis (padding rows carry
    valid=False everywhere, so results are unchanged)."""
    if n <= 1:
        return 1
    return 1 << (n - 1).bit_length()


def _pad_rows_to(mat: np.ndarray, rows: int) -> np.ndarray:
    if mat.shape[0] == rows:
        return mat
    out = np.zeros((rows,) + mat.shape[1:], mat.dtype)
    out[: mat.shape[0]] = mat
    return out


def _sample_key(path: str):
    """Content identity of the index file actually read — what every
    checkpoint block and the manifest bind."""
    from ..parallel.scheduler import file_key

    try:
        return file_key(ic._index_file(path))
    except OSError:
        return (path, -1, -1)


class _SpillStore:
    """Run-local per-(chromosome, chunk) raw/normalized matrices on
    disk, mmap-read at emission time. Spills are host-derived and
    cheap, so they are rebuilt on every run — resume durability lives
    in the checkpoint store, not here."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, ref_id: int, ci: int, kind: str) -> str:
        return os.path.join(self.root, f"r{ref_id}_c{ci}_{kind}.npy")

    def put(self, ref_id: int, ci: int, kind: str,
            mat: np.ndarray) -> None:
        np.save(self._path(ref_id, ci, kind), mat)

    def get(self, ref_id: int, ci: int, kind: str) -> np.ndarray:
        return np.load(self._path(ref_id, ci, kind), mmap_mode="r")

    def drop(self) -> None:
        shutil.rmtree(self.root, ignore_errors=True)


# spill-matrix budget for --chunk-samples 0 (auto): one chunk's raw
# matrices should fit here; the bound is advisory (auto_chunk_samples
# clamps to [8, 4096]), not an allocator limit
AUTO_CHUNK_BUDGET_BYTES = 256 * 1024 * 1024


def run_cohortscan(
    bams: list[str],
    directory: str,
    sex: str = "X,Y",
    exclude_patt: str = ic.DEFAULT_EXCLUDE,
    chrom: str = "",
    fai: str | None = None,
    extra_normalize: bool = False,
    include_gl: bool = False,
    chunk_samples: int = 256,
    manifest_path: str | None = None,
    resume: bool = False,
    checkpoint_dir: str | None = None,
    pca_mode: str = "auto",
    pca_exact_max: int = PCA_EXACT_MAX,
) -> dict:
    os.makedirs(directory, exist_ok=True)
    if chunk_samples < 0:
        raise ValueError(
            "cohortscan: --chunk-samples must be >= 1, or 0 to "
            "auto-size from measured per-sample bytes")
    if pca_mode not in ("auto", "exact", "sharded"):
        raise ValueError(f"cohortscan: unknown pca mode {pca_mode!r}")
    sex_chroms = [s for s in sex.split(",") if s] if sex else []
    exclude = re.compile(exclude_patt) if exclude_patt else None
    reg = get_registry()

    bams = ic.expand_globs(bams)
    refs = ic.references(bams, fai, chrom)
    n_samples = len(bams)
    log.info("cohortscan: %d samples in chunks of %s", n_samples,
             chunk_samples or "auto")

    name = os.path.basename(os.path.abspath(directory))
    base = os.path.join(directory, name + "-indexcov")
    if checkpoint_dir is None:
        checkpoint_dir = os.path.join(directory, ".cohortscan-ck")
    if manifest_path is None:
        manifest_path = base + ".manifest.json"

    from ..resilience.checkpoint import CheckpointStore

    store = CheckpointStore(checkpoint_dir, resume=resume)
    spill = _SpillStore(os.path.join(checkpoint_dir, "spill"))

    params = {"sex": sex, "exclude": exclude_patt, "chrom": chrom,
              "extra_normalize": bool(extra_normalize),
              "tile": ic.TILE, "schema": SCHEMA}

    # ---- manifest diff (informational; invalidation is key-based) ----
    keys = [_sample_key(b) for b in bams]
    prev = None
    if os.path.exists(manifest_path):
        try:
            prev = CohortManifest.load(manifest_path)
        except (OSError, ValueError) as e:
            log.warning("cohortscan: ignoring unreadable manifest: %s", e)
    sample_docs = [{"path": b, "name": None, "key": list(k)}
                   for b, k in zip(bams, keys)]
    if prev is not None and prev.params != params:
        log.warning(
            "cohortscan: scan parameters changed since the committed "
            "manifest — every QC block misses (full recompute)")
        prev = None
    diff = (prev.diff(sample_docs) if prev is not None
            else {"new": list(bams), "changed": [], "unchanged": [],
                  "removed": []})

    from ..utils.profiling import StageTimer

    timer = StageTimer()

    # prior run's per-chunk high-water mark (journaled via note());
    # reported back so a --resume run knows what its predecessor
    # actually paid without re-measuring
    prior_peak = (int(store.meta.get("chunk_peak_bytes") or 0)
                  if resume else 0)
    if prior_peak:
        log.info("cohortscan: prior run peaked at %d bytes/chunk",
                 prior_peak)

    if chunk_samples == 0:
        # auto-size: journaled measurement from the prior run when
        # resuming, else probe one sample's index and extrapolate
        from ..obs.memplane import auto_chunk_samples

        per_sample = int(store.meta.get("per_sample_bytes") or 0)
        src = "journal"
        if per_sample <= 0 and bams:
            with timer.stage("chunk_probe"):
                try:
                    probe = ic.SampleIndex(bams[0])
                except ValueError as e:
                    raise SystemExit(f"cohortscan: {bams[0]}: {e}")
                per_sample = sum(
                    int(np.asarray(probe.normalized_depth(rid)).nbytes)
                    for rid, rname, _ in refs
                    if exclude is None or not exclude.search(rname))
                del probe
            src = "probe"
        chunk_samples = auto_chunk_samples(
            per_sample, AUTO_CHUNK_BUDGET_BYTES, n_samples)
        log.info(
            "cohortscan: auto chunk size %d (%s: %d bytes/sample, "
            "budget %d)", chunk_samples, src, per_sample,
            AUTO_CHUNK_BUDGET_BYTES)

    # ---- pass 1: chunked index parse + raw spills + norm stats ----
    chunks = [(lo, min(lo + chunk_samples, n_samples))
              for lo in range(0, n_samples, chunk_samples)]
    names: list[str] = [None] * n_samples
    mapped = [0] * n_samples
    unmapped = [0] * n_samples
    lengths_by_ref: dict[int, np.ndarray] = {
        rid: np.zeros(n_samples, np.int32) for rid, _, _ in refs}
    stats_by_ref: dict[int, NormStats] = {}
    if extra_normalize and n_samples >= 5:
        for rid, rname, _ in refs:
            if not ic._same_chrom(sex_chroms, rname):
                stats_by_ref[rid] = NormStats()

    def _load(p):
        try:
            return ic.SampleIndex(p)
        except ValueError as e:
            raise SystemExit(f"cohortscan: {p}: {e}")

    chunk_peak_bytes = 0
    spilled_bytes = 0
    for ci, (lo, hi) in enumerate(chunks):
        with timer.stage("index_load"):
            with cf.ThreadPoolExecutor(max_workers=8) as tex:
                idxs = list(tex.map(_load, bams[lo:hi]))
                names[lo:hi] = list(tex.map(ic.get_short_name,
                                            bams[lo:hi]))
        for off, idx in enumerate(idxs):
            mapped[lo + off] = idx.mapped
            unmapped[lo + off] = idx.unmapped
        cbytes = 0
        with timer.stage("spill"):
            for rid, rname, _rlen in refs:
                if exclude is not None and exclude.search(rname):
                    continue
                rows = [idx.normalized_depth(rid) for idx in idxs]
                mat, _valid, lens = ic._pad_rows(rows)
                lengths_by_ref[rid][lo:hi] = lens
                spill.put(rid, ci, "raw", mat)
                cbytes += int(mat.nbytes)
                st = stats_by_ref.get(rid)
                if st is not None:
                    st.accumulate(mat, lens)
        chunk_peak_bytes = max(chunk_peak_bytes, cbytes)
        spilled_bytes += cbytes
        del idxs

    # journal the measured footprint (fsync'd {"meta": ...} line): a
    # --resume run reads it back (store.meta) to report the prior
    # high-water mark and to size auto chunks from evidence instead
    # of a probe
    per_sample_bytes = (spilled_bytes // n_samples) if n_samples else 0
    store.note(chunk_peak_bytes=chunk_peak_bytes,
               per_sample_bytes=per_sample_bytes,
               chunk_samples=chunk_samples)

    # ---- pass 2 + emission ----
    bed_fh = open(base + ".bed.gz", "wb")
    bed = BgzfWriter(bed_fh, level=1)
    bed.write(("#chrom\tstart\tend\t" + "\t".join(names) + "\n")
              .encode())
    roc_fh = open(base + ".roc", "w")
    roc_fh.write("#chrom\tcov\t" + "\t".join(names) + "\n")

    sexes: dict[str, np.ndarray] = {}
    counters = {k: np.zeros(n_samples, np.int64)
                for k in ("in", "out", "hi", "low")}
    slopes = np.zeros(n_samples, np.float32)
    n_slopes = 0
    chrom_names: list[str] = []
    pca_refs: list[tuple[int, int]] = []  # (ref_id, longest) in order
    qc_computed = 0
    qc_resumed = 0

    from ..plan import Executor as PlanExecutor, Step

    pex = PlanExecutor(checkpoint=store)

    def _qc_chunk(rid, rname, rlen, ci, lo, hi, mat, lens, norm_sig):
        """Per-sample QC blocks for one (chromosome, chunk): resume
        committed samples from the store, batch the rest into ONE
        device dispatch, commit per-sample blocks individually."""
        nonlocal qc_computed, qc_resumed
        span = hi - lo
        ck = [("cohortscan.qc", SCHEMA, tuple(keys[lo + i]), rid,
               rname, int(rlen), norm_sig) for i in range(span)]
        missing = [i for i in range(span) if not store.has(ck[i])]
        resumed = [i for i in range(span) if i not in set(missing)]
        blocks: dict[int, np.ndarray] = {
            i: np.asarray(store.get(ck[i]), np.float32)
            for i in resumed}
        qc_resumed += len(resumed)
        if resumed:
            reg.counter("cohort.chrom_qc_samples_resumed_total") \
                .inc(len(resumed))
        if missing:
            sub = np.ascontiguousarray(mat[missing])
            sub_lens = lens[missing]
            rb = _row_bucket(len(missing))
            sub = _pad_rows_to(sub, rb)
            sub_valid = (np.arange(sub.shape[1], dtype=np.int32)[None, :]
                         < _pad_rows_to(sub_lens.reshape(-1, 1),
                                        rb).ravel()[:, None])

            def fn():
                with timer.stage("qc_dispatch"):
                    # longest=0: no tail term — the stored block must
                    # not depend on the cohort's composition
                    packed = np.asarray(ops.chrom_qc(
                        sub, sub_valid, np.int32(0)))
                rocs, cnt, cn = ops.unpack_chrom_qc(packed, rb)
                return [np.concatenate([
                    rocs[i],
                    np.float32([cnt["in"][i], cnt["out"][i],
                                cnt["hi"][i], cnt["low"][i]]),
                    np.float32([cn[i]]),
                ]).astype(np.float32) for i in range(len(missing))]

            vals = pex.run(Step(
                key=("cohortscan.qc", rname, ci), fn=fn, site="shard",
                retry=False,
                checkpoint_keys=[ck[i] for i in missing],
                restore=lambda vs: vs,
                commit=lambda vs: list(zip(
                    [ck[i] for i in missing], vs)),
            ))
            for i, v in zip(missing, vals):
                blocks[i] = np.asarray(v, np.float32)
            qc_computed += len(missing)
            reg.counter("cohort.chrom_qc_samples_computed_total") \
                .inc(len(missing))
        return [blocks[i] for i in range(span)]

    for rid, rname, rlen in refs:
        if exclude is not None and exclude.search(rname):
            continue
        lens = lengths_by_ref[rid]
        longest = int(lens.max()) if n_samples else 0
        is_sex = ic._same_chrom(sex_chroms, rname)

        # global scalars for this chromosome (None → no normalization)
        norm = None
        norm_sig = None
        st = stats_by_ref.get(rid)
        if st is not None and not is_sex:
            with timer.stage("norm_scalars"):
                width = max(
                    (spill.get(rid, ci, "raw").shape[1]
                     for ci in range(len(chunks))), default=0)
                norm = st.finalize(width)
                norm_sig = st.scalars_digest(width)

        # per-chunk: normalize, QC, collect per-sample blocks
        rocs_all = np.zeros((n_samples, ops.SLOTS), np.float32)
        cnt_all = {k: np.zeros(n_samples, np.int64)
                   for k in ("in", "out", "hi", "low")}
        cn_all = np.zeros(n_samples, np.float32)
        for ci, (lo, hi) in enumerate(chunks):
            mat = np.asarray(spill.get(rid, ci, "raw"))
            clens = lens[lo:hi]
            if norm is not None:
                with timer.stage("normalize"):
                    m_all, skip_all = norm
                    w = len(m_all)
                    if mat.shape[1] < w:
                        mat = np.pad(mat, ((0, 0),
                                           (0, w - mat.shape[1])))
                    rb = _row_bucket(mat.shape[0])
                    padded = _pad_rows_to(mat, rb)
                    out = np.asarray(apply_normalization(
                        padded,
                        _pad_rows_to(clens.reshape(-1, 1),
                                     rb).ravel().astype(np.int32),
                        m_all, skip_all))[: mat.shape[0]]
                    valid = (np.arange(out.shape[1],
                                       dtype=np.int32)[None, :]
                             < clens[:, None])
                    mat = np.where(valid, out, 0.0).astype(np.float32)
                    spill.put(rid, ci, "norm", mat)
            if longest > 0:
                blocks = _qc_chunk(rid, rname, rlen, ci, lo, hi,
                                   mat, clens, norm_sig)
                for off, blk in enumerate(blocks):
                    s = lo + off
                    rocs_all[s] = blk[: ops.SLOTS]
                    for ki, k in enumerate(("in", "out", "hi", "low")):
                        cnt_all[k][s] = int(blk[ops.SLOTS + ki])
                    cn_all[s] = blk[ops.SLOTS + 4]
            del mat

        # host tail correction: exactly the monolithic kernel's
        # max(longest - n_valid, 0) additive term
        if longest > 0:
            delta = (longest - lens.astype(np.int64))
            cnt_all["out"] += delta
            cnt_all["low"] += delta

        # ---- emission (byte-identical to run_indexcov._emit) ----
        with timer.stage("bed_gz"):
            for blo in range(0, longest, BED_BLOCK):
                bhi = min(blo + BED_BLOCK, longest)
                parts = []
                vparts = []
                for ci, (lo, hi) in enumerate(chunks):
                    cmat = spill.get(
                        rid, ci, "norm" if norm is not None else "raw")
                    cw = cmat.shape[1]
                    sl = np.asarray(cmat[:, blo:min(bhi, cw)],
                                    np.float32)
                    if sl.shape[1] < bhi - blo:
                        sl = np.pad(sl, ((0, 0),
                                         (0, bhi - blo - sl.shape[1])))
                    parts.append(sl)
                    vparts.append(
                        (np.arange(blo, bhi, dtype=np.int32)[None, :]
                         < lens[lo:hi, None]))
                ic.write_bed_block(bed, rname, blo, bhi,
                                   np.vstack(parts), np.vstack(vparts))

        if is_sex:
            if longest > 0:
                sexes[rname] = cn_all
        else:
            for k in counters:
                if longest > 0:
                    counters[k] += cnt_all[k]
            pca_refs.append((rid, longest))

        if longest > 0:
            with timer.stage("roc"):
                ic.write_roc_rows(roc_fh, rname, rocs_all)
            if (include_gl or not rname.startswith("GL")) and longest > 2:
                if not is_sex and longest > 100:
                    slopes += ops.update_slopes(rocs_all, rlen / 1e6)
                    n_slopes += 1
                chrom_names.append(rname)

    bed.close()
    bed_fh.close()
    roc_fh.close()

    # ---- PCA + ped ----
    with timer.stage("pca_ped"):
        if n_slopes > 0:
            slopes = slopes / np.float32(n_slopes)
        ic._check_sexes(sexes, sex_chroms)
        pcs, var_frac = _cohort_pca(
            spill, chunks, lengths_by_ref, pca_refs, n_samples,
            stats_by_ref, pca_mode, pca_exact_max)
        ped_path = ic._write_ped(
            base, directory, sexes, counters, names, slopes, pcs,
            mapped, unmapped)

    store.close()
    spill.drop()

    # ---- manifest commit ----
    for doc, nm in zip(sample_docs, names):
        doc["name"] = nm
    man = CohortManifest(params, sample_docs, {
        "chrom_qc_samples_computed_total": qc_computed,
        "chrom_qc_samples_resumed_total": qc_resumed,
        "samples_total": n_samples,
        "samples_new": len(diff["new"]),
        "samples_changed": len(diff["changed"]),
        "samples_unchanged": len(diff["unchanged"]),
        "samples_removed": len(diff["removed"]),
    })
    man.save(manifest_path)
    reg.counter("cohort.scans_total").inc()

    return {
        "sexes": sexes,
        "counters": counters,
        "slopes": slopes,
        "pcs": pcs,
        "var_frac": var_frac,
        "ped": ped_path,
        "bed": base + ".bed.gz",
        "roc": base + ".roc",
        "manifest": manifest_path,
        "chrom_names": chrom_names,
        "diff": diff,
        "qc": {"computed": qc_computed, "resumed": qc_resumed},
        "memory": {"chunk_samples": chunk_samples,
                   "chunk_peak_bytes": chunk_peak_bytes,
                   "per_sample_bytes": per_sample_bytes,
                   "prior_chunk_peak_bytes": prior_peak},
        "stages": {k: round(v, 3) for k, v in timer.totals.items()},
    }


def _cohort_pca(spill, chunks, lengths_by_ref, pca_refs, n_samples,
                stats_by_ref, pca_mode, pca_exact_max):
    """PCA over the quantized autosome bins — the oracle below the
    exactness threshold (byte-parity with one-shot indexcov), sharded
    power iteration above it (docs/cohort.md#pca)."""
    total_bins = sum(longest for _, longest in pca_refs)
    if total_bins < 3 or n_samples < 3:
        return None, None
    use_exact = pca_mode == "exact" or (
        pca_mode == "auto" and n_samples <= pca_exact_max)
    k = min(5, n_samples)

    def chunk_rows(ci, lo, hi):
        """One chunk's quantized autosome row block (chunk, total)."""
        parts = []
        for rid, longest in pca_refs:
            if longest == 0:
                continue
            kind = "norm" if stats_by_ref.get(rid) is not None \
                else "raw"
            try:
                cmat = spill.get(rid, ci, kind)
            except FileNotFoundError:
                cmat = spill.get(rid, ci, "raw")
            lens = lengths_by_ref[rid][lo:hi]
            w = cmat.shape[1]
            sl = np.asarray(cmat[:, :min(longest, w)], np.float32)
            if sl.shape[1] < longest:
                sl = np.pad(sl, ((0, 0), (0, longest - sl.shape[1])))
            valid = (np.arange(longest, dtype=np.int32)[None, :]
                     < lens[:, None])
            capped = np.where(valid, np.minimum(sl, ops.MAX_CN), 0.0)
            q = ops.quantize_depths(capped)
            q[~valid] = 0
            parts.append(q)
        return np.concatenate(parts, axis=1).astype(np.float32)

    if use_exact:
        mat = np.vstack([chunk_rows(ci, lo, hi)
                         for ci, (lo, hi) in enumerate(chunks)])
        proj, frac = ops.pca_project(mat, k=k)
        return np.asarray(proj), np.asarray(frac)

    from .pca import sharded_pca

    def chunks_fn():
        for ci, (lo, hi) in enumerate(chunks):
            yield chunk_rows(ci, lo, hi)

    fit = sharded_pca(chunks_fn, k=k)
    proj = np.vstack([fit.project(chunk_rows(ci, lo, hi))
                      for ci, (lo, hi) in enumerate(chunks)])
    return proj, fit.frac_
