"""Cohort plane: streaming, sharded, incremental indexcov at scale.

The one-shot ``indexcov`` path holds the whole (samples × bins) matrix
in memory and normalizes it in a single fused scan — fine for a
thousand samples, hopeless for the 100k-sample continuously-updatable
QC service the roadmap targets. This package is the scale-out of that
path, built so that *nothing changes in the output bytes*:

- :mod:`.streaming` — the two-pass cross-sample normalization: an
  exact, chunk-invariant per-length-class statistics pass plus a
  per-sample device finalize. Chunked output is byte-identical to the
  monolithic path on any chunking (docs/cohort.md derives why).
- :mod:`.pca` — sharded Gram/power-iteration PCA over sample chunks,
  with ``ops.indexcov_ops.pca_project`` kept as the small-cohort
  oracle.
- :mod:`.manifest` — the content-keyed cohort manifest
  (``goleft-tpu.cohort-manifest/1``): per-sample ``file_key`` /
  ``remote_file_key`` identities layered on the PR-5 CheckpointStore,
  so an appended sample recomputes only its own columns.
- :mod:`.scan` — the chunked/incremental engine behind the
  ``goleft-tpu cohortscan`` CLI and the serve ``/v1/cohortscan``
  executor, emitting bed.gz/.roc/.ped byte-identical to one-shot
  ``indexcov`` on the same inputs.
"""

from .streaming import (  # noqa: F401
    NormStats, apply_normalization, normalize_across_samples_chunked,
)
