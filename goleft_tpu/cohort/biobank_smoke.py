"""Biobank-scale cohort QC end-to-end: the ``make biobank-smoke``
body.

A hermetic 15-sample 3-chromosome BAM cohort staged into a loopback
:mod:`~goleft_tpu.io.remote_stub` object store, driven through the
real ``goleft-tpu cohortscan`` CLI in subprocesses:

  1. **URL byte-identity**: a 12-sample cohort of ``http://`` URLs
     scans byte-identical (bed.gz content / .roc / .ped) to one-shot
     local ``indexcov`` — the streaming chunked path over the ranged-
     read data plane reproduces the monolithic artifacts exactly.
  2. **append-k incrementality**: 3 more samples appended to the same
     output directory with ``--resume`` perform EXACTLY 3×n_chroms
     per-sample QC computations (pinned via the committed manifest's
     counters; the original 12 samples' blocks all resume by content
     key) and the artifacts are byte-identical to a fresh 15-sample
     one-shot ``indexcov``.
  3. **crash-resume**: a SIGKILL injected mid-scan
     (``--inject-faults shard:...:kill``) followed by ``--resume``
     lands on the same bytes, with the pre-kill commits replayed from
     the checkpoint journal instead of recomputed.

Host-pinned (JAX_PLATFORMS=cpu) like every other smoke. Run
directly::

    python -m goleft_tpu.cohort.biobank_smoke
"""

from __future__ import annotations

import gzip
import hashlib
import json
import os
import subprocess
import sys
import tempfile
import time

REFS = ("chr1", "X", "Y")
LENS = (900_000, 400_000, 200_000)


def _make_biobank_cohort(d: str, n: int = 15):
    """n BAMs (+.bai) over chr1/X/Y with alternating 'male'/'female'
    coverage so sex inference has real work, plus the .fai."""
    import numpy as np

    from ..io.bai import build_bai, write_bai
    from ..io.bam import BamWriter

    rng = np.random.default_rng(29)
    header = "@HD\tVN:1.6\tSO:coordinate\n" + "".join(
        f"@SQ\tSN:{r}\tLN:{ln}\n" for r, ln in zip(REFS, LENS))
    bams = []
    for i in range(n):
        male = i % 2 == 0
        counts = [2500,
                  (2500 * LENS[1] // LENS[0]) // (2 if male else 1),
                  (2500 * LENS[2] // LENS[0]) // 2 if male else 0]
        p = os.path.join(d, f"s{i:03d}.bam")
        with open(p, "wb") as fh:
            with BamWriter(fh, header
                           + f"@RG\tID:r\tSM:s{i:03d}\n",
                           list(REFS), list(LENS), level=1) as w:
                for tid, cnt in enumerate(counts):
                    if not cnt:
                        continue
                    starts = np.sort(rng.integers(
                        0, LENS[tid] - 150, size=cnt))
                    for j, s in enumerate(starts):
                        w.write_record(tid, int(s), [(100, 0)],
                                       mapq=60, name=f"r{tid}_{j}")
        write_bai(build_bai(p), p + ".bai")
        bams.append(p)
    fai = os.path.join(d, "ref.fa.fai")
    with open(fai, "w") as fh:
        for r, ln in zip(REFS, LENS):
            fh.write(f"{r}\t{ln}\t6\t60\t61\n")
    return bams, fai


def _stage(srv, paths):
    urls = []
    for p in paths:
        with open(p, "rb") as fh:
            urls.append(srv.put(os.path.basename(p), fh.read()))
    return urls


def _run(args, env, timeout_s=300.0, expect_rc=0):
    rc = subprocess.run(
        [sys.executable, "-m", "goleft_tpu", *args], env=env,
        timeout=timeout_s, capture_output=True, text=True)
    if expect_rc is not None and rc.returncode != expect_rc:
        raise RuntimeError(
            f"goleft-tpu {args[0]} exited {rc.returncode}, want "
            f"{expect_rc}:\n{rc.stderr}")
    return rc


def _digests(outdir: str) -> dict:
    """sha256 of the indexcov artifact surface: bed.gz compared by
    CONTENT (gunzipped), .roc/.ped by raw bytes."""
    name = os.path.basename(os.path.abspath(outdir))
    out = {}
    for suffix in (".bed.gz", ".roc", ".ped"):
        p = os.path.join(outdir, f"{name}-indexcov{suffix}")
        with open(p, "rb") as fh:
            data = fh.read()
        if suffix == ".bed.gz":
            data = gzip.decompress(data)
        out[suffix] = hashlib.sha256(data).hexdigest()
    return out


def _manifest_counters(outdir: str) -> dict:
    name = os.path.basename(os.path.abspath(outdir))
    p = os.path.join(outdir, f"{name}-indexcov.manifest.json")
    with open(p) as fh:
        doc = json.load(fh)
    if doc.get("format") != "goleft-tpu.cohort-manifest/1":
        raise RuntimeError(f"unexpected manifest format in {p}")
    return doc["counters"]


def run_smoke(timeout_s: float = 600.0, verbose: bool = True) -> int:
    """Returns 0 on success; raises on any failed leg."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    env = dict(os.environ, JAX_PLATFORMS="cpu", GOLEFT_TPU_PROBE="0")
    env.pop("GOLEFT_TPU_FAULTS", None)  # hermetic (leg 3 adds it)
    from ..io.remote_stub import StubServer

    t0 = time.monotonic()
    with tempfile.TemporaryDirectory(prefix="goleft_bb_") as d, \
            StubServer() as srv:
        bams, fai = _make_biobank_cohort(os.path.join(d, ""))
        urls = _stage(srv, [b for p in bams for b in (p, p + ".bai")])
        bam_urls = urls[::2]
        fai_url = _stage(srv, [fai])[0]

        # ---- leg 1: 12 URL samples == local one-shot indexcov ----
        ref12 = os.path.join(d, "ref12", "qc")
        os.makedirs(ref12)
        _run(["indexcov", "-d", ref12, "--fai", fai, "--no-html",
              *bams[:12]], env)
        out = os.path.join(d, "scan", "qc")
        ck = os.path.join(d, "scan", "ck")
        base = ["cohortscan", "-d", out, "-f", fai_url,
                "--chunk-samples", "5", "--checkpoint-dir", ck]
        _run(base + bam_urls[:12], env)
        if _digests(out) != _digests(ref12):
            raise RuntimeError(
                "12-sample URL cohortscan != local indexcov bytes")
        n_chroms = len(REFS)
        c = _manifest_counters(out)
        if c["chrom_qc_samples_computed_total"] != 12 * n_chroms \
                or c["chrom_qc_samples_resumed_total"] != 0:
            raise RuntimeError(f"cold-scan counters off: {c}")
        if verbose:
            print("biobank-smoke: 12-sample URL cohort byte-"
                  "identical to local indexcov "
                  f"({12 * n_chroms} QC blocks computed)")

        # ---- leg 2: append 3 — exactly 3×n_chroms QC computes ----
        _run(base + ["--resume"] + bam_urls, env)
        c = _manifest_counters(out)
        if c["chrom_qc_samples_computed_total"] != 3 * n_chroms:
            raise RuntimeError(
                f"append-3 computed {c} blocks, want {3 * n_chroms}")
        if c["chrom_qc_samples_resumed_total"] != 12 * n_chroms:
            raise RuntimeError(f"append-3 resumed counters off: {c}")
        if c["samples_new"] != 3 or c["samples_unchanged"] != 12:
            raise RuntimeError(f"append-3 manifest diff off: {c}")
        ref15 = os.path.join(d, "ref15", "qc")
        os.makedirs(ref15)
        _run(["indexcov", "-d", ref15, "--fai", fai, "--no-html",
              *bams], env)
        if _digests(out) != _digests(ref15):
            raise RuntimeError(
                "incremental 15-sample artifacts != fresh one-shot")
        if verbose:
            print("biobank-smoke: +3 incremental append performed "
                  f"exactly {3 * n_chroms} QC computations, bytes == "
                  "fresh 15-sample one-shot")

        # ---- leg 3: SIGKILL mid-scan, then --resume ----
        out_k = os.path.join(d, "kill", "qc")
        ck_k = os.path.join(d, "kill", "ck")
        base_k = ["cohortscan", "-d", out_k, "-f", fai_url,
                  "--chunk-samples", "5", "--checkpoint-dir", ck_k]
        rc = subprocess.run(
            [sys.executable, "-m", "goleft_tpu", *base_k,
             "--inject-faults", "shard:after=4:kill", *bam_urls],
            env=env, timeout=300, capture_output=True)
        if rc.returncode not in (-9, 137):
            raise RuntimeError(
                f"injected kill did not fire: rc={rc.returncode} "
                f"{rc.stderr.decode()}")
        journal = os.path.join(ck_k, "journal.jsonl")
        with open(journal) as fh:
            committed = sum(1 for _ in fh)
        if not 0 < committed < 15 * n_chroms:
            raise RuntimeError(
                f"kill landed outside the scan: {committed} commits")
        _run(base_k + ["--resume"] + bam_urls, env)
        if _digests(out_k) != _digests(ref15):
            raise RuntimeError(
                "post-SIGKILL --resume artifacts != reference bytes")
        c = _manifest_counters(out_k)
        if c["chrom_qc_samples_resumed_total"] != committed:
            raise RuntimeError(
                f"resume replayed {c} blocks, journal holds "
                f"{committed}")
        if c["chrom_qc_samples_computed_total"] \
                != 15 * n_chroms - committed:
            raise RuntimeError(f"resume recompute count off: {c}")
        if verbose:
            print("biobank-smoke: SIGKILL mid-scan resumed byte-"
                  f"identically ({committed} blocks replayed, "
                  f"{15 * n_chroms - committed} recomputed)")
        if time.monotonic() - t0 > timeout_s:
            raise RuntimeError(
                f"biobank-smoke exceeded its {timeout_s:g}s budget")
    if verbose:
        print(f"biobank-smoke: PASS ({time.monotonic() - t0:.1f}s)")
    return 0


if __name__ == "__main__":
    sys.exit(run_smoke())
