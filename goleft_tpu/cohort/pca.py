"""Sharded on-device PCA over sample chunks.

``ops.indexcov_ops.pca_project`` — the small-cohort oracle — runs one
SVD over the full (samples × autosome-bins) matrix, which is exactly
the matrix the cohort plane refuses to materialize. This module
computes the same projection by block power iteration on the Gram
operator: every touch of the data is a chunk-local matmul

    partial = Cᵀ (C Q)        (C = centered chunk, Q the iterate)

summed across chunks — so peak memory is O(chunk × bins) + O(bins × k),
and each matmul runs on device (sharded over the sample axis via
``shard_map`` + psum when the process has several devices, a single
jitted kernel otherwise), accumulating in f64 where the backend allows
(``preferred_float``: CPU/x64 — TPUs accumulate f32).

Semantics match the oracle: column-center for the decomposition,
project the *raw* matrix onto the top-k right singular vectors, report
variance fractions against the TOTAL variance ‖C‖²_F/(n-1) (the oracle
divides by the full spectrum's sum, which is the same quantity). Power
iteration is iterative, so the sharded projection agrees with the
oracle to a tolerance, not byte-for-byte — ``cohortscan`` therefore
uses the oracle below ``--pca-exact-max`` samples (where byte-parity
with one-shot ``indexcov`` is pinned) and this path above it
(docs/cohort.md).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.dtypes import preferred_float


def _check_dims(n_samples: int, k: int) -> None:
    if n_samples < 2:
        raise ValueError(
            f"pca: need at least 2 samples, got {n_samples} — a "
            "single-sample cohort has no cross-sample variance")
    if k > n_samples:
        raise ValueError(
            f"pca: k={k} components exceed n_samples={n_samples}; "
            "pass k <= n_samples")


@jax.jit
def _chunk_stats(chunk: jax.Array):
    """(col_sum f64-where-possible, squared Frobenius norm) of one raw
    chunk — the pass-0 moments behind the mean and total variance."""
    acc_t = preferred_float()
    c = chunk.astype(acc_t)
    return c.sum(axis=0), (c * c).sum()


def _chunk_gram_impl(chunk: jax.Array, mean: jax.Array, q: jax.Array):
    """One chunk's contribution Cᵀ(CQ) to the Gram–iterate product."""
    acc_t = preferred_float()
    c = chunk.astype(acc_t) - mean.astype(acc_t)[None, :]
    w = c @ q.astype(acc_t)
    return c.T @ w


_chunk_gram = jax.jit(_chunk_gram_impl)


def _sharded_gram_fn(mesh):
    """shard_map'd version of the Gram step: rows split over the
    ``data`` axis, partials psummed on device — one collective instead
    of a host gather per chunk."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    def body(chunk, mean, q):
        g = _chunk_gram_impl(chunk, mean, q)
        return jax.lax.psum(g, "data")

    return jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(P("data", None), P(None), P(None, None)),
        out_specs=P(None, None),
    ))


class ShardedPCA:
    """Fitted sharded PCA: top-k right singular directions + variance
    fractions, with a per-chunk projection (never the full matrix)."""

    def __init__(self, components: np.ndarray, frac: np.ndarray,
                 mean: np.ndarray, iters: int):
        self.components_ = components  # (n_bins, k) f32
        self.frac_ = frac              # (k,) f32
        self.mean_ = mean
        self.iters_ = iters

    def project(self, chunk: np.ndarray) -> np.ndarray:
        """Raw-matrix projection of one sample chunk — the oracle's
        ``x @ vt[:k].T`` semantics (indexcov.go:773-807)."""
        x = np.asarray(chunk, np.float32)
        return np.asarray(x @ self.components_, np.float32)


def sharded_pca(chunks_fn, k: int = 5, *, iters: int = 32,
                seed: int = 1, mesh=None) -> ShardedPCA:
    """Fit top-k principal directions by chunked block power iteration.

    ``chunks_fn`` is a zero-arg callable yielding the sample chunks
    (each (chunk, n_bins) float32, all the same width) in cohort order;
    it is called ``iters + 1`` times, so chunks should be cheap to
    re-materialize (the scan engine mmap-reads its spill files).
    """
    # ---- pass 0: mean + total variance ----
    n = 0
    col_sum = None
    sumsq = 0.0
    n_bins = None
    for chunk in chunks_fn():
        chunk = np.asarray(chunk, np.float32)
        if n_bins is None:
            n_bins = chunk.shape[1]
            col_sum = np.zeros(n_bins, np.float64)
        s, ss = _chunk_stats(chunk)
        col_sum += np.asarray(s, np.float64)
        sumsq += float(ss)
        n += chunk.shape[0]
    if n_bins is None:
        raise ValueError("pca: empty cohort")
    _check_dims(n, k)
    k_eff = min(k, n, n_bins)
    mean = (col_sum / n).astype(np.float64)
    # ‖C‖²_F = Σ‖x‖² − n‖mean‖² (f64 throughout: catastrophic
    # cancellation here would poison every variance fraction)
    total_var = max(sumsq - n * float(mean @ mean), 0.0) \
        / max(n - 1, 1)

    mean32 = mean.astype(np.float32)
    gram = _chunk_gram
    if mesh is None and len(jax.local_devices()) > 1:
        from jax.sharding import Mesh

        mesh = Mesh(np.asarray(jax.local_devices()), ("data",))
    if mesh is not None and np.prod(mesh.devices.shape) > 1:
        try:
            sharded = _sharded_gram_fn(mesh)
            n_dev = int(np.prod(mesh.devices.shape))

            def gram(chunk, mean_a, q):  # noqa: F811 — sharded override
                rows = chunk.shape[0]
                pad = (-rows) % n_dev
                if pad:
                    # pad with mean rows: centered contribution is zero
                    chunk = np.concatenate(
                        [chunk, np.broadcast_to(mean_a, (pad,) +
                                                mean_a.shape)], axis=0)
                return sharded(chunk, mean_a, q)
        except Exception:  # noqa: BLE001 — shard_map unavailable: jit path
            gram = _chunk_gram

    # ---- block power iteration on the Gram operator ----
    rng = np.random.default_rng(seed)
    q = np.linalg.qr(
        rng.standard_normal((n_bins, k_eff)).astype(np.float64))[0]
    q = q.astype(np.float32)
    for _ in range(iters):
        acc = np.zeros((n_bins, k_eff), np.float64)
        for chunk in chunks_fn():
            acc += np.asarray(
                gram(np.asarray(chunk, np.float32), mean32, q),
                np.float64)
        q = np.linalg.qr(acc)[0].astype(np.float32)

    # ---- Rayleigh–Ritz rotation inside the converged subspace ----
    g = np.zeros((k_eff, k_eff), np.float64)
    for chunk in chunks_fn():
        w = np.asarray(_chunk_w(np.asarray(chunk, np.float32),
                                mean32, q), np.float64)
        g += w.T @ w
    evals, evecs = np.linalg.eigh(g)  # ascending
    order = np.argsort(evals)[::-1]
    evals = np.maximum(evals[order], 0.0)
    comp = (q.astype(np.float64) @ evecs[:, order]).astype(np.float32)
    # deterministic sign: largest-|loading| entry of each component
    # positive (SVD signs are arbitrary; pin them so re-runs and
    # resumes agree)
    for i in range(comp.shape[1]):
        j = int(np.argmax(np.abs(comp[:, i])))
        if comp[j, i] < 0:
            comp[:, i] = -comp[:, i]
    vars_ = evals / max(n - 1, 1)
    frac = (vars_ / total_var if total_var > 0
            else np.zeros_like(vars_)).astype(np.float32)
    return ShardedPCA(comp, frac[:k_eff], mean32, iters)


@jax.jit
def _chunk_w(chunk: jax.Array, mean: jax.Array, q: jax.Array):
    acc_t = preferred_float()
    c = chunk.astype(acc_t) - mean.astype(acc_t)[None, :]
    return c @ q.astype(acc_t)
