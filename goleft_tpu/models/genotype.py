"""Genotype likelihoods from pair-HMM read×haplotype scores.

The scoring layer behind ``goleft-tpu pairhmm`` and the serve
``pairhmm`` executor: windows of (reads, candidate haplotypes) are
flattened into one read×hap batch for :func:`ops.pairhmm.forward_pairs`
(every pair is independent, so windows from many requests coalesce
into the same bucketed device dispatches — and padding invariance
makes the result bitwise identical however they are batched), then
each window's (R, H) log-likelihood matrix folds into diploid
genotype likelihoods:

    log10 P(reads | G=(a,b)) = Σ_r log10( (10^ll[r,a] + 10^ll[r,b]) / 2 )

over all unordered haplotype pairs a ≤ b in VCF/GATK PL order
(index = b(b+1)/2 + a), normalized to phred-scaled PLs with the best
genotype at 0, and GQ = the second-smallest PL (capped 99).

Resilience: the per-bucket dispatch runs under a RetryPolicy (the
``pairhmm`` fault site) — transients are retried; a bucket that fails
permanently quarantines exactly the windows with pairs in it
(:class:`resilience.policy.Quarantine`) and the rest of the run
completes, mirroring the cohortdepth degraded-run contract (exit 3).
"""

from __future__ import annotations

import numpy as np

from ..ops import pairhmm as ph

PL_CAP = 99999  # phred cap for zero-likelihood genotypes
GQ_CAP = 99


def genotype_likelihoods(loglik: np.ndarray) -> dict:
    """(R, H) per-read log10 P(read|hap) → diploid genotype summary.

    Returns {"gl": (G,) log10 likelihoods in PL order, "pl": (G,) int
    phred-scaled normalized, "best": (a, b), "gq": int}. R may be 0
    (no reads: flat likelihoods, PL all 0, GQ 0).
    """
    ll = np.asarray(loglik, dtype=np.float64)
    n_reads, n_haps = ll.shape
    if n_haps < 1:
        raise ValueError("genotype_likelihoods: need >= 1 haplotype")
    gl = []
    pairs = []
    log2 = np.log10(2.0)
    for b in range(n_haps):
        for a in range(b + 1):
            pairs.append((a, b))
            if n_reads == 0:
                gl.append(0.0)
                continue
            la, lb = ll[:, a], ll[:, b]
            m = np.maximum(la, lb)
            # log10((10^la + 10^lb)/2), stable around the max
            with np.errstate(invalid="ignore"):
                s = m + np.log10(np.power(10.0, la - m)
                                 + np.power(10.0, lb - m)) - log2
            s = np.where(np.isfinite(m), s, -np.inf)
            gl.append(float(np.sum(s)))
    gl = np.array(gl)
    best_i = int(np.argmax(gl))
    mx = gl[best_i]
    with np.errstate(invalid="ignore"):
        pl = np.where(np.isfinite(gl),
                      np.rint(-10.0 * (gl - mx)), PL_CAP)
    pl = np.clip(pl, 0, PL_CAP).astype(np.int64)
    if len(pl) > 1:
        gq = int(min(np.partition(pl, 1)[1], GQ_CAP))
    else:
        gq = 0
    return {"gl": gl, "pl": pl, "best": pairs[best_i], "gq": gq}


def score_windows(windows, *, gap_open: float = ph.DEFAULT_GAP_OPEN,
                  gap_ext: float = ph.DEFAULT_GAP_EXT,
                  dtype=np.float32, policy=None, quarantine=None):
    """Score encoded windows → per-window genotype results.

    ``windows``: list of dicts with chrom/start/end, ``reads`` (list
    of (codes, quals) tuples) and ``haps`` (list of code arrays) —
    the shape :func:`load_windows` produces. All windows' read×hap
    pairs run as ONE bucketed forward batch. Returns (results,
    n_quarantined): ``results`` holds one dict per surviving window,
    in input order; windows hit by a permanently-failed bucket are
    recorded in ``quarantine`` (when given) and skipped.
    """
    flat_reads, flat_quals, flat_haps, owner = [], [], [], []
    spans = []
    for wi, w in enumerate(windows):
        lo = len(flat_reads)
        for codes, quals in w["reads"]:
            for hap in w["haps"]:
                flat_reads.append(codes)
                flat_quals.append(quals)
                flat_haps.append(hap)
                owner.append(wi)
        spans.append((lo, len(flat_reads)))
    vals, failed = ph.forward_pairs_partial(
        flat_reads, flat_quals, flat_haps, gap_open=gap_open,
        gap_ext=gap_ext, dtype=dtype, policy=policy,
        allow_partial=quarantine is not None)
    bad_windows = {owner[i]: err for i, err in failed.items()}
    results = []
    for wi, w in enumerate(windows):
        if wi in bad_windows:
            if quarantine is not None:
                quarantine.add(
                    wi, f"{w['chrom']}:{w['start']}-{w['end']}",
                    w.get("source", ""), bad_windows[wi],
                    classification="permanent", phase="pairhmm")
            continue
        lo, hi = spans[wi]
        n_haps = len(w["haps"])
        n_reads = len(w["reads"])
        ll = vals[lo:hi].reshape(n_reads, n_haps) if n_haps else \
            np.zeros((n_reads, 0))
        g = genotype_likelihoods(ll)
        results.append({
            "chrom": w["chrom"], "start": w["start"], "end": w["end"],
            "n_reads": n_reads, "n_haps": n_haps,
            "genotype": f"{g['best'][0]}/{g['best'][1]}",
            "gq": g["gq"],
            "pl": [int(v) for v in g["pl"]],
            "read_hap_log10": ll,
        })
    return results, len(bad_windows)


HEADER = "#chrom\tstart\tend\treads\thaps\tgenotype\tGQ\tPL\n"


def format_table(results) -> str:
    """The pairhmm output table — the single formatting path the CLI
    writes and the serve executor returns, so byte-identity between
    them is structural."""
    lines = [HEADER]
    for r in results:
        lines.append(
            f"{r['chrom']}\t{r['start']}\t{r['end']}\t{r['n_reads']}"
            f"\t{r['n_haps']}\t{r['genotype']}\t{r['gq']}\t"
            + ",".join(str(v) for v in r["pl"]) + "\n")
    return "".join(lines)


def load_windows(doc, source: str = "") -> list[dict]:
    """Validate + encode a pairhmm-windows document (schema
    ``goleft-tpu.pairhmm-windows/1``) into score_windows' input shape.
    Raises ValueError (the CLI's clean-error contract) on anything
    malformed. Qualities: per-read int list, phred+33 string, or a
    single int applied to every base (default 30 when absent).
    """
    if not isinstance(doc, dict):
        raise ValueError("pairhmm windows: document must be a JSON "
                         "object")
    schema = doc.get("schema", "")
    if not str(schema).startswith("goleft-tpu.pairhmm-windows/1"):
        raise ValueError(
            f"pairhmm windows: unsupported schema {schema!r} "
            "(want goleft-tpu.pairhmm-windows/1)")
    raw = doc.get("windows")
    if not isinstance(raw, list):
        raise ValueError("pairhmm windows: 'windows' must be a list")
    out = []
    for n, w in enumerate(raw):
        where = f"window {n}"
        if not isinstance(w, dict):
            raise ValueError(f"pairhmm windows: {where} must be an "
                             "object")
        try:
            chrom = str(w["chrom"])
            start = int(w["start"])
            end = int(w["end"])
        except (KeyError, TypeError, ValueError):
            raise ValueError(
                f"pairhmm windows: {where} needs chrom/start/end") \
                from None
        haps = w.get("haplotypes")
        if not isinstance(haps, list) or not haps:
            raise ValueError(
                f"pairhmm windows: {where} needs a non-empty "
                "'haplotypes' list")
        enc_haps = []
        for h in haps:
            if not isinstance(h, str) or not h:
                raise ValueError(
                    f"pairhmm windows: {where}: haplotypes must be "
                    "non-empty strings")
            enc_haps.append(ph.encode_seq(h))
        reads = []
        for r in w.get("reads", []):
            if not isinstance(r, dict) or not isinstance(
                    r.get("seq"), str) or not r["seq"]:
                raise ValueError(
                    f"pairhmm windows: {where}: each read needs a "
                    "non-empty 'seq' string")
            seq = r["seq"]
            q = r.get("quals", 30)
            if isinstance(q, str):
                quals = np.frombuffer(q.encode("ascii"),
                                      dtype=np.uint8).astype(
                    np.int64) - 33
            elif isinstance(q, (int, float)):
                quals = np.full(len(seq), int(q), dtype=np.int64)
            elif isinstance(q, list):
                quals = np.asarray(q, dtype=np.int64)
            else:
                raise ValueError(
                    f"pairhmm windows: {where}: quals must be a "
                    "phred+33 string, an int, or an int list")
            if len(quals) != len(seq):
                raise ValueError(
                    f"pairhmm windows: {where}: quals length "
                    f"{len(quals)} != seq length {len(seq)}")
            if (quals < 0).any():
                raise ValueError(
                    f"pairhmm windows: {where}: negative quality")
            # phred clamp: q0 would make the emission prior 0/negative
            # and anything past ~q93 is noise; GATK clamps the same way
            quals = np.clip(quals, 1, 93)
            reads.append((ph.encode_seq(seq), quals))
        out.append({"chrom": chrom, "start": start, "end": end,
                    "haps": enc_haps, "reads": reads,
                    "source": source})
    return out
