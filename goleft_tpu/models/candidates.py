"""CNV candidate intervals: the stable handoff schema between tools.

``emdepth``/``dcnv`` export their aberrant-depth intervals with
``--candidates-out``; ``pairhmm`` consumes them with ``--candidates``
to restrict genotyping to windows the coverage stack flagged. The
format is machine-readable and pinned so the producers and the
consumer can evolve independently:

  - ``*.json``: ``{"schema": "goleft-tpu.cnv-candidates/1",
    "source": "<tool>", "candidates": [{chrom, start, end, sample,
    cn, log2fc}, ...]}``
  - anything else: BED-style TSV with two header lines —
    ``#goleft-tpu-candidates=1 source=<tool>`` then
    ``#chrom\\tstart\\tend\\tsample\\tCN\\tlog2FC`` — one record per
    data row (log2FC printed ``%.4f``)

``read_candidates`` sniffs the format from content (a JSON document
starts with ``{``), so either file round-trips regardless of its
name. Pure numpy/stdlib — no jax import.
"""

from __future__ import annotations

import json

import numpy as np

SCHEMA = "goleft-tpu.cnv-candidates/1"
_BED_MAGIC = "#goleft-tpu-candidates=1"

#: the emdepth merge thresholds (models/emdepth.py _make_cnv): windows
#: with log2 fold-change inside this open interval are "normal"
LOG2FC_LO = -0.5
LOG2FC_HI = 0.3
MERGE_GAP = 30_000  # same 30kb gap rule the emdepth CNV merge uses


def write_candidates(path: str, records, source: str) -> None:
    """Write candidate records (dicts with chrom/start/end/sample/cn/
    log2fc) as JSON (``*.json``) or the BED-style TSV."""
    records = [
        {"chrom": str(r["chrom"]), "start": int(r["start"]),
         "end": int(r["end"]), "sample": str(r["sample"]),
         "cn": int(r["cn"]),
         # 4 decimals in BOTH encodings so BED and JSON exports of
         # the same calls are record-for-record equal
         "log2fc": round(float(r["log2fc"]), 4)}
        for r in records
    ]
    if path.endswith(".json"):
        with open(path, "w") as fh:
            json.dump({"schema": SCHEMA, "source": source,
                       "candidates": records}, fh, indent=1,
                      sort_keys=True)
            fh.write("\n")
        return
    with open(path, "w") as fh:
        fh.write(f"{_BED_MAGIC} source={source}\n")
        fh.write("#chrom\tstart\tend\tsample\tCN\tlog2FC\n")
        for r in records:
            fh.write(f"{r['chrom']}\t{r['start']}\t{r['end']}\t"
                     f"{r['sample']}\t{r['cn']}\t{r['log2fc']:.4f}\n")


def read_candidates(path: str) -> list[dict]:
    """Parse either candidate format → list of record dicts; raises
    ValueError (the CLI's clean-error contract) on anything else."""
    with open(path) as fh:
        text = fh.read()
    stripped = text.lstrip()
    if stripped.startswith("{"):
        try:
            doc = json.loads(text)
        except json.JSONDecodeError as e:
            raise ValueError(f"candidates {path}: bad JSON: {e}") \
                from None
        schema = doc.get("schema", "")
        if not schema.startswith("goleft-tpu.cnv-candidates/1"):
            raise ValueError(
                f"candidates {path}: unsupported schema {schema!r} "
                f"(want {SCHEMA})")
        out = []
        for r in doc.get("candidates", []):
            try:
                out.append({"chrom": str(r["chrom"]),
                            "start": int(r["start"]),
                            "end": int(r["end"]),
                            "sample": str(r.get("sample", "")),
                            "cn": int(r.get("cn", -1)),
                            "log2fc": float(r.get("log2fc", 0.0))})
            except (KeyError, TypeError, ValueError) as e:
                raise ValueError(
                    f"candidates {path}: bad record {r!r}: {e}") \
                    from None
        return out
    lines = text.splitlines()
    if not lines or not lines[0].startswith(_BED_MAGIC):
        raise ValueError(
            f"candidates {path}: not a goleft-tpu candidates file "
            f"(missing {_BED_MAGIC!r} header or JSON document)")
    out = []
    for ln in lines[1:]:
        if not ln or ln.startswith("#"):
            continue
        t = ln.split("\t")
        if len(t) < 6:
            raise ValueError(
                f"candidates {path}: short row {ln!r} (want 6 cols)")
        try:
            out.append({"chrom": t[0], "start": int(t[1]),
                        "end": int(t[2]), "sample": t[3],
                        "cn": int(t[4]), "log2fc": float(t[5])})
        except ValueError as e:
            raise ValueError(
                f"candidates {path}: bad row {ln!r}: {e}") from None
    return out


def overlaps_any(candidates, chrom: str, start: int, end: int) -> bool:
    """True when [start, end) on chrom overlaps any candidate."""
    for c in candidates:
        if c["chrom"] == chrom and c["start"] < end \
                and start < c["end"]:
            return True
    return False


def candidates_from_calls(results) -> list[dict]:
    """emdepth CNV-call tuples (chrom, start, end, sample, CN,
    log2FC) — what ``call_cnvs`` returns — to candidate records."""
    return [{"chrom": c, "start": s, "end": e, "sample": smp,
             "cn": cn, "log2fc": fc}
            for c, s, e, smp, cn, fc in results]


def candidates_from_matrix(chroms, starts, ends, norm, samples,
                           lo: float = LOG2FC_LO,
                           hi: float = LOG2FC_HI,
                           gap: int = MERGE_GAP) -> list[dict]:
    """Aberrant intervals straight from a normalized depth matrix —
    the ``dcnv --candidates-out`` path (dcnv's output is scaled
    coverage around 1.0, so log2 of the value IS the fold change vs
    CN2). Per sample: flag windows with log2fc outside (lo, hi), merge
    same-state runs closer than ``gap`` (the emdepth 30kb rule), and
    report the run's mean fold change with CN = round(2·2^fc)."""
    norm = np.asarray(norm, dtype=np.float64)
    with np.errstate(divide="ignore"):
        fc = np.log2(np.where(norm > 0, norm, np.nan))
    out = []
    for si, sample in enumerate(samples):
        run = None  # [chrom, start, end, [fcs]]

        def flush(run=None, _out=out, _sample=sample):
            if run is None:
                return
            mean_fc = float(np.mean(run[3]))
            _out.append({
                "chrom": run[0], "start": run[1], "end": run[2],
                "sample": _sample,
                "cn": int(np.clip(round(2.0 * 2.0 ** mean_fc), 0, 8)),
                "log2fc": mean_fc,
            })

        for b in range(len(chroms)):
            v = fc[b, si]
            flagged = np.isfinite(v) and not (lo < v < hi)
            zero = not np.isfinite(v)  # depth 0 → full loss
            if zero:
                flagged, v = True, float(np.log2(2 ** LOG2FC_LO / 2))
            if not flagged:
                continue
            c, s, e = str(chroms[b]), int(starts[b]), int(ends[b])
            if run is not None and run[0] == c and s - run[2] < gap:
                run[2] = e
                run[3].append(v)
            else:
                flush(run)
                run = [c, s, e, [v]]
        flush(run)
    out.sort(key=lambda r: (r["chrom"], r["start"], r["sample"]))
    return out
