"""End-to-end smoke for the pair-HMM stack: `make pairhmm-smoke`.

The full candidate → likelihood pipeline as real subprocesses:

  1. ``goleft-tpu emdepth --candidates-out`` on a fabricated depth
     matrix with a planted deletion → a machine-readable candidates
     file naming the aberrant interval
  2. ``goleft-tpu pairhmm --candidates`` on a windows document whose
     reads support the alternate haplotype → the PL table, with the
     off-candidate window filtered out
  3. a real ``goleft-tpu serve`` daemon: the ``/v1/pairhmm`` response
     must be byte-identical to the CLI stdout for the same request
  4. chaos: the same CLI run under an injected transient fault at the
     ``pairhmm`` site (``--inject-faults pairhmm:after=1:...``) must
     retry and produce byte-identical output, exit 0

Host-pinned with the probe skipped, like the other smokes. Run::

    python -m goleft_tpu.models.pairhmm_smoke
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time


def _write_matrix(path: str) -> None:
    """depthwed-style matrix: 8 samples at depth ~50, sample s3
    halved (a heterozygous deletion) over windows 10-15 of chr1."""
    import numpy as np

    rng = np.random.default_rng(5)
    samples = [f"s{i}" for i in range(8)]
    with open(path, "w") as fh:
        fh.write("#chrom\tstart\tend\t" + "\t".join(samples) + "\n")
        for w in range(40):
            start, end = w * 500, (w + 1) * 500
            row = rng.normal(50, 2, size=8)
            if 10 <= w < 16:
                row[3] *= 0.5
            fh.write(f"chr1\t{start}\t{end}\t"
                     + "\t".join(f"{v:.1f}" for v in row) + "\n")


def _write_windows(path: str) -> None:
    """Two windows: one inside the planted deletion (reads split
    between ref and alt haplotypes — a het site), one far away (the
    candidates filter must drop it)."""
    import numpy as np

    rng = np.random.default_rng(6)
    bases = list("ACGT")
    ref = "".join(rng.choice(bases, 60))
    alt = ref[:29] + ("A" if ref[29] != "A" else "C") + ref[30:]
    reads = []
    for i in range(8):
        src = ref if i % 2 else alt
        start = int(rng.integers(0, 10))
        reads.append({"seq": src[start:start + 40], "quals": 35})
    doc = {"schema": "goleft-tpu.pairhmm-windows/1",
           "windows": [
               {"chrom": "chr1", "start": 6100, "end": 6400,
                "haplotypes": [ref, alt], "reads": reads},
               {"chrom": "chr1", "start": 19_500, "end": 19_600,
                "haplotypes": [ref], "reads": reads[:2]},
           ]}
    with open(path, "w") as fh:
        json.dump(doc, fh)


def run_smoke(timeout_s: float = 180.0, verbose: bool = True) -> int:
    from ..models.candidates import read_candidates
    from ..serve.client import ServeClient

    env = dict(os.environ, JAX_PLATFORMS="cpu", GOLEFT_TPU_PROBE="0")
    deadline = time.monotonic() + timeout_s

    def run_cli(*args):
        r = subprocess.run(
            [sys.executable, "-m", "goleft_tpu", *args],
            capture_output=True, text=True, env=env,
            timeout=max(5.0, deadline - time.monotonic()))
        return r

    with tempfile.TemporaryDirectory(prefix="goleft_phmm_") as d:
        matrix = os.path.join(d, "matrix.tsv")
        cand = os.path.join(d, "cand.bed")
        windows = os.path.join(d, "windows.json")
        _write_matrix(matrix)
        _write_windows(windows)

        # 1. emdepth exports machine-readable candidates
        r = run_cli("emdepth", "--candidates-out", cand, matrix)
        if r.returncode != 0:
            raise RuntimeError(f"emdepth failed: {r.stderr}")
        cands = read_candidates(cand)
        hits = [c for c in cands if c["sample"] == "s3"
                and c["start"] < 6400 and 6100 < c["end"]]
        if not hits:
            raise RuntimeError(
                f"emdepth candidates missed the planted deletion: "
                f"{cands}")
        if verbose:
            print(f"pairhmm-smoke: emdepth flagged the deletion "
                  f"({hits[0]['chrom']}:{hits[0]['start']}-"
                  f"{hits[0]['end']} CN{hits[0]['cn']})")

        # 2. pairhmm scores the candidate window (and only it)
        r = run_cli("pairhmm", "--candidates", cand, windows)
        if r.returncode != 0:
            raise RuntimeError(f"pairhmm failed: {r.stderr}")
        table = r.stdout
        lines = [ln for ln in table.splitlines() if ln]
        if len(lines) != 2 or not lines[0].startswith("#chrom"):
            raise RuntimeError(
                f"pairhmm table shape wrong (want header + the one "
                f"candidate window): {table!r}")
        cols = lines[1].split("\t")
        if cols[5] != "0/1":
            raise RuntimeError(
                f"expected het genotype 0/1 at the planted site, "
                f"got {cols[5]} (row: {lines[1]!r})")
        pls = [int(v) for v in cols[7].split(",")]
        if len(pls) != 3 or min(pls) != 0:
            raise RuntimeError(f"malformed PL vector: {cols[7]!r}")
        if verbose:
            print(f"pairhmm-smoke: CLI genotyped the site "
                  f"{cols[5]} GQ={cols[6]} PL={cols[7]}")

        # 3. serve round-trip: byte-identical to the CLI
        child = subprocess.Popen(
            [sys.executable, "-m", "goleft_tpu", "serve", "--port",
             "0", "--no-warmup"],
            stdout=subprocess.PIPE, text=True, env=env)
        try:
            line = child.stdout.readline()
            if "listening on " not in line:
                raise RuntimeError(
                    f"serve did not announce its port: {line!r}")
            url = line.rsplit("listening on ", 1)[1].strip()
            client = ServeClient(url, timeout_s=60.0)
            resp = client.pairhmm(windows, candidates=cand)
            if resp["likelihoods_tsv"] != table:
                raise RuntimeError(
                    "serve pairhmm response is not byte-identical "
                    f"to the CLI:\nCLI: {table!r}\nserve: "
                    f"{resp['likelihoods_tsv']!r}")
            if verbose:
                print("pairhmm-smoke: serve /v1/pairhmm response "
                      "byte-identical to the CLI")
            child.send_signal(signal.SIGTERM)
            rc = child.wait(timeout=max(
                5.0, deadline - time.monotonic()))
            if rc != 0:
                raise RuntimeError(f"serve exited {rc}, want 0")
        finally:
            if child.poll() is None:
                child.kill()
                child.wait(timeout=10.0)
            child.stdout.close()

        # 4. chaos: injected transient at the pairhmm site → retried,
        # byte-identical, exit 0
        r = run_cli("--inject-faults",
                    "pairhmm:after=1:times=1:transient",
                    "pairhmm", "--candidates", cand, windows)
        if r.returncode != 0:
            raise RuntimeError(
                f"pairhmm under injected transient fault exited "
                f"{r.returncode}: {r.stderr}")
        if r.stdout != table:
            raise RuntimeError(
                "retried run's output differs from the clean run")
        if verbose:
            print("pairhmm-smoke: injected transient retried to "
                  "byte-identical output, exit 0")
            print("pairhmm-smoke: OK")
        return 0


if __name__ == "__main__":
    sys.exit(run_smoke())
