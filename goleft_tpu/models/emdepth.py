"""Batched EM copy-number caller (cn.mops-simplified).

TPU-native rebuild of the reference's per-window sequential EM
(emdepth/emdepth.go:117-206): here every genomic window runs as one row of
a (windows × samples) batch inside a single jit — the fixed ≤10-iteration
loop becomes a fori_loop with per-window convergence masking (converged
rows freeze their λ, reproducing the reference's early exit), and the
data-dependent binning becomes vectorized one-hot reductions.

Reference semantics reproduced (citations into /root/reference):
  - λ init: λ0 = 0.01·median, λ2 = median (with the even-length median
    quirk of emdepth.go:25-28), λi = λ2·(i/2)^1.1 (":129-138")
  - binning with CN2 preference inside (λ1, λ3) (":152-176")
  - λ2 ← mean(bin2), with the empty-bin fallback mixing other bins
    (":180-192"); λi ← λ2·i/2; CN1/CN3 basin widening by span/1.5
    (":194-201")
  - convergence when sum|Δλ| ≤ 0.01 or max|Δλ| ≤ 0.5 (":67,143,202")
  - CN assignment: nearest λ with Poisson-PMF tiebreak toward CN2
    (o·0.9 < o2 → CN2, ":293-304")

Documented divergence: depths above λ8 get CN = maxCN = 8. The reference
code returns len(Lambda) = 9 there (emdepth.go:278-279 feeding :296's
``cn < len`` guard, which skips adjustment), yet its own golden test
expects 8 (emdepth_test.go:31-38) — we implement the tested intent.

Host-side streaming CNV merge (Cache/makecnvs, ":310-398") operates on the
device results.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

MAX_CN = 8
MAX_ITER = 10
EPS = 0.01
LOWER = -0.80  # emdepth.go:224
UPPER = 0.40  # emdepth.go:225
N_LAMBDA = MAX_CN + 1


def _median32_even_quirk(d: jax.Array) -> jax.Array:
    """Row median with the reference's even-length quirk: averages the two
    elements above the midpoint (emdepth.go:25-28)."""
    s = jnp.sort(d, axis=-1)
    n = d.shape[-1]
    if n % 2 == 1:
        return s[..., n // 2]
    return (s[..., n // 2] + s[..., n // 2 + 1]) / 2


def _assign_bins(d: jax.Array, lam: jax.Array) -> jax.Array:
    """Per-sample bin index (emdepth.go:152-176). d: (S,), lam: (9,)."""
    # search: count of lam entries < d
    idx = jnp.sum(lam[None, :] < d[:, None], axis=1)
    idx_hi = jnp.minimum(idx, N_LAMBDA - 1)
    near_hi = jnp.abs(d - lam[idx_hi]) < jnp.abs(
        d - lam[jnp.maximum(idx - 1, 0)]
    )
    pick = jnp.where(
        idx == 0,
        0,
        jnp.where(
            idx >= N_LAMBDA,
            N_LAMBDA - 1,
            jnp.where(near_hi, idx_hi, jnp.maximum(idx - 1, 0)),
        ),
    )
    # CN2 preference
    pref2 = (
        (d > lam[1]) & (d < lam[3])
        & (jnp.abs(d - lam[2]) < jnp.abs(d - lam[1]))
        & (jnp.abs(d - lam[2]) < jnp.abs(d - lam[3]))
    )
    return jnp.where(pref2, 2, pick)


def _em_one(d: jax.Array) -> jax.Array:
    """EM for one window's depth vector d (S,) → λ (9,)."""
    dtype = d.dtype
    m = _median32_even_quirk(d)
    i_arr = jnp.arange(N_LAMBDA, dtype=dtype)
    lam0 = jnp.where(
        i_arr == 0,
        EPS * m,
        jnp.where(i_arr == 2, m, m * (i_arr / 2) ** 1.1),
    )

    n = d.shape[0]

    def body(_, carry):
        lam, active = carry
        bins = _assign_bins(d, lam)
        onehot = jax.nn.one_hot(bins, N_LAMBDA, dtype=dtype)  # (S, 9)
        counts = onehot.sum(axis=0)
        sums = (onehot * d[:, None]).sum(axis=0)
        means = jnp.where(counts > 0, sums / jnp.maximum(counts, 1), 0.0)
        lam2 = means[2]
        # empty-bin-2 fallback (emdepth.go:181-192): mix bins 1..7 scaled
        # to CN2, weighted by occupancy
        mid = jnp.arange(1, N_LAMBDA - 1)
        fallback = jnp.sum(
            means[mid] * (2.0 / mid.astype(dtype)) * (counts[mid] / n)
        )
        # reference tests λ2 == 0 exactly (a bin of all-zero depths also
        # triggers the fallback), emdepth.go:181
        lam2 = jnp.where(lam2 != 0, lam2, fallback)
        new = jnp.where(i_arr == 0, lam[0], lam2 * i_arr / 2)
        span = new[2] - new[1]
        new = new.at[1].add(-span / 1.5).at[3].add(span / 1.5)
        diff = jnp.abs(new - lam)
        still = (diff.sum() > EPS) & (diff.max() > 0.5)
        out = jnp.where(active, new, lam)
        return out, active & still

    lam, _ = jax.lax.fori_loop(
        0, MAX_ITER, body, (lam0, jnp.asarray(True))
    )
    return lam


@jax.jit
def em_depth_batch(depths: jax.Array) -> jax.Array:
    """(B, S) normalized depths → (B, 9) λ centers."""
    return jax.vmap(_em_one)(depths)


def _poisson_pmf(k: jax.Array, mu: jax.Array) -> jax.Array:
    lg = jax.scipy.special.gammaln(k.astype(mu.dtype) + 1)
    tiny = jnp.asarray(1e-30, mu.dtype)  # f32-safe log floor
    return jnp.exp(k * jnp.log(jnp.maximum(mu, tiny)) - lg - mu)


@jax.jit
def cn_batch(lambdas: jax.Array, depths: jax.Array) -> jax.Array:
    """Posterior-max CN per (window, sample) with Poisson CN2 tiebreak.
    lambdas: (B, 9), depths: (B, S) → int32 (B, S)."""

    def one(lam, d):
        idx = jnp.sum(lam[None, :] < d[:, None], axis=1)
        idx_hi = jnp.minimum(idx, N_LAMBDA - 1)
        near_hi = jnp.abs(d - lam[idx_hi]) < jnp.abs(
            d - lam[jnp.maximum(idx - 1, 0)]
        )
        cn = jnp.where(
            idx == 0,
            0,
            jnp.where(
                idx >= N_LAMBDA,
                MAX_CN,  # divergence: clamp (see module docstring)
                jnp.where(near_hi, idx_hi, jnp.maximum(idx - 1, 0)),
            ),
        )
        dk = jnp.floor(0.5 + d)
        o = _poisson_pmf(dk, lam[jnp.clip(cn, 0, N_LAMBDA - 1)])
        o2 = _poisson_pmf(dk, lam[2])
        return jnp.where(
            (cn != 2) & (o * 0.9 < o2), 2, cn
        ).astype(jnp.int32)

    return jax.vmap(one)(lambdas, depths)


@jax.jit
def log2fc_batch(lambdas: jax.Array, depths: jax.Array) -> jax.Array:
    """Fold change vs CN2 (emdepth.go:250-260)."""
    return jnp.log2(depths / lambdas[:, 2:3])


# ---------------------------------------------------------------------------
# host-side streaming CNV merge (emdepth.go:310-398)


@dataclass
class EMD:
    """One window's EM result (mirrors the reference EMD struct)."""

    lam: np.ndarray  # (9,)
    depths: np.ndarray  # (S,)
    start: int
    end: int
    _l2: np.ndarray | None = None
    _cn: np.ndarray | None = None

    def log2fc(self) -> np.ndarray:
        if self._l2 is None:
            with np.errstate(divide="ignore"):
                self._l2 = np.log2(
                    self.depths.astype(np.float64) / self.lam[2]
                )
        return self._l2

    def cn(self) -> np.ndarray:
        if self._cn is None:
            self._cn = np.asarray(
                cn_batch(self.lam[None], self.depths[None])
            )[0]
        return self._cn

    def same(self, other: "EMD") -> tuple[list[int], list[int], float]:
        """(non-CN2-in-both samples, changed samples, share unchanged)
        (emdepth.go:227-247)."""
        ee = self.log2fc()
        oo = other.log2fc()
        non2, changed = [], []
        n_same = 0
        for i in range(len(ee)):
            if LOWER < ee[i] < UPPER and LOWER < oo[i] < UPPER:
                n_same += 1
            elif (oo[i] >= UPPER and ee[i] >= UPPER) or (
                oo[i] <= LOWER and ee[i] <= LOWER
            ):
                non2.append(i)
                n_same += 1
            else:
                changed.append(i)
        return non2, changed, n_same / len(self.depths)


def em_depth(depths, start: int = 0, end: int = 0) -> EMD:
    """Single-window convenience mirroring the reference EMDepth()."""
    d = np.asarray(depths, dtype=np.float64)
    lam = np.asarray(em_depth_batch(d[None]))[0]
    return EMD(lam, d, start, end)


@dataclass
class CNV:
    """Merged aberrant-depth run for one sample (emdepth.go:317-324)."""

    sample_i: int
    depth: list
    positions: list  # (start, end) tuples
    log2fc: list
    cn: list
    psize: int = 0


GAP = 30_000  # merge gap, emdepth.go:360


@dataclass
class Cache:
    """Streaming CNV state tracker (emdepth.go:310-373)."""

    last: EMD | None = None
    cnvs: dict = field(default_factory=dict)

    def add(self, e: EMD) -> list[CNV]:
        if self.last is None:
            self.last = e
        ret = self.clear((e.start, e.end))
        non2, _, _ = self.last.same(e)
        for si in non2:
            self.cnvs.setdefault(si, []).append(e)
        self.last = e
        return ret

    def clear(self, pos=None) -> list[CNV]:
        if pos is None:
            if self.last is None:
                return []
            pos = (self.last.start + 100_000, self.last.end + 100_000)
        out = []
        done = []
        for si, emds in self.cnvs.items():
            if pos[0] - emds[-1].end < GAP:
                continue
            put = _make_cnv(emds, si)
            if put is not None:
                put.psize = len(self.cnvs)
                out.append(put)
            done.append(si)
        for k in done:
            del self.cnvs[k]
        return out


def _make_cnv(emds: list[EMD], sample_i: int) -> CNV | None:
    """(emdepth.go:376-398): keep windows with |fc| beyond (-0.5, 0.3)."""
    cnv = None
    for e in emds:
        fc = e.log2fc()[sample_i]
        if -0.5 < fc < 0.3:
            continue
        cn = int(e.cn()[sample_i])
        if cnv is None:
            cnv = CNV(sample_i, [float(e.depths[sample_i])],
                      [(e.start, e.end)], [float(fc)], [cn])
        else:
            cnv.depth.append(float(e.depths[sample_i]))
            cnv.positions.append((e.start, e.end))
            cnv.log2fc.append(float(fc))
            cnv.cn.append(cn)
    return cnv
