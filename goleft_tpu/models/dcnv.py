"""dcnv: depth-matrix normalization — scalers, GC debiasers, SVD.

Rebuild of the reference's prototype dcnv stack (dcnv/dcnv.go,
dcnv/debiaser/debiaser.go, dcnv/scalers/scalers.go) as matrix ops:

  - Scalers (Scale/UnScale round-trip): ZScore per row, Row/Col centering,
    Log2 (log2(1+d) then median column-centering) — scalers.go:25-164
  - GeneralDebiaser: argsort rows by a covariate (GC), divide each sample
    column by its moving median in the sorted order, unsort —
    debiaser.go:56-123. The moving-median alignment replicates the
    reference's push sequence (window median trails by (w-1)/2+1).
  - ChunkDebiaser: bucket rows by covariate span, divide by per-bucket
    nonzero median — debiaser.go:125-171
  - SVD debias: zero leading components with variance% ≥ MinVariancePct —
    debiaser.go:173-199 (the reference's extractSVD passes nil matrices
    and would panic (":202-209"); ours is functional)
  - SampleMedians: 65th percentile of each sample's nonzero depths,
    dcnv.go:108-125

Matrix orientation matches the reference: rows = sites, cols = samples.
"""

from __future__ import annotations

import bisect

import jax.numpy as jnp
import numpy as np


class ZScore:
    """Per-row z-score (scalers.go:25-56)."""

    def scale(self, a: np.ndarray) -> np.ndarray:
        self.means = a.mean(axis=1, keepdims=True)
        self.sds = a.std(axis=1, ddof=1, keepdims=True)
        return (a - self.means) / self.sds

    def unscale(self, a: np.ndarray) -> np.ndarray:
        return np.maximum(0, a * self.sds + self.means)


class RowCentered:
    def __init__(self, centerer=np.mean):
        self.centerer = centerer

    def scale(self, a):
        self.centers = np.apply_along_axis(self.centerer, 1, a)[:, None]
        return a - self.centers

    def unscale(self, a):
        return a + self.centers


class ColCentered:
    def __init__(self, centerer=np.mean):
        self.centerer = centerer

    def scale(self, a):
        self.centers = np.apply_along_axis(self.centerer, 0, a)[None, :]
        return a - self.centers

    def unscale(self, a):
        return a + self.centers


def _gmedian(v):
    """sorted-middle median, as the reference's gmean (scalers.go:125-130)."""
    s = np.sort(v)
    return s[len(s) // 2]


class Log2:
    """log2(1+d) then median column-centering (scalers.go:133-164)."""

    def __init__(self):
        self.cc = ColCentered(_gmedian)

    def scale(self, a):
        return self.cc.scale(np.log2(1 + a))

    def unscale(self, a):
        return np.power(2.0, self.cc.unscale(a))


class _MovingMedian:
    """Median of the last `window` pushed values (JaderDias/movingmedian
    semantics: even counts average the middle pair)."""

    def __init__(self, window: int):
        self.window = window
        self.queue: list[float] = []
        self.sorted: list[float] = []

    def push(self, v: float) -> None:
        self.queue.append(v)
        bisect.insort(self.sorted, v)
        if len(self.queue) > self.window:
            old = self.queue.pop(0)
            del self.sorted[bisect.bisect_left(self.sorted, old)]

    def median(self) -> float:
        s = self.sorted
        n = len(s)
        if n == 0:
            return 0.0
        if n % 2 == 1:
            return s[n // 2]
        return 0.5 * (s[n // 2 - 1] + s[n // 2])


class GeneralDebiaser:
    """Sort rows by covariate, moving-median divide, unsort
    (debiaser.go:56-123)."""

    def __init__(self, vals: np.ndarray, window: int = 65):
        self.vals = np.asarray(vals, dtype=np.float64)
        self.window = window
        self.order: np.ndarray | None = None

    def sort(self, a: np.ndarray) -> np.ndarray:
        self.order = np.argsort(self.vals, kind="stable")
        self.vals = self.vals[self.order]
        return a[self.order]

    def unsort(self, a: np.ndarray) -> np.ndarray:
        if self.order is None:
            raise RuntimeError("unsort: must call sort first")
        inv = np.empty_like(self.order)
        inv[self.order] = np.arange(len(self.order))
        self.vals = self.vals[inv]
        return a[inv]

    def debias(self, a: np.ndarray) -> np.ndarray:
        out = a.copy()
        r = a.shape[0]
        mid = (self.window - 1) // 2 + 1
        for s in range(a.shape[1]):
            col = a[:, s]
            mm = _MovingMedian(self.window)
            new = np.empty(r)
            for i in range(min(mid, r)):
                mm.push(col[i])
            for i in range(min(mid, r)):
                new[i] = col[i] / max(mm.median(), 1.0)
            for i in range(mid, max(r - mid, mid)):
                if i + mid < r:
                    mm.push(col[i + mid])
                new[i] = col[i] / max(mm.median(), 1.0)
            for i in range(max(r - mid, mid), r):
                new[i] = col[i] / max(mm.median(), 1.0)
            out[:, s] = new
        return out


class ChunkDebiaser:
    """Bucketed covariate median divide (debiaser.go:125-171).
    Assumes rows sorted by covariate (call sort() first)."""

    def __init__(self, vals: np.ndarray, score_window: float):
        if score_window == 0:
            raise ValueError("must set ChunkDebiaser.score_window")
        self.vals = np.asarray(vals, dtype=np.float64)
        self.score_window = score_window
        self.order = None

    sort = GeneralDebiaser.sort
    unsort = GeneralDebiaser.unsort

    def debias(self, a: np.ndarray) -> np.ndarray:
        out = a.copy()
        slices = [0]
        v0 = self.vals[0]
        for i in range(len(self.vals)):
            if self.vals[i] - v0 > self.score_window:
                v0 = self.vals[i]
                slices.append(i)
        slices.append(len(self.vals))
        for s in range(a.shape[1]):
            col = out[:, s]
            for si, ei in zip(slices, slices[1:]):
                sub = np.sort(col[si:ei])
                k = int(np.searchsorted(sub, 0, side="right"))
                med = sub[min((ei - si - k) // 2, len(sub) - 1)]
                if med > 0:
                    col[si:ei] /= med
        return out


class SVDDebiaser:
    """Zero the leading singular components carrying ≥ min_variance_pct of
    variance (debiaser.go:173-199); runs on device via jnp.linalg.svd."""

    def __init__(self, min_variance_pct: float = 5.0, max_components: int = 15):
        self.min_variance_pct = min_variance_pct
        self.max_components = max_components

    def debias(self, a: np.ndarray) -> np.ndarray:
        u, s, vt = (np.asarray(x) for x in
                    jnp.linalg.svd(jnp.asarray(a, dtype=jnp.float32),
                                   full_matrices=False))
        total = s.sum()
        n = 0
        while n < min(self.max_components, len(s)) and \
                100 * s[n] / total > self.min_variance_pct:
            n += 1
        s2 = s.copy()
        s2[:n] = 0
        return np.asarray((u * s2[None, :]) @ vt, dtype=a.dtype)


def sample_medians(depths: np.ndarray) -> np.ndarray:
    """65th percentile of nonzero depths per sample column
    (dcnv.go:108-125)."""
    out = np.zeros(depths.shape[1])
    for s in range(depths.shape[1]):
        col = np.sort(depths[:, s])
        k = int(np.searchsorted(col, 0, side="right"))
        rest = col[k:]
        if len(rest):
            out[s] = rest[int(0.65 * len(rest))]
    return out


def normalize_by_sample_median(depths: np.ndarray) -> np.ndarray:
    meds = sample_medians(depths)
    meds[meds == 0] = 1.0
    return depths / meds[None, :]


def gc_debias_pipeline(depths: np.ndarray, gcs: np.ndarray,
                       window: int = 9) -> np.ndarray:
    """The dcnv composition (dcnv.go:331-339): sort raw depths by GC,
    moving-median debias (window 9), unsort, THEN sample-median normalize.
    Debias must see raw depths — its max(median, 1) floor (debiaser.go:
    111-122) is a no-op on already-normalized ≈1 values."""
    db = GeneralDebiaser(gcs, window=window)
    srt = db.sort(np.asarray(depths, dtype=np.float64))
    deb = db.debias(srt)
    unsorted = db.unsort(deb)
    return normalize_by_sample_median(unsorted)
