"""cnveval: precision/recall of a CNV callset against a truth set.

Rebuild of cnveval/cnveval.go: overlap when the smaller interval is
covered ≥ po (default 0.4, cmd/cnveval:26) and the copy numbers agree
with CN>2 collapsed to 3 (":354-362"); stats stratified by sample and by
size class (<20kb, 20-100kb, ≥100kb, ":45-51"); cross-sample FP/TN logic
(":231-285") counts calls matching a truth interval assigned to *other*
samples as FP unless they also match a truth for their own sample.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class CNV:
    chrom: str
    start: int
    end: int
    sample: str
    cn: int
    counted: bool = False


@dataclass
class Truth:
    chrom: str
    start: int
    end: int
    samples: list[str]
    cn: int
    used: set = field(default_factory=set)


SMALL = 20_000
MEDIUM = 100_000
CLASSES = ("small", "medium", "large", "all")


def size_class(start: int, end: int) -> str:
    l = end - start
    if l < SMALL:
        return "small"
    if l < MEDIUM:
        return "medium"
    return "large"


def same_cn(a: int, b: int) -> bool:
    return min(a, 3) == min(b, 3)


def poverlap(a, b) -> float:
    if a.chrom != b.chrom:
        return 0.0
    total = min(a.end - a.start, b.end - b.start)
    ovl = min(a.end, b.end) - max(a.start, b.start)
    if ovl < 0 or total <= 0:
        return 0.0
    return ovl / total


@dataclass
class Stat:
    tp: int = 0
    fp: int = 0
    fn: int = 0
    tn: int = 0

    def precision(self) -> float:
        return self.tp / (self.tp + self.fp) if self.tp + self.fp else 0.0

    def recall(self) -> float:
        return self.tp / (self.tp + self.fn) if self.tp + self.fn else 0.0

    def __str__(self):
        return (
            f"precision: {self.precision():.4f} ({self.tp:<4} / "
            f"({self.tp:<4} + {self.fp:<4})) recall: "
            f"{self.recall():.4f} ({self.tp:<4} / ({self.tp:<4} + "
            f"{self.fn:<4}))"
        )


def _key(x):
    return (x.chrom, x.start)


def evaluate(cnvs: list[CNV], truths: list[Truth], po: float = 0.4
             ) -> dict[tuple[str, str], Stat]:
    """→ {(size_class, sample): Stat} (cnveval.go:163-212)."""
    stat: dict[tuple[str, str], Stat] = {}
    samples = {s for t in truths for s in t.samples} | {
        c.sample for c in cnvs
    }
    by_sample: dict[str, list[Truth]] = {}
    without: dict[str, list[Truth]] = {}
    for t in truths:
        for s in t.samples:
            by_sample.setdefault(s, []).append(t)
        for s in samples:
            if s not in t.samples:
                without.setdefault(s, []).append(t)
    cnv_by_sample: dict[str, list[CNV]] = {}
    for c in cnvs:
        cnv_by_sample.setdefault(c.sample, []).append(c)

    for sample in samples:
        ts = sorted(by_sample.get(sample, []), key=_key)
        cs = sorted(cnv_by_sample.get(sample, []), key=_key)
        _update_positive(stat, ts, cs, po)
        os_ = sorted(without.get(sample, []), key=_key)
        _update_fp(stat, os_, cs, ts, po)
    return stat


def _get(stat, sc, sample) -> Stat:
    return stat.setdefault((sc, sample), Stat())


def _update_positive(stat, truths, cnvs, po):
    """(cnveval.go:289-341)"""
    if not cnvs:
        return
    i = 0
    for t in truths:
        val = _get(stat, size_class(t.start, t.end), cnvs[0].sample)
        found = False
        while i < len(cnvs) and (
            cnvs[i].chrom < t.chrom
            or (cnvs[i].chrom == t.chrom and cnvs[i].end < t.start)
        ):
            i += 1
        if i > 0:
            i -= 1
        for cnv in cnvs[i:]:
            if cnv.chrom > t.chrom or (
                cnv.chrom == t.chrom and cnv.start > t.end
            ):
                break
            if poverlap(cnv, t) >= po and same_cn(cnv.cn, t.cn):
                if cnv.sample not in t.used:
                    val.tp += 1
                    cnv.counted = True
                    found = True
                    t.used.add(cnv.sample)
        if not found:
            val.fn += 1
    for cnv in cnvs:
        if not cnv.counted:
            _get(stat, size_class(cnv.start, cnv.end), cnv.sample).fp += 1


def _update_fp(stat, others, cnvs, truths, po):
    """(cnveval.go:231-285)"""
    if not cnvs or not others:
        return
    i = 0
    for o in others:
        val = _get(stat, size_class(o.start, o.end), cnvs[0].sample)
        while i < len(cnvs) and (
            cnvs[i].chrom < o.chrom
            or (cnvs[i].chrom == o.chrom and cnvs[i].end < o.start)
        ):
            i += 1
        if i > 0:
            i -= 1
        tp_found = False
        fp_found = False
        found = False
        for cnv in cnvs[i:]:
            if cnv.chrom > o.chrom or (
                cnv.chrom == o.chrom and cnv.start > o.end
            ):
                break
            if poverlap(cnv, o) >= po and same_cn(cnv.cn, o.cn):
                fp_found = True
                for t in truths:
                    if t.chrom != cnv.chrom:
                        continue
                    if poverlap(cnv, t) >= po and same_cn(cnv.cn, t.cn):
                        tp_found = True
                        break
            if fp_found and not tp_found:
                val.fp += 1
                found = True
                cnv.counted = True
        if not (found or tp_found):
            val.tn += 1


def tabulate(stat: dict[tuple[str, str], Stat]) -> dict[str, Stat]:
    """Aggregate over samples per size class + "all" (cnveval.go:118-133)."""
    out = {c: Stat() for c in CLASSES}
    for (sc, _), st in stat.items():
        for f in ("tp", "fp", "fn", "tn"):
            setattr(out[sc], f, getattr(out[sc], f) + getattr(st, f))
    for c in ("small", "medium", "large"):
        for f in ("tp", "fp", "fn", "tn"):
            setattr(out["all"], f, getattr(out["all"], f) + getattr(out[c], f))
    return out
