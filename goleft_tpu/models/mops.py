"""cn.mops EM (eqns 1, 5-8 of the cn.mops paper), batched over windows.

Rebuild of emdepth/mops/mops.go:54-161: posterior matrix α_ik over copy
numbers 0..7 per sample, Dirichlet-prior M-step with G=11 on CN2, λ
iterated ≤10 times until |Δλ| ≤ 0.01. All windows run as one vmapped jit.

Numerical note (documented divergence): the reference computes the Poisson
pmf as mu^k·e^-mu/Γ(k+1) (mops.go:36-38), which overflows to NaN for
k ≳ 170; we use the log-space form exp(k·ln mu − lgamma(k+1) − mu), equal
in exact arithmetic and stable for deep coverage.

The reference's own unit tests compare the returned struct to []int and
so cannot pass as written (mops/mops_test.go:13-16); behavior here is
validated by posterior-property tests instead.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

MAX_CN = 8  # copy numbers 0..7 (mops.go:31 iterates alpha of len 8)
EPS = 0.001
MAX_ITER = 10
G = 11.0  # Dirichlet prior weight on CN2, mops.go:96


def _pmf(k: jax.Array, mu: jax.Array) -> jax.Array:
    tiny = jnp.asarray(1e-30, mu.dtype)
    lg = jax.scipy.special.gammaln(k + 1)
    return jnp.exp(k * jnp.log(jnp.maximum(mu, tiny)) - lg - mu)


def _betas(lam: jax.Array, dtype) -> jax.Array:
    """Per-CN Poisson means: i/2·λ with CN0 → eps/2·λ (mops.go:43-47)."""
    i = jnp.arange(MAX_CN, dtype=dtype)
    i = jnp.where(i == 0, EPS, i)
    return i / 2 * lam


def _em_one(d: jax.Array) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One window: depths (S,) → (aik (8,S), alpha (8,), lambda)."""
    dtype = d.dtype
    alpha0 = jnp.full(MAX_CN, EPS, dtype=dtype)
    alpha0 = alpha0.at[2].set(1.0 - 5 * EPS * (MAX_CN - 1))
    k = jnp.floor(d + 0.5)

    def estep(alpha, lam):
        beta = _betas(lam, dtype)  # (8,)
        # note the denominator uses i/2·λ for CN0 (i.e. 0), matching the
        # reference's estep (mops.go:76-80) rather than its pdepth eps
        i = jnp.arange(MAX_CN, dtype=dtype)
        denom_p = _pmf(k[None, :], (i / 2 * lam)[:, None])  # (8, S)
        denom = (alpha[:, None] * denom_p).sum(axis=0)  # (S,)
        num = alpha[:, None] * _pmf(k[None, :], beta[:, None])
        return num / jnp.maximum(denom[None, :], 1e-30)

    def mstep(aik):
        n = MAX_CN
        N = d.shape[0]
        amean = aik.mean(axis=1)  # (8,)
        ys = n + G
        alpha_denom = 1 + 1 / N * (ys - n)
        yi = jnp.where(jnp.arange(n) == 2, 1.0 + G, 1.0)
        alpha = (amean + 1 / N * (yi - 1)) / alpha_denom
        i = jnp.arange(n, dtype=dtype)
        w = jnp.where(i == 0, EPS / 2, i / 2)
        lam_denom = (amean * w).sum()
        return alpha, d.mean() / jnp.maximum(lam_denom, 1e-30)

    def body(carry):
        alpha, lam, nlam, it = carry
        aik = estep(alpha, nlam)
        alpha2, nlam2 = mstep(aik)
        return alpha2, nlam, nlam2, it + 1

    def cond(carry):
        _, lam, nlam, it = carry
        return (jnp.abs(lam - nlam) > 0.01) & (it < MAX_ITER)

    big = jnp.asarray(3.4e37, dtype)
    alpha, lam, nlam, _ = jax.lax.while_loop(
        cond, body, (alpha0, big, d.mean(), 0)
    )
    aik = estep(alpha, nlam)
    return aik, alpha, nlam


@jax.jit
def mops_batch(depths: jax.Array) -> dict:
    """(B, S) depths → {"aik": (B,8,S), "alpha": (B,8), "lambda": (B,)}."""
    aik, alpha, lam = jax.vmap(_em_one)(depths)
    return {"aik": aik, "alpha": alpha, "lambda": lam}


@jax.jit
def information_gain(aik: jax.Array) -> jax.Array:
    """cn.mops eqn 8 (mops.go:110-121): per-window evidence of any CNV."""
    i = jnp.arange(MAX_CN, dtype=aik.dtype)
    v = jnp.where(i == 0, EPS, i)
    w = jnp.abs(jnp.log(v / 2))
    return (aik.mean(axis=-1) * w[None, :]).sum(axis=-1)


@jax.jit
def posterior_cn(aik: jax.Array) -> jax.Array:
    """Per-sample argmax copy number from the posterior matrix."""
    return jnp.argmax(aik, axis=-2).astype(jnp.int32)
