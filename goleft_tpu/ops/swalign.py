"""Banded Smith-Waterman: the read-mapper's extension kernel.

Local alignment of a read against a bounded reference window — the
seed-and-extend mapper's "extend" half (GenPairX / PIM read-mapping in
PAPERS.md both reduce it to exactly this shape). Affine gaps, int32
scores, and the same anti-diagonal wavefront the pair-HMM forward
(ops/pairhmm.py) established: cell (i, j) depends only on diagonals
i+j-1 and i+j-2, so each of the R+W wavefront steps updates three
(R+1)-lane vectors with shifts and elementwise max — no sequential
cell loop, no within-step dependency (the classic affine "F-loop"
problem disappears because F's feeder cells all live on the previous
anti-diagonal).

    H[i,j] = max(0, H[i-1,j-1] + sub(i,j), E[i,j], F[i,j])
    E[i,j] = max(H[i,j-1] + open + ext, E[i,j-1] + ext)   (gap in read)
    F[i,j] = max(H[i-1,j] + open + ext, F[i-1,j] + ext)   (gap in ref)

Everything is exact int32 arithmetic — device scores match the NumPy
oracle (:func:`sw_oracle`) bit for bit, which is what the mapping
tests pin per bucket shape. Padding lanes are masked to the identity
(H=0, E=F=-inf) every step, so a pair's score, argmax cell and
direction bits are bitwise independent of its bucket shape and batch
neighbors — the property that lets the serve executor coalesce map
requests byte-identically.

The device emits per-pair (best score, best cell) plus a per-diagonal
direction-bit plane (2 bits of H-source, one E-extend bit, one
F-extend bit per cell); the traceback walks those bits on the host
(:func:`traceback`) — O(alignment length) host work per read, all the
O(R·W) DP on device. Tie-breaking is pinned on both sides: the best
cell is the lexicographically first (i+j, i) among maximal cells, H
prefers diagonal > E > F on ties, and E/F prefer extension on ties.

Length bucketing mirrors pairhmm: reads pad to ``BUCKET`` (32),
windows to ``WBUCKET`` (64), so arbitrary read cohorts compile
O(#buckets) programs; :func:`align_pairs` is the host entry the
mapping pipeline drives (the ``map`` fault site wraps it one level
up, in mapping/pipeline.py, with per-bucket quarantine).
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

from .pairhmm import encode_seq  # shared A=0 C=1 G=2 T=3, N=4 codes

BUCKET = 32    # read-length bucket granularity
WBUCKET = 64   # window-length bucket granularity
N_CODE = 4
#: "minus infinity" for int32 gap states: low enough to never win a
#: max, high enough that adding a gap penalty cannot wrap
NEG = np.int32(-(1 << 28))


class Scores(NamedTuple):
    """Integer alignment scores (penalties negative)."""

    match: int = 2
    mismatch: int = -4
    gap_open: int = -4   # charged once per gap, on top of gap_ext
    gap_ext: int = -2

    def astuple(self) -> tuple[int, int, int, int]:
        return (int(self.match), int(self.mismatch),
                int(self.gap_open), int(self.gap_ext))


DEFAULT_SCORES = Scores()


def _pad_up(n: int, to: int) -> int:
    return max(to, ((n + to - 1) // to) * to)


def bucket_shape(rlen: int, wlen: int) -> tuple[int, int]:
    """(r_pad, w_pad) signature for one read/window pair."""
    return _pad_up(rlen, BUCKET), _pad_up(wlen, WBUCKET)


def _sw_bucket_impl(reads_p, rlens, wins, wlens, scores):
    """One padded bucket through the wavefront; vmapped over pairs.

    reads_p: (B, R1) uint8 — read base at wavefront lane i (1-based;
             lane 0 is the boundary row), rlens (B,) int32
    wins:    (B, W) uint8 window bases (0-based), wlens (B,) int32
    scores:  (4,) int32 [match, mismatch, gap_open, gap_ext]

    Returns (best (B,) int32, bi (B,) int32, bj (B,) int32,
    dirs (B, steps, R1) uint8): per cell, bits 0-1 = H source
    (0 stop, 1 diag, 2 E, 3 F), bit 2 = E extended, bit 3 = F
    extended. Best cell tie-break: smallest i+j, then smallest i.
    """
    import jax
    import jax.numpy as jnp

    r1 = reads_p.shape[1]
    wcap = wins.shape[1]
    steps = r1 + wcap
    neg = jnp.int32(NEG)
    zero = jnp.int32(0)

    def one_pair(read, rlen, win, wlen):
        s_match, s_mis, s_open, s_ext = (scores[0], scores[1],
                                         scores[2], scores[3])
        ii = jnp.arange(r1, dtype=jnp.int32)

        def shift1(x):
            # x[i-1] with the boundary entering at lane 0
            return jnp.concatenate([x[:1] * 0 + neg, x[:-1]])

        def shift1h(x):
            # H boundary row/col is 0, not -inf
            return jnp.concatenate([x[:1] * 0, x[:-1]])

        def step(k, carry):
            h1, e1, f1, h2, best, bi, bj, dirs = carry
            jj = k - ii
            wb = jnp.where((jj >= 1) & (jj <= wlen),
                           win[jnp.clip(jj - 1, 0, wcap - 1)],
                           jnp.uint8(N_CODE))
            valid = ((ii >= 1) & (ii <= rlen)
                     & (jj >= 1) & (jj <= wlen))
            is_match = (read == wb) & (read != N_CODE) \
                & (wb != N_CODE)
            sub = jnp.where(is_match, s_match, s_mis)
            h_diag = shift1h(h2) + sub
            e_open = h1 + s_open + s_ext
            e_ext = e1 + s_ext
            e = jnp.maximum(e_open, e_ext)
            f_open = shift1h(h1) + s_open + s_ext
            f_ext = shift1(f1) + s_ext
            f = jnp.maximum(f_open, f_ext)
            h = jnp.maximum(jnp.maximum(zero, h_diag),
                            jnp.maximum(e, f))
            h = jnp.where(valid, h, zero)
            e = jnp.where(valid, e, neg)
            f = jnp.where(valid, f, neg)
            # direction bits, tie order diag > E > F > stop; E/F
            # prefer extension on ties (the oracle mirrors all three)
            src = jnp.where(
                h <= zero, 0,
                jnp.where(h == h_diag, 1, jnp.where(h == e, 2, 3)))
            d = (src.astype(jnp.uint8)
                 | ((e_ext >= e_open).astype(jnp.uint8) << 2)
                 | ((f_ext >= f_open).astype(jnp.uint8) << 3))
            d = jnp.where(valid, d, jnp.uint8(0))
            dirs = dirs.at[k].set(d)
            hv = jnp.where(valid, h, jnp.int32(-1))
            m = jnp.max(hv)
            am = jnp.argmax(hv).astype(jnp.int32)
            take = m > best  # strict: keeps the earliest diagonal
            best = jnp.where(take, m, best)
            bi = jnp.where(take, am, bi)
            bj = jnp.where(take, k - am, bj)
            return h, e, f, h1, best, bi, bj, dirs

        z = jnp.zeros(r1, jnp.int32)
        zneg = jnp.full(r1, neg, jnp.int32)
        init = (z, zneg, zneg, z, jnp.int32(0), jnp.int32(0),
                jnp.int32(0), jnp.zeros((steps, r1), jnp.uint8))
        h1, e1, f1, h2, best, bi, bj, dirs = jax.lax.fori_loop(
            1, steps, step, init)
        return best, bi, bj, dirs

    return jax.vmap(one_pair)(reads_p, rlens, wins, wlens)


_SW_JIT = None


def sw_bucket(reads_p, rlens, wins, wlens, scores):
    """Jitted wrapper; one compile per (B, r_pad, w_pad) geometry."""
    global _SW_JIT
    if _SW_JIT is None:
        import jax

        _SW_JIT = jax.jit(_sw_bucket_impl)
    return _SW_JIT(reads_p, rlens, wins, wlens, scores)


def _sw_jit_cache_size() -> int:
    if _SW_JIT is None:
        return 0
    return getattr(_SW_JIT, "_cache_size", lambda: 0)()


def sw_oracle(read_codes: np.ndarray, win_codes: np.ndarray,
              scores: Scores = DEFAULT_SCORES):
    """Exact NumPy reference: plain nested-loop affine-gap local DP.

    Independent of the wavefront formulation (row-major cell loop,
    no shifts, no masks) but pinned to the same int arithmetic and
    tie rules, so device output must match it bit for bit. Returns
    (best, bi, bj, dirs) in the device layout: dirs[k, i] holds the
    bits for cell (i, j=k-i) with i 1-based over the read.
    """
    s_match, s_mis, s_open, s_ext = scores.astuple()
    r = len(read_codes)
    w = len(win_codes)
    neg = int(NEG)
    H = np.zeros((r + 1, w + 1), dtype=np.int64)
    E = np.full((r + 1, w + 1), neg, dtype=np.int64)
    F = np.full((r + 1, w + 1), neg, dtype=np.int64)
    dirs = np.zeros((r + 1 + w, r + 1), dtype=np.uint8)
    for i in range(1, r + 1):
        rb = int(read_codes[i - 1])
        for j in range(1, w + 1):
            wb = int(win_codes[j - 1])
            sub = s_match if (rb == wb and rb != N_CODE
                              and wb != N_CODE) else s_mis
            h_diag = H[i - 1, j - 1] + sub
            e_open = H[i, j - 1] + s_open + s_ext
            e_ext = E[i, j - 1] + s_ext
            e = max(e_open, e_ext)
            f_open = H[i - 1, j] + s_open + s_ext
            f_ext = F[i - 1, j] + s_ext
            f = max(f_open, f_ext)
            h = max(0, h_diag, e, f)
            H[i, j], E[i, j], F[i, j] = h, e, f
            if h <= 0:
                src = 0
            elif h == h_diag:
                src = 1
            elif h == e:
                src = 2
            else:
                src = 3
            dirs[i + j, i] = (src | ((e_ext >= e_open) << 2)
                              | ((f_ext >= f_open) << 3))
    # best cell with the device's tie rule: among maximal cells the
    # lexicographically first (i+j, i) — strict improvement over
    # wavefront steps, first lane within a step
    best = int(max(H.max(), 0))
    bi = bj = 0
    if best > 0:
        cand = np.argwhere(H == best)
        order = np.lexsort((cand[:, 0], cand[:, 0] + cand[:, 1]))
        bi, bj = (int(cand[order[0], 0]), int(cand[order[0], 1]))
    return best, bi, bj, dirs


def traceback(dirs: np.ndarray, bi: int, bj: int):
    """Walk the direction bits back from the best cell.

    ``dirs`` is the (steps, R1) per-pair plane (device or oracle);
    (bi, bj) the 1-based best cell. Returns (read_start, read_end,
    win_start, win_end, cigar) with half-open 0-based spans and a
    SAM-style cigar over M/I/D (I consumes read, D consumes window).
    """
    i, j = int(bi), int(bj)
    if i == 0 and j == 0:
        return 0, 0, 0, 0, ""
    ops: list[tuple[str, int]] = []

    def push(op: str):
        if ops and ops[-1][0] == op:
            ops[-1] = (op, ops[-1][1] + 1)
        else:
            ops.append((op, 1))

    state = "H"
    while True:
        d = int(dirs[i + j, i])
        if state == "H":
            src = d & 3
            if src == 0:
                break
            if src == 1:
                push("M")
                i -= 1
                j -= 1
            elif src == 2:
                state = "E"
            else:
                state = "F"
        elif state == "E":
            push("D")  # gap in read: consumes a window base
            ext = (d >> 2) & 1
            j -= 1
            state = "E" if ext else "H"
        else:
            push("I")  # gap in window: consumes a read base
            ext = (d >> 3) & 1
            i -= 1
            state = "F" if ext else "H"
    cigar = "".join(f"{n}{op}" for op, n in reversed(ops))
    return i, int(bi), j, int(bj), cigar


class Alignment(NamedTuple):
    """One read↔window local alignment (spans 0-based half-open)."""

    score: int
    read_start: int
    read_end: int
    win_start: int
    win_end: int
    cigar: str


def _pack_bucket(idxs, reads, wins, r_pad, w_pad):
    """Pad one bucket's pairs into the kernel layout."""
    b = len(idxs)
    r1 = r_pad + 1
    reads_p = np.full((b, r1), N_CODE, dtype=np.uint8)
    rlens = np.zeros(b, dtype=np.int32)
    wins_p = np.full((b, w_pad), N_CODE, dtype=np.uint8)
    wlens = np.zeros(b, dtype=np.int32)
    for row, n in enumerate(idxs):
        r, w = reads[n], wins[n]
        reads_p[row, 1:len(r) + 1] = r
        rlens[row] = len(r)
        wins_p[row, :len(w)] = w
        wlens[row] = len(w)
    return reads_p, rlens, wins_p, wlens


def align_bucket(reads_p, rlens, wins_p, wlens,
                 scores: Scores = DEFAULT_SCORES):
    """One padded bucket → per-pair :class:`Alignment` list (host
    traceback over the device direction bits)."""
    sc = np.asarray(scores.astuple(), dtype=np.int32)
    best, bi, bj, dirs = sw_bucket(reads_p, rlens, wins_p, wlens, sc)
    best = np.asarray(best)
    bi = np.asarray(bi)
    bj = np.asarray(bj)
    dirs = np.asarray(dirs)
    out = []
    for n in range(len(best)):
        rs, re_, ws, we, cig = traceback(dirs[n], bi[n], bj[n])
        out.append(Alignment(int(best[n]), rs, re_, ws, we, cig))
    return out


def align_pairs(reads, wins, scores: Scores = DEFAULT_SCORES,
                dispatch=None) -> list[Alignment]:
    """Host entry: N (read, window) code pairs → N alignments.

    Pairs bucket by (r_pad, w_pad); each bucket is one vmapped
    wavefront dispatch. ``dispatch``, when given, wraps each bucket
    call — the mapping pipeline passes its plan-Step runner there so
    extension rides the ``map`` fault site with per-bucket
    quarantine; ``None`` dispatches directly (tests, bench).
    """
    out: list[Alignment | None] = [None] * len(reads)
    groups: dict[tuple[int, int], list[int]] = {}
    for n, (r, w) in enumerate(zip(reads, wins)):
        groups.setdefault(bucket_shape(len(r), len(w)), []).append(n)
    for (r_pad, w_pad), idxs in sorted(groups.items()):
        packed = _pack_bucket(idxs, reads, wins, r_pad, w_pad)
        if dispatch is None:
            res = align_bucket(*packed, scores=scores)
        else:
            res = dispatch((r_pad, w_pad, len(idxs)),
                           lambda p=packed: align_bucket(
                               *p, scores=scores))
        for n, a in zip(idxs, res):
            out[n] = a
    return out  # type: ignore[return-value]


def oracle_align(read, win, scores: Scores = DEFAULT_SCORES
                 ) -> Alignment:
    """Oracle counterpart of one :func:`align_pairs` element."""
    r = encode_seq(read)
    w = encode_seq(win)
    best, bi, bj, dirs = sw_oracle(r, w, scores)
    rs, re_, ws, we, cig = traceback(dirs, bi, bj)
    return Alignment(best, rs, re_, ws, we, cig)
