"""Device-resident rANS Nx16 entropy decode (CRAM 3.1 method 5).

Round-2 numbers put device-resident coverage compute at 51.7 Gbases/s
but only 0.155 Gbases/s over the packed wire: host entropy decode plus
H2D transfer is THE speed ceiling (ROADMAP "Close the wire gap"), and
GenPIP's (PAPERS.md) whole thesis is that fusing decode with compute
kills the data-movement wall. This module moves the CRAM block decode
next to the coverage kernels: *compressed* block bytes cross the wire
and the interleaved-rANS state machine runs on the device.

The decoder state machine as a ``lax.scan``
-------------------------------------------
An Nx16 stream decodes round-robin: out[i] advances state i mod N
(N = 4 or 32). One *round* therefore advances all N states — the N
lanes are data-independent within a round except for the shared renorm
byte stream. The scan runs over rounds with carry (R[N] states, read
pointer); each round is pure vector math plus gathers:

  - slot lookup: ``m = R & 0xFFF`` indexes the 4096-entry slot tables
    (symbol / freq / bias), expanded ON DEVICE from the shipped
    (freq[256], cum[257]) int32 arrays by a vectorized searchsorted —
    the wire carries ~2KB of table per block instead of the 48KB
    materialized slot arrays
  - 16-bit renorm as masked gathers: a lane whose next state drops
    below 2^15 reads a little-endian 16-bit word from the shared byte
    stream. Within a round the scalar decoder reads lanes in order, so
    lane j's word sits at ``pos + 2*rank(j)`` where rank counts
    earlier lanes renormalizing this round (an exclusive cumsum); the
    bytes-left guard truncates at the same lane the scalar loop stops
    at, because a denied lane leaves every later lane denied too.

ORDER1 fits the same scan shape: the per-context frequency rows
become a ``(ctx, slot)`` gather against a ``(n_ctx, 2^shift)`` slot
table expanded on device by the same searchsorted (one row per
context present in the shipped compact table — CRAM serializes these
tables themselves order-0-compressed; ``io/rans_nx16.py`` parses them
host-side, O(table) not O(payload)). Each of the N interleaved states
carries its PREVIOUS SYMBOL as a context lane in the scan carry, and
the N lanes decode contiguous output slices (lane j owns
``[j·F, (j+1)·F)`` with the last lane carrying the tail) exactly as
the host oracle walks them — the post-scan gather maps the
round-major scan output back to lane-sliced order. A context absent
from the table raises the host's missing-context error via a carried
diagnostic bit.

CAT blocks skip the scan (payload = literals); RLE and PACK expansion
run as vectorized gathers on the scan/CAT output (cumsum + searchsorted
for run expansion, shift/mask gathers for bit-unpacking). STRIPE
containers dispatch their N' byte-interleaved sub-streams through the
same bucketed machinery (each lane is a complete Nx16 stream), then a
batched transpose-interleave gather reassembles the container — one
call per stripe signature. Together: the full CRAM 3.1 method-5
matrix ORDER0/ORDER1 × CAT × PACK × RLE × NOSZ × STRIPE for both
N=4 and X32 decodes device-resident; only corrupt/foreign streams
fall back (``decode.device_fallback_total``).

Parallelism and compiles: one block is only N lanes wide, so the real
vector width comes from vmapping over many blocks at once. Blocks pad
to power-of-two bucket signatures (payload length, round count,
expansion caps) exactly like ops/pairhmm.py's length bucketing, so a
whole cohort compiles O(#buckets) programs, not O(#shapes). With
ORDER1 × STRIPE the signature space is wider, so a process-wide cap
(``MAX_BUCKET_SIGNATURES``) bounds total compiles: blocks whose NEW
signature would exceed it decode on host (a per-block fallback, not
an error), visible via ``decode.bucket_signatures`` /
``decode.bucket_cap_fallback_total`` and one log line when the cap
first trips.

An experimental Pallas variant (``pallas_decode0``) mirrors
ops/pallas_coverage.py — one block per sequential grid step, lanes as
a VMEM vector, the same round loop as a ``fori_loop``; correctness is
pinned in interpret mode (this container is CPU-only), the XLA scan is
the product path.

``DeviceBlockDecoder`` is the CRAM-facing object: io/cram.py hands it
a container's raw (still compressed) blocks, supported rANS blocks
batch-decode on device through a content-keyed plan Step at the
``decode`` fault site (retry/quarantine compose exactly like every
other dispatch), everything else falls back per-block to the host
codecs, byte-identically.
"""

from __future__ import annotations

import threading

import numpy as np

from ..io import rans_nx16 as _rx
from ..io.rans_nx16 import ParsedNx16, parse_nx16
from ..obs import get_registry
from ..obs.logging import get_logger

TF_SHIFT = _rx.TF_SHIFT
TOTFREQ = _rx.TOTFREQ
RANS_LOW = _rx.RANS_LOW

log = get_logger("ops.rans_device")

#: minimum pad bucket for payload/output axes (pow-2 above, like
#: pairhmm's BUCKET: arbitrary block sizes compile O(#buckets))
MIN_BUCKET = 64

#: process-wide cap on DISTINCT compile signatures (decode buckets +
#: stripe interleave shapes). Each signature is one XLA program kept
#: for the process lifetime; ORDER1 adds (shift, n_ctx_cap) axes and
#: STRIPE multiplies by lane shapes, so an adversarial cohort could
#: otherwise force unbounded compiles. Blocks whose NEW signature
#: would exceed the cap decode on host — a per-block fallback, never
#: an error. Sizing: a real cohort's blocks share a writer, so its
#: shapes collapse to a handful of pow-2 buckets per (N, flags)
#: combo — the 4-sample mixed-matrix smoke cohort compiles ~50;
#: 128 leaves 2-3x headroom before the graceful degradation starts.
MAX_BUCKET_SIGNATURES = 128

_SIG_LOCK = threading.Lock()
_SEEN_SIGS: set[tuple] = set()
_CAP_TRIPPED = False


def bucket(n: int, minimum: int = MIN_BUCKET) -> int:
    b = minimum
    while b < n:
        b <<= 1
    return b


def reset_signature_registry() -> None:
    """Test hook: forget admitted signatures (the jit cache keeps its
    compiled programs — this only re-opens admission)."""
    global _CAP_TRIPPED
    with _SIG_LOCK:
        _SEEN_SIGS.clear()
        _CAP_TRIPPED = False


def _admit_signatures(sigs: list[tuple]) -> bool:
    """Admit a block's compile signatures against the process cap,
    all-or-nothing (a stripe block needs every lane signature plus its
    interleave shape). Over the cap, NEW signatures are refused and
    the block falls back to the host codec; already-seen signatures
    always pass (their programs exist)."""
    global _CAP_TRIPPED
    with _SIG_LOCK:
        # dict.fromkeys dedupes in the caller's deterministic order
        # (signatures mix tuple layouts, so they don't sort)
        fresh = [s for s in dict.fromkeys(sigs)
                 if s not in _SEEN_SIGS]
        if not fresh:
            return True
        if len(_SEEN_SIGS) + len(fresh) > MAX_BUCKET_SIGNATURES:
            if not _CAP_TRIPPED:
                _CAP_TRIPPED = True
                log.warning(
                    "decode: bucket-signature cap reached (%d); new "
                    "block shapes fall back to the host codec "
                    "(decode.bucket_cap_fallback_total counts them)",
                    MAX_BUCKET_SIGNATURES)
            return False
        _SEEN_SIGS.update(fresh)
        get_registry().counter("decode.bucket_signatures").inc(
            len(fresh))
        return True


# ------------------------------------------------------------ XLA path

# jax.jit is applied lazily in _jitted() — this module must import
# without jax (the jax-free fleet/router processes import the package)
def _decode_bucket_impl(payload, plen, states, freq, inner_len,
                        rle_tab, runs, rle_out, pmap, bits, final_len,
                        ctx_index, ctx_freq, alphabet, *, rounds,
                        n_states, cat, rle, pack, order1, shift,
                        n_ctx_cap, lit_cap, mid_cap, out_cap):
    """One padded bucket: (B, …) arrays → ((B, out_cap) uint8 bytes,
    (B, 4) int32 diagnostics [rle_total, marked_total, pack_vmax,
    missing_ctx]).

    Static flags (cat/rle/pack/order1) specialize the program per
    combo; the identity stages compile away. All shapes are the bucket
    caps, all true lengths are traced scalars — one compile per
    signature.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    N = n_states
    lanes = jnp.arange(max(N, 1), dtype=jnp.int32)
    ms = jnp.arange(TOTFREQ, dtype=jnp.int32)

    def one(payload, plen, R0, freq, inner_len, rle_tab, runs,
            rle_out, pmap, bits, final_len, ctx_index, ctx_freq,
            alphabet):
        P = payload.shape[0]
        bad_ctx = jnp.int32(0)
        if cat:
            lit = payload[:lit_cap]
        elif order1:
            # per-context slot tables: the shipped doubly compact
            # (n_ctx, n_ctx) rows (columns are alphabet positions,
            # not raw symbols) expand into (n_ctx_cap, 2^shift)
            # sym/freq/bias tables by the same searchsorted used for
            # ORDER0 — the slot lookup becomes a (ctx_row, slot)
            # gather, with the alphabet mapping the compact column
            # index back to the emitted byte. Each lane carries its
            # previous symbol; ctx_index maps it to its table row
            # (-1 = context absent from the alphabet → the host's
            # missing-context error, carried as a diag bit). Lane j
            # decodes the contiguous slice [j·F, (j+1)·F) with the
            # last lane carrying the tail, so the active mask is
            # per-lane-length, not round-robin.
            target = 1 << shift
            ms1 = jnp.arange(target, dtype=jnp.int32)
            cf = ctx_freq.astype(jnp.int32)
            cum1 = jnp.concatenate([
                jnp.zeros((n_ctx_cap, 1), jnp.int32),
                jnp.cumsum(cf, axis=1, dtype=jnp.int32)], axis=1)
            col1 = jnp.clip(jax.vmap(
                lambda c: jnp.searchsorted(c, ms1, side="right"))(
                    cum1).astype(jnp.int32) - 1, 0, n_ctx_cap - 1)
            freq1 = jnp.take_along_axis(cf, col1, axis=1) \
                .astype(jnp.uint32)
            bias1 = (ms1[None, :] - jnp.take_along_axis(
                cum1, col1, axis=1)).astype(jnp.uint32)
            sym1 = alphabet.astype(jnp.int32)[col1]
            ci = ctx_index.astype(jnp.int32)
            F = inner_len // N
            rem = inner_len - F * N
            lens = F + jnp.where(lanes == N - 1, rem, 0)

            def round1_fn(carry, r):
                R, pos, last, bad = carry
                active = r < lens
                row = ci[last]
                bad = bad | jnp.any(
                    active & (row < 0)).astype(jnp.int32)
                rowc = jnp.clip(row, 0, n_ctx_cap - 1)
                m = (R & jnp.uint32(target - 1)).astype(jnp.int32)
                s = sym1[rowc, m]
                x = freq1[rowc, m] * (R >> jnp.uint32(shift)) \
                    + bias1[rowc, m]
                want = active & (x < jnp.uint32(RANS_LOW))
                avail = jnp.maximum(jnp.int32(0), (plen - pos) // 2)
                wi = want.astype(jnp.int32)
                rank = jnp.cumsum(wi, dtype=jnp.int32) - wi
                need = want & (rank < avail)
                offs = pos + 2 * rank
                b0 = payload[jnp.clip(offs, 0, P - 1)] \
                    .astype(jnp.uint32)
                b1 = payload[jnp.clip(offs + 1, 0, P - 1)] \
                    .astype(jnp.uint32)
                xr = (x << jnp.uint32(16)) | b0 | (b1 << jnp.uint32(8))
                x = jnp.where(need, xr, x)
                R = jnp.where(active, x, R)
                pos = pos + 2 * jnp.sum(need, dtype=jnp.int32)
                last = jnp.where(active, s, last)
                return (R, pos, last, bad), s.astype(jnp.uint8)

            (_, _, _, bad_ctx), syms = lax.scan(
                round1_fn,
                (R0, jnp.int32(0),
                 jnp.zeros(N, jnp.int32), jnp.int32(0)),
                jnp.arange(rounds, dtype=jnp.int32))
            # syms[r, j] is out[j·F + r]: gather back to lane-sliced
            # linear order (position p belongs to lane
            # min(p // F, N-1) — every p ≥ (N-1)·F is the last lane's)
            pidx = jnp.arange(lit_cap, dtype=jnp.int32)
            jl = jnp.where(pidx < (N - 1) * F,
                           pidx // jnp.maximum(F, 1),
                           jnp.int32(N - 1))
            rr = pidx - jl * F
            lit = syms.reshape(rounds * N)[
                jnp.clip(rr * N + jl, 0, rounds * N - 1)]
        else:
            # the wire ships only the int16 frequency row (~0.5KB);
            # cum and the 4096-entry slot tables expand on device. The
            # largest s with cum[s] <= m is the scalar decoder's lut
            # for every normalized table (zero-freq symbols collapse
            # to equal cum entries, skipped by side="right")
            cum = jnp.concatenate([
                jnp.zeros(1, jnp.int32),
                jnp.cumsum(freq, dtype=jnp.int32)])
            sym = jnp.clip(
                jnp.searchsorted(cum, ms, side="right").astype(
                    jnp.int32) - 1, 0, 255)
            sfreq = freq[sym].astype(jnp.uint32)  # freq ≤ 4096: exact
            sbias = (ms - cum[sym]).astype(jnp.uint32)

            def round_fn(carry, r):
                R, pos = carry
                active = (r * N + lanes) < inner_len
                m = (R & jnp.uint32(TOTFREQ - 1)).astype(jnp.int32)
                s = sym[m]
                x = sfreq[m] * (R >> jnp.uint32(TF_SHIFT)) + sbias[m]
                want = active & (x < jnp.uint32(RANS_LOW))
                avail = jnp.maximum(jnp.int32(0), (plen - pos) // 2)
                wi = want.astype(jnp.int32)
                rank = jnp.cumsum(wi, dtype=jnp.int32) - wi
                need = want & (rank < avail)
                offs = pos + 2 * rank
                b0 = payload[jnp.clip(offs, 0, P - 1)] \
                    .astype(jnp.uint32)
                b1 = payload[jnp.clip(offs + 1, 0, P - 1)] \
                    .astype(jnp.uint32)
                xr = (x << jnp.uint32(16)) | b0 | (b1 << jnp.uint32(8))
                x = jnp.where(need, xr, x)
                R = jnp.where(active, x, R)
                pos = pos + 2 * jnp.sum(need, dtype=jnp.int32)
                return (R, pos), s.astype(jnp.uint8)

            (_, _), syms = lax.scan(
                round_fn, (R0, jnp.int32(0)),
                jnp.arange(rounds, dtype=jnp.int32))
            lit = syms.reshape(rounds * N)[:lit_cap]

        # ---- RLE expansion: each marked literal repeats 1 + its run
        # extension; output position p maps back to the literal whose
        # cumulative start covers it (searchsorted over the exclusive
        # cumsum — the vectorized form of the host's sequential walk)
        if rle:
            idx = jnp.arange(lit_cap, dtype=jnp.int32)
            in_range = idx < inner_len
            marked = rle_tab[lit.astype(jnp.int32)] & in_range
            mi = marked.astype(jnp.int32)
            rank = jnp.cumsum(mi, dtype=jnp.int32) - mi
            rcap = runs.shape[0]
            rep = jnp.where(
                in_range,
                1 + jnp.where(marked,
                              runs[jnp.clip(rank, 0, rcap - 1)], 0),
                0)
            starts = jnp.cumsum(rep, dtype=jnp.int32) - rep
            rle_total = starts[-1] + rep[-1]
            marked_total = jnp.sum(mi, dtype=jnp.int32)
            posn = jnp.arange(mid_cap, dtype=jnp.int32)
            src = jnp.clip(
                jnp.searchsorted(starts, posn, side="right").astype(
                    jnp.int32) - 1, 0, lit_cap - 1)
            mid = jnp.where(posn < rle_out, lit[src],
                            jnp.uint8(0))
            mid_len = rle_out
        else:
            mid = lit
            mid_len = inner_len
            rle_total = inner_len
            marked_total = jnp.int32(0)

        # ---- PACK expansion: shift/mask gathers (bits ∈ {0,1,2,4},
        # LSB-first like the host's _unpack)
        if pack:
            i = jnp.arange(out_cap, dtype=jnp.int32)
            per = 8 // jnp.maximum(bits, 1)
            idxp = jnp.clip(i // per, 0, mid_cap - 1)
            sh = bits * (i % per)
            maskb = (jnp.int32(1) << bits) - 1
            v = (mid[idxp].astype(jnp.int32) >> sh) & maskb
            vc = jnp.clip(v, 0, 15)
            outb = jnp.where(bits == 0, pmap[0], pmap[vc]) \
                .astype(jnp.uint8)
            vmax = jnp.max(jnp.where((i < final_len) & (bits > 0),
                                     v, 0))
        else:
            outb = mid
            vmax = jnp.int32(0)
        del mid_len
        diag = jnp.stack([rle_total.astype(jnp.int32),
                          marked_total, vmax, bad_ctx])
        return outb, diag

    return jax.vmap(one)(payload, plen, states, freq, inner_len,
                         rle_tab, runs, rle_out, pmap, bits,
                         final_len, ctx_index, ctx_freq, alphabet)


def _interleave_impl(lanes_arr, final_len, *, n_lanes, out_cap):
    """Batched STRIPE reassembly: (B, n_lanes, lane_cap) decoded lane
    bytes → (B, out_cap) interleaved output. Output position i comes
    from lane ``i mod N'`` at offset ``i // N'`` — the transpose-
    interleave the host does with strided assignment, as one gather
    per stripe signature."""
    import jax
    import jax.numpy as jnp

    idx = jnp.arange(out_cap, dtype=jnp.int32)

    def one(lanes_b, flen):
        out = lanes_b[idx % n_lanes, idx // n_lanes]
        return jnp.where(idx < flen, out, jnp.uint8(0)) \
            .astype(jnp.uint8)

    return jax.vmap(one)(lanes_arr, final_len)


_JIT_CACHE: dict = {}


def _jitted():
    fn = _JIT_CACHE.get("xla")
    if fn is None:
        import jax

        fn = jax.jit(_decode_bucket_impl, static_argnames=(
            "rounds", "n_states", "cat", "rle", "pack", "order1",
            "shift", "n_ctx_cap", "lit_cap", "mid_cap", "out_cap"))
        _JIT_CACHE["xla"] = fn
    return fn


def _jitted_interleave():
    fn = _JIT_CACHE.get("ilv")
    if fn is None:
        import jax

        fn = jax.jit(_interleave_impl,
                     static_argnames=("n_lanes", "out_cap"))
        _JIT_CACHE["ilv"] = fn
    return fn


# --------------------------------------------------------- Pallas path

def pallas_decode0(payload, plen, states, slot_sym, slot_freq,
                   slot_bias, inner_len, *, rounds, n_states,
                   interpret: bool = False):
    """The rANS scan as a Pallas kernel: one block per sequential grid
    step, the N states as a lane vector, the round loop as a
    ``fori_loop`` with (states, read pointer, output buffer) carried —
    the same one-item-per-grid-step pattern as
    ops/pairhmm.py::pallas_forward_bucket. EXPERIMENTAL like its
    siblings: interpret-mode-pinned against the XLA scan (this
    container is CPU-only); expansions (RLE/PACK) stay in the shared
    XLA stages either way.

    payload (B, P) int32, states (B, N) int32, slots (B, 4096) int32
    → (B, rounds*N) int32 symbols.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, P = payload.shape
    N = n_states
    L = rounds * N

    def kernel(meta_ref, states_ref, payload_ref, sym_ref, freq_ref,
               bias_ref, out_ref):
        plen_b = meta_ref[0, 0]
        inner_b = meta_ref[0, 1]
        pay = payload_ref[0, :]
        sym = sym_ref[0, :]
        sfreq = freq_ref[0, :]
        sbias = bias_ref[0, :]
        lanes = jax.lax.broadcasted_iota(jnp.int32, (1, N), 1)

        def round_fn(r, carry):
            R, pos, outbuf = carry
            active = (r * N + lanes) < inner_b
            m = R & (TOTFREQ - 1)
            s = jnp.take(sym, m[0, :], axis=0)[None, :]
            f = jnp.take(sfreq, m[0, :], axis=0)[None, :]
            bi = jnp.take(sbias, m[0, :], axis=0)[None, :]
            # int32 is exact here: valid states stay < 2^31 (renorm
            # bound) so freq*(x>>12)+bias < 2^31 and the x<<16 of a
            # sub-2^15 state fits — the uint32 XLA path and this agree
            # bit-for-bit on every well-formed stream
            x = f * (R >> TF_SHIFT) + bi
            want = active & (x < RANS_LOW)
            avail = jnp.maximum(0, (plen_b - pos) // 2)
            wi = want.astype(jnp.int32)
            rank = jnp.cumsum(wi, axis=1, dtype=jnp.int32) - wi
            need = want & (rank < avail)
            offs = pos + 2 * rank
            b0 = jnp.take(pay, jnp.clip(offs[0, :], 0, P - 1),
                          axis=0)[None, :]
            b1 = jnp.take(pay, jnp.clip(offs[0, :] + 1, 0, P - 1),
                          axis=0)[None, :]
            xr = (x << 16) | b0 | (b1 << 8)
            x = jnp.where(need, xr, x)
            R = jnp.where(active, x, R)
            pos = pos + 2 * jnp.sum(need, dtype=jnp.int32)
            outbuf = jax.lax.dynamic_update_slice(outbuf, s,
                                                  (0, r * N))
            return R, pos, outbuf

        R0 = states_ref[0, :][None, :]
        out0 = jnp.zeros((1, L), jnp.int32)
        _, _, outbuf = jax.lax.fori_loop(
            0, rounds, round_fn, (R0, jnp.int32(0), out0))
        out_ref[0] = outbuf[0]

    meta = jnp.stack([plen, inner_len], axis=1).astype(jnp.int32)
    return pl.pallas_call(
        kernel,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, 2), lambda t: (t, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, N), lambda t: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, P), lambda t: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, TOTFREQ), lambda t: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, TOTFREQ), lambda t: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, TOTFREQ), lambda t: (t, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, L), lambda t: (t, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B, L), jnp.int32),
        interpret=interpret,
    )(meta, states, payload, slot_sym, slot_freq, slot_bias)


def _pallas_scan_bytes(group: list[ParsedNx16], n_states: int,
                       rounds: int, p_cap: int,
                       interpret: bool) -> np.ndarray:
    """Run a non-CAT group's rANS stage through the Pallas kernel,
    returning (B, rounds*N) uint8 symbols (the XLA expansion stages
    consume them unchanged)."""
    import jax.numpy as jnp

    B = len(group)
    payload = np.zeros((B, p_cap), np.int32)
    plen = np.zeros(B, np.int32)
    states = np.zeros((B, n_states), np.int32)
    ssym = np.zeros((B, TOTFREQ), np.int32)
    sfreq = np.zeros((B, TOTFREQ), np.int32)
    sbias = np.zeros((B, TOTFREQ), np.int32)
    inner = np.zeros(B, np.int32)
    ms = np.arange(TOTFREQ, dtype=np.int64)
    for i, p in enumerate(group):
        payload[i, :p.payload.shape[0]] = p.payload
        plen[i] = p.payload.shape[0]
        states[i] = p.states.astype(np.int64).astype(np.int32)
        lut = _rx._slot_lut(p.freq.astype(np.int64),
                            p.cum.astype(np.int64)).astype(np.int64)
        ssym[i] = lut.astype(np.int32)
        sfreq[i] = p.freq[lut]
        sbias[i] = (ms - p.cum[lut]).astype(np.int32)
        inner[i] = p.inner_len
    got = pallas_decode0(
        jnp.asarray(payload), jnp.asarray(plen), jnp.asarray(states),
        jnp.asarray(ssym), jnp.asarray(sfreq), jnp.asarray(sbias),
        jnp.asarray(inner), rounds=rounds, n_states=n_states,
        interpret=interpret)
    return np.asarray(got).astype(np.uint8)


# ---------------------------------------------------------- batch glue

def _signature(p: ParsedNx16) -> tuple:
    """Pad-to-bucket compile signature (pairhmm-style): every axis
    rounds up to a power of two so arbitrary cohorts stay O(#buckets)
    compiles. ORDER1 adds (shift, n_ctx_cap) axes and widens the
    round count by N-1 (the last lane's tail rounds beyond F)."""
    n = p.n_states
    lit_cap = bucket(max(p.inner_len, 1))
    if not p.cat:
        rounds = (lit_cap + n - 1) // n
        lit_cap = rounds * n
        if p.order1:
            # lane j needs F = inner//N rounds, the last lane F+rem
            # with rem < N; F ≤ lit_cap//N so this covers every block
            # in the bucket
            rounds += n - 1
    else:
        rounds = 0
    p_cap = bucket(max(p.payload.shape[0], 1))
    if p.cat:
        p_cap = max(p_cap, lit_cap)  # CAT payload IS the literals
    mid_cap = bucket(max(p.rle_out_len, 1)) if p.rle else lit_cap
    out_cap = bucket(max(p.final_len, 1)) if p.pack else mid_cap
    runs_cap = bucket(len(p.rle_runs) if p.rle_runs is not None
                      else 0, minimum=16)
    shift = p.shift if p.order1 else TF_SHIFT
    n_ctx_cap = bucket(max(p.n_ctx, 1), minimum=16) if p.order1 else 1
    return (n, p.cat, p.order1, shift, p.rle, p.pack, rounds, p_cap,
            lit_cap, mid_cap, out_cap, runs_cap, n_ctx_cap)


def _stripe_shape(p: ParsedNx16) -> tuple[int, int, int]:
    """(n_lanes, lane_cap, out_cap) of a stripe container's batched
    interleave dispatch."""
    lane_cap = bucket(max((p.final_len + p.n_lanes - 1) // p.n_lanes,
                          1))
    return (p.n_lanes, lane_cap, bucket(max(p.final_len, 1)))


def plan_signatures(p: ParsedNx16) -> list[tuple]:
    """Every compile signature decoding this block requires (a stripe
    container needs each lane's bucket plus its interleave shape) —
    the admission unit for the ``MAX_BUCKET_SIGNATURES`` cap."""
    if p.stripe:
        sigs = [_signature(ch) for ch in p.children or []]
        sigs.append(("ilv",) + _stripe_shape(p))
        return sigs
    return [_signature(p)]


def _decode_flat(plans: list[ParsedNx16], *, backend: str,
                 interpret: bool, stage,
                 device_idx: set[int] | None = None) -> list:
    """The bucketed + vmapped dispatch over non-stripe plans.

    ``device_idx`` marks plan indices whose decoded output should stay
    device-resident: those entries come back as the bucket's (out_cap,)
    uint8 device row instead of host bytes (valid through the plan's
    ``final_len``; trailing lanes are whatever the kernel left there).
    STRIPE reassembly uses this so lane bytes feed the interleave
    gather without a device→host→device round-trip."""
    results: list = [None] * len(plans)
    groups: dict[tuple, list[int]] = {}
    for i, p in enumerate(plans):
        groups.setdefault(_signature(p), []).append(i)
    for sig in sorted(groups):
        idxs = groups[sig]
        (n, cat, order1, shift, rle, pack, rounds, p_cap, lit_cap,
         mid_cap, out_cap, runs_cap, n_ctx_cap) = sig
        grp = [plans[i] for i in idxs]
        B = len(grp)
        payload = np.zeros((B, p_cap), np.uint8)
        plen = np.zeros(B, np.int32)
        states = np.zeros((B, max(n, 1)), np.uint32)
        # freq ships int16 (≤ 4096 each); cum expands on device
        freq = np.zeros((B, 256), np.int16)
        inner = np.zeros(B, np.int32)
        rle_tab = np.zeros((B, 256), bool)
        runs = np.zeros((B, runs_cap), np.int32)
        rle_out = np.zeros(B, np.int32)
        pmap = np.zeros((B, 16), np.int32)
        bits = np.zeros(B, np.int32)
        final = np.zeros(B, np.int32)
        # ORDER1 doubly compact context rows (int16 on the wire,
        # ≤ 4096 each; columns are alphabet positions) + the ctx→row
        # map + the column→symbol alphabet; (B, 1, 1)/(B, 1) dummies
        # for ORDER0 groups so the jit signature stays uniform
        ctx_index = np.full((B, 256), -1, np.int16)
        ctx_freq = np.zeros((B, n_ctx_cap, n_ctx_cap), np.int16)
        alphabet = np.zeros((B, n_ctx_cap), np.int16)
        for j, p in enumerate(grp):
            payload[j, :p.payload.shape[0]] = p.payload
            plen[j] = p.payload.shape[0]
            inner[j] = p.inner_len
            final[j] = p.final_len
            if not cat:
                states[j] = p.states
                if order1:
                    ctx_index[j] = p.ctx_index
                    ctx_freq[j, :p.n_ctx, :p.n_ctx] = \
                        p.ctx_freq.astype(np.int16)
                    alphabet[j, :p.n_ctx] = p.alphabet
                else:
                    freq[j] = p.freq.astype(np.int16)
            if rle:
                rle_tab[j] = p.rle_tab
                runs[j, :len(p.rle_runs)] = p.rle_runs
                rle_out[j] = p.rle_out_len
            if pack:
                pmap[j] = p.pack_map
                bits[j] = p.pack_bits
        host = dict(payload=payload, plen=plen, states=states,
                    freq=freq, inner=inner, rle_tab=rle_tab,
                    runs=runs, rle_out=rle_out, pmap=pmap, bits=bits,
                    final=final, ctx_index=ctx_index,
                    ctx_freq=ctx_freq, alphabet=alphabet)
        if stage is None:
            import jax

            dev = {k: jax.device_put(v) for k, v in host.items()}
        else:
            dev = stage(host)
        from ..obs.compiles import TRACKER

        # exact per-bucket compile attribution against the shared jit
        # object's own cache (one geometry = one cache entry)
        jit_fn = _jitted()
        cache_size = getattr(jit_fn, "_cache_size", None)
        if backend == "pallas" and not cat and not order1:
            # the experimental kernel covers the ORDER0 rANS stage;
            # ORDER1 buckets take the XLA scan either way
            lit = _pallas_scan_bytes(grp, n, rounds, p_cap, interpret)
            # expansions reuse the XLA stages by re-entering as CAT
            # with the scan's output as payload
            with TRACKER.observe("rans", signature=sig,
                                 cache_size_fn=cache_size,
                                 trigger="rans_decode"):
                out, diag = jit_fn(
                    lit, dev["plen"], dev["states"], dev["freq"],
                    dev["inner"], dev["rle_tab"], dev["runs"],
                    dev["rle_out"], dev["pmap"], dev["bits"],
                    dev["final"], dev["ctx_index"], dev["ctx_freq"],
                    dev["alphabet"],
                    rounds=0, n_states=n, cat=True,
                    rle=rle, pack=pack, order1=False, shift=TF_SHIFT,
                    n_ctx_cap=n_ctx_cap, lit_cap=lit.shape[1],
                    mid_cap=mid_cap, out_cap=out_cap)
        else:
            with TRACKER.observe("rans", signature=sig,
                                 cache_size_fn=cache_size,
                                 trigger="rans_decode"):
                out, diag = jit_fn(
                    dev["payload"], dev["plen"], dev["states"],
                    dev["freq"], dev["inner"],
                    dev["rle_tab"], dev["runs"], dev["rle_out"],
                    dev["pmap"], dev["bits"], dev["final"],
                    dev["ctx_index"], dev["ctx_freq"],
                    dev["alphabet"],
                    rounds=rounds, n_states=n, cat=cat, rle=rle,
                    pack=pack, order1=order1, shift=shift,
                    n_ctx_cap=n_ctx_cap, lit_cap=lit_cap,
                    mid_cap=mid_cap, out_cap=out_cap)
        diag = np.asarray(diag)
        keep = device_idx or ()
        # bulk host fetch only when no row of this bucket stays on
        # device; mixed buckets fetch their host rows individually
        host_out = np.asarray(out) \
            if not any(i in keep for i in idxs) else None
        for j, (i, p) in enumerate(zip(idxs, grp)):
            if order1 and int(diag[j, 3]):
                raise ValueError(
                    "rans-nx16: missing order-1 context")
            if rle:
                if int(diag[j, 0]) != p.rle_out_len:
                    raise ValueError(
                        "rans-nx16: rle expansion length mismatch")
                if p.rle_runs is not None \
                        and int(diag[j, 1]) > len(p.rle_runs):
                    raise ValueError(
                        "rans-nx16: rle metadata exhausted")
            if pack and p.pack_bits > 0 \
                    and int(diag[j, 2]) >= p.pack_nsym:
                raise ValueError(
                    "rans-nx16: pack index out of range")
            if i in keep:
                results[i] = out[j]
            elif host_out is not None:
                results[i] = bytes(host_out[j, :p.final_len])
            else:
                results[i] = bytes(np.asarray(out[j, :p.final_len]))
    return results


def decode_parsed(plans: list[ParsedNx16], *, backend: str = "scan",
                  interpret: bool = False,
                  stage=None) -> list[bytes]:
    """Decode parsed streams on device, bucketed + vmapped; returns
    bytes per stream, byte-identical to ``rans_nx16.decode``.

    STRIPE containers flatten into their lane sub-streams (decoded
    through the same buckets as standalone blocks), then reassemble
    via one batched transpose-interleave gather per stripe shape.
    Lane outputs stay device-resident between the decode buckets and
    the interleave dispatch — only the final interleaved block is
    fetched to the host (plain rows fetch as before).

    ``backend``: "scan" (the XLA product path) or "pallas" (the
    experimental kernel for the ORDER0 rANS stage; ORDER1 and the
    expansions take the XLA path).
    ``stage``: optional callable mapping a dict of host arrays to
    device arrays (parallel.prefetch.stage_block_arrays — the
    compressed-wire staging/accounting step); default stages without
    accounting.
    """
    flat: list[ParsedNx16] = []
    spec: list[tuple] = []
    lane_idx: set[int] = set()
    for p in plans:
        if p.stripe:
            idxs = []
            for ch in p.children or []:
                idxs.append(len(flat))
                lane_idx.add(len(flat))
                flat.append(ch)
            spec.append(("stripe", idxs, p))
        else:
            spec.append(("plain", len(flat), p))
            flat.append(p)
    decoded = _decode_flat(flat, backend=backend,
                           interpret=interpret, stage=stage,
                           device_idx=lane_idx)

    results: list[bytes | None] = [None] * len(plans)
    stripe_groups: dict[tuple, list[int]] = {}
    for i, entry in enumerate(spec):
        if entry[0] == "plain":
            results[i] = decoded[entry[1]]
        else:
            stripe_groups.setdefault(_stripe_shape(entry[2]),
                                     []).append(i)
    if stripe_groups:
        import jax.numpy as jnp
    for shape in sorted(stripe_groups):
        n_lanes, lane_cap, out_cap = shape
        members = stripe_groups[shape]
        B = len(members)
        rows = []
        flens = np.zeros(B, np.int32)
        for b, i in enumerate(members):
            _, idxs, p = spec[i]
            flens[b] = p.final_len
            for k in idxs:
                # device row from the lane's decode bucket: valid
                # through the lane's final_len, and the interleave
                # gather never reads past it for output positions
                # < final_len (lane j holds exactly ceil((flen-j)/N)
                # bytes), so pad/trim to lane_cap without re-zeroing
                r = decoded[k]
                if r.shape[0] >= lane_cap:
                    r = r[:lane_cap]
                else:
                    r = jnp.pad(r, (0, lane_cap - r.shape[0]))
                rows.append(r)
        lanes_arr = jnp.stack(rows).reshape(B, n_lanes, lane_cap)
        out = np.asarray(_jitted_interleave()(
            lanes_arr, flens, n_lanes=n_lanes, out_cap=out_cap))
        for b, i in enumerate(members):
            results[i] = bytes(out[b, :spec[i][2].final_len])
    return results


def decode_streams(datas: list[bytes],
                   expected_lens: list[int | None] | None = None,
                   *, backend: str = "scan",
                   interpret: bool = False) -> list[bytes | None]:
    """Parse + device-decode many standalone Nx16 streams; None marks
    a stream that stays host-side (unsupported/corrupt layout, or a
    new bucket shape past the signature cap — the caller falls back
    to ``rans_nx16.decode``). The fuzz-parity surface tests pin
    against the host oracle."""
    if expected_lens is None:
        expected_lens = [None] * len(datas)
    plans, order = [], []
    results: list[bytes | None] = [None] * len(datas)
    for i, (d, el) in enumerate(zip(datas, expected_lens)):
        p = parse_nx16(d, el)
        if p is not None and _admit_signatures(plan_signatures(p)):
            plans.append(p)
            order.append(i)
    decoded = decode_parsed(plans, backend=backend,
                            interpret=interpret)
    for i, b in zip(order, decoded):
        results[i] = b
    return results


# ------------------------------------------------- CRAM block decoder

class DeviceBlockDecoder:
    """Per-container CRAM block decode with the entropy stage on
    device.

    io/cram.py hands :meth:`decode_blocks` one container's raw (still
    compressed) blocks. rANS-Nx16 blocks batch-decode in one bucketed
    vmapped dispatch — the full method-5 matrix (ORDER0/ORDER1 ×
    CAT/PACK/RLE/NOSZ/STRIPE, N=4/X32) — as a content-keyed plan Step
    at the ``decode`` fault site, so a transient device fault costs
    one backoff and the per-sample quarantine above composes
    unchanged. The fallback surface is now corrupt/foreign rANS
    streams and new bucket shapes past ``MAX_BUCKET_SIGNATURES``
    (``decode.device_fallback_total``; cap refusals additionally in
    ``decode.bucket_cap_fallback_total``); non-rANS methods decode on
    host as before (``decode.host_blocks_total``).

    Wire accounting (the point of the exercise): compressed payload
    plus the table arrays per block cross the link instead of the
    inflated bytes — ~0.5KB of table for ORDER0, ~(n_ctx+2)·0.5KB
    for ORDER1's compact context rows (``decode.table_bytes_total``
    isolates that share) — ``decode.wire_bytes_compressed_total`` vs
    ``decode.wire_bytes_uncompressed_total``; the staging itself runs
    through parallel.prefetch.stage_block_arrays so the existing
    prefetch byte counters and stage spans record it.
    """

    def __init__(self, backend: str = "scan", interpret: bool = False,
                 policy=None):
        from ..plan import Executor
        from ..resilience.policy import DEFAULT_POLICY

        self.backend = backend
        self.interpret = interpret
        self._pex = Executor(policy=policy if policy is not None
                             else DEFAULT_POLICY)
        reg = get_registry()
        self._c_dev = reg.counter("decode.device_blocks_total")
        self._c_fall = reg.counter("decode.device_fallback_total")
        self._c_cap = reg.counter("decode.bucket_cap_fallback_total")
        self._c_host = reg.counter("decode.host_blocks_total")
        self._c_wire_c = reg.counter("decode.wire_bytes_compressed_total")
        self._c_wire_u = reg.counter(
            "decode.wire_bytes_uncompressed_total")
        self._c_table = reg.counter("decode.table_bytes_total")

    def _stage(self, host_arrays: dict) -> dict:
        from ..parallel.prefetch import stage_block_arrays

        return stage_block_arrays(host_arrays)

    def decode_blocks(self, raws) -> list[bytes]:
        """raw blocks (io.cram.RawBlock) → uncompressed bytes, in
        order; byte-identical to the host path for every block."""
        from ..io import cram as _cram

        results: list[bytes | None] = [None] * len(raws)
        plans: list[ParsedNx16] = []
        order: list[int] = []
        for i, rb in enumerate(raws):
            if rb.method == _cram.M_RANSNX16:
                p = parse_nx16(rb.raw, rb.rsize)
                if p is not None:
                    if _admit_signatures(plan_signatures(p)):
                        plans.append(p)
                        order.append(i)
                        continue
                    self._c_cap.inc()
                self._c_fall.inc()
            elif rb.method != _cram.M_RAW:
                self._c_host.inc()
            results[i] = _cram._decompress(rb.method, rb.raw,
                                           rb.rsize)
        if plans:
            from ..plan import Step

            table_b = sum(p.table_bytes for p in plans)
            wire_c = sum(p.payload_bytes for p in plans) + table_b
            wire_u = sum(p.final_len for p in plans)
            crc = tcrc = 0
            for p in plans:
                crc = p.payload_crc(crc)
                tcrc = p.table_crc(tcrc)
            # the table CRC joins the content key: same payload bytes
            # under a different table is a different decode
            key = ("decode", self.backend, len(plans), wire_c, crc,
                   tcrc)
            decoded = self._pex.run(Step(
                key=key, site="decode", span="decode.device",
                attrs={"blocks": len(plans), "wire_bytes": wire_c},
                fn=lambda: decode_parsed(
                    plans, backend=self.backend,
                    interpret=self.interpret, stage=self._stage)))
            self._c_dev.inc(len(plans))
            self._c_wire_c.inc(wire_c)
            self._c_wire_u.inc(wire_u)
            self._c_table.inc(table_b)
            for i, b in zip(order, decoded):
                results[i] = b
        return results
