"""Device-resident rANS Nx16 entropy decode (CRAM 3.1 method 5).

Round-2 numbers put device-resident coverage compute at 51.7 Gbases/s
but only 0.155 Gbases/s over the packed wire: host entropy decode plus
H2D transfer is THE speed ceiling (ROADMAP "Close the wire gap"), and
GenPIP's (PAPERS.md) whole thesis is that fusing decode with compute
kills the data-movement wall. This module moves the CRAM block decode
next to the coverage kernels: *compressed* block bytes cross the wire
and the interleaved-rANS state machine runs on the device.

The decoder state machine as a ``lax.scan``
-------------------------------------------
An Nx16 stream decodes round-robin: out[i] advances state i mod N
(N = 4 or 32). One *round* therefore advances all N states — the N
lanes are data-independent within a round except for the shared renorm
byte stream. The scan runs over rounds with carry (R[N] states, read
pointer); each round is pure vector math plus gathers:

  - slot lookup: ``m = R & 0xFFF`` indexes the 4096-entry slot tables
    (symbol / freq / bias), expanded ON DEVICE from the shipped
    (freq[256], cum[257]) int32 arrays by a vectorized searchsorted —
    the wire carries ~2KB of table per block instead of the 48KB
    materialized slot arrays
  - 16-bit renorm as masked gathers: a lane whose next state drops
    below 2^15 reads a little-endian 16-bit word from the shared byte
    stream. Within a round the scalar decoder reads lanes in order, so
    lane j's word sits at ``pos + 2*rank(j)`` where rank counts
    earlier lanes renormalizing this round (an exclusive cumsum); the
    bytes-left guard truncates at the same lane the scalar loop stops
    at, because a denied lane leaves every later lane denied too.

CAT blocks skip the scan (payload = literals); RLE and PACK expansion
run as vectorized gathers on the scan/CAT output (cumsum + searchsorted
for run expansion, shift/mask gathers for bit-unpacking), completing
the supported combo matrix ORDER0 × CAT × PACK × RLE × NOSZ for both
N=4 and X32. ORDER1 and STRIPE stay host-side this PR (counted in
``decode.device_fallback_total``).

Parallelism and compiles: one block is only N lanes wide, so the real
vector width comes from vmapping over many blocks at once. Blocks pad
to power-of-two bucket signatures (payload length, round count,
expansion caps) exactly like ops/pairhmm.py's length bucketing, so a
whole cohort compiles O(#buckets) programs, not O(#shapes).

An experimental Pallas variant (``pallas_decode0``) mirrors
ops/pallas_coverage.py — one block per sequential grid step, lanes as
a VMEM vector, the same round loop as a ``fori_loop``; correctness is
pinned in interpret mode (this container is CPU-only), the XLA scan is
the product path.

``DeviceBlockDecoder`` is the CRAM-facing object: io/cram.py hands it
a container's raw (still compressed) blocks, supported rANS blocks
batch-decode on device through a content-keyed plan Step at the
``decode`` fault site (retry/quarantine compose exactly like every
other dispatch), everything else falls back per-block to the host
codecs, byte-identically.
"""

from __future__ import annotations

import zlib

import numpy as np

from ..io import rans_nx16 as _rx
from ..io.rans_nx16 import ParsedNx16, parse_nx16
from ..obs import get_registry

TF_SHIFT = _rx.TF_SHIFT
TOTFREQ = _rx.TOTFREQ
RANS_LOW = _rx.RANS_LOW

#: minimum pad bucket for payload/output axes (pow-2 above, like
#: pairhmm's BUCKET: arbitrary block sizes compile O(#buckets))
MIN_BUCKET = 64


def bucket(n: int, minimum: int = MIN_BUCKET) -> int:
    b = minimum
    while b < n:
        b <<= 1
    return b


# ------------------------------------------------------------ XLA path

# jax.jit is applied lazily in _jitted() — this module must import
# without jax (the jax-free fleet/router processes import the package)
def _decode_bucket_impl(payload, plen, states, freq, inner_len,
                        rle_tab, runs, rle_out, pmap, bits, final_len,
                        *, rounds, n_states, cat, rle, pack, lit_cap,
                        mid_cap, out_cap):
    """One padded bucket: (B, …) arrays → ((B, out_cap) uint8 bytes,
    (B, 3) int32 diagnostics [rle_total, marked_total, pack_vmax]).

    Static flags (cat/rle/pack) specialize the program per combo; the
    identity stages compile away. All shapes are the bucket caps, all
    true lengths are traced scalars — one compile per signature.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    N = n_states
    lanes = jnp.arange(N, dtype=jnp.int32)
    ms = jnp.arange(TOTFREQ, dtype=jnp.int32)

    def one(payload, plen, R0, freq, inner_len, rle_tab, runs,
            rle_out, pmap, bits, final_len):
        P = payload.shape[0]
        if cat:
            lit = payload[:lit_cap]
        else:
            # the wire ships only the int16 frequency row (~0.5KB);
            # cum and the 4096-entry slot tables expand on device. The
            # largest s with cum[s] <= m is the scalar decoder's lut
            # for every normalized table (zero-freq symbols collapse
            # to equal cum entries, skipped by side="right")
            cum = jnp.concatenate([
                jnp.zeros(1, jnp.int32),
                jnp.cumsum(freq, dtype=jnp.int32)])
            sym = jnp.clip(
                jnp.searchsorted(cum, ms, side="right").astype(
                    jnp.int32) - 1, 0, 255)
            sfreq = freq[sym].astype(jnp.uint32)  # freq ≤ 4096: exact
            sbias = (ms - cum[sym]).astype(jnp.uint32)

            def round_fn(carry, r):
                R, pos = carry
                active = (r * N + lanes) < inner_len
                m = (R & jnp.uint32(TOTFREQ - 1)).astype(jnp.int32)
                s = sym[m]
                x = sfreq[m] * (R >> jnp.uint32(TF_SHIFT)) + sbias[m]
                want = active & (x < jnp.uint32(RANS_LOW))
                avail = jnp.maximum(jnp.int32(0), (plen - pos) // 2)
                wi = want.astype(jnp.int32)
                rank = jnp.cumsum(wi, dtype=jnp.int32) - wi
                need = want & (rank < avail)
                offs = pos + 2 * rank
                b0 = payload[jnp.clip(offs, 0, P - 1)] \
                    .astype(jnp.uint32)
                b1 = payload[jnp.clip(offs + 1, 0, P - 1)] \
                    .astype(jnp.uint32)
                xr = (x << jnp.uint32(16)) | b0 | (b1 << jnp.uint32(8))
                x = jnp.where(need, xr, x)
                R = jnp.where(active, x, R)
                pos = pos + 2 * jnp.sum(need, dtype=jnp.int32)
                return (R, pos), s.astype(jnp.uint8)

            (_, _), syms = lax.scan(
                round_fn, (R0, jnp.int32(0)),
                jnp.arange(rounds, dtype=jnp.int32))
            lit = syms.reshape(rounds * N)[:lit_cap]

        # ---- RLE expansion: each marked literal repeats 1 + its run
        # extension; output position p maps back to the literal whose
        # cumulative start covers it (searchsorted over the exclusive
        # cumsum — the vectorized form of the host's sequential walk)
        if rle:
            idx = jnp.arange(lit_cap, dtype=jnp.int32)
            in_range = idx < inner_len
            marked = rle_tab[lit.astype(jnp.int32)] & in_range
            mi = marked.astype(jnp.int32)
            rank = jnp.cumsum(mi, dtype=jnp.int32) - mi
            rcap = runs.shape[0]
            rep = jnp.where(
                in_range,
                1 + jnp.where(marked,
                              runs[jnp.clip(rank, 0, rcap - 1)], 0),
                0)
            starts = jnp.cumsum(rep, dtype=jnp.int32) - rep
            rle_total = starts[-1] + rep[-1]
            marked_total = jnp.sum(mi, dtype=jnp.int32)
            posn = jnp.arange(mid_cap, dtype=jnp.int32)
            src = jnp.clip(
                jnp.searchsorted(starts, posn, side="right").astype(
                    jnp.int32) - 1, 0, lit_cap - 1)
            mid = jnp.where(posn < rle_out, lit[src],
                            jnp.uint8(0))
            mid_len = rle_out
        else:
            mid = lit
            mid_len = inner_len
            rle_total = inner_len
            marked_total = jnp.int32(0)

        # ---- PACK expansion: shift/mask gathers (bits ∈ {0,1,2,4},
        # LSB-first like the host's _unpack)
        if pack:
            i = jnp.arange(out_cap, dtype=jnp.int32)
            per = 8 // jnp.maximum(bits, 1)
            idxp = jnp.clip(i // per, 0, mid_cap - 1)
            sh = bits * (i % per)
            maskb = (jnp.int32(1) << bits) - 1
            v = (mid[idxp].astype(jnp.int32) >> sh) & maskb
            vc = jnp.clip(v, 0, 15)
            outb = jnp.where(bits == 0, pmap[0], pmap[vc]) \
                .astype(jnp.uint8)
            vmax = jnp.max(jnp.where((i < final_len) & (bits > 0),
                                     v, 0))
        else:
            outb = mid
            vmax = jnp.int32(0)
        del mid_len
        diag = jnp.stack([rle_total.astype(jnp.int32),
                          marked_total, vmax])
        return outb, diag

    return jax.vmap(one)(payload, plen, states, freq, inner_len,
                         rle_tab, runs, rle_out, pmap, bits, final_len)


_JIT_CACHE: dict = {}


def _jitted():
    fn = _JIT_CACHE.get("xla")
    if fn is None:
        import jax

        fn = jax.jit(_decode_bucket_impl, static_argnames=(
            "rounds", "n_states", "cat", "rle", "pack", "lit_cap",
            "mid_cap", "out_cap"))
        _JIT_CACHE["xla"] = fn
    return fn


# --------------------------------------------------------- Pallas path

def pallas_decode0(payload, plen, states, slot_sym, slot_freq,
                   slot_bias, inner_len, *, rounds, n_states,
                   interpret: bool = False):
    """The rANS scan as a Pallas kernel: one block per sequential grid
    step, the N states as a lane vector, the round loop as a
    ``fori_loop`` with (states, read pointer, output buffer) carried —
    the same one-item-per-grid-step pattern as
    ops/pairhmm.py::pallas_forward_bucket. EXPERIMENTAL like its
    siblings: interpret-mode-pinned against the XLA scan (this
    container is CPU-only); expansions (RLE/PACK) stay in the shared
    XLA stages either way.

    payload (B, P) int32, states (B, N) int32, slots (B, 4096) int32
    → (B, rounds*N) int32 symbols.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, P = payload.shape
    N = n_states
    L = rounds * N

    def kernel(meta_ref, states_ref, payload_ref, sym_ref, freq_ref,
               bias_ref, out_ref):
        plen_b = meta_ref[0, 0]
        inner_b = meta_ref[0, 1]
        pay = payload_ref[0, :]
        sym = sym_ref[0, :]
        sfreq = freq_ref[0, :]
        sbias = bias_ref[0, :]
        lanes = jax.lax.broadcasted_iota(jnp.int32, (1, N), 1)

        def round_fn(r, carry):
            R, pos, outbuf = carry
            active = (r * N + lanes) < inner_b
            m = R & (TOTFREQ - 1)
            s = jnp.take(sym, m[0, :], axis=0)[None, :]
            f = jnp.take(sfreq, m[0, :], axis=0)[None, :]
            bi = jnp.take(sbias, m[0, :], axis=0)[None, :]
            # int32 is exact here: valid states stay < 2^31 (renorm
            # bound) so freq*(x>>12)+bias < 2^31 and the x<<16 of a
            # sub-2^15 state fits — the uint32 XLA path and this agree
            # bit-for-bit on every well-formed stream
            x = f * (R >> TF_SHIFT) + bi
            want = active & (x < RANS_LOW)
            avail = jnp.maximum(0, (plen_b - pos) // 2)
            wi = want.astype(jnp.int32)
            rank = jnp.cumsum(wi, axis=1, dtype=jnp.int32) - wi
            need = want & (rank < avail)
            offs = pos + 2 * rank
            b0 = jnp.take(pay, jnp.clip(offs[0, :], 0, P - 1),
                          axis=0)[None, :]
            b1 = jnp.take(pay, jnp.clip(offs[0, :] + 1, 0, P - 1),
                          axis=0)[None, :]
            xr = (x << 16) | b0 | (b1 << 8)
            x = jnp.where(need, xr, x)
            R = jnp.where(active, x, R)
            pos = pos + 2 * jnp.sum(need, dtype=jnp.int32)
            outbuf = jax.lax.dynamic_update_slice(outbuf, s,
                                                  (0, r * N))
            return R, pos, outbuf

        R0 = states_ref[0, :][None, :]
        out0 = jnp.zeros((1, L), jnp.int32)
        _, _, outbuf = jax.lax.fori_loop(
            0, rounds, round_fn, (R0, jnp.int32(0), out0))
        out_ref[0] = outbuf[0]

    meta = jnp.stack([plen, inner_len], axis=1).astype(jnp.int32)
    return pl.pallas_call(
        kernel,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, 2), lambda t: (t, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, N), lambda t: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, P), lambda t: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, TOTFREQ), lambda t: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, TOTFREQ), lambda t: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, TOTFREQ), lambda t: (t, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, L), lambda t: (t, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B, L), jnp.int32),
        interpret=interpret,
    )(meta, states, payload, slot_sym, slot_freq, slot_bias)


def _pallas_scan_bytes(group: list[ParsedNx16], n_states: int,
                       rounds: int, p_cap: int,
                       interpret: bool) -> np.ndarray:
    """Run a non-CAT group's rANS stage through the Pallas kernel,
    returning (B, rounds*N) uint8 symbols (the XLA expansion stages
    consume them unchanged)."""
    import jax.numpy as jnp

    B = len(group)
    payload = np.zeros((B, p_cap), np.int32)
    plen = np.zeros(B, np.int32)
    states = np.zeros((B, n_states), np.int32)
    ssym = np.zeros((B, TOTFREQ), np.int32)
    sfreq = np.zeros((B, TOTFREQ), np.int32)
    sbias = np.zeros((B, TOTFREQ), np.int32)
    inner = np.zeros(B, np.int32)
    ms = np.arange(TOTFREQ, dtype=np.int64)
    for i, p in enumerate(group):
        payload[i, :p.payload.shape[0]] = p.payload
        plen[i] = p.payload.shape[0]
        states[i] = p.states.astype(np.int64).astype(np.int32)
        lut = _rx._slot_lut(p.freq.astype(np.int64),
                            p.cum.astype(np.int64)).astype(np.int64)
        ssym[i] = lut.astype(np.int32)
        sfreq[i] = p.freq[lut]
        sbias[i] = (ms - p.cum[lut]).astype(np.int32)
        inner[i] = p.inner_len
    got = pallas_decode0(
        jnp.asarray(payload), jnp.asarray(plen), jnp.asarray(states),
        jnp.asarray(ssym), jnp.asarray(sfreq), jnp.asarray(sbias),
        jnp.asarray(inner), rounds=rounds, n_states=n_states,
        interpret=interpret)
    return np.asarray(got).astype(np.uint8)


# ---------------------------------------------------------- batch glue

def _signature(p: ParsedNx16) -> tuple:
    """Pad-to-bucket compile signature (pairhmm-style): every axis
    rounds up to a power of two so arbitrary cohorts stay O(#buckets)
    compiles."""
    n = p.n_states
    lit_cap = bucket(max(p.inner_len, 1))
    if not p.cat:
        rounds = (lit_cap + n - 1) // n
        lit_cap = rounds * n
    else:
        rounds = 0
    p_cap = bucket(max(p.payload.shape[0], 1))
    if p.cat:
        p_cap = max(p_cap, lit_cap)  # CAT payload IS the literals
    mid_cap = bucket(max(p.rle_out_len, 1)) if p.rle else lit_cap
    out_cap = bucket(max(p.final_len, 1)) if p.pack else mid_cap
    runs_cap = bucket(len(p.rle_runs) if p.rle_runs is not None
                      else 0, minimum=16)
    return (n, p.cat, p.rle, p.pack, rounds, p_cap, lit_cap, mid_cap,
            out_cap, runs_cap)


def decode_parsed(plans: list[ParsedNx16], *, backend: str = "scan",
                  interpret: bool = False,
                  stage=None) -> list[bytes]:
    """Decode parsed streams on device, bucketed + vmapped; returns
    bytes per stream, byte-identical to ``rans_nx16.decode``.

    ``backend``: "scan" (the XLA product path) or "pallas" (the
    experimental kernel for the rANS stage; expansions shared).
    ``stage``: optional callable mapping a dict of host arrays to
    device arrays (parallel.prefetch.stage_block_arrays — the
    compressed-wire staging/accounting step); default stages without
    accounting.
    """
    results: list[bytes | None] = [None] * len(plans)
    groups: dict[tuple, list[int]] = {}
    for i, p in enumerate(plans):
        groups.setdefault(_signature(p), []).append(i)
    for sig in sorted(groups):
        idxs = groups[sig]
        (n, cat, rle, pack, rounds, p_cap, lit_cap, mid_cap, out_cap,
         runs_cap) = sig
        grp = [plans[i] for i in idxs]
        B = len(grp)
        payload = np.zeros((B, p_cap), np.uint8)
        plen = np.zeros(B, np.int32)
        states = np.zeros((B, n), np.uint32)
        # freq ships int16 (≤ 4096 each); cum expands on device
        freq = np.zeros((B, 256), np.int16)
        inner = np.zeros(B, np.int32)
        rle_tab = np.zeros((B, 256), bool)
        runs = np.zeros((B, runs_cap), np.int32)
        rle_out = np.zeros(B, np.int32)
        pmap = np.zeros((B, 16), np.int32)
        bits = np.zeros(B, np.int32)
        final = np.zeros(B, np.int32)
        for j, p in enumerate(grp):
            payload[j, :p.payload.shape[0]] = p.payload
            plen[j] = p.payload.shape[0]
            inner[j] = p.inner_len
            final[j] = p.final_len
            if not cat:
                states[j] = p.states
                freq[j] = p.freq.astype(np.int16)
            if rle:
                rle_tab[j] = p.rle_tab
                runs[j, :len(p.rle_runs)] = p.rle_runs
                rle_out[j] = p.rle_out_len
            if pack:
                pmap[j] = p.pack_map
                bits[j] = p.pack_bits
        host = dict(payload=payload, plen=plen, states=states,
                    freq=freq, inner=inner, rle_tab=rle_tab,
                    runs=runs, rle_out=rle_out, pmap=pmap, bits=bits,
                    final=final)
        if stage is None:
            import jax

            dev = {k: jax.device_put(v) for k, v in host.items()}
        else:
            dev = stage(host)
        if backend == "pallas" and not cat:
            lit = _pallas_scan_bytes(grp, n, rounds, p_cap, interpret)
            # expansions reuse the XLA stages by re-entering as CAT
            # with the scan's output as payload
            out, diag = _jitted()(
                lit, dev["plen"], dev["states"], dev["freq"],
                dev["inner"], dev["rle_tab"], dev["runs"],
                dev["rle_out"], dev["pmap"], dev["bits"],
                dev["final"], rounds=0, n_states=n, cat=True,
                rle=rle, pack=pack, lit_cap=lit.shape[1],
                mid_cap=mid_cap, out_cap=out_cap)
        else:
            out, diag = _jitted()(
                dev["payload"], dev["plen"], dev["states"],
                dev["freq"], dev["inner"],
                dev["rle_tab"], dev["runs"], dev["rle_out"],
                dev["pmap"], dev["bits"], dev["final"],
                rounds=rounds, n_states=n, cat=cat, rle=rle,
                pack=pack, lit_cap=lit_cap, mid_cap=mid_cap,
                out_cap=out_cap)
        out = np.asarray(out)
        diag = np.asarray(diag)
        for j, (i, p) in enumerate(zip(idxs, grp)):
            if rle:
                if int(diag[j, 0]) != p.rle_out_len:
                    raise ValueError(
                        "rans-nx16: rle expansion length mismatch")
                if p.rle_runs is not None \
                        and int(diag[j, 1]) > len(p.rle_runs):
                    raise ValueError(
                        "rans-nx16: rle metadata exhausted")
            if pack and p.pack_bits > 0 \
                    and int(diag[j, 2]) >= p.pack_nsym:
                raise ValueError(
                    "rans-nx16: pack index out of range")
            results[i] = bytes(out[j, :p.final_len])
    return results


def decode_streams(datas: list[bytes],
                   expected_lens: list[int | None] | None = None,
                   *, backend: str = "scan",
                   interpret: bool = False) -> list[bytes | None]:
    """Parse + device-decode many standalone Nx16 streams; None marks
    a stream whose combo stays host-side (the caller falls back to
    ``rans_nx16.decode``). The fuzz-parity surface tests pin against
    the host oracle."""
    if expected_lens is None:
        expected_lens = [None] * len(datas)
    plans, order = [], []
    results: list[bytes | None] = [None] * len(datas)
    for i, (d, el) in enumerate(zip(datas, expected_lens)):
        p = parse_nx16(d, el)
        if p is not None:
            plans.append(p)
            order.append(i)
    decoded = decode_parsed(plans, backend=backend,
                            interpret=interpret)
    for i, b in zip(order, decoded):
        results[i] = b
    return results


# ------------------------------------------------- CRAM block decoder

class DeviceBlockDecoder:
    """Per-container CRAM block decode with the entropy stage on
    device.

    io/cram.py hands :meth:`decode_blocks` one container's raw (still
    compressed) blocks. rANS-Nx16 blocks whose flag combo the device
    path supports batch-decode in one bucketed vmapped dispatch — a
    content-keyed plan Step at the ``decode`` fault site, so a
    transient device fault costs one backoff and the per-sample
    quarantine above composes unchanged. Every other block (gzip,
    ORDER1, STRIPE, …) decodes on host exactly as before, counted in
    ``decode.device_fallback_total`` (rANS combos deferred this PR)
    or ``decode.host_blocks_total`` (other codecs).

    Wire accounting (the point of the exercise): compressed payload +
    ~2KB of table arrays per block cross the link instead of the
    inflated bytes — ``decode.wire_bytes_compressed_total`` vs
    ``decode.wire_bytes_uncompressed_total``; the staging itself runs
    through parallel.prefetch.stage_block_arrays so the existing
    prefetch byte counters and stage spans record it.
    """

    def __init__(self, backend: str = "scan", interpret: bool = False,
                 policy=None):
        from ..plan import Executor
        from ..resilience.policy import DEFAULT_POLICY

        self.backend = backend
        self.interpret = interpret
        self._pex = Executor(policy=policy if policy is not None
                             else DEFAULT_POLICY)
        reg = get_registry()
        self._c_dev = reg.counter("decode.device_blocks_total")
        self._c_fall = reg.counter("decode.device_fallback_total")
        self._c_host = reg.counter("decode.host_blocks_total")
        self._c_wire_c = reg.counter("decode.wire_bytes_compressed_total")
        self._c_wire_u = reg.counter(
            "decode.wire_bytes_uncompressed_total")

    def _stage(self, host_arrays: dict) -> dict:
        from ..parallel.prefetch import stage_block_arrays

        return stage_block_arrays(host_arrays)

    def decode_blocks(self, raws) -> list[bytes]:
        """raw blocks (io.cram.RawBlock) → uncompressed bytes, in
        order; byte-identical to the host path for every block."""
        from ..io import cram as _cram

        results: list[bytes | None] = [None] * len(raws)
        plans: list[ParsedNx16] = []
        order: list[int] = []
        for i, rb in enumerate(raws):
            if rb.method == _cram.M_RANSNX16:
                p = parse_nx16(rb.raw, rb.rsize)
                if p is not None:
                    plans.append(p)
                    order.append(i)
                    continue
                self._c_fall.inc()
            elif rb.method != _cram.M_RAW:
                self._c_host.inc()
            results[i] = _cram._decompress(rb.method, rb.raw,
                                           rb.rsize)
        if plans:
            from ..plan import Step

            wire_c = sum(int(p.payload.nbytes) + p.table_bytes
                         for p in plans)
            wire_u = sum(p.final_len for p in plans)
            crc = 0
            for p in plans:
                crc = zlib.crc32(p.payload, crc)
            key = ("decode", self.backend, len(plans), wire_c, crc)
            decoded = self._pex.run(Step(
                key=key, site="decode", span="decode.device",
                attrs={"blocks": len(plans), "wire_bytes": wire_c},
                fn=lambda: decode_parsed(
                    plans, backend=self.backend,
                    interpret=self.interpret, stage=self._stage)))
            self._c_dev.inc(len(plans))
            self._c_wire_c.inc(wire_c)
            self._c_wire_u.inc(wire_u)
            for i, b in zip(order, decoded):
                results[i] = b
        return results
