"""Fused per-shard depth pipeline: segments → per-base depth → window sums
+ callable classes, one jit compile per (padded length, window, bucket).

Shards are computed relative to w0 = floor(region_start/W)*W so the window
grid is always aligned and lpad never varies — the dynamic region bounds
(rs, re) arrive as traced scalars and only mask, never reshape. This keeps
XLA compilations to a handful for a whole-genome run (one per segment
bucket), where a naive per-region shape would compile per chromosome tail.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..obs import InstrumentedDispatch as _InstrumentedDispatch


def _pipeline_body(seg_start, seg_end, keep, w0, region_start,
                   region_end, depth_cap, min_cov, max_mean_depth,
                   length, window):
    s = jnp.clip(jnp.maximum(seg_start, region_start) - w0, 0, length)
    e = jnp.clip(jnp.minimum(seg_end, region_end) - w0, 0, length)
    s = jnp.where(keep, s, length)
    e = jnp.where(keep, e, length)
    delta = jnp.zeros(length + 1, dtype=jnp.int32)
    delta = delta.at[s].add(1).at[e].add(-1)
    depth = jnp.cumsum(delta[:length])
    depth = jnp.minimum(depth, depth_cap)
    pos = jnp.arange(length, dtype=jnp.int32) + w0
    in_region = (pos >= region_start) & (pos < region_end)
    depth = jnp.where(in_region, depth, 0)

    # f32 window sums are exact while window*depth_cap < 2**24 (every
    # partial sum an exact int), which covers the reference defaults
    # (W=250, cap=2500 → 625000); beyond that relative error ≤ 1e-7 is
    # far below the 0.5-absolute oracle tolerance (depth/test/cmp.py:12).
    window_sums = depth.astype(jnp.float32).reshape(-1, window).sum(axis=1)

    cls = jnp.where(
        depth == 0,
        0,
        jnp.where(
            depth < min_cov,
            1,
            jnp.where(
                (max_mean_depth > 0) & (depth >= max_mean_depth), 3, 2
            ),
        ),
    ).astype(jnp.int8)
    return window_sums, cls, depth


@functools.partial(jax.jit, static_argnames=("length", "window"))
def shard_depth_pipeline(
    seg_start: jax.Array,
    seg_end: jax.Array,
    keep: jax.Array,
    w0: jax.Array,
    region_start: jax.Array,
    region_end: jax.Array,
    depth_cap: jax.Array,
    min_cov: jax.Array,
    max_mean_depth: jax.Array,
    length: int,
    window: int,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Returns (window_sums f32, per-base classes i8, per-base depth i32)
    over [w0, w0+length); bases outside [region_start, region_end) are
    zeroed (samtools -r only counts in-region bases).

    length must be a multiple of window and ≥ region_end - w0.
    """
    return _pipeline_body(seg_start, seg_end, keep, w0, region_start,
                          region_end, depth_cap, min_cov,
                          max_mean_depth, length, window)


def _pack_cls_2bit(cls: jax.Array, length: int) -> jax.Array:
    """int8 classes (values 0..3) → 2-bit packed uint8, little-end-first
    within each byte — quarters the device→host transfer of the
    per-base class array (the depth CLI's D2H bottleneck on slow links).
    """
    pad = (-length) % 4
    if pad:
        cls = jnp.concatenate([cls, jnp.zeros(pad, cls.dtype)])
    c4 = cls.reshape(-1, 4).astype(jnp.uint8)
    return (c4[:, 0] | (c4[:, 1] << 2) | (c4[:, 2] << 4)
            | (c4[:, 3] << 6))


def unpack_cls_2bit(packed: "np.ndarray", length: int):
    """Host inverse of _pack_cls_2bit → int8 (length,)."""
    import numpy as np

    bits = (packed[:, None] >> np.array([0, 2, 4, 6], np.uint8)) & 3
    return bits.reshape(-1)[:length].astype(np.int8)


@functools.partial(jax.jit, static_argnames=("length", "window"))
def shard_depth_pipeline_cls_packed(
    seg_start: jax.Array,
    seg_end: jax.Array,
    keep: jax.Array,
    w0: jax.Array,
    region_start: jax.Array,
    region_end: jax.Array,
    depth_cap: jax.Array,
    min_cov: jax.Array,
    max_mean_depth: jax.Array,
    length: int,
    window: int,
) -> tuple[jax.Array, jax.Array]:
    """(window_sums, 2-bit packed classes) — the depth CLI's fetch set."""
    sums, cls, _ = _pipeline_body(seg_start, seg_end, keep, w0,
                                  region_start, region_end, depth_cap,
                                  min_cov, max_mean_depth, length, window)
    return sums, _pack_cls_2bit(cls, length)


def _unpack_wire(deltas, lens, base):
    """u16 wire (sorted start deltas + lengths) → absolute endpoints +
    keep mask; zero-length entries are padding/gap fillers."""
    seg_start = base + jnp.cumsum(deltas.astype(jnp.int32))
    lens32 = lens.astype(jnp.int32)
    return seg_start, seg_start + lens32, lens32 > 0


@functools.partial(jax.jit, static_argnames=("length", "window"))
def shard_depth_pipeline_packed_cls_packed(
    deltas: jax.Array,
    lens: jax.Array,
    base: jax.Array,
    w0: jax.Array,
    region_start: jax.Array,
    region_end: jax.Array,
    depth_cap: jax.Array,
    min_cov: jax.Array,
    max_mean_depth: jax.Array,
    length: int,
    window: int,
) -> tuple[jax.Array, jax.Array]:
    """Packed u16 wire in, 2-bit packed classes out."""
    s, e, keep = _unpack_wire(deltas, lens, base)
    sums, cls, _ = _pipeline_body(s, e, keep, w0, region_start,
                                  region_end, depth_cap, min_cov,
                                  max_mean_depth, length, window)
    return sums, _pack_cls_2bit(cls, length)


@functools.partial(jax.jit, static_argnames=("length", "window"))
def shard_depth_pipeline_packed(
    deltas: jax.Array,
    lens: jax.Array,
    base: jax.Array,
    w0: jax.Array,
    region_start: jax.Array,
    region_end: jax.Array,
    depth_cap: jax.Array,
    min_cov: jax.Array,
    max_mean_depth: jax.Array,
    length: int,
    window: int,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Same pipeline fed by the packed u16 wire format (4 bytes/segment
    instead of 9: sorted start deltas + lengths, see
    ops/coverage.py::pack_segments_u16) — host→device traffic halves and
    the absolute endpoints are reconstructed on device with one cumsum.
    """
    s, e, keep = _unpack_wire(deltas, lens, base)
    return _pipeline_body(s, e, keep, w0, region_start, region_end,
                          depth_cap, min_cov, max_mean_depth, length,
                          window)


# Device-event instrumentation: the module's dispatch boundaries are
# proxies that (only when device events are on — --trace-out /
# GOLEFT_TPU_DEVICE_EVENTS=1) wrap each call in a span carrying
# backend/platform/device-kind attributes and fence it with
# block_until_ready, so per-dispatch device time is honest instead of
# enqueue-microseconds. Off (the default), a call is a flag check away
# from the raw jitted function, async dispatch intact. Jit attributes
# (_cache_size, lower, …) forward through — bench.py's compile-cache
# cross-check keeps working — and calls made INSIDE a jax trace (the
# vmapped wrappers in commands/depth.py and commands/cohortdepth.py
# close over these names) pass straight through untouched.
shard_depth_pipeline = _InstrumentedDispatch(
    shard_depth_pipeline, "shard_depth_pipeline")
shard_depth_pipeline_cls_packed = _InstrumentedDispatch(
    shard_depth_pipeline_cls_packed, "shard_depth_pipeline_cls_packed")
shard_depth_pipeline_packed_cls_packed = _InstrumentedDispatch(
    shard_depth_pipeline_packed_cls_packed,
    "shard_depth_pipeline_packed_cls_packed")
shard_depth_pipeline_packed = _InstrumentedDispatch(
    shard_depth_pipeline_packed, "shard_depth_pipeline_packed")
