"""Pair-HMM forward likelihood: anti-diagonal wavefront on the device.

The genotype-likelihood kernel behind ``goleft-tpu pairhmm`` — the
GATK-class forward pass P(read | haplotype) that gpuPairHMM / Endeavor
(PAPERS.md) identify as the field's consensus bottleneck after
coverage. Three DP matrices over read (rows) × haplotype (cols):

    M[i,j] = prior(i,j)·(tMM·M[i-1,j-1] + tIM·I[i-1,j-1]
                                        + tDM·D[i-1,j-1])
    I[i,j] = tMI·M[i-1,j] + tII·I[i-1,j]
    D[i,j] = tMD·M[i,j-1] + tDD·D[i,j-1]

with the free-start first row (M=I=0, D[0,j]=1/|hap|), transitions
from phred gap-open/extend scores (δ=10^(-open/10), ε=10^(-ext/10);
tMM=1-2δ, tMI=tMD=δ, tIM=tDM=1-ε, tII=tDD=ε), emission priors from
per-base qualities (match 1-err, mismatch err/3, N always matches),
and L = Σ_j M[R,j] + I[R,j].

Cell (i,j) depends only on diagonals i+j-1 and i+j-2, so the sweep
runs over anti-diagonals: each of the R+H wavefront steps updates
three (R+1)-vectors with shifts and elementwise math — one vectorized
sweep per step instead of a sequential cell loop, which is what makes
the recurrence a device kernel at all. Batches vmap over the wavefront
with padded reads/haps; padding is masked to exact zeros every step,
so a pair's result is **bitwise independent** of its bucket shape and
batch neighbors (tests/test_pairhmm.py pins this — it is what lets
the serve executor coalesce requests byte-identically).

f32 with per-row rescaling (the gpuPairHMM/Endeavor trick that avoids
f64), adapted to the wavefront: the diagonal buffers are indexed by
read row, so each lane carries its own scale counter — lane i's
stored values are the true probabilities times 2^(30·shift[i]).
A single scale per diagonal cannot work here: one anti-diagonal mixes
0-emission boundary cells (constant 1/|hap|) with full-read-prefix
cells hundreds of decades smaller, far beyond f32's exponent range —
measured on a 400bp read, diagonal-global rescaling silently flushes
the dominant paths and loses ~4 log10. Per lane, whenever a row's
live magnitude leaves [2^-30, 2^30] it is renormalized by 2^∓30 and
its counter adjusts (symmetric, because a lane inherits its scale
from the sweep frontier before its own bulk values arrive, and the
two can disagree in either direction); recurrence terms crossing
lanes are reconciled by 2^(30·Δshift), with Δ self-bounding: scales
track each lane's live magnitude, adjacent rows' magnitudes are
within one emission+transition of each other, and a lane stops
renormalizing the moment a differently-scaled neighbor dominates it.
The kernel emits the O(R+H) per-step final-row contributions together
with their scales instead of accumulating on device; the host folds
them with an exact f64 log-sum-exp, so likelihoods far below f32's
range (a 400bp junk read is ~10^-400) come back accurate to ~1e-5
log10 with no running-accumulator scale state at all.

Length bucketing bounds recompiles: pairs group by lengths rounded up
to BUCKET (default 32), so a cohort of arbitrary read/hap lengths
compiles O(#buckets) programs, not O(#shapes). ``forward_pairs`` is
the host entry: encode → bucket → per-bucket dispatch (the
``pairhmm`` fault-injection site, retried under a RetryPolicy) →
scatter back to input order.

A Pallas inner-loop variant (``pallas_forward_bucket``) mirrors
ops/pallas_coverage.py's pattern — one pair per sequential grid step,
diagonal buffers live in VMEM as (1, Rpad) lane vectors, the
haplotype diagonal maintained by a shift-in register instead of a
per-step gather. EXPERIMENTAL like its coverage sibling: correctness
is pinned in interpret mode; the XLA wavefront is the product path.
"""

from __future__ import annotations

import math

import numpy as np

from ..obs import get_registry

BUCKET = 32  # length-bucket granularity (pads lengths up to this)
#: f32 rescaling: a lane renormalizes by 2^±SCALE_EXP whenever its
#: live max leaves [2^-SCALE_EXP, 2^SCALE_EXP]. 30 keeps every
#: intermediate normal (worst one-step decay, a q93 mismatch times a
#: gap open, is ~2^-48 — the next step's boost catches up) while
#: leaving enough f32 exponent headroom that a cross-lane conversion
#: of up to 2^(30·3) applied to a ≤2^30-ish stored value stays finite.
SCALE_EXP = 30
#: cross-lane scale differences are self-bounding (see module
#: docstring); the clip only ever truncates factors applied to zeros
_DMIN, _DMAX = -4, 3
_LOG10_2 = math.log10(2.0)

# base codes: A C G T = 0..3, N/other = 4 (always treated as a match)
_ENCODE = np.full(256, 4, dtype=np.uint8)
for _i, _b in enumerate(b"ACGT"):
    _ENCODE[_b] = _i
    _ENCODE[ord(chr(_b).lower())] = _i
N_CODE = np.uint8(4)

DEFAULT_GAP_OPEN = 45.0  # phred; δ = 10^-4.5 ≈ 3.2e-5
DEFAULT_GAP_EXT = 10.0   # phred; ε = 0.1


def encode_seq(seq) -> np.ndarray:
    """str/bytes → uint8 base codes (A=0 C=1 G=2 T=3, other=N=4)."""
    if isinstance(seq, np.ndarray):
        return seq.astype(np.uint8)
    if isinstance(seq, str):
        seq = seq.encode("ascii")
    return _ENCODE[np.frombuffer(bytes(seq), dtype=np.uint8)]


def phred_to_err(quals) -> np.ndarray:
    """Phred qualities → base error probabilities, f64."""
    q = np.asarray(quals, dtype=np.float64)
    return np.power(10.0, -q / 10.0)


def transition_probs(gap_open: float = DEFAULT_GAP_OPEN,
                     gap_ext: float = DEFAULT_GAP_EXT) -> np.ndarray:
    """(5,) f64 [tMM, tMI=tMD, tIM=tDM, tII=tDD, delta-unused-pad] —
    computed once in f64; the bucket kernel casts to its compute
    dtype."""
    delta = 10.0 ** (-float(gap_open) / 10.0)
    eps = 10.0 ** (-float(gap_ext) / 10.0)
    return np.array([1.0 - 2.0 * delta, delta, 1.0 - eps, eps, delta],
                    dtype=np.float64)


def _forward_bucket_impl(reads_p, pm, px, rlens, haps, hlens, trans,
                         *, rescale: bool):
    """One padded bucket through the wavefront; vmapped over pairs.

    reads_p: (B, R1) uint8 — read base at diag index i (i is 1-based;
             index 0 is an N sentinel for the boundary row)
    pm/px:   (B, R1) match / mismatch emission priors per read index
    rlens:   (B,) int32 true read lengths
    haps:    (B, H) uint8, hlens (B,) int32
    trans:   (5,) transition probs in the compute dtype

    With ``rescale`` (the f32 path) each lane i — read row i of the
    wavefront — carries its own scale counter: stored = true ·
    2^(30·s[i]). Same-lane terms (the D recurrence) need no
    adjustment; cross-lane terms (M from row i-1 two diagonals back,
    I from row i-1 one back) are multiplied by 2^(30·(s[i]-s[i-1])).
    The difference is self-bounding — scales track each lane's live
    magnitude both up and down, and adjacent rows' magnitudes are
    within one emission·transition of each other — so the clip to
    [_DMIN, _DMAX] only ever truncates factors applied to zeros.
    All-zero lanes adopt their left neighbor's scale: the adoption
    ramp advances one lane per step, in sync with the frontier, so a
    lane enters the sweep at its feeder's scale instead of a stale 0.

    Returns (contribs, shifts): per wavefront step k, the final-row
    contribution M[R, k-R] + I[R, k-R] stored at scale 2^(30·shift) —
    the caller folds them into log10(L) on host with an exact f64
    log-sum-exp (no running-accumulator scale state on device).
    """
    import jax
    import jax.numpy as jnp

    dtype = pm.dtype
    r1 = reads_p.shape[1]
    hcap = haps.shape[1]
    steps = r1 + hcap
    t_mm, t_mi, t_im, t_ii = (trans[0], trans[1], trans[2], trans[3])
    below = jnp.asarray(2.0 ** -SCALE_EXP, dtype)
    above = jnp.asarray(2.0 ** SCALE_EXP, dtype)
    up = jnp.asarray(2.0 ** SCALE_EXP, dtype)
    down = jnp.asarray(2.0 ** -SCALE_EXP, dtype)
    one = jnp.asarray(1.0, dtype)
    zero = jnp.asarray(0.0, dtype)

    def one_pair(read, pmv, pxv, rlen, hap, hlen):
        ii = jnp.arange(r1, dtype=jnp.int32)
        inv_h = one / hlen.astype(dtype)

        def shift1(x):
            # x[i-1] with a zero entering at i=0
            return jnp.concatenate([x[:1] * 0, x[:-1]])

        def scale_fix(s_to, s_from):
            d = jnp.clip(s_to - s_from, _DMIN, _DMAX)
            return jnp.exp2((SCALE_EXP * d).astype(dtype))

        def step(k, carry):
            m1, i1, d1, s1, m2, i2, d2, s2, contribs, shifts = carry
            jj = k - ii
            hb = jnp.where(
                (jj >= 1) & (jj <= hlen),
                hap[jnp.clip(jj - 1, 0, hcap - 1)], N_CODE)
            valid = ((ii >= 1) & (ii <= rlen)
                     & (jj >= 1) & (jj <= hlen))
            is_match = (read == hb) | (read == N_CODE) | (hb == N_CODE)
            prior = jnp.where(is_match, pmv, pxv)
            mterm = (t_mm * shift1(m2) + t_im * shift1(i2)
                     + t_im * shift1(d2))
            iterm = t_mi * shift1(m1) + t_ii * shift1(i1)
            if rescale:
                mterm = mterm * scale_fix(s1, shift1(s2))
                iterm = iterm * scale_fix(s1, shift1(s1))
            mk = prior * mterm
            ik = iterm
            dk = t_mi * m1 + t_ii * d1
            mk = jnp.where(valid, mk, zero)
            ik = jnp.where(valid, ik, zero)
            dk = jnp.where(valid, dk, zero)
            # boundary row i=0: D[0, j] = 1/|hap| (free start), M=I=0.
            # Lane 0's magnitude never drops below 1/|hap| while the
            # boundary is live, so its scale counter stays 0 and the
            # injected constant needs no adjustment.
            d0 = jnp.where(k <= hlen, inv_h, zero)
            dk = dk.at[0].set(d0)
            # final-row contribution: cell (rlen, k-rlen) when in range
            live = (k - rlen >= 1) & (k - rlen <= hlen)
            contribs = contribs.at[k].set(
                jnp.where(live, mk[rlen] + ik[rlen], zero))
            if rescale:
                shifts = shifts.at[k].set(s1[rlen])
                mx = jnp.maximum(jnp.maximum(mk, ik), dk)
                grow = ((mx > zero) & (mx < below)).astype(jnp.int32)
                shrink = (mx > above).astype(jnp.int32)
                f = jnp.where(grow == 1, up,
                              jnp.where(shrink == 1, down, one))
                mk, ik, dk = mk * f, ik * f, dk * f
                s_base = s1 + grow - shrink
                # scale adoption: an all-zero lane's scale is
                # meaningless (0 stores true 0 at any scale), so it
                # tracks its left neighbor — the adoption ramp
                # advances one lane per step, in sync with the
                # wavefront frontier
                s_new = jnp.where(mx > zero, s_base, shift1(s_base))
            else:
                s_new = s1
            return mk, ik, dk, s_new, m1, i1, d1, s1, contribs, shifts

        z = jnp.zeros(r1, dtype)
        zi = jnp.zeros(r1, jnp.int32)
        d_init = z.at[0].set(inv_h)  # diag k=0: cell (0,0)
        init = (z, z, d_init, zi, z, z, z, zi,
                jnp.zeros(steps, dtype), jnp.zeros(steps, jnp.int32))
        out = jax.lax.fori_loop(1, steps, step, init)
        return out[8], out[9]

    return jax.vmap(one_pair)(reads_p, pm, px, rlens, haps, hlens)


def _fold_contribs(contribs: np.ndarray, shifts: np.ndarray
                   ) -> np.ndarray:
    """(B, steps) per-step contributions at per-step scales →
    (B,) log10 likelihood, folded on host in f64 (exact log-sum-exp;
    a pair with no surviving mass comes back -inf)."""
    c = np.asarray(contribs, dtype=np.float64)
    s = np.asarray(shifts, dtype=np.float64)
    with np.errstate(divide="ignore"):
        logv = np.where(c > 0.0,
                        np.log10(np.where(c > 0.0, c, 1.0))
                        - s * (SCALE_EXP * _LOG10_2),
                        -np.inf)
    m = np.max(logv, axis=1)
    safe_m = np.where(np.isfinite(m), m, 0.0)
    tot = np.sum(np.where(np.isfinite(logv),
                          np.power(10.0, logv - safe_m[:, None]), 0.0),
                 axis=1)
    with np.errstate(divide="ignore"):
        return np.where(np.isfinite(m), safe_m + np.log10(tot),
                        -np.inf)


_FORWARD_JIT = None


def _forward_bucket(*args, rescale: bool):
    global _FORWARD_JIT
    if _FORWARD_JIT is None:
        import jax

        _FORWARD_JIT = jax.jit(_forward_bucket_impl,
                               static_argnames=("rescale",))
    return _FORWARD_JIT(*args, rescale=rescale)


def _pad_up(n: int, to: int = BUCKET) -> int:
    return max(to, ((n + to - 1) // to) * to)


def bucket_pairs(reads, haps, bucket: int = BUCKET):
    """Group (read, qual, hap) triples by padded-length signature.

    Returns {(r_pad, h_pad): [indices]} — each bucket compiles one
    program geometry, so arbitrary cohorts cost O(#buckets) compiles.
    """
    groups: dict[tuple[int, int], list[int]] = {}
    for n, (r, h) in enumerate(zip(reads, haps)):
        key = (_pad_up(len(r), bucket), _pad_up(len(h), bucket))
        groups.setdefault(key, []).append(n)
    return groups


def _pack_bucket(idxs, reads, errs, haps, r_pad, h_pad, dtype):
    """Pad one bucket's pairs into the kernel's array layout."""
    b = len(idxs)
    r1 = r_pad + 1  # diag index 0 is the boundary row
    reads_p = np.full((b, r1), N_CODE, dtype=np.uint8)
    pm = np.zeros((b, r1), dtype=dtype)
    px = np.zeros((b, r1), dtype=dtype)
    rlens = np.zeros(b, dtype=np.int32)
    haps_p = np.full((b, h_pad), N_CODE, dtype=np.uint8)
    hlens = np.zeros(b, dtype=np.int32)
    for row, n in enumerate(idxs):
        r, e, h = reads[n], errs[n], haps[n]
        rl, hl = len(r), len(h)
        reads_p[row, 1:rl + 1] = r
        pm[row, 1:rl + 1] = (1.0 - e).astype(dtype)
        px[row, 1:rl + 1] = (e / 3.0).astype(dtype)
        rlens[row] = rl
        haps_p[row, :hl] = h
        hlens[row] = hl
    return reads_p, pm, px, rlens, haps_p, hlens


def forward_pairs(reads, quals, haps, *,
                  gap_open: float = DEFAULT_GAP_OPEN,
                  gap_ext: float = DEFAULT_GAP_EXT,
                  dtype=np.float32, bucket: int = BUCKET,
                  policy=None) -> np.ndarray:
    """log10 P(read|hap) for N (read, qual, hap) triples → (N,) f64.

    reads/haps: sequences (str or uint8 codes), quals: per-base phred
    arrays (or a scalar applied to the whole read). Pairs are length-
    bucketed, each bucket runs one vmapped wavefront dispatch — the
    ``pairhmm`` fault-injection site, executed under ``policy`` (a
    resilience.RetryPolicy; None = the default retry-once policy) so
    transient device/tunnel faults are re-attempted. A permanently
    failing bucket raises resilience.RetriesExhausted with NaN left in
    its slots only if ``policy`` is given with ``allow_partial`` via
    :func:`forward_pairs_partial` (the quarantine path callers use).
    """
    vals, failed = forward_pairs_partial(
        reads, quals, haps, gap_open=gap_open, gap_ext=gap_ext,
        dtype=dtype, bucket=bucket, policy=policy, allow_partial=False)
    return vals


def forward_pairs_partial(reads, quals, haps, *,
                          gap_open: float = DEFAULT_GAP_OPEN,
                          gap_ext: float = DEFAULT_GAP_EXT,
                          dtype=np.float32, bucket: int = BUCKET,
                          policy=None, allow_partial: bool = True):
    """Like :func:`forward_pairs` but returns ``(log10 (N,) f64,
    failed_error_by_index dict)``: when ``allow_partial`` and a
    bucket's dispatch fails permanently (retries exhausted), its
    pairs' slots hold NaN and map to the causing exception — the
    caller (models/genotype.py) quarantines the affected windows
    instead of losing the whole run.
    """
    from ..plan import Executor as PlanExecutor, Step
    from ..resilience.policy import DEFAULT_POLICY

    if not (len(reads) == len(quals) == len(haps)):
        raise ValueError(
            f"forward_pairs: {len(reads)} reads, {len(quals)} quals, "
            f"{len(haps)} haps — lengths must match")
    n = len(reads)
    out = np.full(n, np.nan, dtype=np.float64)
    failed: dict[int, BaseException] = {}
    if n == 0:
        return out, failed
    enc_reads, errs, enc_haps = [], [], []
    for r, q, h in zip(reads, quals, haps):
        er = encode_seq(r)
        if len(er) == 0:
            raise ValueError("forward_pairs: empty read")
        eh = encode_seq(h)
        if len(eh) == 0:
            raise ValueError("forward_pairs: empty haplotype")
        e = phred_to_err(np.broadcast_to(np.asarray(q), (len(er),)))
        enc_reads.append(er)
        errs.append(e)
        enc_haps.append(eh)

    dtype = np.dtype(dtype)
    rescale = dtype == np.float32
    trans = transition_probs(gap_open, gap_ext).astype(dtype)
    if policy is None:
        policy = DEFAULT_POLICY
    reg = get_registry()
    reg.counter("pairhmm.pairs_total").inc(n)

    from .. import obs

    pex = PlanExecutor(policy=policy)
    groups = bucket_pairs(enc_reads, enc_haps, bucket)
    for (r_pad, h_pad), idxs in sorted(groups.items()):
        packed = _pack_bucket(idxs, enc_reads, errs, enc_haps,
                              r_pad, h_pad, dtype)
        key = ("pairhmm", r_pad, h_pad, len(idxs))

        def thunk(packed=packed, r_pad=r_pad, h_pad=h_pad,
                  b=len(idxs)):
            from ..obs.compiles import TRACKER

            # exact per-bucket compile attribution: the jit object's
            # own cache size is the ground truth for this geometry
            with TRACKER.observe(
                    "pairhmm",
                    signature={"r_pad": r_pad, "h_pad": h_pad,
                               "b": b, "rescale": rescale,
                               "dtype": dtype.name},
                    cache_size_fn=lambda: getattr(
                        _FORWARD_JIT, "_cache_size", lambda: 0)()
                    if _FORWARD_JIT is not None else 0,
                    trigger="pairhmm_forward"):
                contribs, shifts = obs.dispatch(
                    "pairhmm_forward", _forward_bucket, *packed,
                    trans, rescale=rescale)
            return np.asarray(contribs), np.asarray(shifts)

        reg.counter("pairhmm.buckets_total").inc()
        # one bucket dispatch = one plan Step at the 'pairhmm' fault
        # site, retried under the policy like every other dispatch
        outcome = pex.run_step(Step(key=key, fn=thunk,
                                    site="pairhmm"))
        if outcome.error is not None:
            if not allow_partial:
                raise outcome.retries_exhausted
            for i in idxs:
                failed[i] = outcome.error
            reg.counter("pairhmm.buckets_failed_total").inc()
            continue
        contribs, shifts = outcome.value
        out[np.asarray(idxs)] = _fold_contribs(contribs, shifts)
    return out, failed


def total_cells(reads, haps) -> int:
    """DP cell count Σ |read|·|hap| — the GCUPS denominator."""
    return int(sum(len(r) * len(h) for r, h in zip(reads, haps)))


# ---------------------------------------------------------------------------
# Pallas inner-loop variant (EXPERIMENTAL — see module docstring)

_LANES = 128


def pallas_forward_bucket(reads_p, pm, px, rlens, haps, hlens, trans,
                          interpret: bool = False):
    """The wavefront's inner loop as a Pallas TPU kernel: one pair per
    sequential grid step, the three diagonal buffers held as (1, Rpad)
    lane vectors in registers/VMEM, and the haplotype anti-diagonal
    maintained by a shift-in register (hb'[i] = hb[i-1], new base
    entering at lane 0) instead of a per-step gather — the same
    VMEM-resident carry pattern ops/pallas_coverage.py establishes.

    Array layout matches :func:`_forward_bucket_impl` except lanes pad
    to 128 (host side pads; extra lanes are masked like any other
    padding). f32 only, always rescaled. Returns (contribs (B, S),
    shifts (B, S) int32) with S = r1 + hcap padded to a lane multiple
    — feed them to the same host-side f64 fold as the XLA path.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, r1 = reads_p.shape
    hcap = haps.shape[1]
    rpad = ((r1 + _LANES - 1) // _LANES) * _LANES
    hpad = ((hcap + _LANES - 1) // _LANES) * _LANES
    spad = ((r1 + hcap + _LANES - 1) // _LANES) * _LANES

    def pad_lanes(a, width, fill):
        out = np.full((b, width), fill, a.dtype)
        out[:, :a.shape[1]] = a
        return out

    reads32 = pad_lanes(reads_p.astype(np.int32), rpad, int(N_CODE))
    pm_p = pad_lanes(np.asarray(pm, np.float32), rpad, 0.0)
    px_p = pad_lanes(np.asarray(px, np.float32), rpad, 0.0)
    haps32 = pad_lanes(haps.astype(np.int32), hpad, int(N_CODE))
    lens = np.stack([np.asarray(rlens, np.int32),
                     np.asarray(hlens, np.int32)], axis=1)
    tr = np.asarray(trans, np.float32).reshape(1, -1)
    below = np.float32(2.0 ** -SCALE_EXP)
    above = np.float32(2.0 ** SCALE_EXP)
    f_up = np.float32(2.0 ** SCALE_EXP)
    f_dn = np.float32(2.0 ** -SCALE_EXP)

    def kernel(lens_ref, read_ref, pm_ref, px_ref, hap_ref, tr_ref,
               out_ref):
        rlen = lens_ref[0, 0]
        hlen = lens_ref[0, 1]
        t_mm = tr_ref[0, 0]
        t_mi = tr_ref[0, 1]
        t_im = tr_ref[0, 2]
        t_ii = tr_ref[0, 3]
        ii = jax.lax.broadcasted_iota(jnp.int32, (1, rpad), 1)
        read = read_ref[0][None, :]
        pmv = pm_ref[0][None, :]
        pxv = px_ref[0][None, :]
        inv_h = 1.0 / hlen.astype(jnp.float32)
        zero_row = jnp.zeros((1, rpad), jnp.float32)
        zero_i = jnp.zeros((1, rpad), jnp.int32)

        def shift1(x):
            return jnp.concatenate([x[:, :1] * 0, x[:, :-1]], axis=1)

        def scale_fix(s_to, s_from):
            d = jnp.clip(s_to - s_from, _DMIN, _DMAX)
            return jnp.exp2((SCALE_EXP * d).astype(jnp.float32))

        def step(k, carry):
            m1, i1, d1, s1, m2, i2, d2, s2, hb, cs, ss = carry
            # shift-in: lane i takes lane i-1's hap base; hap[k-1]
            # (the diag's new j=k position, clamped+masked) enters
            new_hb = jnp.where(
                k - 1 < hlen,
                pl.load(hap_ref,
                        (pl.ds(0, 1),
                         pl.ds(jnp.minimum(k - 1, hcap - 1), 1)))[0, 0],
                jnp.int32(N_CODE))
            hb = jnp.concatenate(
                [jnp.full((1, 1), new_hb, jnp.int32), hb[:, :-1]],
                axis=1)
            jj = k - ii
            valid = ((ii >= 1) & (ii <= rlen)
                     & (jj >= 1) & (jj <= hlen))
            is_match = ((read == hb) | (read == N_CODE)
                        | (hb == N_CODE))
            prior = jnp.where(is_match, pmv, pxv)
            mk = prior * ((t_mm * shift1(m2) + t_im * shift1(i2)
                           + t_im * shift1(d2))
                          * scale_fix(s1, shift1(s2)))
            ik = ((t_mi * shift1(m1) + t_ii * shift1(i1))
                  * scale_fix(s1, shift1(s1)))
            dk = t_mi * m1 + t_ii * d1
            mk = jnp.where(valid, mk, 0.0)
            ik = jnp.where(valid, ik, 0.0)
            dk = jnp.where(valid, dk, 0.0)
            d0 = jnp.where(k <= hlen, inv_h, 0.0)
            dk = jnp.where(ii == 0, d0, dk)
            live = (k - rlen >= 1) & (k - rlen <= hlen)
            sel = ((ii == rlen) & (jj >= 1) & (jj <= hlen))
            contrib = jnp.where(
                live,
                jnp.sum(jnp.where(sel, mk + ik, 0.0),
                        dtype=jnp.float32),
                jnp.float32(0.0))
            s_r = jnp.sum(jnp.where(ii == rlen, s1, 0),
                          dtype=jnp.int32)
            # per-step emission: the host folds (contrib, scale)
            # pairs with an exact f64 log-sum-exp, like the XLA path
            cs = jax.lax.dynamic_update_slice(
                cs, contrib.reshape(1, 1), (0, k))
            ss = jax.lax.dynamic_update_slice(
                ss, s_r.reshape(1, 1), (0, k))
            mx = jnp.maximum(jnp.maximum(mk, ik), dk)
            grow = ((mx > 0.0) & (mx < below)).astype(jnp.int32)
            shrink = (mx > above).astype(jnp.int32)
            f = jnp.where(grow == 1, f_up,
                          jnp.where(shrink == 1, f_dn,
                                    jnp.float32(1.0)))
            s_base = s1 + grow - shrink
            # zero lanes adopt the left neighbor's scale (see the XLA
            # wavefront: keeps entering lanes at their feeder's scale)
            s_new = jnp.where(mx > 0.0, s_base, shift1(s_base))
            return (mk * f, ik * f, dk * f, s_new, m1, i1, d1, s1,
                    hb, cs, ss)

        d_init = jnp.where(ii == 0, inv_h, 0.0)
        hb0 = jnp.full((1, rpad), jnp.int32(N_CODE))
        init = (zero_row, zero_row, d_init, zero_i, zero_row,
                zero_row, zero_row, zero_i, hb0,
                jnp.zeros((1, spad), jnp.float32),
                jnp.zeros((1, spad), jnp.int32))
        out = jax.lax.fori_loop(1, r1 + hcap, step, init)
        out_ref[0] = jnp.concatenate(
            [out[9], out[10].astype(jnp.float32)], axis=0)

    res = pl.pallas_call(
        kernel,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, 2), lambda t: (t, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, rpad), lambda t: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, rpad), lambda t: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, rpad), lambda t: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, hpad), lambda t: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 8), lambda t: (0, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((1, 2, spad), lambda t: (t, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, 2, spad), jnp.float32),
        interpret=interpret,
    )(lens, reads32, pm_p, px_p, haps32,
      np.concatenate([tr, np.zeros((1, 8 - tr.shape[1]), np.float32)],
                     axis=1))
    contribs = np.asarray(res[:, 0, :])
    shifts = np.asarray(res[:, 1, :]).astype(np.int32)
    return contribs, shifts
