from .coverage import (  # noqa: F401
    depth_from_segments, windowed_sums, callable_classes, run_length_encode,
    bucket_size,
)
