"""Pallas TPU kernel: per-base depth from segment endpoints.

Alternative to the XLA scatter+cumsum path (ops/depth_pipeline.py) that
avoids the HBM scatter entirely. The genome splits into TILE-base tiles;
the host buckets segment endpoints per tile (sorted, padded with an
int32-max sentinel). The kernel runs a sequential grid over tiles:

    depth[p] = carry + #(starts ≤ p) − #(ends ≤ p)        (p in tile)

computed as vectorized compare-reductions over the tile's endpoint
buckets in VMEM, with the running carry (reads entering from the left)
held in SMEM scratch across grid steps — the TPU grid is sequential, so
this IS the segmented prefix sum, one pass over HBM: endpoints in,
depth out, no 40MB delta array written and re-read.

Windowed sums / callable classes stay in XLA (cheap fused elementwise on
the kernel's output).

STATUS: EXPERIMENTAL — parked, not a product path. Measured on TPU v5e
(10Mb shard, 30×/150bp): 0.26 ms/shard (~39 Gbases/s) — correct but
slower than the XLA scatter+cumsum pipeline (~0.06 ms device-resident;
the recorded comparison lives in BENCH_details.json
``pallas_vs_xla_depth``). The XLA path sits at the HBM roofline
(bench.py kernel roofline block), so no amount of VMEM fusion of the
window sums / class packing recovers the gap: this kernel's cost is
O(endpoints/tile) vector compares per position — algorithmic, not
traffic. Kept tested (tests/test_pallas_coverage.py) as the template
for future VMEM-resident variants and as the only in-repo example of
the sequential-grid carry pattern.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE = 1024  # positions per grid step, laid out (8, 128)
SENTINEL = np.int32(2**31 - 1)
_CHUNK = 128  # endpoints compared per VMEM-resident block


def _kernel(starts_ref, ends_ref, out_ref, carry_ref):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        carry_ref[0] = 0

    base = t * TILE
    row = jax.lax.broadcasted_iota(jnp.int32, (8, 128), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (8, 128), 1)
    pos = base + row * 128 + col  # global position of each lane

    p_cap = starts_ref.shape[1]
    n_chunks = p_cap // _CHUNK

    def body(i, acc):
        # endpoints live on the SUBLANE axis ((P, 1) layout) so the
        # broadcast against lane-major positions needs no transpose
        s = starts_ref[0, pl.ds(i * _CHUNK, _CHUNK), :]  # (CHUNK, 1)
        e = ends_ref[0, pl.ds(i * _CHUNK, _CHUNK), :]
        s3 = s[:, :, None]  # (CHUNK, 1, 1)
        e3 = e[:, :, None]
        ds = jnp.sum(
            (s3 <= pos[None]).astype(jnp.int32)
            - (e3 <= pos[None]).astype(jnp.int32),
            axis=0, dtype=jnp.int32,
        )
        return acc + ds

    rel = jax.lax.fori_loop(
        0, n_chunks, body, jnp.zeros((8, 128), jnp.int32)
    )
    carry = carry_ref[0]
    out_ref[0] = carry + rel
    carry_ref[0] = carry + rel[7, 127]


@functools.partial(jax.jit, static_argnames=("n_tiles", "interpret"))
def pallas_depth(starts_tiled: jax.Array, ends_tiled: jax.Array,
                 n_tiles: int, interpret: bool = False) -> jax.Array:
    """(n_tiles, P) sorted per-tile endpoints (SENTINEL-padded) →
    (n_tiles*TILE,) int32 per-base depth."""
    p_cap = starts_tiled.shape[1]
    assert p_cap % _CHUNK == 0
    # (n_tiles, P, 1): endpoints on the sublane axis (see _kernel), and
    # the block's trailing two dims exactly match the array dims (TPU
    # BlockSpec tiling requirement)
    starts3 = starts_tiled.reshape(n_tiles, p_cap, 1)
    ends3 = ends_tiled.reshape(n_tiles, p_cap, 1)
    out = pl.pallas_call(
        _kernel,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((1, p_cap, 1), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, p_cap, 1), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 8, 128), lambda t: (t, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_tiles, 8, 128), jnp.int32),
        scratch_shapes=[pltpu.SMEM((1,), jnp.int32)],
        interpret=interpret,
    )(starts3, ends3)
    return out.reshape(n_tiles * TILE)


def bucket_endpoints(seg_start: np.ndarray, seg_end: np.ndarray,
                     keep: np.ndarray, length: int,
                     p_cap: int | None = None):
    """Host-side tiling: endpoints sorted and bucketed per TILE-base tile,
    padded to a fixed per-tile capacity with SENTINEL.

    Endpoints ≥ length are dropped (same semantics as clipping at the
    global end). Returns (starts_tiled, ends_tiled, n_tiles).
    """
    n_tiles = (length + TILE - 1) // TILE
    ss = np.sort(seg_start[keep])
    ee = np.sort(seg_end[keep])
    ss = ss[(ss >= 0) & (ss < length)]
    ee = ee[(ee >= 0) & (ee < length)]
    bounds = np.arange(n_tiles + 1, dtype=np.int64) * TILE
    s_off = np.searchsorted(ss, bounds)
    e_off = np.searchsorted(ee, bounds)
    max_n = int(max(np.diff(s_off).max(initial=0),
                    np.diff(e_off).max(initial=0), 1))
    if p_cap is None:
        p_cap = _CHUNK
        while p_cap < max_n:
            p_cap *= 2
    elif max_n > p_cap:
        raise ValueError(f"p_cap {p_cap} < densest tile {max_n}")
    st = np.full((n_tiles, p_cap), SENTINEL, dtype=np.int32)
    et = np.full((n_tiles, p_cap), SENTINEL, dtype=np.int32)
    # vectorized scatter: each sorted endpoint's tile is value//TILE and
    # its slot is its rank within the tile (position minus the tile's
    # searchsorted offset) — no per-tile Python loop
    if len(ss):
        qs = ss // TILE
        st[qs, np.arange(len(ss)) - s_off[qs]] = ss
    if len(ee):
        qe = ee // TILE
        et[qe, np.arange(len(ee)) - e_off[qe]] = ee
    return st, et, n_tiles
