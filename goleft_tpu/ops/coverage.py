"""Device coverage kernels: scatter-add deltas → cumsum → windowed means.

This is the TPU replacement for the reference's per-base text pipeline
(``samtools depth`` piped into the parser at depth/depth.go:282-325). Reads
arrive as columnar ref-aligned segments (io.bam.ReadColumns); depth is
computed as a segmented prefix sum:

    delta[p] += 1 for each segment start, delta[p] -= 1 for each segment end
    depth = cumsum(delta)

Windowed means and callable classes reproduce the reference semantics
exactly (see ops below for the specific depth.go line citations). All
kernels are jit-compiled with static region length; segment arrays are
padded to power-of-two buckets so recompilation is rare.

Filtering (MAPQ cutoff, flag mask, depth cap) happens on device so
threshold changes never re-decode the BAM. The flag/MAPQ defaults mirror
``samtools depth -Q 1`` as invoked at depth/depth.go:45: skip
UNMAP/SECONDARY/QCFAIL/DUP reads, keep mapq >= Q.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def bucket_size(n: int, minimum: int = 1024) -> int:
    """Next power of two ≥ n (≥ minimum) — pad target for segment arrays."""
    b = minimum
    while b < n:
        b <<= 1
    return b


def pack_segments_u16(seg_start: np.ndarray, seg_end: np.ndarray,
                      keep: np.ndarray):
    """Packed wire format for host→device segment transfer: 4 bytes per
    segment (u16 start-delta + u16 length) instead of 9 (two i32 + bool).

    Host applies the keep filter and sorts; the device reconstructs
    absolute endpoints with one cumsum (shard_depth_pipeline_packed).
    Gaps > 65535 insert filler entries (delta=65535, len=0) and padding
    is (0, 0) — zero-length entries contribute nothing. Returns
    (deltas u16, lens u16, base i32, n_entries) — arrays are unpadded;
    callers bucket-pad with zeros. Falls back to None when any segment
    is ≥ 65536 bases (ultra-long reads ride the unpacked path).
    """
    s = seg_start[keep].astype(np.int64)
    e = seg_end[keep].astype(np.int64)
    if len(s) == 0:
        return (np.zeros(0, np.uint16), np.zeros(0, np.uint16),
                np.int32(0), 0)
    order = None
    if np.any(s[:-1] > s[1:]):
        order = np.argsort(s, kind="stable")
        s, e = s[order], e[order]
    lens = e - s
    if int(lens.max()) > 0xFFFF:
        return None
    base = int(s[0])
    deltas = np.empty(len(s), np.int64)
    deltas[0] = 0
    np.subtract(s[1:], s[:-1], out=deltas[1:])
    q = deltas // 0xFFFF  # fillers of 65535 each
    nq = int(q.sum())
    if nq == 0:
        return (deltas.astype(np.uint16), lens.astype(np.uint16),
                np.int32(base), len(s))
    total = len(s) + nq
    out_d = np.full(total, 0xFFFF, np.uint16)
    out_l = np.zeros(total, np.uint16)
    last = np.cumsum(q + 1) - 1
    out_d[last] = (deltas % 0xFFFF).astype(np.uint16)
    out_l[last] = lens.astype(np.uint16)
    return out_d, out_l, np.int32(base), total


@functools.partial(jax.jit, static_argnames=("length",))
def depth_from_segments(
    seg_start: jax.Array,
    seg_end: jax.Array,
    keep: jax.Array,
    length: int,
    region_start: int | jax.Array = 0,
    depth_cap: int | jax.Array = 0x7FFFFFFF,
) -> jax.Array:
    """Per-base int32 depth over [region_start, region_start+length).

    ``keep`` masks padded/filtered segments. Segments are clipped to the
    region; fully-outside segments contribute +1/-1 at the same clipped
    index and cancel. The per-base cap mirrors samtools' ``-d`` limit the
    reference passes as MaxMeanDepth+2500 (depth/depth.go:45,116).
    """
    s = jnp.clip(seg_start - region_start, 0, length)
    e = jnp.clip(seg_end - region_start, 0, length)
    s = jnp.where(keep, s, length)
    e = jnp.where(keep, e, length)
    delta = jnp.zeros(length + 1, dtype=jnp.int32)
    delta = delta.at[s].add(1).at[e].add(-1)
    depth = jnp.cumsum(delta[:length])
    return jnp.minimum(depth, depth_cap)


def segment_filter(
    mapq: jax.Array,
    flag: jax.Array,
    seg_read: jax.Array,
    min_mapq: int,
    skip_flags: int = 0x704,
) -> jax.Array:
    """Per-segment keep mask from per-read mapq/flag columns."""
    read_ok = (mapq >= min_mapq) & ((flag & skip_flags) == 0)
    return read_ok[seg_read]


@functools.partial(
    jax.jit, static_argnames=("length", "window", "lpad", "rpad")
)
def windowed_sums(
    depth: jax.Array, length: int, window: int, lpad: int, rpad: int
) -> jax.Array:
    """Sum per absolute-coordinate-aligned window.

    The reference aligns windows to absolute position (window i covers
    [i*W, (i+1)*W) clipped to the region — depth/depth.go:293-305), so the
    caller passes lpad = region_start - floor(region_start/W)*W and rpad to
    complete the final window. Means are sums / clipped window span.
    """
    padded = jnp.concatenate(
        [
            jnp.zeros(lpad, depth.dtype),
            depth,
            jnp.zeros(rpad, depth.dtype),
        ]
    )
    return padded.reshape(-1, window).sum(axis=1)


def window_bounds(
    region_start: int, region_end: int, window: int
) -> tuple[np.ndarray, np.ndarray, int, int]:
    """(starts, ends, lpad, rpad) for absolute-aligned windows over a region."""
    w0 = region_start // window * window
    n_win = (region_end - w0 + window - 1) // window
    starts = np.maximum(region_start, w0 + np.arange(n_win) * window)
    ends = np.minimum(region_end, w0 + (np.arange(n_win) + 1) * window)
    lpad = region_start - w0
    rpad = n_win * window - (region_end - w0)
    return starts, ends, lpad, rpad


# class codes match getCovClass strings (depth/depth.go:223-234)
CLASS_NAMES = ("NO_COVERAGE", "LOW_COVERAGE", "CALLABLE", "EXCESSIVE_COVERAGE")


@jax.jit
def callable_classes(
    depth: jax.Array, min_cov: int | jax.Array,
    max_mean_depth: int | jax.Array
) -> jax.Array:
    """Per-base class codes; max_mean_depth <= 0 disables EXCESSIVE."""
    cls = jnp.where(
        depth == 0,
        0,
        jnp.where(
            depth < min_cov,
            1,
            jnp.where((max_mean_depth > 0) & (depth >= max_mean_depth), 3, 2),
        ),
    )
    return cls.astype(jnp.int8)


def run_length_encode(arr: np.ndarray) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(starts, ends, values) of equal-value runs. Host-side; the device
    returns the dense class array and this collapses it the way the
    reference's streaming state machine does (depth/depth.go:307-323)."""
    arr = np.asarray(arr)
    if arr.size == 0:
        z = np.zeros(0, dtype=np.int64)
        return z, z, z
    change = np.flatnonzero(arr[1:] != arr[:-1]) + 1
    starts = np.concatenate(([0], change))
    ends = np.concatenate((change, [arr.size]))
    return starts, ends, arr[starts]
