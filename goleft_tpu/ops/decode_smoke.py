"""Device-decode smoke: the ``make decode-smoke`` body.

Real ``goleft-tpu cohortdepth`` subprocesses over a hermetic CRAM
cohort whose blocks are rANS-Nx16 spanning the full method-5 matrix —
ORDER0, ORDER1 (per-context tables), and STRIPE samples, ALL
device-decodable since the ORDER1/STRIPE scan landed:

  1. the default run and the ``--decode-device`` run produce
     BYTE-IDENTICAL matrices (the tentpole's contract: the wire format
     changed, the bytes did not);
  2. the ``--decode-device`` run's ``--metrics-out`` manifest carries
     the decode counters — device blocks > 0, fallbacks == 0 (the
     ORDER1 sample that used to force per-block host fallbacks now
     decodes on device; any fallback is a matrix regression), wire
     byte counters and the ORDER1 table share
     (``decode.table_bytes_total``) visible (on tiny fixture blocks
     the per-block table floor dominates — the ratio only wins at
     CRAM-typical block sizes, which the bench records);
  3. an injected transient fault at the ``decode`` site is retried
     under the RetryPolicy to the same byte-identical output (the
     decode step is a real plan Step, not a bare device call).

Run directly::

    python -m goleft_tpu.ops.decode_smoke
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile


def make_cram_cohort(d: str, ref_len: int = 50_000,
                     n_reads: int = 400) -> tuple[list[str], str]:
    """(cram paths, fai): four single-chromosome CRAMs with .crai,
    rANS-Nx16 blocks spanning the method-5 matrix — two ORDER0, one
    ORDER1 (per-context tables, order-0-compressed on the wire) and
    one STRIPE (4 byte-interleaved lanes per block), so
    --decode-device exercises every device decode shape."""
    import numpy as np

    from ..io import cram
    from ..io.bam import parse_cigar

    rng = np.random.default_rng(7)
    paths = []
    for i, (order, stripe) in enumerate(
            ((0, 0), (0, 0), (1, 0), (0, 4))):
        hdr = f"@HD\tVN:1.6\tSO:coordinate\n@RG\tID:r\tSM:cr{i}\n"
        p = os.path.join(d, f"cr{i}.cram")
        reads = sorted(
            (0, int(rng.integers(0, ref_len - 200)), "100M", 60, 0)
            for _ in range(n_reads))
        with open(p, "wb") as fh:
            with cram.CramWriter(fh, hdr, ["chr1"], [ref_len],
                                 records_per_container=150,
                                 block_method=cram.M_RANSNX16,
                                 rans_order=order, minor=1,
                                 rans_stripe=stripe) as w:
                for j, (tid, pos, cig, mq, fl) in enumerate(reads):
                    w.write_record(tid, pos, parse_cigar(cig),
                                   mapq=mq, flag=fl, name=f"r{j:04d}")
            w.write_crai(p + ".crai")
        paths.append(p)
    fai = os.path.join(d, "ref.fa.fai")
    with open(fai, "w") as fh:
        fh.write(f"chr1\t{ref_len}\t6\t60\t61\n")
    return paths, fai


def _run(args, env, timeout_s):
    rc = subprocess.run(args, env=env, timeout=timeout_s,
                        capture_output=True, text=True)
    if rc.returncode != 0:
        raise RuntimeError(
            f"{' '.join(args[-6:])} failed ({rc.returncode}):\n"
            f"{rc.stderr}")
    return rc.stdout


def run_smoke(timeout_s: float = 240.0, verbose: bool = True) -> int:
    """Returns 0 on success; raises on any failed step."""
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",     # CI has no accelerator;
               GOLEFT_TPU_PROBE="0")    # don't pay a probe timeout
    with tempfile.TemporaryDirectory(prefix="goleft_dec_") as d:
        crams, fai = make_cram_cohort(d)
        base_cmd = [sys.executable, "-m", "goleft_tpu", "cohortdepth",
                    "--fai", fai, "-w", "500"] + crams

        plain = _run(base_cmd, env, timeout_s)
        manifest_p = os.path.join(d, "run.json")
        dev_cmd = [sys.executable, "-m", "goleft_tpu", "cohortdepth",
                   "--metrics-out", manifest_p, "--fai", fai,
                   "-w", "500", "--decode-device"] + crams
        on_device = _run(dev_cmd, env, timeout_s)
        if plain != on_device:
            raise RuntimeError(
                "--decode-device matrix differs from the default path")
        if verbose:
            rows = plain.count("\n") - 1
            print(f"decode-smoke: byte-identical matrices ({rows} "
                  "windows)")

        with open(manifest_p) as fh:
            man = json.load(fh)
        counters = man["metrics"]["counters"]
        dev = counters.get("decode.device_blocks_total", 0)
        fall = counters.get("decode.device_fallback_total", 0)
        wire_c = counters.get("decode.wire_bytes_compressed_total", 0)
        wire_u = counters.get(
            "decode.wire_bytes_uncompressed_total", 0)
        table_b = counters.get("decode.table_bytes_total", 0)
        if dev <= 0:
            raise RuntimeError(
                "manifest shows no device-decoded blocks "
                f"(counters: {sorted(counters)[:12]})")
        if fall != 0:
            raise RuntimeError(
                f"{fall} host fallbacks on a fully-supported cohort "
                "— the ORDER1/STRIPE device matrix regressed")
        if not (0 < wire_c and 0 < wire_u):
            raise RuntimeError("wire byte counters missing")
        if table_b <= 0:
            raise RuntimeError(
                "decode.table_bytes_total missing — ORDER1 table "
                "wire accounting not recorded")
        if verbose:
            print(f"decode-smoke: manifest ok (device blocks={dev}, "
                  f"fallbacks={fall}, wire {wire_c}B compressed / "
                  f"{wire_u}B inflated, {table_b}B tables)")

        fault_env = dict(env,
                         GOLEFT_TPU_FAULTS="decode:after=1:transient")
        retried = _run(base_cmd[:-len(crams)] + ["--decode-device"]
                       + crams, fault_env, timeout_s)
        if retried != plain:
            raise RuntimeError(
                "injected transient decode fault was not retried to "
                "byte-identical output")
        if verbose:
            print("decode-smoke: injected decode fault retried, "
                  "bytes identical")
            print("decode-smoke: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(run_smoke())
