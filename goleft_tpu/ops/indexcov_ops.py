"""indexcov numerics: normalization, ROC, bin counters, copy number, PCA.

Device (JAX, float32 — matching the reference's float32 math) kernels for
the per-bin work that dominates a cohort run, vmapped over the sample axis;
the tiny integer-exact per-sample median init stays on host in int64 numpy
(bit-exact vs the reference's int64 sort/cumsum at indexcov/indexcov.go:
104-124, where ragged chromosome lists make device layout pointless).

Reference semantics reproduced (citations into /root/reference):
  - median size per tile: sort sizes, cap at the 98th percentile, take the
    value where the capped cumsum first exceeds total/2
    (indexcov/indexcov.go:104-124)
  - NormalizedDepth: float32 size/median, capped at 50000 (":129-151")
  - CountsAtDepth: slot = trunc(d * (70 * float32(2/3)) + 0.5) clipped to
    [0, 70) (":153-177")
  - CountsROC: reverse cumulative counts / total (":181-193")
  - counter: in = depth in (0.85, 1.15); low < 0.15; hi > 1.15; bins missing
    past a sample's end count as out+low (":1050-1078")
  - GetCN: drop zero bins; if >30% of all bins are (nonzero) < 0.02 also
    drop those; CN = Ploidy * sorted[0.4*len] (":957-991")
  - cross-sample normalization + 7-tap smoothing, sequentially dependent on
    previously-normalized columns → lax.scan (":549-597")
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

SLOTS = 70
SLOTS_MID = 2.0 / 3.0
MAX_CN = 8.0
PLOIDY = 2
DEPTH_CAP = 50000.0


def median_size_per_tile(sizes: list[np.ndarray]) -> float:
    """Host, int64-exact (indexcov/indexcov.go:96-124)."""
    flat = np.concatenate([np.asarray(s, dtype=np.int64) for s in sizes]) \
        if sizes else np.zeros(0, dtype=np.int64)
    if flat.size < 1:
        raise ValueError("indexcov: no usable chromosomes in index")
    flat = np.sort(flat)
    n98 = flat[int(0.98 * len(flat))]
    capped = np.minimum(flat, n98)
    cumsum = np.cumsum(capped)
    total = int(cumsum[-1])
    idx = int(np.searchsorted(cumsum, total // 2, side="right"))
    idx = min(idx, len(flat) - 1)
    return float(flat[idx])


def normalized_depth(sizes: np.ndarray, median: float) -> np.ndarray:
    """float32 scaled depth, capped at 50000 (indexcov.go:129-151)."""
    if median == 0:
        return np.zeros(0, dtype=np.float32)
    d = (np.asarray(sizes, dtype=np.float64) / median).astype(np.float32)
    return np.minimum(d, np.float32(DEPTH_CAP))


_SCALE = np.float32(SLOTS * np.float32(SLOTS_MID))  # 46.666668 in f32


@jax.jit
def counts_at_depth(depths: jax.Array, valid: jax.Array) -> jax.Array:
    """(n_samples, n_bins) → (n_samples, SLOTS) int32 histogram."""
    idx = jnp.clip(
        (depths * _SCALE + jnp.float32(0.5)).astype(jnp.int32), 0, SLOTS - 1
    )
    idx = jnp.where(valid, idx, SLOTS)  # dropped slot for padding
    one = jnp.ones_like(idx, dtype=jnp.int32)

    def hist(i, o):
        return jnp.zeros(SLOTS, jnp.int32).at[i].add(o, mode="drop")

    return jax.vmap(hist)(idx, one)


@jax.jit
def counts_roc(counts: jax.Array) -> jax.Array:
    """Reverse-cumulative proportion (indexcov.go:181-193). counts:
    (..., SLOTS)."""
    totals = jnp.cumsum(counts[..., ::-1], axis=-1)[..., ::-1]
    return totals.astype(jnp.float32) / totals[..., :1].astype(jnp.float32)


@jax.jit
def bin_counters(
    depths: jax.Array, valid: jax.Array, longest: jax.Array
) -> dict:
    """Per-sample in/out/low/hi counts (indexcov.go:1050-1078).

    ``longest`` is the bin count of the longest sample for this chromosome;
    missing tail bins count as out+low.
    """
    d = depths
    inside = valid & (d >= 0.85) & (d <= 1.15)
    out = valid & ((d < 0.85) | (d > 1.15))
    hi = valid & (d > 1.15)
    low = valid & (d < 0.15)
    n_valid = valid.sum(axis=-1)
    tail = jnp.maximum(longest - n_valid, 0)
    return {
        "in": inside.sum(axis=-1).astype(jnp.int32),
        "out": (out.sum(axis=-1) + tail).astype(jnp.int32),
        "hi": hi.sum(axis=-1).astype(jnp.int32),
        "low": (low.sum(axis=-1) + tail).astype(jnp.int32),
    }


@functools.partial(jax.jit, static_argnames=("ploidy",))
def get_cn(depths: jax.Array, valid: jax.Array, ploidy: int = PLOIDY
           ) -> jax.Array:
    """Per-sample copy number of one chromosome (indexcov.go:957-991).

    depths: (n_samples, n_bins) padded; valid masks real bins.
    """

    def one(d, v):
        nz = v & (d != 0)
        k = nz.sum()
        lows = (nz & (d < 0.02)).sum()
        n_total = v.sum()
        p_lo = lows.astype(jnp.float32) / jnp.maximum(
            n_total, 1
        ).astype(jnp.float32)
        # ascending sort of nonzero values; invalid/zero → +inf tail
        vals = jnp.sort(jnp.where(nz, d, jnp.inf))
        base = jnp.where(p_lo > 0.3, lows, 0)
        m = k - base
        # reference index: int(float64(m)*0.4) — exactly (2m)//5 for every
        # representable m (0.4 rounds up in binary, so the product can only
        # sit just above an exact multiple), computed in integers so TPU
        # (no f64) matches the f64 semantics bit-for-bit
        idx = base + (m * 2) // 5
        med = jnp.where(
            m > 0,
            jnp.float32(ploidy) * vals[jnp.clip(idx, 0, d.shape[0] - 1)],
            0.0,
        )
        return jnp.where(k > 0, med, jnp.float32(-0.1))

    return jax.vmap(one)(depths, valid)


def normalize_across_samples(
    depths: jax.Array, lengths: jax.Array
) -> jax.Array:
    """Cross-sample normalization + 7-tap smoothing (indexcov.go:549-597).

    Column j is divided by the cohort mean of its 3-bin neighborhood —
    where columns < j were already normalized+smoothed — then smoothed with
    a 7-tap window mixing processed (j-3..j) and still-raw (j+1..j+3)/m
    values.

    Since PR 17 this lowers onto the streaming two-pass form
    (:mod:`goleft_tpu.cohort.streaming`): a host f64 per-length-class
    statistics pass yields the per-bin cohort scalars — the reference
    accumulates this neighborhood mean in float64 (indexcov.go:560-581),
    which the host pass now honors on every backend, TPU included —
    then a jitted per-sample scan applies them. The monolithic call here
    and the chunked cohort path share both passes, so chunked output is
    byte-identical to this function on any chunking of the sample axis.

    depths: (n_samples, n_bins) zero-padded; lengths: per-sample bin counts.
    Returns processed depths (same shape).
    """
    from ..cohort.streaming import NormStats, apply_normalization

    d = np.asarray(depths, dtype=np.float32)
    n_samples, n_bins = d.shape
    if n_samples < 5:
        return depths
    lengths_np = np.asarray(lengths, dtype=np.int64)
    stats = NormStats()
    stats.accumulate(d, lengths_np)
    m, skip = stats.finalize(n_bins)
    return apply_normalization(
        d, lengths_np.astype(np.int32), m, skip)


def quantize_depths(
    depths: np.ndarray, bug_compat_u8: bool = False
) -> np.ndarray:
    """PCA input quantization.

    The reference computes ``uint8(65535/MaxCN*dp+0.5)`` (indexcov.go:698)
    — a uint16-scale value truncated into a uint8, which wraps mod 256 for
    nearly all depths. We default to a non-wrapping uint16 quantization
    (documented divergence: same intent, no wraparound); set
    ``bug_compat_u8`` to reproduce the wrapped values exactly.
    """
    d = np.minimum(np.asarray(depths, dtype=np.float32), np.float32(MAX_CN))
    q = (np.float32(65535.0 / MAX_CN) * d + np.float32(0.5))
    if bug_compat_u8:
        return q.astype(np.uint16).astype(np.uint8)
    return q.astype(np.uint16)


@functools.partial(jax.jit, static_argnames=("k",))
def _pca_project_jit(mat: jax.Array, k: int) -> tuple[jax.Array, jax.Array]:
    x = mat.astype(jnp.float32)
    centered = x - x.mean(axis=0, keepdims=True)
    _, s, vt = jnp.linalg.svd(centered, full_matrices=False)
    n = x.shape[0]
    vars_ = (s * s) / jnp.float32(max(n - 1, 1))
    frac = vars_ / vars_.sum()
    proj = x @ vt[:k].T
    return proj, frac[:k]


def pca_project(mat, k: int = 5) -> tuple[jax.Array, jax.Array]:
    """Principal-component projection (indexcov.go:773-807).

    gonum's stat.PC column-centers the matrix for the SVD; the reference
    then projects the *raw* matrix onto the top-k right singular vectors.
    Returns (proj (n, k), variance fractions (k,)).

    This is the small-cohort oracle; biobank-scale cohorts go through
    :func:`goleft_tpu.cohort.pca.sharded_pca`, which never materializes
    the full matrix. Degenerate requests fail here with a clear error
    instead of a backend-dependent solver failure: ``k`` may not exceed
    the sample count (the SVD has no k-th right singular vector to
    project onto), and a single-sample cohort has no cross-sample
    variance to decompose.
    """
    n_samples = int(np.asarray(mat.shape[0]))
    if n_samples < 2:
        raise ValueError(
            f"pca: need at least 2 samples, got {n_samples} — a "
            "single-sample cohort has no cross-sample variance")
    if k > n_samples:
        raise ValueError(
            f"pca: k={k} components exceed n_samples={n_samples}; "
            "pass k <= n_samples (indexcov clamps to min(5, n_samples))")
    return _pca_project_jit(mat, k)


@jax.jit
def chrom_qc(depths: jax.Array, valid: jax.Array,
             longest: jax.Array) -> jax.Array:
    """One fused per-chromosome QC program returning ONE packed f32
    vector: [rocs (S·SLOTS)] [in|out|hi|low (4·S)] [cn (S)].

    The per-call device→host latency of a slow link dominates when ROC,
    counters, and CN fetch separately (~6 round trips per chromosome);
    this packs everything the host needs into a single transfer. All
    values are integers (or f32 already) well under 2**24, so the f32
    packing is exact.
    """
    counts = counts_at_depth(depths, valid)
    rocs = counts_roc(counts)
    cnt = bin_counters(depths, valid, longest)
    cn = get_cn(depths, valid)
    return jnp.concatenate([
        rocs.ravel(),
        cnt["in"].astype(jnp.float32),
        cnt["out"].astype(jnp.float32),
        cnt["hi"].astype(jnp.float32),
        cnt["low"].astype(jnp.float32),
        cn.astype(jnp.float32),
    ])


def unpack_chrom_qc(packed: np.ndarray, n_samples: int):
    """Host split of chrom_qc's packed vector →
    (rocs (S, SLOTS) f32, counters dict of int64 (S,), cn f32 (S,))."""
    S = n_samples
    rocs = packed[: S * SLOTS].reshape(S, SLOTS)
    off = S * SLOTS
    cnt = {}
    for k in ("in", "out", "hi", "low"):
        cnt[k] = packed[off:off + S].astype(np.int64)
        off += S
    cn = packed[off:off + S]
    return rocs, cnt, cn


def update_slopes(rocs: np.ndarray, scalar: float) -> np.ndarray:
    """Per-sample ROC drop between 1±0.15 scaled depth, chromosome-length
    weighted (indexcov.go:739-750). rocs: (n_samples, SLOTS)."""
    n = 0.1
    ilo = int(0.5 + (SLOTS_MID - n) * SLOTS)
    ihi = int(0.5 + (SLOTS_MID + n) * SLOTS)
    return (rocs[:, ilo] - rocs[:, ihi]) * np.float32(scalar)
