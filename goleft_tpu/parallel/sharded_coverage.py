"""Sequence-parallel coverage: segmented cumsum with inter-shard carries.

This is the rebuild's true "sequence parallelism" (SURVEY.md §2.5): the
genome-position axis is sharded across the mesh's ``seq`` axis. Each
device scatter-adds the delta endpoints that fall in its shard (reads
straddling shard boundaries contribute their +1 and −1 to *different*
shards — no duplication or boundary bookkeeping, unlike the reference's
window flush/backfill code at depth/depth.go:293-359), computes a local
cumsum, then adds the exclusive prefix of all left-shard totals, obtained
with one small all_gather over ICI. Sample batches ride the ``data`` axis
(fully independent — no collectives).

Layout contract: callers pass segment endpoint arrays already partitioned
per seq-shard (equal padded length per shard) — the host scheduler's
bucketing (indexsplit-style even-data planning) produces exactly this.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.6 exports shard_map at top level (check_vma kwarg)
    from jax import shard_map as _shard_map

    _CHECK_KW = "check_vma"
except ImportError:  # older jax: experimental namespace, check_rep
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = "check_rep"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **{_CHECK_KW: check_vma})


def sharded_depth_fn(mesh: Mesh, shard_len: int, window: int,
                     seq_axis: str = "seq", data_axis: str = "data",
                     carry_mode: str = "all_gather"):
    """Build a jitted (samples × genome) coverage function over ``mesh``.

    Returns fn(seg_start, seg_end, keep) with shapes
      seg_start/seg_end: (S, n_seq * n_per_shard) int32, genome-absolute
      keep: same shape bool
    computing (S, n_seq * shard_len) per-base depth and
    (S, n_win_total) window sums. S must be divisible by the data axis.

    carry_mode picks the inter-shard exclusive-prefix collective:
      - "all_gather": one gather of the n_seq shard totals, mask+sum
        locally — one hop, right for small seq axes (≤ a pod slice)
      - "scan": Hillis-Steele log2(n_seq) ppermute doubling steps —
        traffic per device stays O(S) regardless of n_seq, the
        large-mesh choice (each step only talks to one ICI neighbor
        at distance 2^k)
    """
    n_seq = mesh.shape[seq_axis]
    if shard_len % window:
        raise ValueError("shard_len must be a multiple of window")
    if carry_mode not in ("all_gather", "scan"):
        raise ValueError(f"unknown carry_mode {carry_mode!r}")

    def local(seg_s, seg_e, keep, shard_id):
        # seg arrays: (S_local, n_per_shard) — endpoints for THIS shard
        lo = shard_id * shard_len
        s = jnp.where(keep, seg_s - lo, shard_len)
        e = jnp.where(keep, seg_e - lo, shard_len)
        s = jnp.clip(s, 0, shard_len)
        e = jnp.clip(e, 0, shard_len)

        def one(si, ei):
            delta = jnp.zeros(shard_len + 1, jnp.int32)
            delta = delta.at[si].add(1).at[ei].add(-1)
            return delta[:shard_len]

        deltas = jax.vmap(one)(s, e)  # (S_local, shard_len)
        local_cs = jnp.cumsum(deltas, axis=1)
        totals = local_cs[:, -1]  # (S_local,)
        if carry_mode == "all_gather":
            # exclusive prefix over seq shards: one gather on ICI
            all_totals = jax.lax.all_gather(
                totals, seq_axis, axis=0
            )  # (n_seq, S_local)
            carry = jnp.sum(
                jnp.where(
                    (jnp.arange(n_seq) < shard_id)[:, None],
                    all_totals, 0
                ),
                axis=0,
            )
        else:
            # Hillis-Steele inclusive scan via ppermute doubling, then
            # subtract own totals for the exclusive prefix
            acc = totals
            k = 1
            while k < n_seq:
                perm = [(src, src + k) for src in range(n_seq - k)]
                shifted = jax.lax.ppermute(acc, seq_axis, perm)
                acc = acc + jnp.where(shard_id >= k, shifted, 0)
                k *= 2
            carry = acc - totals
        depth = local_cs + carry[:, None]
        wsums = depth.astype(jnp.float32).reshape(
            depth.shape[0], -1, window
        ).sum(axis=2)
        return depth, wsums

    def wrapped(seg_s, seg_e, keep):
        def inner(seg_s, seg_e, keep):
            sid = jax.lax.axis_index(seq_axis)
            return local(seg_s, seg_e, keep, sid)

        return shard_map(
            inner,
            mesh=mesh,
            in_specs=(P(data_axis, seq_axis),) * 3,
            out_specs=(P(data_axis, seq_axis), P(data_axis, seq_axis)),
            check_vma=False,
        )(seg_s, seg_e, keep)

    return jax.jit(wrapped)


def partition_segments(seg_start, seg_end, keep, n_seq: int,
                       shard_len: int, pad_to: int | None = None):
    """Host-side endpoint partitioning for the sharded kernel.

    Each segment's +1 endpoint goes to the shard containing its start and
    its −1 endpoint to the shard containing its end; an endpoint at or
    past the sharded extent is dropped (its effect is identical to
    clipping at the global end). Returns (seg_s, seg_e, keep) arrays of
    shape (S, n_seq * per_shard) laid out shard-major for P("data","seq").
    """
    import numpy as np

    S = seg_start.shape[0]
    L = n_seq * shard_len

    # Semantics: half-open on the same side for starts and ends — an end
    # exactly at a shard's lo belongs to THAT shard as a −1 at local
    # position 0 (putting it at the previous shard's top slot would drop
    # it from that shard's total and over-carry every shard to the
    # right). Endpoints at or past the sharded extent are dropped
    # (identical effect to clipping at the global end).
    #
    # Vectorized in two passes (round 1's O(samples × shards) Python
    # double loop with per-shard masks was VERDICT weak #3). The common
    # case — position-sorted endpoints — takes a searchsorted fast path
    # with no division, bincount, or gather.

    def analyze(vals):
        """→ (vals_in_range, per_shard_counts, shard_ids_or_None)."""
        n = len(vals)
        sorted_ = n < 2 or bool(vals[0] <= vals[-1]) and bool(
            np.all(vals[:-1] <= vals[1:])
        )
        if sorted_:
            lo = int(np.searchsorted(vals, 0))
            hi = int(np.searchsorted(vals, L))
            vals = vals[lo:hi]  # view, no copy
            bounds = np.arange(1, n_seq, dtype=np.int64) * shard_len
            off = np.searchsorted(vals, bounds)
            counts = np.diff(np.concatenate(([0], off, [len(vals)])))
            return vals, counts, None
        vals = vals[(vals >= 0) & (vals < L)]
        q = vals.astype(np.int64) // shard_len
        return vals, np.bincount(q, minlength=n_seq), q

    def place(out_b, vals, counts, q):
        """Scatter vals into (shard, rank) slots of one sample row."""
        if not len(vals):
            return
        if q is None:  # sorted: flat slot = i + (shard*per − shard_off)
            off = np.cumsum(counts[:-1])
            base = np.arange(n_seq, dtype=np.int64) * per
            base[1:] -= off
            flat = np.arange(len(vals), dtype=np.int64) + \
                np.repeat(base, counts)
        else:
            off = np.zeros(n_seq, dtype=np.int64)
            np.cumsum(counts[:-1], out=off[1:])
            order = None
            if np.any(q[:-1] > q[1:]):
                order = np.argsort(q, kind="stable")
                vals, q = vals[order], q[order]
            rank = np.arange(len(q)) - off[q]
            flat = q * per + rank
        out_b.reshape(-1)[flat] = vals

    rows = []
    per = pad_to or 0
    for b in range(S):
        kk = keep[b]
        if kk.all():
            ss, ee = seg_start[b], seg_end[b]
        else:
            ss, ee = seg_start[b][kk], seg_end[b][kk]
        ss, cs, qs = analyze(ss)
        ee, ce, qe = analyze(ee)
        rows.append((ss, cs, qs, ee, ce, qe))
        if len(ss) or len(ee):
            per = max(per, int(np.maximum(cs, ce).max()))
    per = max(per, 1)

    # unused slots hold the shard's top (the kernel's clip slot: no
    # effect); starts and ends balance independently per cell. Only the
    # padding tails are filled — the scatter covers everything else.
    seg_s = np.empty((S, n_seq, per), dtype=np.int32)
    seg_e = np.empty((S, n_seq, per), dtype=np.int32)
    hi = ((np.arange(n_seq) + 1) * np.int64(shard_len)).astype(np.int32)
    kp = np.zeros((S, n_seq, per), dtype=bool)
    ar = np.arange(per)
    for b in range(S):
        ss, cs, qs, ee, ce, qe = rows[b]
        place(seg_s[b], ss, cs, qs)
        place(seg_e[b], ee, ce, qe)
        for q in range(n_seq):
            seg_s[b, q, cs[q]:] = hi[q]
            seg_e[b, q, ce[q]:] = hi[q]
        kp[b] = ar[None, :] < np.maximum(cs, ce)[:, None]
    return (
        seg_s.reshape(S, n_seq * per),
        seg_e.reshape(S, n_seq * per),
        kp.reshape(S, n_seq * per),
    )
