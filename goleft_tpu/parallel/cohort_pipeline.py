"""The flagship end-to-end cohort step: sharded coverage → scaled depth →
batched EM copy number, as ONE jitted program over the device mesh.

This is the TPU composition of the reference's whole pipeline
(depth → depthwed → emdepth, SURVEY.md §3.1/§3.5): genome axis sharded
(``seq``), samples data-parallel (``data``); the only cross-device
traffic is the segmented-cumsum carry all_gather inside
sharded_coverage and the resharding between the coverage layout
(samples × genome) and the EM layout (windows × samples).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.emdepth import em_depth_batch, cn_batch
from .sharded_coverage import sharded_depth_fn


def build_cohort_step(mesh: Mesh, shard_len: int, window: int,
                      carry_mode: str = "all_gather"):
    """Returns jitted fn(seg_s, seg_e, keep) → dict(depth, wmeans, lambdas,
    cn). Input arrays (S, n_seq*per) laid out for P('data','seq').
    ``carry_mode`` selects the inter-shard prefix collective (see
    sharded_depth_fn): all_gather for small seq axes, the log-step
    ppermute scan for large meshes."""
    coverage = sharded_depth_fn(mesh, shard_len, window,
                                carry_mode=carry_mode)

    def step(seg_s, seg_e, keep):
        depth, wsums = coverage(seg_s, seg_e, keep)
        wmeans = wsums / window  # (S, n_win)
        # The SHIPPING normalization — identical to what `cnv` runs
        # (commands/emdepth_cmd.py::call_cnvs, per the emdepth contract
        # that inputs are pre-normalized comparable depths,
        # emdepth/emdepth.go:117-138): round-half-up integer window means
        # (the depthwed matrix values), each sample scaled to its global
        # median, rescaled by the cohort median-of-medians. The genome
        # axis is sharded, so the medians are cross-shard reductions XLA
        # lowers onto ICI.
        vals = jnp.floor(wmeans + 0.5)
        med = jnp.median(vals, axis=1)  # per-sample global median
        med = jnp.where(med == 0, 1.0, med)
        scaled = vals / med[:, None] * jnp.median(med)
        # reshard: EM wants (windows, samples) with windows on 'seq'
        wm = jax.lax.with_sharding_constraint(
            scaled.T, NamedSharding(mesh, P("seq", "data"))
        )
        lambdas = em_depth_batch(wm)
        cn = cn_batch(lambdas, wm)
        return {
            "depth": depth,
            "wmeans": wmeans,
            "lambdas": lambdas,
            "cn": cn,
        }

    in_shard = NamedSharding(mesh, P("data", "seq"))
    return jax.jit(step, in_shardings=(in_shard,) * 3)
