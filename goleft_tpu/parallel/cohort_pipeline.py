"""The flagship end-to-end cohort step: sharded coverage → scaled depth →
batched EM copy number, as ONE jitted program over the device mesh.

This is the TPU composition of the reference's whole pipeline
(depth → depthwed → emdepth, SURVEY.md §3.1/§3.5): genome axis sharded
(``seq``), samples data-parallel (``data``); the only cross-device
traffic is the segmented-cumsum carry all_gather inside
sharded_coverage and the resharding between the coverage layout
(samples × genome) and the EM layout (windows × samples).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.emdepth import em_depth_batch, cn_batch
from ..obs import InstrumentedDispatch as _InstrumentedDispatch
from .sharded_coverage import sharded_depth_fn


def _normalize_and_em(mesh: Mesh, wmeans):
    """The SHIPPING normalization + EM tail, shared by the monolithic
    step and the chunked finalize so both compile the same op sequence —
    identical to what `cnv` runs (commands/emdepth_cmd.py::call_cnvs,
    per the emdepth contract that inputs are pre-normalized comparable
    depths, emdepth/emdepth.go:117-138): round-half-up integer window
    means (the depthwed matrix values), each sample scaled to its global
    median, rescaled by the cohort median-of-medians. The genome axis is
    sharded, so the medians are cross-shard reductions XLA lowers onto
    ICI."""
    vals = jnp.floor(wmeans + 0.5)
    med = jnp.median(vals, axis=1)  # per-sample global median
    med = jnp.where(med == 0, 1.0, med)
    scaled = vals / med[:, None] * jnp.median(med)
    # reshard: EM wants (windows, samples) with windows on 'seq'
    wm = jax.lax.with_sharding_constraint(
        scaled.T, NamedSharding(mesh, P("seq", "data"))
    )
    lambdas = em_depth_batch(wm)
    cn = cn_batch(lambdas, wm)
    return lambdas, cn


def build_cohort_step(mesh: Mesh, shard_len: int, window: int,
                      carry_mode: str = "all_gather"):
    """Returns jitted fn(seg_s, seg_e, keep) → dict(depth, wmeans, lambdas,
    cn). Input arrays (S, n_seq*per) laid out for P('data','seq').
    ``carry_mode`` selects the inter-shard prefix collective (see
    sharded_depth_fn): all_gather for small seq axes, the log-step
    ppermute scan for large meshes."""
    coverage = sharded_depth_fn(mesh, shard_len, window,
                                carry_mode=carry_mode)

    def step(seg_s, seg_e, keep):
        depth, wsums = coverage(seg_s, seg_e, keep)
        wmeans = wsums / window  # (S, n_win)
        lambdas, cn = _normalize_and_em(mesh, wmeans)
        return {
            "depth": depth,
            "wmeans": wmeans,
            "lambdas": lambdas,
            "cn": cn,
        }

    in_shard = NamedSharding(mesh, P("data", "seq"))
    # dispatch boundary: span + block_until_ready fence when device
    # events are on (obs.dispatch), plain jitted call otherwise
    return _InstrumentedDispatch(
        jax.jit(step, in_shardings=(in_shard,) * 3), "cohort_step")


def build_chunked_cohort_step(mesh: Mesh, shard_len: int, window: int,
                              carry_mode: str = "all_gather",
                              donate: bool | None = None):
    """Chunked variant of :func:`build_cohort_step` for the prefetch
    staging pipeline (parallel/prefetch.py): the genome is fed as a
    sequence of chunks of ``n_seq * shard_len`` positions, each staged
    and transferred while the previous chunk computes.

    Returns ``(chunk_fn, finalize_fn, in_sharding, carry_sharding)``:

      - ``chunk_fn(seg_s, seg_e, keep, carry) → (depth, wsums, carry')``
        runs the sharded coverage on one chunk's endpoint arrays
        (chunk-relative coordinates, laid out like the monolithic
        step's inputs) and threads ``carry`` — the (S,) int32 running
        depth at the chunk boundary — so per-base depth and window sums
        stay bit-identical to the monolithic program: a segment
        straddling a chunk boundary contributes its +1 to one chunk and
        its −1 to the next, exactly like shard boundaries inside one
        program. ``carry'`` is the depth at this chunk's last position.
      - ``finalize_fn(wsums) → dict(wmeans, lambdas, cn)`` takes the
        host-concatenated (S, n_win_total) window sums and runs the one
        shipping normalization + EM tail over the whole cohort extent.

    On non-CPU backends (or with ``donate=True``) the chunk step
    donates its segment-endpoint input buffers: the consumed device
    staging buffers are recycled into the outputs, bounding device
    memory at O(prefetch_depth) chunks instead of O(n_chunks).
    """
    coverage = sharded_depth_fn(mesh, shard_len, window,
                                carry_mode=carry_mode)

    def chunk(seg_s, seg_e, keep, carry):
        depth, wsums = coverage(seg_s, seg_e, keep)
        depth = depth + carry[:, None]
        # adding ``carry`` to every base of a window adds carry*window
        # to its sum — exact in f32 within the same < 2**24 bound the
        # monolithic window sums already rely on
        wsums = wsums + (carry.astype(wsums.dtype) * window)[:, None]
        return depth, wsums, depth[:, -1]

    def finalize(wsums):
        wmeans = wsums / window  # (S, n_win_total)
        lambdas, cn = _normalize_and_em(mesh, wmeans)
        return {"wmeans": wmeans, "lambdas": lambdas, "cn": cn}

    in_shard = NamedSharding(mesh, P("data", "seq"))
    carry_shard = NamedSharding(mesh, P("data"))
    if donate is None:
        # donation is a no-op (with a warning) on CPU; only ask for it
        # where the runtime can actually alias buffers
        donate = next(iter(mesh.devices.flat)).platform != "cpu"
    chunk_fn = _InstrumentedDispatch(jax.jit(
        chunk,
        in_shardings=(in_shard,) * 3 + (carry_shard,),
        donate_argnums=(0, 1, 2) if donate else (),
    ), "cohort_chunk")
    finalize_fn = _InstrumentedDispatch(
        jax.jit(finalize, in_shardings=(in_shard,)),
        "cohort_finalize")
    return chunk_fn, finalize_fn, in_shard, carry_shard
