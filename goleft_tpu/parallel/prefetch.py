"""Async prefetch & staging: overlap host decode + H2D transfer with
device compute in the cohort path.

The device-side cohort engine loses to the host hybrid on small meshes
because decode/transfer is serialized with compute (round-5 VERDICT):
the chip idles while the host decodes the next chunk of BAM/CRAM
segments, and the host idles while the chip computes. This module is
the missing execution subsystem — a bounded, double-buffered staging
pipeline in the spirit of gpuPairHMM's streamed batch staging
(arxiv 2411.11547) and GenPIP's decode/compute integration
(arxiv 2209.08600):

  producer workers (decode pool, utils/decode_scaling affinity sizing)
      │  decode: BAM/CRAM → per-sample segment endpoint tuples
      │  stage:  pack into padded host buffers (the wire layout)
      │  transfer: jax.device_put onto the target sharding — dispatch
      │           is asynchronous, so the H2D copy of chunk k+1 runs
      │           while chunk k's jitted step executes
      ▼
  bounded ordered queue (backpressure at ``depth`` staged chunks)
      ▼
  consumer: the jitted cohort step (which, via
      cohort_pipeline.build_chunked_cohort_step, donates consumed
      staging buffers back to the allocator)

Guarantees:
  - deterministic chunk ordering: chunks are delivered strictly in
    submission order no matter how producers complete
  - backpressure: at most ``depth`` chunks are in flight beyond the one
    being consumed, bounding host+device staging memory
  - error propagation: a worker exception surfaces in the consumer at
    the failing chunk's ordinal position as PrefetchWorkerError (the
    original exception chained), after every earlier chunk was
    delivered intact
  - cancellation: closing the prefetcher (or abandoning iteration)
    cancels queued work and stops workers at the next chunk boundary

``depth=0`` is the caller's serial path — callers keep their existing
loop; this module only ever runs with depth >= 1.
"""

from __future__ import annotations

import concurrent.futures as cf
import threading
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Iterable, Sequence

import numpy as np

from .. import obs
from ..utils.decode_scaling import auto_processes


def _nbytes(value) -> int:
    """Best-effort byte count of a staged/transferred chunk value
    (tuples of host or device arrays); 0 for opaque values."""
    if isinstance(value, (tuple, list)):
        return sum(int(getattr(a, "nbytes", 0) or 0) for a in value)
    return int(getattr(value, "nbytes", 0) or 0)


class PrefetchCancelled(Exception):
    """Raised inside workers when the prefetcher was closed mid-run."""


class PrefetchWorkerError(RuntimeError):
    """A producer failed; re-raised at the chunk's ordered position."""

    def __init__(self, index: int, meta, cause: BaseException):
        super().__init__(
            f"prefetch worker failed on chunk {index} ({meta!r}): "
            f"{cause!r}")
        self.index = index
        self.meta = meta
        self.cause = cause


@dataclass
class StagedChunk:
    """One chunk, staged and (if a transfer fn was given) already on
    its way to the device when the consumer receives it."""

    index: int
    meta: Any
    value: Any


class ChunkPrefetcher:
    """Bounded ordered producer/consumer over a sequence of chunk
    descriptors.

    ``produce(meta)`` runs on a decode-pool worker thread (sized by the
    host's effective cores, capped at ``depth`` — more workers than
    in-flight slots measure nothing) and returns the staged host value;
    ``transfer(value, meta)``, when given, runs on the same worker
    immediately after — issuing an asynchronous ``jax.device_put``
    there is what overlaps H2D with the consumer's compute. Iterating
    yields :class:`StagedChunk` in exact submission order.

    Use as a context manager (or call :meth:`close`); abandoning the
    iterator mid-run cancels outstanding work.
    """

    def __init__(self, chunks: Sequence | Iterable,
                 produce: Callable[[Any], Any],
                 depth: int = 2,
                 transfer: Callable[[Any, Any], Any] | None = None,
                 processes: int | None = None):
        if depth < 1:
            raise ValueError(
                f"prefetch depth must be >= 1 (got {depth}); depth 0 "
                "is the caller's serial path")
        self._meta = iter(enumerate(chunks))
        self._produce = produce
        self._transfer = transfer
        self.depth = depth
        if processes is None:
            processes = auto_processes()
        self._ex = cf.ThreadPoolExecutor(
            max_workers=max(1, min(processes, depth)),
            thread_name_prefix="goleft-prefetch")
        self._pending: deque = deque()  # (index, meta, future), ordered
        self._cancelled = threading.Event()
        self._closed = False
        # cross-thread trace propagation: workers record their decode/
        # stage/transfer spans under the CONSUMER's trace and parent
        # span (captured here, on the constructing thread), so a
        # --trace-out timeline shows producer work overlapping the
        # consumer's compute as real same-trace data
        self._span_ctx = obs.capture()
        reg = obs.get_registry()
        self._c_chunks = reg.counter("prefetch.chunks_total")
        self._c_staged = reg.counter("prefetch.bytes_staged_total")
        self._c_xfer = reg.counter("prefetch.bytes_transferred_total")
        self._g_depth = reg.gauge("prefetch.queue_depth")

    def _run_one(self, index: int, meta):
        if self._cancelled.is_set():
            raise PrefetchCancelled(index)
        with obs.attach(self._span_ctx):
            value = self._produce(meta)
            self._c_staged.inc(_nbytes(value))
            if self._transfer is not None \
                    and not self._cancelled.is_set():
                value = self._transfer(value, meta)
                self._c_xfer.inc(_nbytes(value))
        self._c_chunks.inc()
        return value

    def _top_up(self) -> None:
        # memory backpressure: while any armed pressure controller in
        # this process is tripped (obs.memplane — the serve daemon
        # registers its band), staging clamps to ONE in-flight chunk
        # instead of the configured depth. Already-staged chunks keep
        # draining; the clamp only stops NEW allocations until RSS
        # recovers below the low-water mark.
        from ..obs.memplane import under_pressure

        depth = 1 if under_pressure() else self.depth
        while len(self._pending) < depth:
            try:
                index, meta = next(self._meta)
            except StopIteration:
                break
            self._pending.append(
                (index, meta, self._ex.submit(self._run_one, index,
                                              meta)))
        # decode-pool queue depth: staged chunks in flight beyond the
        # one being consumed (the registry's live gauge)
        self._g_depth.set(len(self._pending))

    def __iter__(self):
        try:
            self._top_up()
            while self._pending:
                index, meta, fut = self._pending.popleft()
                try:
                    value = fut.result()
                except PrefetchCancelled:
                    return
                except cf.CancelledError:
                    return
                except Exception as e:  # noqa: BLE001 — ordered rethrow
                    raise PrefetchWorkerError(index, meta, e) from e
                # refill BEFORE handing the chunk to the consumer, so
                # decode/transfer of later chunks runs under its compute
                self._top_up()
                yield StagedChunk(index, meta, value)
        finally:
            self.close()

    def close(self) -> None:
        """Cancel outstanding work and release the pool (idempotent)."""
        if self._closed:
            return
        self._closed = True
        self._cancelled.set()
        for _, _, fut in self._pending:
            fut.cancel()
        self._pending.clear()
        self._ex.shutdown(wait=True)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def stage_block_arrays(host_arrays: dict) -> dict:
    """Stage one group of host arrays to the device, counted.

    The compressed-wire staging step of ``--decode-device``
    (ops/rans_device.py): the dict holds still-compressed block
    payloads plus their table arrays, so the bytes recorded in the
    existing ``prefetch.bytes_staged_total`` /
    ``prefetch.bytes_transferred_total`` counters — and visible in the
    stage spans wrapping the caller — drop to COMPRESSED size instead
    of the inflated blocks. The accounting is over the dict's REAL
    padded arrays, so ORDER1 buckets honestly pay for their compact
    per-context rows (``ctx_freq``/``ctx_index`` — KBs per block, vs
    ~0.5KB for an ORDER0 freq row; ``decode.table_bytes_total``
    isolates the logical table share). ``jax.device_put`` dispatch is
    asynchronous, same as the chunk pipeline's transfer stage.
    """
    import jax

    reg = obs.get_registry()
    out = {k: jax.device_put(np.ascontiguousarray(a))
           for k, a in host_arrays.items()}
    nbytes = sum(int(a.nbytes) for a in host_arrays.values())
    reg.counter("prefetch.bytes_staged_total").inc(nbytes)
    reg.counter("prefetch.bytes_transferred_total").inc(nbytes)
    return out


def _null_timer():
    from ..utils.profiling import StageTimer

    return StageTimer()


def _pack_chunk(starts, ends, keep, n_seq: int, shard_len: int):
    """Stage one chunk: partition endpoint arrays for P('data','seq')
    and pad the per-shard width to a power-of-two bucket so every chunk
    of similar occupancy hits the same compiled program."""
    from ..ops.coverage import bucket_size
    from .sharded_coverage import partition_segments

    seg_s, seg_e, kp = partition_segments(starts, ends, keep, n_seq,
                                          shard_len)
    per = seg_s.shape[1] // n_seq
    b = bucket_size(per, minimum=64)
    if b != per:
        seg_s, seg_e, kp = partition_segments(starts, ends, keep,
                                              n_seq, shard_len,
                                              pad_to=b)
    return seg_s, seg_e, kp


def run_prefetched_cohort(mesh, shard_len: int, window: int,
                          chunks: Sequence, decode_chunk,
                          n_samples: int,
                          prefetch_depth: int = 2,
                          carry_mode: str = "all_gather",
                          timer=None, processes: int | None = None,
                          keep_depth: bool = True,
                          checkpoint=None):
    """The chunked flagship cohort path through the staging pipeline.

    ``chunks`` is an ordered list of chunk descriptors; each covers the
    next ``mesh.shape['seq'] * shard_len`` genome positions.
    ``decode_chunk(desc) → (starts, ends, keep)`` returns (S, n) int32
    CHUNK-RELATIVE segment endpoint arrays (the producer stages them
    with :func:`partition_segments` and transfers onto the
    P('data','seq') layout). Per-stage spans land in ``timer``
    (decode / stage / transfer / compute).

    ``prefetch_depth=0`` runs the identical code strictly serially —
    the byte-identity reference. Returns dict(depth?, wmeans, lambdas,
    cn, carry): per-base depth (host np, concatenated across chunks;
    omitted when ``keep_depth`` is False), window means and the EM
    outputs over the full extent — bit-identical to the monolithic
    :func:`~goleft_tpu.parallel.cohort_pipeline.build_cohort_step`
    program fed the same segments, by the carry-threading argument in
    build_chunked_cohort_step.

    ``checkpoint`` (a resilience.CheckpointStore) persists each
    consumed chunk's (depth slice, wsums, carry) after its compute;
    because the carry threads chunk-to-chunk, resume restores the
    longest committed *prefix* of chunks (decode/stage/transfer/compute
    all skipped for it), re-seeds the carry from the last committed
    chunk, and runs only the remainder — bit-identical to a cold run,
    since the stored host arrays are exactly the values the device
    produced. Keys bind the run geometry (shard_len, window,
    carry_mode, n_samples, keep_depth) and each chunk's descriptor.
    """
    import jax
    import jax.numpy as jnp

    from .cohort_pipeline import build_chunked_cohort_step

    timer = timer if timer is not None else _null_timer()
    n_seq = mesh.shape["seq"]
    chunk_fn, finalize_fn, in_shard, carry_shard = \
        build_chunked_cohort_step(mesh, shard_len, window,
                                  carry_mode=carry_mode)

    def produce(desc):
        with timer.stage("decode"):
            starts, ends, keep = decode_chunk(desc)
        with timer.stage("stage"):
            seg_s, seg_e, kp = _pack_chunk(starts, ends, keep, n_seq,
                                           shard_len)
        return seg_s, seg_e, kp

    def transfer(value, desc):
        with timer.stage("transfer"):
            # asynchronous dispatch: the H2D copy proceeds while the
            # consumer's current chunk_fn executes
            return tuple(jax.device_put(a, in_shard) for a in value)

    carry = jax.device_put(
        jnp.zeros(n_samples, jnp.int32), carry_shard)
    depth_parts: list[np.ndarray] = []
    wsums_parts = []

    def _chunk_key(i, desc):
        return ("prefetched_cohort", shard_len, window, carry_mode,
                n_samples, keep_depth, i, repr(desc))

    done_prefix = 0
    if checkpoint is not None:
        # the carry threads chunk-to-chunk, so only a contiguous
        # committed PREFIX is resumable; the first gap recomputes from
        # there with the last committed carry re-seeded
        for i, desc in enumerate(chunks):
            rec = checkpoint.get(_chunk_key(i, desc))
            if rec is None:
                break
            if keep_depth:
                depth_parts.append(rec["depth"])
            wsums_parts.append(jnp.asarray(rec["wsums"]))
            carry = jax.device_put(jnp.asarray(rec["carry"]),
                                   carry_shard)
            done_prefix = i + 1

    from ..plan import Executor as PlanExecutor, Step

    pex = PlanExecutor(checkpoint=checkpoint)

    def consume(staged: StagedChunk):
        """One chunk's compute+commit as a plan Step. ``resumable=
        False``: the carry threads chunk-to-chunk, so resume is the
        contiguous-prefix scan above, never a per-step store skip —
        the Step only owns the atomic commit (and the 'shard' fault
        site, uniform with the other cohort boundaries)."""
        nonlocal carry

        def fn():
            nonlocal carry
            with timer.stage("compute"):
                depth, wsums, carry = chunk_fn(*staged.value, carry)
                if keep_depth:
                    # D2H fetch synchronizes this chunk's compute;
                    # without depth the wsums stay device-resident
                    # until finalize
                    depth_parts.append(np.asarray(depth))
                wsums_parts.append(wsums)
            return wsums, carry

        def commit(res):
            wsums, carry2 = res
            rec = {"wsums": np.asarray(wsums),
                   "carry": np.asarray(carry2)}
            if keep_depth:
                rec["depth"] = depth_parts[-1]
            return [(_chunk_key(staged.index + done_prefix,
                                staged.meta), rec)]

        pex.run(Step(
            key=("prefetched_cohort", staged.index + done_prefix),
            fn=fn, site="shard", retry=False, resumable=False,
            checkpoint_key=(_chunk_key(staged.index + done_prefix,
                                       staged.meta)
                            if checkpoint is not None else None),
            commit=commit))

    todo = list(chunks)[done_prefix:]
    if prefetch_depth < 1:
        for i, desc in enumerate(todo):
            consume(StagedChunk(i, desc, transfer(produce(desc), desc)))
    else:
        with ChunkPrefetcher(todo, produce, depth=prefetch_depth,
                             transfer=transfer,
                             processes=processes) as pf:
            for staged in pf:
                consume(staged)

    wsums_all = jnp.concatenate(wsums_parts, axis=1)
    with timer.stage("compute"):
        out = dict(finalize_fn(wsums_all))
        jax.block_until_ready(out)
    out["carry"] = np.asarray(carry)
    if keep_depth:
        out["depth"] = np.concatenate(depth_parts, axis=1)
    return out
