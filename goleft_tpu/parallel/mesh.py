"""Device-mesh construction for the cohort pipeline.

The reference's parallelism is process pools over genome shards and
goroutines over samples (SURVEY.md §2.5); the TPU-native mapping is a 2D
``jax.sharding.Mesh``:

  - ``data`` axis: samples (cohort data parallelism — the analog of the
    8-goroutine index readers, indexcov/indexcov.go:417-434)
  - ``seq`` axis: genome position (sequence parallelism — the analog of
    the 10Mb shard loop, depth/depth.go:150-153, but with on-device
    carry exchange instead of tmp-file merges)

Multi-host: call ``init_distributed()`` first (jax.distributed over DCN),
then the same mesh code spans all hosts' devices — collectives ride ICI
within a slice and DCN across slices.
"""

from __future__ import annotations

import os

from ..obs.logging import get_logger as _get_logger

import jax
import numpy as np
from jax.sharding import Mesh


def best_grid(n: int, prefer_seq: int | None = None) -> tuple[int, int]:
    """(data, seq) grid for n devices; seq gets the larger factor since
    genome length dwarfs cohort size."""
    if prefer_seq:
        if n % prefer_seq:
            raise ValueError(f"{prefer_seq} does not divide {n}")
        return n // prefer_seq, prefer_seq
    best = (1, n)
    for d in range(1, int(np.sqrt(n)) + 1):
        if n % d == 0:
            best = (d, n // d)
    return best


def make_mesh(n_devices: int | None = None,
              axis_names: tuple[str, str] = ("data", "seq"),
              prefer_seq: int | None = None) -> Mesh:
    """Topology-aware 2D mesh.

    ``mesh_utils.create_device_mesh`` orders devices so the trailing
    (``seq``) axis — which carries the cumsum-carry ppermute traffic of
    the sharded coverage kernel — maps to physically adjacent ICI
    neighbors on real TPU topologies, instead of the raw ``jax.devices()``
    enumeration order (round-1 VERDICT weak #4). Falls back to a plain
    reshape when the requested count is a strict subset of the process's
    devices (subset meshes have no topology guarantee anyway).
    """
    devs = jax.devices()
    n = n_devices or len(devs)
    if n > len(devs):
        raise ValueError(f"requested {n} devices, have {len(devs)}")
    d, s = best_grid(n, prefer_seq)
    if n == len(devs):
        try:
            from jax.experimental import mesh_utils

            grid = mesh_utils.create_device_mesh((d, s), devices=devs)
            return Mesh(grid, axis_names)
        except Exception as e:  # noqa: BLE001 - virtual/CPU platforms
            if devs[0].platform not in ("cpu",):
                _get_logger("mesh").warning(
                    "topology-aware mesh unavailable (%s); falling back "
                    "to enumeration order — ICI adjacency not guaranteed",
                    e,
                )
    grid = np.asarray(devs[:n]).reshape(d, s)
    return Mesh(grid, axis_names)


_distributed_up = False


def init_distributed() -> None:
    """Multi-host bring-up over DCN (no-op single-host, idempotent).

    Honors the standard JAX coordinator env vars; the reference has no
    distributed backend at all (SURVEY.md §2.5) — this is the rebuild's
    equivalent of an NCCL/MPI world init. Must run before anything
    initializes the XLA backend — the CLI dispatcher calls it ahead of
    its device bring-up watchdog.
    """
    global _distributed_up

    addr = os.environ.get("GOLEFT_TPU_COORDINATOR")
    if not addr or _distributed_up:
        return
    jax.distributed.initialize(
        coordinator_address=addr,
        num_processes=int(os.environ.get("GOLEFT_TPU_NUM_PROCESSES", "1")),
        process_id=int(os.environ.get("GOLEFT_TPU_PROCESS_ID", "0")),
    )
    _distributed_up = True
