"""Multi-host cohort decode: shard the SAMPLES across jax.distributed
processes, assemble the matrix over the collective fabric.

The cohort pipeline's wall clock is the host decode stage (fused C++
BGZF+record walk); within one host it scales across decode threads
(utils/decode_scaling). This module scales it across HOSTS: process i
decodes ``bams[i::P]`` with the ordinary cohort machinery, then one
``process_allgather`` moves the (windows × local-samples) int32 means
over DCN and every process reassembles the full matrix in original
sample order. Decode wall time divides by the process count; the
gathered payload is the O(windows × samples) matrix — the same reduced
product the single-host hierarchy ships over the device link, never
per-read data.

The reference has no multi-machine story at all (its parallelism is one
process pool per invocation, depth/depth.go:392-394; SURVEY.md §2.5);
this is the rebuild's answer at the cohort-tool level, riding the same
jax.distributed world that mesh.init_distributed brings up.
"""

from __future__ import annotations

import contextlib
import os
import sys

import numpy as np

_NAME_BYTES = 256  # fixed-width utf-8 slot per sample name for the gather


@contextlib.contextmanager
def _stdout_to_stderr():
    """Divert fd 1 to stderr (fd-level: catches native prints too)."""
    sys.stdout.flush()
    saved = os.dup(1)
    try:
        os.dup2(2, 1)
        yield
    finally:
        sys.stdout.flush()
        os.dup2(saved, 1)
        os.close(saved)


def cohort_coords(fai_path: str, chrom: str, window: int,
                  bed: str | None = None):
    """(chroms, starts, ends) for every window of the cohort matrix,
    derived from the .fai alone — exactly the coordinates
    cohort_matrix_blocks emits (same gen_regions shards, same
    window_bounds), so a process holding zero local samples can still
    label the gathered matrix."""
    from ..commands.cohortdepth import cohort_regions
    from ..io.fai import read_fai
    from ..ops.coverage import window_bounds

    regions = cohort_regions(read_fai(fai_path), chrom, window, bed)
    ch, st, en = [], [], []
    for c, s, e in regions:
        starts, ends, _, _ = window_bounds(s, e, window)
        ch.extend([c] * len(starts))
        st.append(starts)
        en.append(ends)
    if not st:
        return np.empty(0, object), np.empty(0, np.int64), \
            np.empty(0, np.int64)
    return (np.array(ch, dtype=object), np.concatenate(st),
            np.concatenate(en))


def _local_matrix(local_bams, n_win, reference, fai, window, mapq,
                  chrom, processes, engine, bed, prefetch_depth=0,
                  stage_timer=None):
    """Drain cohort_matrix_blocks for this process's sample shard into
    an int32 (n_win, n_local) matrix of round-half-up window means."""
    from ..commands.cohortdepth import cohort_matrix_blocks

    if not local_bams:
        return [], np.zeros((n_win, 0), dtype=np.int32)
    names, total, blocks = cohort_matrix_blocks(
        local_bams, reference=reference, fai=fai, window=window,
        mapq=mapq, chrom=chrom, processes=processes, engine=engine,
        bed=bed, prefetch_depth=prefetch_depth,
        stage_timer=stage_timer,
    )
    assert total == n_win, (total, n_win)
    mat = np.empty((n_win, len(names)), dtype=np.int32)
    row = 0
    for _, starts, _, vals in blocks:
        k = len(starts)
        mat[row : row + k] = vals.T
        row += k
    assert row == n_win, (row, n_win)
    return names, mat


def _pack_names(names, pad_to: int) -> np.ndarray:
    out = np.zeros((pad_to, _NAME_BYTES), dtype=np.uint8)
    for i, nm in enumerate(names):
        b = nm.encode("utf-8")[:_NAME_BYTES]
        # a hard byte cut can split a multi-byte codepoint and make
        # _unpack_name's decode raise mid-assembly; re-truncate on a
        # codepoint boundary instead
        b = b.decode("utf-8", errors="ignore").encode("utf-8")
        out[i, : len(b)] = np.frombuffer(b, dtype=np.uint8)
    return out


def _unpack_name(row: np.ndarray) -> str:
    return bytes(row[row != 0]).decode("utf-8")


def distributed_cohort_matrix(
    bams: list[str],
    reference: str | None = None,
    fai: str | None = None,
    window: int = 250,
    mapq: int = 1,
    chrom: str = "",
    processes: int = 8,
    engine: str = "auto",
    bed: str | None = None,
    prefetch_depth: int = 0,
    stage_timer=None,
):
    """(names, chroms, starts, ends, matrix) with matrix int32
    (n_windows, n_samples) of round-half-up window means, identical to
    a single-process cohortdepth run over the same BAMs.

    Every process returns the full assembled result (process_allgather
    is symmetric), so callers can write output on process 0 and use the
    arrays everywhere else.

    ``prefetch_depth`` >= 1 routes each process's LOCAL shard loop
    through the async staging pipeline (parallel/prefetch.py) — the
    decode/stage/transfer spans land in this process's ``stage_timer``;
    the DCN gather is unaffected (it moves the already-reduced matrix).
    """
    import jax

    from ..io.fai import write_fai

    fai_path = fai or (reference + ".fai" if reference else None)
    if fai_path is None:
        raise SystemExit("cohortdepth: need -r reference or --fai")
    P = jax.process_count()
    pid = jax.process_index()
    if not os.path.exists(fai_path) and reference:
        # shared-FS race: only process 0 may generate the index; the
        # barrier keeps the others from reading a half-written file
        # (and from every host writing the same path at once)
        if pid == 0:
            write_fai(reference)
        if P > 1:
            from jax.experimental import multihost_utils

            with _stdout_to_stderr():
                multihost_utils.sync_global_devices(
                    "goleft_tpu_fai_ready")
    chroms, starts, ends = cohort_coords(fai_path, chrom, window,
                                         bed=bed)
    n_win = len(starts)
    if P == 1:
        names, mat = _local_matrix(bams, n_win, reference, fai_path,
                                   window, mapq, chrom, processes,
                                   engine, bed, prefetch_depth,
                                   stage_timer)
        return names, chroms, starts, ends, mat

    local = bams[pid::P]
    names_l, mat_l = _local_matrix(local, n_win, reference, fai_path,
                                   window, mapq, chrom, processes,
                                   engine, bed, prefetch_depth,
                                   stage_timer)
    # fixed-shape padding: allgather needs identical shapes everywhere
    pad = (len(bams) + P - 1) // P
    mat_pad = np.zeros((n_win, pad), dtype=np.int32)
    mat_pad[:, : mat_l.shape[1]] = mat_l

    from jax.experimental import multihost_utils

    # the CPU collective backend (gloo) prints a connection banner to
    # STDOUT on its first collective — which would corrupt the matrix
    # a piped `cohortdepth > m.tsv` is writing there. Divert fd 1 to
    # stderr for the gathers (all output writing happens after).
    with _stdout_to_stderr():
        g_mat = np.asarray(
            multihost_utils.process_allgather(mat_pad)
        )  # (P, n_win, pad)
        g_names = np.asarray(
            multihost_utils.process_allgather(_pack_names(names_l, pad))
        )  # (P, pad, NAME_BYTES)

    # global sample k was decoded by process k % P at local slot k // P
    n = len(bams)
    mat = np.empty((n_win, n), dtype=np.int32)
    names = []
    for k in range(n):
        mat[:, k] = g_mat[k % P, :, k // P]
        names.append(_unpack_name(g_names[k % P, k // P]))
    return names, chroms, starts, ends, mat
