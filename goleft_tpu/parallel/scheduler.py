"""Host shard scheduler: decode pipeline with retry + result cache.

The reference's execution layer is gargs' process pool with
``Options{Retries: 1, Ordered}`` and red-banner error propagation
(depth/depth.go:392-399); here the units of work are (bam, region) decode
tasks feeding the device, run on a thread pool with:

  - retry per shard under the unified RetryPolicy
    (resilience/policy.py): the default retry-once matches
    ``Retries: 1``, but permanent failures (missing/corrupt input)
    fail fast instead of burning a blind re-attempt, transients back
    off with deterministic jitter, and both scheduler paths share ONE
    cache-lookup + retry helper (``plan.executor.execute_task`` — the
    plan layer every dispatch path lowers into)
  - ordered result consumption (matching Ordered)
  - max-exit-code-style error propagation: failures are recorded, other
    shards keep running, and the first exception re-raises at the end
  - an optional on-disk result cache keyed by (file identity, region,
    params) making reruns/resume nearly free (SURVEY.md §5 checkpoint
    gap: the reference restarts from scratch)

``iter_prefetched`` runs the same pool as the PRODUCER of the async
staging pipeline (parallel/prefetch.py): identical shard semantics,
but results flow through the prefetcher's bounded ordered queue so
decode overlaps the consumer's device compute.
"""

from __future__ import annotations

import concurrent.futures as cf
import hashlib
import os
import pickle
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Iterable, Sequence

from ..obs import get_registry
from ..plan.core import Step
from ..plan.executor import Executor as PlanExecutor
from ..resilience import faults
from ..resilience.policy import RetryPolicy


def _shard_step(pex: "PlanExecutor", key: tuple, thunk,
                cacheable: bool) -> ShardResult:
    """One shard task through the plan layer — the ShardResult shape
    both scheduler paths yield."""
    out = pex.run_step(Step(key=key, fn=thunk, site="shard",
                            cacheable=cacheable))
    return ShardResult(key, out.value, error=out.error,
                       attempts=out.attempts,
                       from_cache=out.from_cache)


@dataclass
class ShardResult:
    key: tuple
    value: Any = None
    error: Exception | None = None
    attempts: int = 1
    from_cache: bool = False


#: the eviction lease file's name under a cache directory
EVICT_LEASE = ".evict.lease"


class ResultCache:
    """Pickle-per-key cache under a directory.

    ``max_bytes`` bounds the on-disk footprint with mtime-LRU eviction:
    every hit touches the entry's mtime, and after each ``put`` the
    oldest entries are removed until the directory fits the bound
    (long-lived consumers — the serve daemon's session layer — would
    otherwise grow it without limit). ``hits``/``misses`` count lookups
    for observability; both are safe under concurrent get/put from many
    threads (writes are tmp-file + atomic ``os.replace``, and eviction
    tolerates entries vanishing under it).

    **Eviction is coordinated across consumers of one directory**: a
    fleet's ``--shared-cache`` tier used to pay N independent LRU
    scans over the SAME directory — every worker's every put walked
    the whole listing. Now a single elected SWEEPER owns eviction: a
    lock-file lease (``.evict.lease`` under the cache dir, atomic
    O_EXCL create) names the holder; non-holders skip the scan
    entirely. The holder renews the lease (mtime) on each sweep; a
    lease older than ``lease_ttl_s`` is presumed orphaned (its holder
    crashed or was SIGKILLed) and taken over via atomic rename —
    ``cache.evict_lease_steals_total`` counts takeovers,
    ``cache.evict_sweeps_total`` the sweeps that actually ran. Two
    racing stealers can both sweep once (last rename wins the lease);
    eviction is idempotent, so the race costs one redundant scan,
    never correctness.
    """

    def __init__(self, directory: str, max_bytes: int | None = None,
                 lease_ttl_s: float = 30.0):
        self.dir = directory
        self.max_bytes = max_bytes
        self.lease_ttl_s = lease_ttl_s
        self.hits = 0
        self.misses = 0
        self._lock = threading.Lock()
        # pid + instance id: distinct per consumer even when several
        # caches in ONE process share a directory (tests do)
        self._lease_token = f"{os.getpid()}.{id(self):x}"
        os.makedirs(directory, exist_ok=True)

    def _path(self, key: tuple) -> str:
        h = hashlib.sha256(repr(key).encode()).hexdigest()[:32]
        return os.path.join(self.dir, h + ".pkl")

    def get(self, key: tuple):
        p = self._path(key)
        faults.maybe_fail("cache", key)
        try:
            with open(p, "rb") as fh:
                val = pickle.load(fh)
        except FileNotFoundError:
            with self._lock:
                self.misses += 1
            get_registry().counter("result_cache.misses_total").inc()
            return None
        except Exception:
            # corrupt entry (truncated/garbled pickle): counting it as
            # a miss but leaving it on disk made every later get re-pay
            # the failed load — unlink it (tolerating a concurrent
            # remove/replace) so the next put heals the slot
            try:
                os.remove(p)
            except OSError:
                pass
            get_registry().counter("result_cache.corrupt_total").inc()
            with self._lock:
                self.misses += 1
            get_registry().counter("result_cache.misses_total").inc()
            return None
        try:
            os.utime(p)  # LRU touch: a hit is recent use
        except OSError:
            pass  # evicted/replaced underneath us — the value is fine
        with self._lock:
            self.hits += 1
        get_registry().counter("result_cache.hits_total").inc()
        return val

    def put(self, key: tuple, value) -> None:
        p = self._path(key)
        tmp = p + f".{os.getpid()}.{threading.get_ident()}.tmp"
        faults.maybe_fail("cache", key)
        try:
            # a failed dump (unpicklable value, disk full) used to leak
            # the .tmp forever: eviction and stats() skip non-.pkl
            # names, so orphans grew unbounded
            with open(tmp, "wb") as fh:
                pickle.dump(value, fh)
            os.replace(tmp, p)
        except BaseException:
            try:
                os.remove(tmp)
            except OSError:
                pass
            raise
        if self.max_bytes is not None:
            self._evict()

    def _acquire_sweep_lease(self) -> bool:
        """May THIS consumer run the eviction sweep right now?

        True for the lease holder (created or renewed); False when
        another consumer holds a live lease (skip the scan — the
        holder sweeps for everyone). A stale lease (older than
        ``lease_ttl_s``) is stolen via atomic rename."""
        path = os.path.join(self.dir, EVICT_LEASE)
        reg = get_registry()
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            pass
        except OSError:
            return False  # unwritable dir: never fail a put over it
        else:
            try:
                os.write(fd, self._lease_token.encode())
            finally:
                os.close(fd)
            return True
        try:
            with open(path) as fh:
                owner = fh.read().strip()
            st = os.stat(path)
        except OSError:
            # the lease vanished or is being replaced under us: skip
            # this sweep, the next put re-contends
            return False
        if owner == self._lease_token:
            try:
                os.utime(path)  # renew: a live holder keeps the seat
            except OSError:
                pass
            return True
        if time.time() - st.st_mtime <= self.lease_ttl_s:
            return False
        # stale: the holder stopped sweeping (crashed worker, removed
        # slot) — take the seat over atomically
        tmp = path + f".{self._lease_token}.tmp"
        try:
            with open(tmp, "w") as fh:
                fh.write(self._lease_token)
            os.replace(tmp, path)
        except OSError:
            try:
                os.remove(tmp)
            except OSError:
                pass
            return False
        reg.counter("cache.evict_lease_steals_total").inc()
        return True

    def _evict(self) -> None:
        if not self._acquire_sweep_lease():
            return
        get_registry().counter("cache.evict_sweeps_total").inc()
        entries = []
        try:
            # gtlint: ok det-unsorted-iter — eviction order comes from
            # sorted(entries) by (mtime, size, name) below, not from
            # the scan order
            names = os.listdir(self.dir)
        except OSError:
            return
        for n in names:
            if not n.endswith(".pkl"):
                continue
            try:
                st = os.stat(os.path.join(self.dir, n))
            except OSError:
                continue  # concurrent eviction/replace
            entries.append((st.st_mtime_ns, st.st_size, n))
        total = sum(s for _, s, _ in entries)
        evictions = get_registry().counter(
            "result_cache.evictions_total")
        for _, size, name in sorted(entries):
            if total <= self.max_bytes:
                break
            try:
                os.remove(os.path.join(self.dir, name))
            except OSError:
                continue
            total -= size
            evictions.inc()

    def stats(self) -> dict:
        """{hits, misses, entries, bytes} snapshot (entries/bytes scan
        the directory; cheap at cache-bound entry counts)."""
        n = b = 0
        try:
            # gtlint: ok det-unsorted-iter — pure accumulation (count
            # + byte total); no order reaches output or keys
            for name in os.listdir(self.dir):
                if not name.endswith(".pkl"):
                    continue
                try:
                    b += os.stat(os.path.join(self.dir, name)).st_size
                    n += 1
                except OSError:
                    continue
        except OSError:
            pass
        with self._lock:
            return {"hits": self.hits, "misses": self.misses,
                    "entries": n, "bytes": b}


def file_key(path: str) -> tuple:
    """Cache-key component identifying a file's content cheaply.

    Uses ``st_mtime_ns``: truncating to whole seconds aliased a
    same-second same-size rewrite to a stale cache hit. Remote URLs
    mirror the same 3-tuple shape as ``(url, length, etag-token)``
    (``io.remote.remote_file_key``) — an object rewrite changes the
    key exactly like a local mtime bump, so caching, checkpointing,
    dedup and ring affinity compose unchanged."""
    if "://" in path:
        from ..io import remote

        if remote.is_remote(path):
            return remote.remote_file_key(path)
    st = os.stat(path)
    return (os.path.abspath(path), st.st_size, st.st_mtime_ns)


def run_sharded(
    tasks: Sequence[tuple] | Iterable[tuple],
    fn: Callable[..., Any],
    processes: int = 4,
    retries: int = 1,
    cache: ResultCache | None = None,
    ordered: bool = True,
    strict: bool = False,
    max_in_flight: int | None = None,
    policy: RetryPolicy | None = None,
) -> Iterable[ShardResult]:
    """Run fn(*task) per task; yield ShardResults in task order (ordered)
    or completion order. Failed shards come back with .error set and the
    rest keep running (the reference's max-exit-code behavior); with
    strict=True the first error re-raises once all tasks finish.

    ``policy`` overrides the retry behavior wholesale; without one,
    ``retries`` builds the default RetryPolicy (kept for the historical
    signature — retry-once, permanent errors fail fast).

    At most ``max_in_flight`` shards (default 2 × processes) are submitted
    ahead of the consumer, so a slow writer bounds host memory at
    O(max_in_flight) shard outputs instead of buffering the whole genome's
    results in completed futures (round-1 VERDICT weak #5).
    """

    # worker spans (the shard fn's decode/compute stages) parent under
    # the submitting thread's trace — captured once here, attached per
    # attempt on the pool threads
    from .. import obs

    if policy is None:
        policy = RetryPolicy(retries=retries)
    span_ctx = obs.capture()
    pex = PlanExecutor(policy=policy, cache=cache)

    def attempt(task) -> ShardResult:
        key = tuple(task)
        with obs.attach(span_ctx):
            return _shard_step(pex, key, lambda: fn(*task),
                               cache is not None)

    if max_in_flight is None:
        max_in_flight = 2 * max(processes, 1)
    max_in_flight = max(max_in_flight, 1)
    first_error: Exception | None = None
    task_iter = iter(tasks)
    with cf.ThreadPoolExecutor(max_workers=max(processes, 1)) as ex:

        def top_up(in_flight, add):
            """Submit tasks until in_flight holds max_in_flight futures."""
            while len(in_flight) < max_in_flight:
                try:
                    t = next(task_iter)
                except StopIteration:
                    return
                add(ex.submit(attempt, t))

        if ordered:
            pending: deque = deque()
            top_up(pending, pending.append)
            while pending:
                res = pending.popleft().result()
                top_up(pending, pending.append)
                if res.error is not None and first_error is None:
                    first_error = res.error
                yield res
        else:
            live: set = set()
            top_up(live, live.add)
            while live:
                done, live = cf.wait(live, return_when=cf.FIRST_COMPLETED)
                for f in done:
                    res = f.result()
                    if res.error is not None and first_error is None:
                        first_error = res.error
                    yield res
                top_up(live, live.add)
    if strict and first_error is not None:
        raise first_error


def iter_prefetched(
    tasks: Sequence[tuple] | Iterable[tuple],
    fn: Callable[..., Any],
    depth: int = 2,
    processes: int | None = None,
    retries: int = 1,
    cache: ResultCache | None = None,
    policy: RetryPolicy | None = None,
) -> Iterable[ShardResult]:
    """The scheduler's PRODUCER role in the async staging pipeline
    (parallel/prefetch.py): run ``fn(*task)`` per task on the decode
    pool with this module's shard semantics — the unified RetryPolicy
    (default retry-once, permanent errors fail fast), optional result
    cache, failures yielded as ``.error`` results while other shards
    keep running — delivered in task order through the prefetcher's
    bounded queue, so at most ``depth`` results are staged ahead of
    the consumer.

    Equivalent to ``run_sharded(ordered=True, max_in_flight=depth)``
    but on the prefetch machinery: chunk k+1's decode (and anything the
    caller chains in ``fn``, e.g. packing + an async device_put) runs
    under the consumer's processing of chunk k. Both paths lower
    their shard tasks through the one plan-layer Executor."""
    from .prefetch import ChunkPrefetcher

    if policy is None:
        policy = RetryPolicy(retries=retries)
    pex = PlanExecutor(policy=policy, cache=cache)

    def produce(task) -> ShardResult:
        key = tuple(task)
        return _shard_step(pex, key, lambda: fn(*task),
                           cache is not None)

    with ChunkPrefetcher(tasks, produce, depth=depth,
                         processes=processes) as pf:
        for chunk in pf:
            yield chunk.value
