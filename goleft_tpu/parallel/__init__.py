from .mesh import make_mesh, best_grid  # noqa: F401
