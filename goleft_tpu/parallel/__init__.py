from .mesh import make_mesh, best_grid  # noqa: F401
from .prefetch import (  # noqa: F401
    ChunkPrefetcher,
    PrefetchWorkerError,
    StagedChunk,
    run_prefetched_cohort,
)
