"""Interactive HTML (chart.js) + static PNG (matplotlib) report writers.

Covers the reference's reporting layer (indexcov/plot.go, 577 LoC +
indexcov/template.go) with our own template: each page is a self-contained
HTML document loading chart.js from a CDN, mirroring the reference's output
surface (<base>-depth-<chrom>.html, <base>-roc-<chrom>.html, index.html,
and .png twins). Honors the same environment knobs: INDEXCOV_FMT (extra
static formats, plot.go:528-536).
"""

from __future__ import annotations

import json
import os
import random


_PAGE = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>{title}</title>
<script src="https://cdn.jsdelivr.net/npm/chart.js@2.9.4/dist/Chart.min.js"></script>
<style>
body {{ font-family: sans-serif; margin: 20px; }}
.chartbox {{ display: inline-block; margin: 10px; }}
h2 {{ font-weight: normal; }}
nav a {{ margin-right: 12px; }}
</style></head>
<body>
{nav}
{body}
<script>
{scripts}
</script>
</body></html>
"""


_HTML_MAX_POINTS = 2048  # per series; INDEXCOV_HTML_MAX_POINTS overrides


def _html_max_points() -> int:
    """Per-series point cap for interactive charts. The reference
    subsamples its static plots 1/5-1/10 at whole-genome sizes for
    exactly this reason (indexcov/plot.go:484-487); an 850px canvas
    cannot show more than ~1700 distinct x anyway, and chart.js with
    30x15k points is unusably slow in-browser. 0 disables."""
    try:
        return max(0, int(os.environ.get("INDEXCOV_HTML_MAX_POINTS",
                                         str(_HTML_MAX_POINTS))))
    except ValueError:
        return _HTML_MAX_POINTS


def _subsample_xy(x, y, cap: int):
    """Stride-subsample to <= cap+1 points, always keeping the last
    point so the x-extent (chromosome end) is preserved."""
    import numpy as np

    if not cap or len(x) <= cap:
        return x, y
    xa = np.asarray(x)
    ya = np.asarray(y)
    stride = -(-len(xa) // cap)
    idx = np.arange(0, len(xa), stride)
    if idx[-1] != len(xa) - 1:
        idx = np.append(idx, len(xa) - 1)
    return xa[idx], ya[idx]


def _n_backgrounds() -> int:
    """INDEXCOV_N_BACKGROUNDS: the first n samples plot gray
    (reference plot.go:85-96)."""
    try:
        return int(os.environ.get("INDEXCOV_N_BACKGROUNDS", "") or 0)
    except ValueError:
        return 0


def _color_rgb(i: int, background: bool = False) -> tuple[int, int, int]:
    if background:
        return (180, 180, 180)
    rng = random.Random(i)
    return (rng.randrange(256), rng.randrange(256), rng.randrange(256))


def _color(i: int, background: bool = False) -> str:
    r, g, b = _color_rgb(i, background)
    return f"rgba({r},{g},{b},0.94)"


def line_chart(
    chart_id: str,
    series: list[dict],
    xlabel: str,
    ylabel: str,
    y_max: float | None = None,
    stepped: bool = True,
    legend: bool = True,
    per_sample: bool = True,
) -> tuple[str, str]:
    """Return (div html, js) for a multi-series line chart.

    series entries: {"label", "x": list, "y": list, optional "color"}.
    ``per_sample`` marks series as one-per-sample, which honors
    INDEXCOV_N_BACKGROUNDS (first n gray — reference randomColor(i,
    check=true), plot.go:98-107; scatter/group charts pass check=false).
    """
    from ..io import native

    n_bg = _n_backgrounds() if per_sample else 0
    dataset_parts = []
    for i, s in enumerate(series):
        meta = {
            "label": s["label"],
            "fill": False,
            "pointRadius": 0,
            "borderWidth": s.get("width", 0.75),
            "borderColor": s.get("color", _color(i, background=i < n_bg)),
            "backgroundColor": s.get("color",
                                     _color(i, background=i < n_bg)),
            "steppedLine": stepped,
            "pointHitRadius": 6,
        }
        # whole-genome series are stride-subsampled to the canvas's
        # useful resolution before serialization — at 30 samples x 25
        # chroms this cuts the written html ~7x and was 60% of the
        # indexcov e2e wall on slow filesystems
        sx, sy = _subsample_xy(s["x"], s["y"], _html_max_points())
        # point serialization is the report writer's hot loop at
        # whole-genome sizes — C++ formats the pair array directly; the
        # Python fallback emits the SAME bytes (%.10g/%.5g, null for
        # non-finite — json.dumps would write invalid NaN literals)
        b = native.format_xy_json(sx, sy)
        if b is not None:
            data_json = b.decode("ascii")
        else:
            import math

            def _pt(v, prec):
                v = float(v)
                return format(v, f".{prec}g") if math.isfinite(v) \
                    else "null"

            data_json = "[" + ",".join(
                f'{{"x":{_pt(x, 10)},"y":{_pt(y, 5)}}}'
                for x, y in zip(sx, sy)
            ) + "]"
        mjson = json.dumps(meta)
        dataset_parts.append(mjson[:-1] + ',"data":' + data_json + "}")
    datasets_json = "[" + ",".join(dataset_parts) + "]"
    opts = {
        "responsive": False,
        "animation": False,
        "legend": {"display": legend},
        "tooltips": {"mode": "nearest"},
        "scales": {
            "xAxes": [
                {
                    "type": "linear",
                    "position": "bottom",
                    "scaleLabel": {"display": True, "labelString": xlabel,
                                   "fontSize": 16},
                }
            ],
            "yAxes": [
                {
                    "type": "linear",
                    "position": "left",
                    "ticks": ({"min": 0, "max": y_max} if y_max else {}),
                    "scaleLabel": {"display": True, "labelString": ylabel,
                                   "fontSize": 16},
                }
            ],
        },
    }
    div = (
        f'<div class="chartbox"><canvas id="{chart_id}" width="850" '
        f'height="550"></canvas></div>'
    )
    js = (
        f'new Chart(document.getElementById("{chart_id}").getContext("2d"),'
        f'{{"type":"line","data":{{"datasets":{datasets_json}}},'
        f'"options":{json.dumps(opts)}}});'
    )
    return div, js


def scatter_chart(
    chart_id: str,
    points: list[dict],
    xlabel: str,
    ylabel: str,
    labels: list[str] | None = None,
) -> tuple[str, str]:
    """points: [{"label", "x": [..], "y": [..], "names": [..]}] groups."""
    datasets = []
    for i, g in enumerate(points):
        data = [
            {"x": round(float(x), 4), "y": round(float(y), 4)}
            for x, y in zip(g["x"], g["y"])
        ]
        datasets.append(
            {
                "label": g["label"],
                "data": data,
                "pointRadius": 4,
                "pointHitRadius": 6,
                "showLine": False,
                "fill": False,
                "backgroundColor": g.get("color", _color(i + 7)),
                "borderColor": g.get("color", _color(i + 7)),
            }
        )
    names = json.dumps([g.get("names", []) for g in points])
    opts = {
        "responsive": False,
        "animation": False,
        "tooltips": {"mode": "nearest"},
        "scales": {
            "xAxes": [{"type": "linear", "position": "bottom",
                       "scaleLabel": {"display": True,
                                      "labelString": xlabel}}],
            "yAxes": [{"type": "linear", "position": "left",
                       "scaleLabel": {"display": True,
                                      "labelString": ylabel}}],
        },
    }
    div = (
        f'<div class="chartbox"><canvas id="{chart_id}" width="650" '
        f'height="550"></canvas></div>'
    )
    js = (
        f'(function(){{var names={names};'
        f'var cfg={{"type":"scatter","data":{{"datasets":'
        f'{json.dumps(datasets)}}},"options":{json.dumps(opts)}}};'
        f'cfg.options.tooltips.callbacks={{label:function(t,d){{'
        f'return (names[t.datasetIndex][t.index]||"")+" ("+t.xLabel+", "+'
        f't.yLabel+")";}}}};'
        f'new Chart(document.getElementById("{chart_id}").getContext("2d"),'
        f"cfg);}})();"
    )
    return div, js


def write_page(path: str, title: str, charts: list[tuple[str, str]],
               nav_html: str = "", extra_html: str = "") -> None:
    body = "\n".join(div for div, _ in charts) + extra_html
    scripts = "\n".join(js for _, js in charts)
    page = _PAGE.format(title=title, nav=nav_html, body=body,
                        scripts=scripts)
    # binary write: these pages are tens of MB at whole-genome sizes and
    # the text-codec write path costs ~2x a single encode
    with open(path, "wb") as fh:
        fh.write(page.encode("utf-8"))


def _nice_ticks(lo: float, hi: float, n: int = 5) -> list[float]:
    """~n round tick positions covering [lo, hi]."""
    import math

    span = hi - lo
    if span <= 0 or not math.isfinite(span):
        return [lo]
    raw = span / n
    mag = 10.0 ** math.floor(math.log10(raw))
    for mult in (1.0, 2.0, 2.5, 5.0, 10.0):
        if raw <= mult * mag:
            step = mult * mag
            break
    t0 = math.ceil(lo / step) * step
    out = []
    t = t0
    while t <= hi + step * 1e-9:
        out.append(0.0 if abs(t) < step * 1e-9 else t)
        t += step
    return out


def _tick_label(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        a = abs(int(v))
        if a >= 10_000_000:  # genomic positions: Mb units read better
            return f"{v / 1e6:g}M"
        return str(int(v))
    return f"{v:g}"


def save_png(path: str, series: list[dict], xlabel: str, ylabel: str,
             y_max: float | None = None, kind: str = "line",
             subsample: int = 1) -> None:
    """Static twin of the html charts (the reference renders PNGs via
    gonum/plot with 1/5-1/10 subsampling, plot.go:484-487).

    Rasterized directly with Pillow: the former matplotlib renderer cost
    ~180ms per whole-genome panel (axes machinery + path drawing was
    ~40% of indexcov e2e wall); drawing the polylines into an RGB canvas
    is ~100x cheaper. matplotlib remains the fallback when INDEXCOV_FMT
    requests non-png formats (svg/pdf/...)."""
    extra = os.environ.get("INDEXCOV_FMT", "")
    if extra:
        _save_matplotlib(path, series, xlabel, ylabel, y_max, kind,
                         subsample, extra)
        return
    try:
        from PIL import Image, ImageDraw, ImageFont
    except Exception:  # pragma: no cover - pillow always in image
        _save_matplotlib(path, series, xlabel, ylabel, y_max, kind,
                         subsample, "")
        return
    import numpy as np

    W, H = 480, 360
    ML, MR, MT, MB = 58, 12, 10, 44
    img = Image.new("RGB", (W, H), (255, 255, 255))
    draw = ImageDraw.Draw(img)
    font = ImageFont.load_default()

    # data ranges
    xlo, xhi = np.inf, -np.inf
    ylo, yhi = 0.0, -np.inf
    pre = []
    for s in series:
        x = np.asarray(s["x"], dtype=np.float64)[::subsample]
        y = np.asarray(s["y"], dtype=np.float64)[::subsample]
        # a 480-pixel panel cannot show more than ~2500 distinct steps
        if len(x) > 2500:
            step = (len(x) + 2499) // 2500
            x = x[::step]
            y = y[::step]
        ok = np.isfinite(x) & np.isfinite(y)
        x, y = x[ok], y[ok]
        pre.append((x, y))
        if len(x):
            xlo = min(xlo, float(x.min()))
            xhi = max(xhi, float(x.max()))
            ylo = min(ylo, float(y.min()))
            yhi = max(yhi, float(y.max()))
    if not np.isfinite(xlo) or xhi <= xlo:
        xlo, xhi = 0.0, 1.0
    if y_max is not None:
        ylo, yhi = 0.0, float(y_max)
    if not np.isfinite(yhi) or yhi <= ylo:
        ylo, yhi = 0.0, 1.0
    xspan, yspan = xhi - xlo, yhi - ylo
    pw, ph = W - ML - MR, H - MT - MB

    def px(x):
        return ML + (x - xlo) * (pw / xspan)

    def py(y):
        return MT + (yhi - y) * (ph / yspan)

    axis = (60, 60, 60)
    # frame + ticks + labels
    draw.rectangle([ML, MT, W - MR, H - MB], outline=axis)
    for t in _nice_ticks(xlo, xhi):
        xp = px(t)
        draw.line([xp, H - MB, xp, H - MB + 4], fill=axis)
        draw.text((xp, H - MB + 6), _tick_label(t), fill=axis, font=font,
                  anchor="ma")
    for t in _nice_ticks(ylo, yhi):
        yp = py(t)
        draw.line([ML - 4, yp, ML, yp], fill=axis)
        draw.text((ML - 6, yp), _tick_label(t), fill=axis, font=font,
                  anchor="rm")
    draw.text((ML + pw / 2, H - 16), xlabel, fill=(0, 0, 0), font=font,
              anchor="ma")
    # vertical y label rendered into a side strip
    if ylabel:
        strip = Image.new("RGB", (ph, 14), (255, 255, 255))
        ImageDraw.Draw(strip).text((ph // 2, 1), ylabel, fill=(0, 0, 0),
                                   font=font, anchor="ma")
        img.paste(strip.transpose(Image.ROTATE_90), (2, MT))

    n_bg = _n_backgrounds() if kind == "line" else 0
    for i, (x, y) in enumerate(pre):
        if not len(x):
            continue
        rgb = series[i].get("_rgb") or _color_rgb(i, background=i < n_bg)
        xs = px(x)
        ys = py(np.clip(y, ylo, yhi))
        if kind == "line":
            if len(x) > 1:
                # stepped (where="post"): insert (x[k+1], y[k]) knees
                fx = np.empty(2 * len(x) - 1)
                fy = np.empty_like(fx)
                fx[0::2] = xs
                fx[1::2] = xs[1:]
                fy[0::2] = ys
                fy[1::2] = ys[:-1]
            else:
                fx, fy = xs, ys
            flat = np.empty(2 * len(fx))
            flat[0::2] = fx
            flat[1::2] = fy
            draw.line(flat.tolist(), fill=rgb, width=1)
        else:
            for xp, yp in zip(xs, ys):
                draw.ellipse([xp - 3, yp - 3, xp + 3, yp + 3], fill=rgb)
    img.save(path, compress_level=1)


import threading as _threading

_MPL_LOCK = _threading.Lock()


def _save_matplotlib(path, series, xlabel, ylabel, y_max, kind,
                     subsample, extra) -> None:
    # indexcov renders pages from worker threads; pyplot's global
    # figure manager is not thread-safe, so the fallback serializes
    with _MPL_LOCK:
        _save_matplotlib_locked(path, series, xlabel, ylabel, y_max,
                                kind, subsample, extra)


def _save_matplotlib_locked(path, series, xlabel, ylabel, y_max, kind,
                            subsample, extra) -> None:
    try:
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except Exception:  # pragma: no cover - matplotlib always in image
        return
    fig, ax = plt.subplots(figsize=(4, 3), dpi=120)
    for i, s in enumerate(series):
        x = s["x"][::subsample]
        y = s["y"][::subsample]
        # a 480-pixel-wide panel cannot show more than ~2500 distinct
        # steps; cap the vertex count so whole-genome renders don't pay
        # matplotlib path costs for invisible detail
        if len(x) > 2500:
            step = (len(x) + 2499) // 2500
            x = x[::step]
            y = y[::step]
        if kind == "line":
            ax.step(x, y, lw=0.5, where="post")
        else:
            ax.plot(x, y, "o", ms=3)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel)
    if y_max is not None:
        ax.set_ylim(0, y_max)
    fig.tight_layout()
    fmts = [path]
    if extra:
        base = path.rsplit(".", 1)[0]
        fmts += [f"{base}.{e}" for e in extra.split(",") if e]
    for p in fmts:
        fig.savefig(p)
    plt.close(fig)
