"""Interactive HTML (chart.js) + static PNG (matplotlib) report writers.

Covers the reference's reporting layer (indexcov/plot.go, 577 LoC +
indexcov/template.go) with our own template: each page is a self-contained
HTML document loading chart.js from a CDN, mirroring the reference's output
surface (<base>-depth-<chrom>.html, <base>-roc-<chrom>.html, index.html,
and .png twins). Honors the same environment knobs: INDEXCOV_FMT (extra
static formats, plot.go:528-536).
"""

from __future__ import annotations

import json
import os
import random


_PAGE = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>{title}</title>
<script src="https://cdn.jsdelivr.net/npm/chart.js@2.9.4/dist/Chart.min.js"></script>
<style>
body {{ font-family: sans-serif; margin: 20px; }}
.chartbox {{ display: inline-block; margin: 10px; }}
h2 {{ font-weight: normal; }}
nav a {{ margin-right: 12px; }}
</style></head>
<body>
{nav}
{body}
<script>
{scripts}
</script>
</body></html>
"""


def _n_backgrounds() -> int:
    """INDEXCOV_N_BACKGROUNDS: the first n samples plot gray
    (reference plot.go:85-96)."""
    try:
        return int(os.environ.get("INDEXCOV_N_BACKGROUNDS", "") or 0)
    except ValueError:
        return 0


def _color(i: int, background: bool = False) -> str:
    if background:
        return "rgba(180,180,180,0.94)"
    rng = random.Random(i)
    return f"rgba({rng.randrange(256)},{rng.randrange(256)},{rng.randrange(256)},0.94)"


def line_chart(
    chart_id: str,
    series: list[dict],
    xlabel: str,
    ylabel: str,
    y_max: float | None = None,
    stepped: bool = True,
    legend: bool = True,
    per_sample: bool = True,
) -> tuple[str, str]:
    """Return (div html, js) for a multi-series line chart.

    series entries: {"label", "x": list, "y": list, optional "color"}.
    ``per_sample`` marks series as one-per-sample, which honors
    INDEXCOV_N_BACKGROUNDS (first n gray — reference randomColor(i,
    check=true), plot.go:98-107; scatter/group charts pass check=false).
    """
    from ..io import native

    n_bg = _n_backgrounds() if per_sample else 0
    dataset_parts = []
    for i, s in enumerate(series):
        meta = {
            "label": s["label"],
            "fill": False,
            "pointRadius": 0,
            "borderWidth": s.get("width", 0.75),
            "borderColor": s.get("color", _color(i, background=i < n_bg)),
            "backgroundColor": s.get("color",
                                     _color(i, background=i < n_bg)),
            "steppedLine": stepped,
            "pointHitRadius": 6,
        }
        # point serialization is the report writer's hot loop at
        # whole-genome sizes — C++ formats the pair array directly; the
        # Python fallback emits the SAME bytes (%.10g/%.5g, null for
        # non-finite — json.dumps would write invalid NaN literals)
        b = native.format_xy_json(s["x"], s["y"])
        if b is not None:
            data_json = b.decode("ascii")
        else:
            import math

            def _pt(v, prec):
                v = float(v)
                return format(v, f".{prec}g") if math.isfinite(v) \
                    else "null"

            data_json = "[" + ",".join(
                f'{{"x":{_pt(x, 10)},"y":{_pt(y, 5)}}}'
                for x, y in zip(s["x"], s["y"])
            ) + "]"
        mjson = json.dumps(meta)
        dataset_parts.append(mjson[:-1] + ',"data":' + data_json + "}")
    datasets_json = "[" + ",".join(dataset_parts) + "]"
    opts = {
        "responsive": False,
        "animation": False,
        "legend": {"display": legend},
        "tooltips": {"mode": "nearest"},
        "scales": {
            "xAxes": [
                {
                    "type": "linear",
                    "position": "bottom",
                    "scaleLabel": {"display": True, "labelString": xlabel,
                                   "fontSize": 16},
                }
            ],
            "yAxes": [
                {
                    "type": "linear",
                    "position": "left",
                    "ticks": ({"min": 0, "max": y_max} if y_max else {}),
                    "scaleLabel": {"display": True, "labelString": ylabel,
                                   "fontSize": 16},
                }
            ],
        },
    }
    div = (
        f'<div class="chartbox"><canvas id="{chart_id}" width="850" '
        f'height="550"></canvas></div>'
    )
    js = (
        f'new Chart(document.getElementById("{chart_id}").getContext("2d"),'
        f'{{"type":"line","data":{{"datasets":{datasets_json}}},'
        f'"options":{json.dumps(opts)}}});'
    )
    return div, js


def scatter_chart(
    chart_id: str,
    points: list[dict],
    xlabel: str,
    ylabel: str,
    labels: list[str] | None = None,
) -> tuple[str, str]:
    """points: [{"label", "x": [..], "y": [..], "names": [..]}] groups."""
    datasets = []
    for i, g in enumerate(points):
        data = [
            {"x": round(float(x), 4), "y": round(float(y), 4)}
            for x, y in zip(g["x"], g["y"])
        ]
        datasets.append(
            {
                "label": g["label"],
                "data": data,
                "pointRadius": 4,
                "pointHitRadius": 6,
                "showLine": False,
                "fill": False,
                "backgroundColor": g.get("color", _color(i + 7)),
                "borderColor": g.get("color", _color(i + 7)),
            }
        )
    names = json.dumps([g.get("names", []) for g in points])
    opts = {
        "responsive": False,
        "animation": False,
        "tooltips": {"mode": "nearest"},
        "scales": {
            "xAxes": [{"type": "linear", "position": "bottom",
                       "scaleLabel": {"display": True,
                                      "labelString": xlabel}}],
            "yAxes": [{"type": "linear", "position": "left",
                       "scaleLabel": {"display": True,
                                      "labelString": ylabel}}],
        },
    }
    div = (
        f'<div class="chartbox"><canvas id="{chart_id}" width="650" '
        f'height="550"></canvas></div>'
    )
    js = (
        f'(function(){{var names={names};'
        f'var cfg={{"type":"scatter","data":{{"datasets":'
        f'{json.dumps(datasets)}}},"options":{json.dumps(opts)}}};'
        f'cfg.options.tooltips.callbacks={{label:function(t,d){{'
        f'return (names[t.datasetIndex][t.index]||"")+" ("+t.xLabel+", "+'
        f't.yLabel+")";}}}};'
        f'new Chart(document.getElementById("{chart_id}").getContext("2d"),'
        f"cfg);}})();"
    )
    return div, js


def write_page(path: str, title: str, charts: list[tuple[str, str]],
               nav_html: str = "", extra_html: str = "") -> None:
    body = "\n".join(div for div, _ in charts) + extra_html
    scripts = "\n".join(js for _, js in charts)
    with open(path, "w") as fh:
        fh.write(
            _PAGE.format(title=title, nav=nav_html, body=body,
                         scripts=scripts)
        )


def save_png(path: str, series: list[dict], xlabel: str, ylabel: str,
             y_max: float | None = None, kind: str = "line",
             subsample: int = 1) -> None:
    """Static twin of the html charts via matplotlib (replaces the
    reference's gonum/plot PNGs with 1/5-1/10 subsampling, plot.go:484-487).
    """
    try:
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except Exception:  # pragma: no cover - matplotlib always in image
        return
    fig, ax = plt.subplots(figsize=(4, 3), dpi=120)
    for i, s in enumerate(series):
        x = s["x"][::subsample]
        y = s["y"][::subsample]
        # a 480-pixel-wide panel cannot show more than ~2500 distinct
        # steps; cap the vertex count so whole-genome renders don't pay
        # matplotlib path costs for invisible detail
        if len(x) > 2500:
            step = (len(x) + 2499) // 2500
            x = x[::step]
            y = y[::step]
        if kind == "line":
            ax.step(x, y, lw=0.5, where="post")
        else:
            ax.plot(x, y, "o", ms=3)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel)
    if y_max is not None:
        ax.set_ylim(0, y_max)
    fig.tight_layout()
    fmts = [path]
    extra = os.environ.get("INDEXCOV_FMT", "")
    if extra:
        base = path.rsplit(".", 1)[0]
        fmts += [f"{base}.{e}" for e in extra.split(",") if e]
    for p in fmts:
        fig.savefig(p)
    plt.close(fig)
