"""Device bring-up guard: escape hatch + hang diagnostics.

The accelerator may sit behind a tunnel (this dev environment) or a
driver that can wedge; a product CLI must never hang silently on
backend bring-up with no way out. Two mechanisms:

- ``GOLEFT_TPU_CPU=1`` pins the jax platform to CPU before any backend
  initializes (``maybe_force_cpu`` runs at CLI dispatch). Every tool
  runs correctly on host — slower, never stuck.
- ``devices_with_watchdog()`` wraps the first device discovery: if
  bring-up exceeds the deadline, a warning names the likely cause and
  the escape hatch while the attempt continues (the reference's analog
  is its red shard-failure banner — failures must be loud and
  actionable, depth/depth.go:396-399).
"""

from __future__ import annotations

import os
import threading

from ..obs.logging import get_logger

log = get_logger("device")

def _watchdog_seconds() -> float:
    raw = os.environ.get("GOLEFT_TPU_DEVICE_WATCHDOG_SECONDS", "30")
    try:
        v = float(raw)
    except ValueError:
        log.warning(
            "ignoring malformed GOLEFT_TPU_DEVICE_WATCHDOG_SECONDS=%r",
            raw)
        return 30.0
    return v if v > 0 else 30.0


WATCHDOG_SECONDS = _watchdog_seconds()


def maybe_force_cpu() -> bool:
    """Pin the jax platform to CPU when GOLEFT_TPU_CPU is set. Must run
    before any jax backend initializes; returns True when pinned.
    Failure to honor an explicitly-set knob is LOUD — the user set it
    because the device is wedged."""
    if not os.environ.get("GOLEFT_TPU_CPU"):
        return False
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception as e:  # backend already up — nothing safe to do
        log.warning(
            "GOLEFT_TPU_CPU=1 set but the jax backend is already "
            "initialized (%s) — execution may still target the "
            "accelerator", e)
        return False
    return True


_PROBE_SNIPPET = ("import jax; d = jax.devices(); "
                  "assert d and d[0].platform != 'cpu', d")


def arm_traceback_snippet(snippet: str, timeout_s: float) -> str:
    """Prefix a ``python -c`` probe snippet with a faulthandler timer
    that dumps every thread's stack to stderr shortly BEFORE the
    parent's timeout expires — a wedged bring-up then yields a
    traceback in the probe record, not just an attempt count
    (round-4 VERDICT item 8). ``exit=False``: the child is never
    killed (see probe_device), so the dump must not change its
    lifecycle."""
    arm = max(1.0, timeout_s * 0.8)
    return (f"import faulthandler; "
            f"faulthandler.dump_traceback_later({arm:.1f}, exit=False); "
            + snippet)


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _touch(path: str) -> None:
    try:
        with open(path, "w"):
            pass
    except OSError:
        pass


def _probe_cache_path(kind: str = "ok") -> str:
    import tempfile

    uid = os.getuid() if hasattr(os, "getuid") else 0
    return os.path.join(tempfile.gettempdir(),
                        f"goleft-tpu-probe-{kind}-{uid}")


def probe_device(timeout_s: float | None = None, argv=None,
                 settle_s: float | None = None) -> dict:
    """Probe accelerator bring-up in a SUBPROCESS — the ONE shared
    implementation (bench.py's ``_probe_once`` wraps this): a wedged
    tunnel hangs ``jax.devices()`` indefinitely, and only an isolated
    child can be abandoned safely. The child is never killed — SIGKILL
    mid-bring-up is a documented way to wedge the remote session; on
    timeout the orphan is left to finish on its own and the probe
    reports not-ok.

    ``argv`` overrides the probe command (tests simulate hangs with a
    sleeping child; overriding also skips the post-success settle —
    there is no real device session to let tear down). ``settle_s``
    overrides the settle explicitly (bench uses a longer one for its
    tunnel). Returns {ok, seconds, rc, stdout?, error?}."""
    import subprocess
    import sys
    import tempfile
    import time

    if timeout_s is None:
        timeout_s = WATCHDOG_SECONDS
    if settle_s is None:
        settle_s = 0.0 if argv is not None else 2.0
    rec: dict = {"timeout_s": timeout_s}
    t0 = time.monotonic()
    # child output goes to TEMP FILES, not pipes: a verbose bring-up
    # failure must not block the (never-killed) child on a full pipe
    fo = tempfile.TemporaryFile(mode="w+")
    fe = tempfile.TemporaryFile(mode="w+")
    try:
        # gtlint: ok res-leak — deliberately orphaned: killing a probe
        # mid-bring-up wedges the remote device session (docstring);
        # the poll() loop below reaps the exit path, the hang path
        # abandons the child BY DESIGN
        child = subprocess.Popen(
            argv or [sys.executable, "-c",
                     arm_traceback_snippet(_PROBE_SNIPPET, timeout_s)],
            stdout=fo, stderr=fe,
        )
    except OSError as e:
        rec.update(ok=False, rc=None, error=f"spawn failed: {e!r}")
        return rec
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        rc = child.poll()
        if rc is not None:
            rec.update(ok=rc == 0, rc=rc,
                       seconds=round(time.monotonic() - t0, 1))
            if rc != 0:
                fe.seek(0)
                tail = (fe.read().strip().splitlines()
                        or ["<no stderr>"])[-1]
                rec["error"] = tail[:300]
            else:
                fo.seek(0)
                rec["stdout"] = fo.read().strip()[:300]
                time.sleep(settle_s)  # let the probe session tear down
            return rec
        time.sleep(0.2)
    rec.update(ok=False, rc=None,
               seconds=round(time.monotonic() - t0, 1),
               error="probe hung past timeout (child left to finish)")
    # harvest whatever the child wrote so far — with the default argv
    # that includes the faulthandler stack dump armed at 0.8×timeout,
    # turning "it hung" into "it hung HERE"
    try:
        fe.seek(0)
        tail = fe.read().strip()
        if tail:
            rec["traceback_tail"] = tail[-1500:]
    except (OSError, ValueError):
        pass
    return rec


def ensure_usable_backend(probe_argv=None) -> str:
    """CLI device bring-up: subprocess-probe the accelerator and
    degrade to HOST mode with one loud line when it is unusable,
    instead of hanging until the watchdog (round-3 VERDICT item 8 —
    the same wedged tunnel that hit the bench hits users).

    Returns "device" (probe ok), "host" (probe failed -> platform
    pinned to CPU), or "unprobed" (probing disabled/irrelevant:
    GOLEFT_TPU_CPU already pinned, GOLEFT_TPU_PROBE=0, a multi-host
    world under GOLEFT_TPU_COORDINATOR, or the backend already up)."""
    if os.environ.get("GOLEFT_TPU_CPU"):
        return "unprobed"  # explicitly pinned at dispatch already
    if os.environ.get("GOLEFT_TPU_PROBE", "1").lower() in (
            "0", "no", "false"):
        return "unprobed"
    if os.environ.get("GOLEFT_TPU_COORDINATOR"):
        return "unprobed"  # distributed worlds manage their own backend
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        # host explicitly requested — but some accelerator plugins
        # force-override this env var, so honor the intent through the
        # config API (the only pin that sticks) instead of trusting it
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:  # backend already up — leave it
            pass
        return "unprobed"
    # cache a recent success: healthy hosts must not pay child bring-up
    # + settle on every CLI invocation (GOLEFT_TPU_PROBE_TTL_SECONDS
    # overrides; 0 disables probe caching entirely).
    ttl = _env_float("GOLEFT_TPU_PROBE_TTL_SECONDS", 300.0)
    # failures cache too, with their own (shorter) TTL: in a wedged-
    # tunnel environment every CLI invocation would otherwise hang for
    # the full probe timeout before degrading — 10 commands = 5 wasted
    # minutes. The cost is up to fail-TTL of host-mode runs after the
    # device RECOVERS, which the warning states. Defaults to 0 (off)
    # when the main TTL knob disables caching, unless its own knob
    # (GOLEFT_TPU_PROBE_FAIL_TTL_SECONDS) is set explicitly.
    fail_ttl = _env_float("GOLEFT_TPU_PROBE_FAIL_TTL_SECONDS",
                          120.0 if ttl > 0 else 0.0)
    cache = _probe_cache_path()
    fail_cache = _probe_cache_path("fail")
    rec = None
    if probe_argv is None:
        import time

        if ttl > 0:
            try:
                if time.time() - os.path.getmtime(cache) < ttl:
                    return "device"
            except OSError:
                pass
        if fail_ttl > 0:
            try:
                age = time.time() - os.path.getmtime(fail_cache)
                if age < fail_ttl:
                    rec = {"error": f"probe failed {age:.0f}s ago "
                                    "(cached; set GOLEFT_TPU_PROBE_"
                                    "FAIL_TTL_SECONDS=0 to re-probe "
                                    "every run)"}
            except OSError:
                pass
    if rec is None:
        rec = probe_device(argv=probe_argv)
        if rec["ok"]:
            if probe_argv is None:
                try:
                    os.remove(fail_cache)  # recovered — forget failures
                except OSError:
                    pass
                if ttl > 0:
                    _touch(cache)
            return "device"
        # only cache failures that mean "the DEVICE is unusable" —
        # a spawn failure (fork/ENOMEM) is about this host's moment,
        # and pinning 120s of host mode on it would be wrong
        if (fail_ttl > 0 and probe_argv is None
                and not str(rec.get("error", "")).startswith(
                    "spawn failed")):
            _touch(fail_cache)
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception as e:  # backend already initialized — leave it
        log.warning(
            "accelerator probe failed (%s) but the jax backend is "
            "already initialized (%s) — cannot fall back",
            rec.get("error"), e)
        return "unprobed"
    log.warning(
        "accelerator unusable (%s) — running on the host CPU instead; "
        "set GOLEFT_TPU_PROBE=0 to skip this probe or GOLEFT_TPU_CPU=1 "
        "to always pin the host", rec.get("error"))
    return "host"


def devices_with_watchdog(seconds: float | None = None):
    """``jax.devices()`` with a hang warning: if backend bring-up takes
    longer than ``seconds``, log what is probably wrong and how to
    escape (GOLEFT_TPU_CPU=1), while the attempt continues."""
    import jax

    deadline = WATCHDOG_SECONDS if seconds is None else seconds
    done = threading.Event()

    def _warn():
        if not done.wait(deadline):
            log.warning(
                "accelerator bring-up has taken >%.0fs — the device "
                "backend or its tunnel may be down. Rerun with "
                "GOLEFT_TPU_CPU=1 to execute on the host CPU instead.",
                deadline,
            )

    t = threading.Thread(target=_warn, daemon=True)
    t.start()
    try:
        return jax.devices()
    finally:
        done.set()
        # the wait() returns the moment done is set, so this join is
        # immediate — and without it the warn thread could outlive the
        # call, firing a stale hang warning into a caller that already
        # got its devices (gtlint thr-unjoined)
        t.join(timeout=5.0)
