"""Device bring-up guard: escape hatch + hang diagnostics.

The accelerator may sit behind a tunnel (this dev environment) or a
driver that can wedge; a product CLI must never hang silently on
backend bring-up with no way out. Two mechanisms:

- ``GOLEFT_TPU_CPU=1`` pins the jax platform to CPU before any backend
  initializes (``maybe_force_cpu`` runs at CLI dispatch). Every tool
  runs correctly on host — slower, never stuck.
- ``devices_with_watchdog()`` wraps the first device discovery: if
  bring-up exceeds the deadline, a warning names the likely cause and
  the escape hatch while the attempt continues (the reference's analog
  is its red shard-failure banner — failures must be loud and
  actionable, depth/depth.go:396-399).
"""

from __future__ import annotations

import logging
import os
import threading

log = logging.getLogger("goleft-tpu.device")

def _watchdog_seconds() -> float:
    raw = os.environ.get("GOLEFT_TPU_DEVICE_WATCHDOG_SECONDS", "30")
    try:
        v = float(raw)
    except ValueError:
        log.warning(
            "ignoring malformed GOLEFT_TPU_DEVICE_WATCHDOG_SECONDS=%r",
            raw)
        return 30.0
    return v if v > 0 else 30.0


WATCHDOG_SECONDS = _watchdog_seconds()


def maybe_force_cpu() -> bool:
    """Pin the jax platform to CPU when GOLEFT_TPU_CPU is set. Must run
    before any jax backend initializes; returns True when pinned.
    Failure to honor an explicitly-set knob is LOUD — the user set it
    because the device is wedged."""
    if not os.environ.get("GOLEFT_TPU_CPU"):
        return False
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception as e:  # backend already up — nothing safe to do
        log.warning(
            "GOLEFT_TPU_CPU=1 set but the jax backend is already "
            "initialized (%s) — execution may still target the "
            "accelerator", e)
        return False
    return True


def devices_with_watchdog(seconds: float | None = None):
    """``jax.devices()`` with a hang warning: if backend bring-up takes
    longer than ``seconds``, log what is probably wrong and how to
    escape (GOLEFT_TPU_CPU=1), while the attempt continues."""
    import jax

    deadline = WATCHDOG_SECONDS if seconds is None else seconds
    done = threading.Event()

    def _warn():
        if not done.wait(deadline):
            log.warning(
                "accelerator bring-up has taken >%.0fs — the device "
                "backend or its tunnel may be down. Rerun with "
                "GOLEFT_TPU_CPU=1 to execute on the host CPU instead.",
                deadline,
            )

    t = threading.Thread(target=_warn, daemon=True)
    t.start()
    try:
        return jax.devices()
    finally:
        done.set()
