"""Dtype policy: float64 where the backend supports it, float32 on TPU.

Tests run on CPU with x64 enabled so EM kernels verify exactly against
float64 oracles; on TPU (no native f64) the same kernels run in float32
— the reference's own EM math is float64 for λ but float32 depths, and
the CN outputs are integer-stable well beyond f32 precision for real
coverage data.
"""

from __future__ import annotations

import numpy as np


def preferred_float():
    import jax

    if jax.config.jax_enable_x64 and jax.default_backend() == "cpu":
        return np.float64
    return np.float32
