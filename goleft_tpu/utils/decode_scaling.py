"""Decode-thread scaling measurement shared by the bench and tests.

The cohort engine's native calls release the GIL, so per-sample window
reductions scale across decode threads on multi-core hosts (the
reference's equivalent is its process pool, depth/depth.go:392-394).
``measure_scaling`` runs that claim: N concurrent ``window_reduce``
calls on distinct mmap-backed files vs the same calls serial.
bench.py records the numbers in BENCH_details.json;
tests/test_thread_scaling.py asserts them.
"""

from __future__ import annotations

import concurrent.futures as cf
import os
import shutil
import time

import numpy as np


def effective_cores() -> int:
    """Affinity/cgroup-aware core count (a container pinned to 1 CPU on
    a 64-core host must count as 1)."""
    try:
        return len(os.sched_getaffinity(0))
    except AttributeError:  # pragma: no cover - non-Linux
        return os.cpu_count() or 1


def build_cohort(tmp_dir, n_files: int = 4, ref_len: int = 2_000_000,
                 coverage: int = 4, read_len: int = 100):
    """Fabricate ``n_files`` identical single-chromosome BAMs+BAIs."""
    from ..io.bam import BamWriter
    from ..io.bai import build_bai, write_bai

    n_reads = ref_len * coverage // read_len
    rng = np.random.default_rng(5)
    starts = np.sort(rng.integers(0, ref_len - read_len, size=n_reads))
    base = os.path.join(str(tmp_dir), "s0.bam")
    with open(base, "wb") as fh:
        with BamWriter(
            fh, "@HD\tVN:1.6\tSO:coordinate\n@SQ\tSN:chr1\tLN:"
            f"{ref_len}\n", ["chr1"], [ref_len], level=1,
        ) as w:
            for i, s in enumerate(starts):
                w.write_record(0, int(s), [(read_len, 0)], mapq=60,
                               name=f"r{i}")
    write_bai(build_bai(base), base + ".bai")
    paths = [base]
    for i in range(1, n_files):
        p = os.path.join(str(tmp_dir), f"s{i}.bam")
        shutil.copyfile(base, p)
        shutil.copyfile(base + ".bai", p + ".bai")
        paths.append(p)
    return paths, ref_len


def _timed(fn) -> float:
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def measure_scaling(paths, ref_len: int, window: int = 500,
                    repeats: int = 2):
    """(serial_seconds, threaded_seconds, n_tasks) for one full-region
    reduce per file, best-of-``repeats`` — the two-point special case
    of :func:`measure_scaling_curve`."""
    if not len(paths):
        # without the guard this times the serial pass twice and then
        # dies with an opaque KeyError(0) on curve[len(paths)]
        raise ValueError("measure_scaling: paths is empty — need at "
                         "least one BAM to measure decode scaling")
    curve = measure_scaling_curve(paths, ref_len, window, repeats,
                                  thread_counts=[1, len(paths)])
    return curve[1], curve[len(paths)], len(paths)


def default_thread_counts(cores: int | None = None, n_tasks: int = 4):
    """Worker counts worth measuring on this host: 1, the core count,
    the midpoint, one oversubscribed point (capped by tasks — more
    workers than tasks measures nothing) and the full task width (the
    historical bench point, kept so threaded_over_serial stays
    comparable across rounds)."""
    cores = effective_cores() if cores is None else cores
    cand = {1, min(2, n_tasks), min(cores, n_tasks),
            min(2 * cores, n_tasks), n_tasks}
    return sorted(cand)


def measure_scaling_curve(paths, ref_len: int, window: int = 500,
                          repeats: int = 2, thread_counts=None):
    """Speedup-vs-workers curve: {n_workers: best_seconds} for one
    full-region reduce per file under an ``n_workers``-thread pool
    (n=1 is the serial wall). The analog the reference tunes with its
    process pool (depth/depth.go:392-394); on a 1-core host the curve
    is flat-plus-overhead, on multi-core it must fall toward
    serial/min(workers, cores)."""
    from ..io.bam import BamFile

    if not len(paths):
        raise ValueError("measure_scaling_curve: paths is empty — "
                         "need at least one BAM to measure decode "
                         "scaling")
    if thread_counts is None:
        thread_counts = default_thread_counts(n_tasks=len(paths))
    # handles (and their mmaps) are function-local: the reduce outputs
    # are fresh arrays, so nothing retains the mapped views past return
    handles = [BamFile.from_file(p, lazy=True) for p in paths]

    def reduce_one(h):
        return h.window_reduce(0, 0, ref_len, 0, ref_len, window,
                               2500, 1, 0x704)

    for h in handles:  # warm page cache + native lib
        reduce_one(h)

    curve = {}
    for n in thread_counts:
        if n <= 1:
            curve[1] = min(
                _timed(lambda: [reduce_one(h) for h in handles])
                for _ in range(repeats))
            continue
        with cf.ThreadPoolExecutor(max_workers=n) as ex:
            curve[n] = min(
                _timed(lambda: list(ex.map(reduce_one, handles)))
                for _ in range(repeats))
    return curve


def optimal_threads(curve: dict) -> int:
    """The worker count a cohort run should use: fastest point of the
    measured curve; ties break toward FEWER threads (less memory, less
    churn)."""
    return min(sorted(curve), key=lambda n: (curve[n], n))


def auto_processes(cap: int = 8) -> int:
    """Affinity-aware default worker count for decode pools: one per
    effective core, capped. On a 1-core host this is 1, which routes
    the cohort engine onto its serial path (no thread churn)."""
    return max(1, min(cap, effective_cores()))
