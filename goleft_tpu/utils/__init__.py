from .xopen import xopen  # noqa: F401
