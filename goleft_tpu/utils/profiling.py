"""Profiling/observability: JAX profiler traces + per-stage wall clocks.

The reference's only profiling hook is an unconditional CPU pprof dump in
the cnveval CLI (cnveval/cmd/cnveval/cnveval.go:41-46, SURVEY.md §5); the
TPU rebuild gets first-class hooks: a ``trace(dir)`` context manager
around any pipeline (view with TensorBoard / xprof) and a ``StageTimer``
whose report shows where host decode vs device compute time goes.
"""

from __future__ import annotations

import contextlib
import logging
import time
from collections import defaultdict

log = logging.getLogger("goleft-tpu.profile")


@contextlib.contextmanager
def trace(trace_dir: str | None):
    """jax.profiler trace context; no-op when trace_dir is falsy."""
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(trace_dir):
        yield
    log.info("profiler trace written to %s", trace_dir)


class StageTimer:
    """Accumulating wall-clock timers keyed by stage name."""

    def __init__(self):
        self.totals: dict[str, float] = defaultdict(float)
        self.counts: dict[str, int] = defaultdict(int)

    @contextlib.contextmanager
    def stage(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.totals[name] += time.perf_counter() - t0
            self.counts[name] += 1

    def report(self) -> str:
        lines = []
        for name in sorted(self.totals, key=self.totals.get, reverse=True):
            lines.append(
                f"{name:<24} {self.totals[name]:8.3f}s "
                f"({self.counts[name]} calls)"
            )
        return "\n".join(lines)

    def log_report(self) -> None:
        for line in self.report().splitlines():
            log.info("%s", line)
