"""Profiling/observability: JAX profiler traces + per-stage wall clocks.

The reference's only profiling hook is an unconditional CPU pprof dump in
the cnveval CLI (cnveval/cmd/cnveval/cnveval.go:41-46, SURVEY.md §5); the
TPU rebuild gets first-class hooks: a ``trace(dir)`` context manager
around any pipeline (view with TensorBoard / xprof) and a ``StageTimer``
whose report shows where host decode vs device compute time goes.

``StageTimer`` is now a compatibility shim over the unified tracing
subsystem (:mod:`goleft_tpu.obs`): every ``stage`` use still feeds the
local totals/counts/spans this module always kept, AND records a real
hierarchical span on the process tracer — so a ``--trace-out`` run
shows the same stages on the Perfetto timeline that ``--profile``
logs as totals, in the right parent/thread rows.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import defaultdict, deque

from ..obs import get_tracer
from ..obs.logging import get_logger

log = get_logger("profile")


@contextlib.contextmanager
def trace(trace_dir: str | None):
    """jax.profiler trace context; no-op when trace_dir is falsy."""
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(trace_dir):
        yield
    log.info("profiler trace written to %s", trace_dir)


class StageTimer:
    """Accumulating wall-clock timers keyed by stage name.

    Thread-safe: the prefetch staging pipeline records spans from
    decode-pool worker threads concurrently with the consumer's compute
    spans. Every ``stage`` use also appends a ``(name, t0, t1)`` span
    (perf_counter seconds) so overlap between stages can be measured,
    not just per-stage totals — and mirrors the same interval onto the
    process tracer (:mod:`goleft_tpu.obs`), where it lands under the
    caller's current trace/span context.

    The span list is a RING: a long-lived holder (the serve daemon
    keeps one timer for its whole life) retains only the most recent
    ``max_spans`` intervals, counting evictions in ``spans_dropped``.
    ``totals``/``counts`` are unaffected by the bound — they accumulate
    forever — and ``wall()`` measures the retained window's extent.
    """

    def __init__(self, max_spans: int = 8192):
        self.totals: dict[str, float] = defaultdict(float)
        self.counts: dict[str, int] = defaultdict(int)
        self.spans: deque[tuple[str, float, float]] = \
            deque(maxlen=max_spans)
        self.spans_dropped = 0
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def stage(self, name: str):
        with get_tracer().span(name, category="stage"):
            t0 = time.perf_counter()
            try:
                yield
            finally:
                t1 = time.perf_counter()
                with self._lock:
                    self.totals[name] += t1 - t0
                    self.counts[name] += 1
                    if len(self.spans) == self.spans.maxlen:
                        self.spans_dropped += 1
                    self.spans.append((name, t0, t1))

    def as_dict(self, ndigits: int = 4) -> dict:
        """{stage: {"seconds", "calls"}} snapshot for bench artifacts."""
        with self._lock:
            return {
                name: {
                    "seconds": round(self.totals[name], ndigits),
                    "calls": self.counts[name],
                }
                for name in sorted(self.totals)
            }

    def wall(self) -> float:
        """Span-extent wall clock: last span end minus first span start
        over the RETAINED ring (0.0 when nothing was recorded)."""
        with self._lock:
            if not self.spans:
                return 0.0
            return (max(t1 for _, _, t1 in self.spans)
                    - min(t0 for _, t0, _ in self.spans))

    def report(self) -> str:
        lines = []
        for name in sorted(self.totals, key=self.totals.get, reverse=True):
            lines.append(
                f"{name:<24} {self.totals[name]:8.3f}s "
                f"({self.counts[name]} calls)"
            )
        return "\n".join(lines)

    def log_report(self) -> None:
        for line in self.report().splitlines():
            log.info("%s", line)


def percentiles(values, qs=(50, 95, 99), ndigits: int = 4) -> dict:
    """{"p50": ..., "p95": ..., "p99": ..., "max": ..., "count": n}
    nearest-rank percentiles over a sequence of seconds — the latency
    summary the serve daemon's /metrics endpoint, the obs registry's
    histograms and the bench's serve_throughput entry all share.
    Empty input returns {"count": 0} (no fabricated zeros)."""
    vals = sorted(float(v) for v in values)
    out: dict = {"count": len(vals)}
    if not vals:
        return out
    import math

    for q in qs:
        rank = max(1, min(len(vals), math.ceil(q / 100.0 * len(vals))))
        out[f"p{q:g}"] = round(vals[rank - 1], ndigits)
    out["max"] = round(vals[-1], ndigits)
    return out


def overlap_efficiency(timer: StageTimer, wall: float | None = None,
                       compute_stage: str = "compute") -> float | None:
    """How much of the non-compute pipeline work was hidden behind
    ``compute_stage``, in [0, 1].

    With per-stage totals summing to T and a measured wall clock W, the
    pipeline hid ``T - W`` seconds of work by overlapping stages; the
    maximum hideable is the total of every stage except compute (a
    perfectly overlapped pipeline's wall equals its compute total,
    assuming compute dominates). Returns None when nothing hideable was
    recorded (no producer-side spans). ``wall`` defaults to the timer's
    span extent.
    """
    totals = dict(timer.totals)
    hideable = sum(v for k, v in totals.items() if k != compute_stage)
    if hideable <= 0.0:
        return None
    if wall is None:
        wall = timer.wall()
    hidden = sum(totals.values()) - wall
    return max(0.0, min(1.0, hidden / hideable))
