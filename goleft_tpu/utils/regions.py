"""Interval sets for BED overlap queries.

Replaces the reference's biogo interval tree usage (depth/intervals.go:
25-79) with sorted start/end arrays + binary search — the same O(log n)
query without a tree, and trivially vectorizable.
"""

from __future__ import annotations

import numpy as np

from .xopen import xopen


class IntervalSet:
    """Static set of (start, end) intervals supporting overlap queries."""

    def __init__(self, starts, ends):
        order = np.argsort(starts, kind="stable")
        self.starts = np.asarray(starts, dtype=np.int64)[order]
        self.ends = np.asarray(ends, dtype=np.int64)[order]
        # running max of ends lets a single binary search bound the scan
        self.max_ends = np.maximum.accumulate(self.ends)

    def overlaps(self, start: int, end: int) -> bool:
        i = int(np.searchsorted(self.starts, end, side="left"))
        if i == 0:
            return False
        return bool(self.max_ends[i - 1] > start)


def read_tree(path: str) -> dict[str, IntervalSet]:
    """BED file → per-chromosome IntervalSet (depth/intervals.go:42-62)."""
    per: dict[str, list] = {}
    with xopen(path) as fh:
        for line in fh:
            line = line.strip()
            if not line or line.startswith(("#", "track")):
                continue
            t = line.split("\t")
            per.setdefault(t[0], []).append((int(t[1]), int(t[2])))
    return {
        c: IntervalSet([s for s, _ in iv], [e for _, e in iv])
        for c, iv in per.items()
    }


def overlaps(tree: dict[str, IntervalSet] | None, chrom: str, start: int,
             end: int) -> bool:
    if tree is None:
        return False
    ivs = tree.get(chrom)
    return ivs.overlaps(start, end) if ivs is not None else False
