"""Transparent text/gzip/bgzip file IO.

Covers the role of brentp/xopen in the reference (see SURVEY.md §2.4): every
subcommand reads/writes plain or (b)gzipped files through one helper.
"""

from __future__ import annotations

import gzip
import io
import sys


def _is_gzip(path: str) -> bool:
    with open(path, "rb") as fh:
        return fh.read(2) == b"\x1f\x8b"


def xopen(path: str, mode: str = "r"):
    """Open ``path`` transparently.

    - "-" means stdin/stdout.
    - Reading: gzip is auto-detected from magic bytes (BGZF is a valid gzip
      stream, so .bam/.bed.gz both inflate correctly).
    - Writing: paths ending in .gz are gzip-compressed.
    """
    if path == "-":
        if "r" in mode:
            return sys.stdin if "b" not in mode else sys.stdin.buffer
        return sys.stdout if "b" not in mode else sys.stdout.buffer
    if "r" in mode:
        if _is_gzip(path):
            fh = gzip.open(path, "rb")
            if "b" in mode:
                return fh
            return io.TextIOWrapper(fh)
        return open(path, mode)
    if path.endswith(".gz"):
        fh = gzip.open(path, "wb")
        if "b" in mode:
            return fh
        return io.TextIOWrapper(fh)
    return open(path, mode)
