"""CNV calls → VCF 4.2.

The reference stops at tab text for its CNV prototypes (emdepth emits
`chrom start end sample CN` structs, dcnv a normalized bed); downstream
tooling (truvari, bcftools, IGV) speaks VCF, so the productized `cnv` /
`emdepth` commands can also emit symbolic-allele records
(`<DEL>`/`<DUP>` with END/SVLEN INFO and per-sample GT:CN:L2FC), one
record per distinct (chrom, start, end, svtype) event with every cohort
sample genotyped (non-carriers 0/0:2:.).

Reference parity note: no VCF writer exists in /root/reference — this is
a capability extension, mapped from emdepth's CNV struct
(emdepth/emdepth.go:330-346: chrom/start/end/sample/CN/log2FC).
"""

from __future__ import annotations

from ..utils.xopen import xopen

_HEADER_LINES = [
    "##ALT=<ID=DEL,Description=\"Deletion relative to the cohort "
    "median depth\">",
    "##ALT=<ID=DUP,Description=\"Duplication relative to the cohort "
    "median depth\">",
    "##INFO=<ID=SVTYPE,Number=1,Type=String,Description=\"CNV type "
    "(DEL or DUP)\">",
    "##INFO=<ID=END,Number=1,Type=Integer,Description=\"End of the "
    "event (1-based inclusive)\">",
    "##INFO=<ID=SVLEN,Number=1,Type=Integer,Description=\"Signed event "
    "length (negative for DEL)\">",
    "##INFO=<ID=NCARRIER,Number=1,Type=Integer,Description=\"Samples "
    "carrying this event\">",
    "##FORMAT=<ID=GT,Number=1,Type=String,Description=\"Genotype "
    "(0/1 het, 1/1 hom-del at CN 0; 0/0 non-carrier)\">",
    "##FORMAT=<ID=CN,Number=1,Type=Integer,Description=\"Median EM "
    "copy number over the event's windows (2 on a carrier marks a "
    "mixed-direction merged run; see L2FC)\">",
    "##FORMAT=<ID=L2FC,Number=1,Type=Float,Description=\"Mean log2 "
    "fold change over the event's windows\">",
]


class _BgzfText:
    """Minimal text façade over the streaming BGZF writer."""

    def __init__(self, path: str):
        from ..io.bgzf import BgzfWriter

        self._raw = open(path, "wb")
        self._w = BgzfWriter(self._raw)

    def write(self, s: str) -> None:
        self._w.write(s.encode("utf-8"))

    def close(self) -> None:
        self._w.close()
        self._raw.close()


def _gt(cn: int) -> str:
    if cn == 0:
        return "1/1"
    return "0/1"  # het del (CN1) and any gain both carry one alt allele


def write_cnv_vcf(path_or_fh, calls, samples, contig_lengths=None,
                  source: str = "goleft-tpu cnv",
                  ref_fasta: str | None = None,
                  ref_fai: str | None = None):
    """Write CNV ``calls`` as a multi-sample VCF.

    ``calls``: iterable of (chrom, start, end, sample, cn, log2fc) —
    exactly what :func:`commands.emdepth_cmd.call_cnvs` returns, with
    0-based half-open [start, end) coordinates. ``samples`` fixes the
    column order (every cohort sample appears, carrier or not).
    ``contig_lengths``: optional {chrom: length} for ##contig headers;
    chroms seen only in calls still get an ID-only ##contig line.
    ``ref_fasta``: when given, symbolic-allele records are anchored per
    the VCF 4.2 padding-base convention — POS is the base preceding the
    event and REF is the actual reference base there; without it, POS
    is the first altered base with REF=N (accepted by bcftools/truvari/
    IGV but flagged by strict validators), and the header records which
    convention is in effect either way. ``ref_fai`` points Faidx at a
    user-supplied index; anchoring is best-effort — an unreadable
    fasta/index downgrades to the no-fasta convention rather than
    failing the write after the whole pipeline has run.
    Returns the number of VCF records written.
    """
    fx = None
    if ref_fasta:
        from ..io.fai import Faidx

        try:
            fx = Faidx(ref_fasta, fai_path=ref_fai)
        except Exception:  # noqa: BLE001 — anchoring is best-effort
            fx = None
    samples = list(samples)
    col = {s: i for i, s in enumerate(samples)}
    # group per-sample calls into events keyed by locus + direction
    events: dict[tuple, list] = {}
    chrom_order: list[str] = []
    for chrom, start, end, sample, cn, fc in calls:
        if chrom not in chrom_order:
            chrom_order.append(chrom)
        # the 30kb merge can blend a sample's DEL and DUP runs into one
        # call whose MEDIAN CN rounds to 2 (models/emdepth.py Cache) —
        # classify those by the fold-change sign instead of mislabeling
        # a depth loss as <DUP>
        if cn < 2 or (cn == 2 and fc < 0):
            svtype = "DEL"
        else:
            svtype = "DUP"
        events.setdefault((chrom, int(start), int(end), svtype),
                          []).append((sample, int(cn), float(fc)))

    own = isinstance(path_or_fh, str)
    if own and path_or_fh.endswith(".gz"):
        # BGZF, not plain gzip: the named consumers (bcftools index,
        # tabix, IGV) require bgzip-compressed .vcf.gz; BGZF is still a
        # valid gzip stream for everything else
        fh = _BgzfText(path_or_fh)
    elif own:
        fh = xopen(path_or_fh, "w")
    else:
        fh = path_or_fh
    try:
        fh.write("##fileformat=VCFv4.2\n")
        fh.write(f"##source={source}\n")
        fh.write("##cnv_pos_convention=" + (
            "padding-base (POS/REF anchor the reference base preceding "
            "the event per VCF 4.2; events without a resolvable "
            "A/C/G/T anchor — telomeric start, contig absent from the "
            "fasta, or an N-gap anchor base — fall back to REF=N at "
            "the first altered base)" if fx else
            "first-altered-base with REF=N (no reference fasta "
            "consulted; bcftools/truvari/IGV accept this, strict "
            "validators may flag REF)") + "\n")
        contigs = dict(contig_lengths or {})
        for c in chrom_order:
            contigs.setdefault(c, None)
        for c, ln in contigs.items():
            if ln:
                fh.write(f"##contig=<ID={c},length={int(ln)}>\n")
            else:
                fh.write(f"##contig=<ID={c}>\n")
        for line in _HEADER_LINES:
            fh.write(line + "\n")
        fh.write("#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\t"
                 "FORMAT\t" + "\t".join(samples) + "\n")
        n = 0
        order = {c: i for i, c in enumerate(chrom_order)}
        for key in sorted(events, key=lambda k: (order[k[0]], k[1],
                                                 k[2], k[3])):
            chrom, start, end, svtype = key
            carriers = events[key]
            fields = ["0/0:2:."] * len(samples)
            for sample, cn, fc in carriers:
                fields[col[sample]] = f"{_gt(cn)}:{cn}:{fc:.3f}"
            svlen = end - start
            if svtype == "DEL":
                svlen = -svlen
            # padding-base anchoring when the reference is available
            # (ADVICE r3: strict validators flag REF=N at the first
            # altered base); END stays the 1-based inclusive event end
            # under both conventions
            pos1, refb = start + 1, "N"
            if fx is not None and start > 0 and chrom in fx.records:
                try:
                    b = fx.fetch(chrom, start - 1, start).decode(
                        "ascii", "replace").upper()
                except OSError:
                    b = ""
                if b in ("A", "C", "G", "T"):
                    pos1, refb = start, b
            fh.write(
                f"{chrom}\t{pos1}\t"
                f"{svtype}_{chrom}_{start + 1}_{end}\t{refb}\t"
                f"<{svtype}>\t"
                f".\tPASS\tSVTYPE={svtype};END={end};SVLEN={svlen};"
                f"NCARRIER={len(carriers)}\tGT:CN:L2FC\t"
                + "\t".join(fields) + "\n"
            )
            n += 1
        return n
    finally:
        if fx is not None:
            fx.close()
        if own:
            fh.close()
