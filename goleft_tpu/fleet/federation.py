"""Federation tier: one thin process in front of N fleets.

PR 10 made a single fleet self-healing and PR 13 made it observable,
but the fleet's router is still a single point of failure and a single
saturation domain. This module is the next rung up: a stdlib,
jax-free **FederationRouter** that fronts N fleets (each a supervised
``goleft-tpu fleet`` with its own router and workers) the same way a
fleet router fronts N workers — :class:`~goleft_tpu.fleet.router
.HashRing` reused one level up, with the affinity key unchanged
(:func:`~goleft_tpu.fleet.router.request_affinity_key` on input file
identity), so a file's WHOLE serving path — fleet, worker, shared
cache, jitted programs — stays warm per fleet. Three robustness
behaviors layer on top:

  - **whole-fleet failover**: a connection-level forward failure or
    ``down_after`` consecutive poll failures marks a fleet DOWN;
    in-flight and new requests retry the next ring candidate
    (byte-identically — every workload is a deterministic content-
    keyed computation, so replay on a sibling fleet is safe by
    construction). A fleet that heals rejoins through a HALF-OPEN
    probe, like the per-endpoint circuit breakers: once its healthz
    answers again it may serve exactly one in-flight request; success
    restores it, failure sends it straight back down. Losing an
    entire fleet (router included) degrades capacity, never
    availability.
  - **saturation spillover**: each fleet's polled ``/fleet/metrics``
    ``slo.burn_rate_max`` (the PR-13 rollup gauge) is the routing
    signal. A fleet burning past ``spill_threshold`` stops receiving
    NEW affinity keys — keys already homed there keep landing (cache
    warmth is the point of affinity) until it recovers or trips
    fully. Spilled keys are tagged with their ring home so they
    MIGRATE back the moment the home fleet is up and under threshold
    (``federation.spill_migrations_total``).
  - **tenant-scoped overload isolation as a contract**: the
    federation computes per-tenant burn rates — its own windowed
    per-tenant outcomes (latency vs the p99 target; 5xx and 429
    outcomes against the error budget) merged with the per-tenant
    ``slo.tenants`` blocks the fleets roll up from their workers —
    published as ``federation.tenant.burn_rate.<tenant>`` gauges in
    BOTH /metrics encodings. A tenant whose burn rate breaches
    ``tenant_burn_threshold`` has its BEST-EFFORT traffic
    (``priority > 0``) shed with 429 + an honest ``retry_after_s``
    (when the breaching outcomes age out of the window), while every
    other tenant's traffic is untouched — isolation by contract, not
    by side effect.

Cross-FLEET tracing composes the PR-13 graft rules: the federation
opens ``federation.request.*`` / per-attempt ``federation.forward.*``
spans, forwards ``x-goleft-trace`` with the forward span id, and
``GET /fleet/trace/<id>`` pulls each fleet's own stitched document
and grafts it under the forward that carried it
(:func:`~goleft_tpu.obs.fleetplane.stitch_federation`) — a federation
hop is one more ``remote_parent`` level, and ``goleft-tpu trace``
renders client → federation → fleet router → worker as one tree. The
poller runs the same midpoint clock handshake against fleet routers
that fleet routers run against workers.

Routes mirror the fleet router so every existing client works
unchanged: ``POST /v1/<kind>``, ``GET /healthz``, ``GET /metrics``
(JSON default, ``?format=prom`` Prometheus), ``GET /fleet/trace/<id>``
(the federation-wide stitched trace), ``POST /fleet/plan`` (debug:
the candidate FLEET order for a body).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from collections import OrderedDict, deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .. import obs
from ..obs.fleetplane import (
    TRACE_HEADER, format_trace_header, merge_tenant_slos,
    parse_trace_header, perfetto_export, poll_jitter_frac,
    stitch_federation,
)
from ..obs.logging import get_logger
from ..obs.metrics import MetricsRegistry
from .admission import QuotaExceeded
from .router import HashRing, request_affinity_key

log = get_logger("fleet.federation")

#: fleet states (the half-open probe machine, breaker-shaped)
UP = "up"
DOWN = "down"
PROBE = "probe"

#: most affinity keys tracked for home/spill bookkeeping; beyond this
#: the least-recently-routed key is forgotten (it re-resolves from the
#: ring on its next request, which is exactly the cold behavior)
MAX_TRACKED_KEYS = 8192


class TenantSLOTracker:
    """Per-tenant outcome windows at the federation tier.

    Each FORWARDED request lands in its tenant's bounded window as
    (timestamp, burned, latency). "Burned" means 5xx or 429 — a
    throttled tenant is spending its own budget, which is the signal
    tenant-scoped shedding isolates on. Federation-shed responses are
    deliberately NOT recorded: feeding the shed's own 429s back into
    the burn rate would latch the shed open forever.

    ``snapshot()`` returns the same per-tenant shape workers publish
    (``window_requests``/``error_rate``/``p99_latency_ratio``), so the
    federation's own evidence merges with the fleets' rollups through
    one code path (:func:`~goleft_tpu.obs.fleetplane
    .merge_tenant_slos`)."""

    def __init__(self, window_s: float = 300.0,
                 p99_target_s: float = 2.0, max_tenants: int = 64,
                 maxlen: int = 1024, clock=time.monotonic):
        self.window_s = window_s
        self.p99_target_s = p99_target_s
        self.max_tenants = max_tenants
        self.maxlen = maxlen
        self._clock = clock
        self._lock = threading.Lock()
        self._outcomes: dict[str, deque] = {}

    def record(self, tenant: str, code: int,
               seconds: float | None = None) -> None:
        burned = code >= 500 or code == 429
        with self._lock:
            dq = self._outcomes.get(tenant)
            if dq is None:
                while len(self._outcomes) >= self.max_tenants:
                    stale = min(
                        self._outcomes,
                        key=lambda t: self._outcomes[t][-1][0]
                        if self._outcomes[t] else 0.0)
                    del self._outcomes[stale]
                dq = self._outcomes[tenant] = deque(
                    maxlen=self.maxlen)
            dq.append((self._clock(), burned, seconds))

    def snapshot(self) -> dict:
        now = self._clock()
        with self._lock:
            items = [(t, list(dq))
                     for t, dq in self._outcomes.items()]
        out: dict = {}
        for tenant, rows in sorted(items):
            recent = [(burned, sec) for ts, burned, sec in rows
                      if now - ts <= self.window_s]
            if not recent:
                continue
            n = len(recent)
            errs = sum(1 for burned, _ in recent if burned)
            rec = {"window_requests": n,
                   "error_rate": round(errs / n, 6)}
            lats = [s for _, s in recent if s is not None]
            if lats and self.p99_target_s > 0:
                from ..utils.profiling import percentiles

                rec["p99_latency_ratio"] = round(
                    percentiles(lats)["p99"] / self.p99_target_s, 4)
            out[tenant] = rec
        return out

    def burn_clear_s(self, tenant: str) -> float:
        """Seconds until this tenant's OLDEST burned outcome ages out
        of the window — the honest half of a shed's retry_after_s
        (the burn rate cannot improve before the evidence expires)."""
        now = self._clock()
        with self._lock:
            rows = list(self._outcomes.get(tenant) or ())
        burned_ts = [ts for ts, burned, _ in rows
                     if burned and now - ts <= self.window_s]
        if not burned_ts:
            return 0.0
        return max(0.0, self.window_s - (now - min(burned_ts)))


class _Fleet:
    """Mutable polled state for one fleet (lock: the pool's)."""

    def __init__(self, url: str):
        self.url = url.rstrip("/")
        self.state = UP          # optimistic until a poll says no
        self.probing = False     # one in-flight half-open probe
        self.consecutive_fails = 0
        self.healthy_workers = 0
        self.burn_rate: float | None = None   # slo.burn_rate_max
        self.saturated = False   # burn_rate > spill_threshold
        self.tenants: dict = {}  # the fleet rollup's slo.tenants
        self.last_metrics: dict | None = None
        self.clock_offset_s: float | None = None
        self.last_poll_s: float | None = None
        self.next_poll_at = 0.0


class FleetPool:
    """Polled fleet state + the poller thread (the WorkerPool pattern
    one level up: healthz for liveness, /fleet/metrics for the burn
    and tenant signals, deterministic per-fleet scrape phase)."""

    def __init__(self, urls: list[str], poll_interval_s: float = 2.0,
                 down_after: int = 2, timeout_s: float = 5.0,
                 spill_threshold: float = 0.0,
                 spill_recover: float | None = None,
                 registry: MetricsRegistry | None = None):
        self.fleets = {u.rstrip("/"): _Fleet(u) for u in urls}
        self.poll_interval_s = poll_interval_s
        self.down_after = down_after
        self.timeout_s = timeout_s
        self.spill_threshold = spill_threshold
        # two-sided spill hysteresis (the autoscaler's pattern): spill
        # when burn rises past spill_threshold, return home only once
        # it falls to/below spill_recover — a burn rate flapping in
        # the (recover, threshold] band keeps its current placement
        # instead of thrashing key migration. Default = threshold,
        # which reproduces the historical single-threshold behavior.
        self.spill_recover = spill_threshold \
            if spill_recover is None else min(spill_recover,
                                              spill_threshold)
        # called with the fleet URL after a half-open probe succeeds
        # (outside the pool lock) — the federation wires cache
        # replication's rejoin warm-up here
        self.on_rejoin = None
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        for f in self.fleets.values():
            self._schedule_first_poll(f)
        self._thread = threading.Thread(
            target=self._poll_loop, daemon=True,
            name="goleft-federation-poller")

    def _schedule_first_poll(self, f: _Fleet) -> None:
        f.next_poll_at = time.monotonic() + \
            poll_jitter_frac(f.url) * self.poll_interval_s

    def start(self) -> "FleetPool":
        self.poll_all()
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=10.0)

    # ---- polling ----

    def _fetch_json(self, url: str) -> dict:
        req = urllib.request.Request(
            url, headers={"Accept": "application/json"})
        with urllib.request.urlopen(req,
                                    timeout=self.timeout_s) as r:
            return json.loads(r.read().decode())

    def _poll_one(self, f: _Fleet) -> None:
        try:
            t0_wall = time.time()
            h = self._fetch_json(f.url + "/healthz")
            t1_wall = time.time()
            m = self._fetch_json(f.url + "/fleet/metrics")
        except Exception as e:  # noqa: BLE001 — any poll failure
            # (refused, reset, timeout, a 503-degraded fleet with zero
            # healthy workers) is a miss
            with self._lock:
                f.consecutive_fails += 1
                f.last_poll_s = time.monotonic()
                if f.consecutive_fails >= self.down_after \
                        and f.state != DOWN:
                    f.state = DOWN
                    f.probing = False
                    log.warning("federation: fleet %s marked DOWN "
                                "(%r)", f.url, e)
                    self.registry.counter(
                        "federation.fleet_down_total").inc()
            return
        slo = m.get("slo") or {}
        burn = slo.get("burn_rate_max")
        offset = None
        if isinstance(h.get("now"), (int, float)) \
                and not isinstance(h.get("now"), bool):
            offset = float(h["now"]) - (t0_wall + t1_wall) / 2.0
        with self._lock:
            f.consecutive_fails = 0
            if f.state == DOWN:
                # half-open: healthz answers again, but the keyspace
                # does not flood back — the next forwarded request is
                # the single probe that decides
                f.state = PROBE
                f.probing = False
                log.warning("federation: fleet %s healthz recovered "
                            "— half-open probe", f.url)
                self.registry.counter(
                    "federation.fleet_probe_total").inc()
            f.healthy_workers = int(h.get("healthy") or 0)
            f.burn_rate = burn if isinstance(burn, (int, float)) \
                else None
            if self.spill_threshold <= 0 or f.burn_rate is None:
                f.saturated = False
            elif f.burn_rate > self.spill_threshold:
                f.saturated = True
            elif f.burn_rate <= self.spill_recover:
                f.saturated = False
            # else: inside the (recover, threshold] band — hold the
            # previous saturation verdict (hysteresis, no thrash)
            f.tenants = slo.get("tenants") or {}
            if offset is not None:
                f.clock_offset_s = offset if f.clock_offset_s is None \
                    else 0.7 * f.clock_offset_s + 0.3 * offset
            f.last_metrics = m
            f.last_poll_s = time.monotonic()

    def poll_all(self) -> None:
        for f in list(self.fleets.values()):
            self._poll_one(f)

    def _due_fleets(self, now: float) -> list[_Fleet]:
        """Schedule reads under the pool lock — the same ``_Worker``
        discipline WorkerPool follows (gtlint lck-foreign-write): the
        fleet set is static today, but the field contract is "lock:
        the pool's" and the poller must not be the one exception."""
        with self._lock:
            return [f for f in self.fleets.values()
                    if f.next_poll_at <= now]

    def _advance_schedule(self, f: _Fleet) -> None:
        with self._lock:
            f.next_poll_at += self.poll_interval_s
            if f.next_poll_at <= time.monotonic():
                f.next_poll_at = time.monotonic() \
                    + self.poll_interval_s

    def _next_poll_due(self, default: float) -> float:
        with self._lock:
            return min((f.next_poll_at
                        for f in self.fleets.values()),
                       default=default)

    def _poll_loop(self) -> None:
        while not self._stop.is_set():
            now = time.monotonic()
            for f in self._due_fleets(now):
                self._poll_one(f)
                self._advance_schedule(f)
            nxt = self._next_poll_due(now + self.poll_interval_s)
            wait = min(self.poll_interval_s,
                       max(0.02, nxt - time.monotonic()))
            self._stop.wait(wait)

    # ---- forward outcomes (the half-open machine's verdicts) ----

    def mark_failed(self, url: str) -> None:
        """A forward died at the connection level: the fleet's router
        is gone (or unreachable) — take the whole fleet out NOW."""
        f = self.fleets.get(url.rstrip("/"))
        if f is None:
            return
        with self._lock:
            if f.state != DOWN:
                log.warning("federation: fleet %s marked DOWN "
                            "(connection failure mid-request)", f.url)
                self.registry.counter(
                    "federation.fleet_down_total").inc()
            f.state = DOWN
            f.probing = False
            f.consecutive_fails = max(f.consecutive_fails,
                                      self.down_after)

    def try_begin_forward(self, url: str) -> bool:
        """May a forward to this fleet proceed right now? UP: always.
        PROBE: exactly one in-flight probe at a time (the breaker's
        half-open discipline). DOWN: never."""
        f = self.fleets.get(url.rstrip("/"))
        if f is None:
            return False
        with self._lock:
            if f.state == UP:
                return True
            if f.state == PROBE and not f.probing:
                f.probing = True
                return True
            return False

    def settle_forward(self, url: str, ok: bool) -> None:
        """Deliver a forward's outcome to a probing fleet: any HTTP
        answer proves the fleet router alive (``ok=True`` — even a
        503 is an ANSWER; per-request retry handles its content), a
        connection failure went through :meth:`mark_failed`."""
        f = self.fleets.get(url.rstrip("/"))
        if f is None:
            return
        rejoined = False
        with self._lock:
            if f.state != PROBE:
                return
            f.probing = False
            if ok:
                f.state = UP
                rejoined = True
                log.warning("federation: fleet %s probe succeeded — "
                            "rejoined", f.url)
                self.registry.counter(
                    "federation.fleet_rejoin_total").inc()
        if rejoined and self.on_rejoin is not None:
            # outside the lock — and the hook itself must return
            # promptly: settle_forward runs on live request threads
            # as well as the poller, so a warm-up that does network
            # I/O has to happen on its own thread (sync_soon)
            try:
                self.on_rejoin(f.url)
            except Exception as e:  # noqa: BLE001 — hook is best-effort
                log.warning("federation: on_rejoin hook failed for "
                            "%s: %s", f.url, e)

    # ---- routing state ----

    def eligible(self) -> set[str]:
        """Fleets a request may be forwarded to right now (UP, plus
        PROBE fleets — the forward gate enforces the single-probe
        discipline)."""
        with self._lock:
            return {u for u, f in self.fleets.items()
                    if f.state in (UP, PROBE)}

    def spill_targets(self) -> set[str]:
        """Fleets that may receive NEW affinity keys: fully up and
        under the spill threshold (a probing fleet earns its keyspace
        back before it earns new keys)."""
        with self._lock:
            return {u for u, f in self.fleets.items()
                    if f.state == UP and not f.saturated}

    def saturated_fleets(self) -> set[str]:
        with self._lock:
            return {u for u, f in self.fleets.items() if f.saturated}

    def clock_offsets(self) -> dict[str, float]:
        with self._lock:
            return {u: f.clock_offset_s
                    for u, f in sorted(self.fleets.items())
                    if f.clock_offset_s is not None}

    def tenant_blocks(self) -> list[dict]:
        """Each live fleet's rolled-up ``slo.tenants`` block — the
        downstream half of the federation's tenant burn evidence."""
        with self._lock:
            return [dict(f.tenants) for _, f in
                    sorted(self.fleets.items()) if f.tenants]

    def snapshot(self) -> dict:
        with self._lock:
            return {
                u: {
                    "state": f.state,
                    "healthy_workers": f.healthy_workers,
                    "burn_rate": f.burn_rate,
                    "saturated": f.saturated,
                    "consecutive_fails": f.consecutive_fails,
                    "clock_offset_s": (
                        round(f.clock_offset_s, 6)
                        if f.clock_offset_s is not None else None),
                }
                for u, f in sorted(self.fleets.items())
            }


class FederationRouter:
    """Routing + tenant-isolation logic over N fleets, independent of
    any socket (tests drive it in-process,
    commands/federation.py serves it)."""

    def __init__(self, fleet_urls: list[str],
                 poll_interval_s: float = 2.0,
                 down_after: int = 2,
                 default_timeout_s: float = 120.0,
                 spill_threshold: float = 0.0,
                 spill_recover: float | None = None,
                 tenant_burn_threshold: float = 0.0,
                 tenant_shed_min_requests: int = 4,
                 error_budget: float = 0.01,
                 slo_p99_target_s: float = 2.0,
                 slo_window_s: float = 300.0,
                 vnodes: int = 64,
                 registry: MetricsRegistry | None = None,
                 flight_records: int = 64,
                 quotas: list[str] | None = None,
                 cache_sync_interval_s: float = 0.0):
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self.ring = HashRing(fleet_urls, vnodes=vnodes)
        self.pool = FleetPool(fleet_urls,
                              poll_interval_s=poll_interval_s,
                              down_after=down_after,
                              spill_threshold=spill_threshold,
                              spill_recover=spill_recover,
                              registry=self.registry)
        self.default_timeout_s = default_timeout_s
        self.spill_threshold = spill_threshold
        # federation-level admission: the fleet tier's token-bucket
        # table lifted to the front door, so a flooding tenant is
        # refused in ONE place instead of burning N fleets' budgets
        from .admission import QuotaTable

        self.quotas = QuotaTable(quotas)
        # cross-fleet cache replication (anti-entropy rounds over the
        # UP fleets + an immediate warm-up on half-open rejoin).
        # sync_soon, not sync_now: the hook fires from settle_forward
        # on a live request thread — an inline round (every
        # list/pull/push under its network timeout) would block that
        # client for the round's whole duration
        from .cachesync import CacheSync

        self.cache_sync = CacheSync(
            lambda: sorted(self.pool.eligible()),
            interval_s=cache_sync_interval_s,
            registry=self.registry)
        self.pool.on_rejoin = \
            lambda url: self.cache_sync.sync_soon("rejoin")
        self.tenant_burn_threshold = tenant_burn_threshold
        self.tenant_shed_min_requests = tenant_shed_min_requests
        self.error_budget = error_budget
        self.tenants = TenantSLOTracker(window_s=slo_window_s,
                                        p99_target_s=slo_p99_target_s)
        self.started = time.time()
        # affinity bookkeeping: where each key currently lands
        # (_homes) and, for keys routed away from a saturated home,
        # the ring home they migrate back to (_spilled ⊆ _homes keys)
        self._affinity_lock = threading.Lock()
        self._homes: OrderedDict[str, str] = OrderedDict()
        self._spilled: dict[str, str] = {}
        # the federation's own flight ring: federation.request.* trees
        # (root + per-attempt forward spans) — the top layer of every
        # stitched cross-fleet trace
        from ..serve.flight import FlightRecorder

        self.flight = FlightRecorder(max_records=flight_records)
        self._tracer = obs.get_tracer()
        self._tracer.add_listener(self.flight.on_span)

    def start(self) -> "FederationRouter":
        self.pool.start()
        self.cache_sync.start()
        return self

    def close(self) -> None:
        self.cache_sync.close()
        self.pool.close()
        self._tracer.remove_listener(self.flight.on_span)

    # ---- affinity + spillover ----

    def affinity_key(self, kind: str, req: dict) -> str:
        return request_affinity_key(kind, req)

    def _remember_home(self, key: str, url: str) -> None:
        # caller holds _affinity_lock
        self._homes[key] = url
        self._homes.move_to_end(key)
        while len(self._homes) > MAX_TRACKED_KEYS:
            old, _ = self._homes.popitem(last=False)
            self._spilled.pop(old, None)

    def resolve_target(self, kind: str, key: str) -> str:
        """The fleet this key should land on RIGHT NOW, applying the
        spillover contract: existing keys keep their home while it
        stands (even saturated — cache warmth), new keys avoid
        saturated fleets, spilled keys migrate home the moment the
        home recovers. Failover past the choice is the caller's
        per-request retry walk; it never rewrites the home."""
        order = self.ring.candidates(key)
        ring_home = order[0]
        spill_ok = self.pool.spill_targets()
        c = self.registry.counter
        with self._affinity_lock:
            origin = self._spilled.get(key)
            if origin is not None:
                if origin in spill_ok:
                    # the home fleet recovered: reclaim its key
                    del self._spilled[key]
                    self._remember_home(key, origin)
                    c("federation.spill_migrations_total").inc()
                    return origin
                cur = self._homes.get(key)
                if cur is not None:
                    self._homes.move_to_end(key)
                    return cur
            cur = self._homes.get(key)
            if cur is not None:
                self._homes.move_to_end(key)
                return cur
            # a NEW key: ring home unless it is saturated and a
            # non-saturated candidate exists to spill to
            if self.spill_threshold > 0 \
                    and ring_home not in spill_ok:
                target = next((u for u in order if u in spill_ok),
                              None)
                if target is not None and target != ring_home \
                        and ring_home in self.pool.eligible():
                    # spill only AROUND a saturated-but-alive home; a
                    # DOWN home is plain failover, not a spill
                    self._spilled[key] = ring_home
                    self._remember_home(key, target)
                    c("federation.spills_total").inc()
                    return target
            self._remember_home(key, ring_home)
            return ring_home

    def plan(self, kind: str, req: dict) -> list[str]:
        """Candidate FLEET order for this request: the spill-aware
        target first, then the ring walk (eligible fleets before
        ineligible, affinity preserved within each class)."""
        key = self.affinity_key(kind, req)
        order = self.ring.candidates(key)
        target = self.resolve_target(kind, key)
        rest = [u for u in order if u != target]
        ok = self.pool.eligible()
        return [target] \
            + [u for u in rest if u in ok] \
            + [u for u in rest if u not in ok]

    # ---- tenant-scoped burn ----

    def tenant_burn_rates(self) -> dict:
        """Per-tenant burn across the federation: the federation's own
        windowed outcomes merged with every fleet's rolled-up
        ``slo.tenants`` block, burn =
        ``max(p99_ratio, error_rate / error_budget)``. Publishes the
        ``federation.tenant.burn_rate.<tenant>`` gauges (the contract
        surface the shed decision — and the acceptance test — read)."""
        merged = merge_tenant_slos(
            [self.tenants.snapshot()] + self.pool.tenant_blocks(),
            self.error_budget)
        g = self.registry.gauge
        for tenant, rec in merged.items():
            g(f"federation.tenant.burn_rate.{tenant}").set(
                rec["burn_rate"])
        return merged

    def _maybe_shed_tenant(self, tenant: str, priority: int) \
            -> dict | None:
        """The tenant-isolation gate: shed this request (a 429 body)
        iff its tenant's burn rate breaches the threshold, the tenant
        has enough windowed evidence, and the request is best-effort
        (priority > 0 — interactive traffic is never shed here)."""
        if self.tenant_burn_threshold <= 0 or priority <= 0:
            return None
        rec = self.tenant_burn_rates().get(tenant)
        if rec is None \
                or rec["burn_rate"] <= self.tenant_burn_threshold \
                or rec["window_requests"] \
                < self.tenant_shed_min_requests:
            return None
        self.registry.counter(
            f"federation.tenant_shed_total.{tenant}").inc()
        retry_after = min(30.0, max(
            1.0, self.tenants.burn_clear_s(tenant)))
        return {
            "error": f"tenant {tenant!r} burn rate "
                     f"{rec['burn_rate']:g} exceeds "
                     f"{self.tenant_burn_threshold:g}; best-effort "
                     "traffic shed until the breaching window ages "
                     "out",
            "tenant": tenant,
            "shed": "tenant-burn",
            "burn_rate": rec["burn_rate"],
            "retry_after_s": round(retry_after, 3),
        }

    # ---- request handling ----

    def handle_traced(self, kind: str, body: bytes,
                      trace_header: str | None = None) \
            -> tuple[int, dict | bytes, str]:
        parsed = parse_trace_header(trace_header)
        tid, remote_parent = parsed if parsed else (None, None)
        with obs.trace(f"federation.request.{kind}", kind="serve",
                       trace_id=tid,
                       remote_parent=remote_parent) as root:
            code, payload = self.handle(kind, body)
            root.attrs["status"] = code
            return code, payload, root.trace_id

    def handle(self, kind: str, body: bytes) \
            -> tuple[int, dict | bytes]:
        try:
            req = json.loads(body or b"{}")
            if not isinstance(req, dict):
                raise ValueError("request body must be a JSON object")
        except ValueError as e:
            return 400, {"error": f"bad JSON body: {e}"}
        tenant = str(req.get("tenant") or "default")
        priority = int(req.get("priority", 0))
        timeout_s = float(req.get("timeout_s",
                                  self.default_timeout_s))
        self.registry.counter(
            f"federation.requests_total.{kind}").inc()
        try:
            self.quotas.check(tenant)
        except QuotaExceeded as e:
            # admission rejections mirror tenant sheds: honest
            # retry_after_s, and NOT recorded in the SLO tracker — a
            # refused request burned no fleet budget
            self.registry.counter(
                f"federation.admission_rejected_total.{tenant}").inc()
            return 429, {"error": f"tenant {tenant!r} over quota",
                         "shed": "admission",
                         "tenant": tenant,
                         "retry_after_s": e.retry_after_s}
        shed = self._maybe_shed_tenant(tenant, priority)
        if shed is not None:
            # NOT recorded in the tracker: the shed's own 429s must
            # not feed the burn rate that caused them
            return 429, shed
        t0 = time.perf_counter()
        code, payload = self._route(kind, req, body, timeout_s)
        self.tenants.record(tenant, code,
                            time.perf_counter() - t0)
        return code, payload

    def _forward(self, url: str, kind: str, body: bytes,
                 timeout_s: float,
                 trace: tuple[str, int] | None = None) \
            -> tuple[int, bytes]:
        headers = {"Content-Type": "application/json",
                   "Accept": "application/json"}
        if trace is not None:
            headers[TRACE_HEADER] = format_trace_header(*trace)
        req = urllib.request.Request(
            url + "/v1/" + kind, data=body, headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=timeout_s) as r:
                return r.status, r.read()
        except urllib.error.HTTPError as e:
            return e.code, e.read()

    def _route(self, kind: str, req: dict, body: bytes,
               timeout_s: float) -> tuple[int, dict | bytes]:
        candidates = self.plan(kind, req)
        eligible = self.pool.eligible()
        live = [u for u in candidates if u in eligible]
        c = self.registry.counter
        if not live:
            c("federation.no_fleet_total").inc()
            return 503, {
                "error": f"no live fleet for {kind!r} "
                         f"({len(candidates)} known, 0 eligible)",
                "retry_after_s": self.pool.poll_interval_s}
        last_err: dict | None = None
        attempts = 0
        for url in live:
            if not self.pool.try_begin_forward(url):
                # a probing fleet already has its one probe in flight
                continue
            if attempts > 0:
                c("federation.retries_total").inc()
            attempts += 1
            fl = url.rsplit(":", 1)[-1]  # port: stable short label
            try:
                with obs.span(f"federation.forward.{kind}", url=url,
                              attempt=attempts - 1) as fsp:
                    status, payload = self._forward(
                        url, kind, body, timeout_s,
                        trace=(fsp.trace_id, fsp.span_id))
                    fsp.attrs["status"] = status
            except Exception as e:  # noqa: BLE001 — connection-level
                # death: the FLEET (its router), not the request —
                # eject the whole fleet and walk to the next ring
                # candidate; content-keyed steps make the replay
                # byte-identical by construction
                self.pool.mark_failed(url)
                c(f"federation.fleet_errors_total.{fl}").inc()
                last_err = {"error": f"fleet {url} unreachable: "
                                     f"{e!r}"}
                continue
            self.pool.settle_forward(url, ok=True)
            if status == 503:
                # the fleet answered but cannot serve (no healthy
                # worker, shedding): spill this request reactively
                c(f"federation.fleet_shed_total.{fl}").inc()
                try:
                    last_err = json.loads(payload.decode())
                except ValueError:
                    last_err = {"error": f"fleet {url} shed (503)"}
                continue
            c(f"federation.routed_total.{fl}.{kind}").inc()
            if url == candidates[0]:
                c(f"federation.affinity_hits_total.{kind}").inc()
            return status, payload
        return 503, {**(last_err
                        or {"error": "all fleets failed"}),
                     "retry_after_s": self.pool.poll_interval_s}

    # ---- operability ----

    def healthz(self) -> tuple[int, dict]:
        snap = self.pool.snapshot()
        n_up = sum(1 for f in snap.values() if f["state"] == UP)
        n_live = sum(1 for f in snap.values()
                     if f["state"] in (UP, PROBE))
        body = {
            "status": "ok" if n_up == len(snap) and snap
            else ("degraded" if n_live else "down"),
            "fleets": len(snap),
            "fleets_up": n_up,
            "uptime_s": round(time.time() - self.started, 1),
            "now": round(time.time(), 6),
        }
        return (200 if n_live else 503), body

    def metrics_snapshot(self) -> dict:
        self._refresh_gauges()
        snap = self.registry.snapshot()
        return {
            "counters": snap["counters"],
            "gauges": snap["gauges"],
            "histograms": snap.get("histograms", {}),
            "fleets": self.pool.snapshot(),
            "tenants": self.tenant_burn_rates(),
        }

    def metrics_prometheus(self) -> str:
        """The same registry state as Prometheus text exposition —
        the ``federation.tenant.burn_rate.<tenant>`` gauges ride both
        encodings (the acceptance surface)."""
        from ..obs import prometheus

        self._refresh_gauges()
        return prometheus.render(self.registry.snapshot())

    def _refresh_gauges(self) -> None:
        g = self.registry.gauge
        snap = self.pool.snapshot()
        g("federation.fleets").set(len(snap))
        g("federation.fleets_up").set(
            sum(1 for f in snap.values() if f["state"] == UP))
        for url, rec in snap.items():
            fl = url.rsplit(":", 1)[-1]
            if isinstance(rec["burn_rate"], (int, float)):
                g(f"federation.fleet.burn_rate.{fl}").set(
                    rec["burn_rate"])
            g(f"federation.fleet.saturated.{fl}").set(
                1 if rec["saturated"] else 0)
        with self._affinity_lock:
            g("federation.spilled_keys").set(len(self._spilled))
            g("federation.tracked_keys").set(len(self._homes))
        self.tenant_burn_rates()

    # ---- cross-fleet trace stitching ----

    def fleet_trace(self, trace_id: str) -> tuple[int, dict]:
        """``GET /fleet/trace/<id>`` one level up: every fleet's own
        stitched document grafted under the federation's forward
        spans, with the Perfetto export attached. 404 only when NO
        tier holds the trace."""
        from urllib.parse import quote

        own = self.flight.snapshot(trace_id=trace_id)
        fleet_docs: dict[str, dict | None] = {}
        for url in sorted(self.pool.fleets):
            try:
                fleet_docs[url] = self.pool._fetch_json(
                    url + "/fleet/trace/" + quote(trace_id))
            except Exception:  # noqa: BLE001 — a dead fleet (or a
                # 404 from one that never saw the trace) cannot veto
                # the stitched view of the others
                fleet_docs[url] = None
        stitched = stitch_federation(
            trace_id, own, fleet_docs,
            clock_offsets=self.pool.clock_offsets())
        if stitched is None:
            return 404, {
                "error": f"no flight record for trace {trace_id!r} "
                         "in the federation or any fleet (rings are "
                         "bounded — the trace may have been "
                         "evicted)"}
        stitched["perfetto"] = perfetto_export(trace_id, stitched)
        return 200, stitched

    def fleet_profile(self, seconds: float) -> dict:
        """``GET /fleet/profile`` one level up: each fleet's merged
        rollup collected IN PARALLEL (overlapping windows, same as
        the fleet router over its workers) and merged stack-wise —
        exact sums compose across tiers."""
        from urllib.parse import quote

        from ..obs.profiler import MAX_WINDOW_S, merge_profiles

        seconds = max(0.0, min(float(seconds), MAX_WINDOW_S))
        urls = sorted(self.pool.fleets)
        bodies: list[dict | None] = [None] * len(urls)
        errors: dict[str, str] = {}

        def fetch(i: int, url: str) -> None:
            req = urllib.request.Request(
                url + f"/fleet/profile?seconds={quote(str(seconds))}",
                headers={"Accept": "application/json"})
            try:
                with urllib.request.urlopen(
                        req, timeout=seconds + 20.0) as r:
                    bodies[i] = json.loads(r.read().decode())
            except Exception as e:  # noqa: BLE001 — per-fleet fault
                errors[url] = str(e)

        threads: list[threading.Thread] = []
        for i, url in enumerate(urls):
            t = threading.Thread(target=fetch, args=(i, url),
                                 name=f"goleft-fed-profile-{i}")
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=seconds + 40.0)
        merged = merge_profiles([b for b in bodies if b is not None])
        merged["seconds"] = seconds
        merged["per_fleet"] = {
            url: ({"error": errors[url]} if url in errors else {
                "samples_total":
                    int((bodies[i] or {}).get("samples_total") or 0),
                "stacks": len((bodies[i] or {}).get("stacks") or {}),
            })
            for i, url in enumerate(urls)
        }
        return merged

    def fleet_memory(self) -> dict:
        """``GET /fleet/memory`` one level up: each fleet's merged
        memory document combined tier-wise (counter sums stay exact,
        gauge aggregates compose min/max/sum) — the federation's
        numbers equal a flat merge over every worker. Instant
        collection, so serial fetch like the fleet router's."""
        from ..obs.memplane import merge_merged_memory

        bodies: list[dict] = []
        per_fleet: dict[str, dict] = {}
        for url in sorted(self.pool.fleets):
            try:
                d = self.pool._fetch_json(url + "/fleet/memory")
                bodies.append(d)
                per_fleet[url] = {
                    "workers": int(d.get("workers") or 0),
                    "workers_in_pressure":
                        int(d.get("workers_in_pressure") or 0),
                    "enabled": bool(d.get("enabled")),
                }
            except Exception as e:  # noqa: BLE001 — per-fleet fault
                per_fleet[url] = {"error": str(e)}
        merged = merge_merged_memory(bodies)
        merged["per_fleet"] = per_fleet
        return merged


class _FederationHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        log.debug("%s " + fmt, self.address_string(), *args)

    @property
    def app(self) -> FederationRouter:
        return self.server.app

    def _respond_json(self, code: int, body: dict,
                      extra_headers: dict | None = None) -> None:
        self._respond_raw(code, json.dumps(body).encode(),
                          extra_headers=extra_headers)

    def _respond_raw(self, code: int, data: bytes,
                     content_type: str = "application/json",
                     extra_headers: dict | None = None) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.send_header("Connection", "close")
        self.end_headers()
        self.wfile.write(data)
        self.close_connection = True

    def do_GET(self):  # noqa: N802 — http.server contract
        from urllib.parse import parse_qs, unquote, urlparse

        u = urlparse(self.path)
        if u.path == "/healthz":
            code, body = self.app.healthz()
            self._respond_json(code, body)
        elif u.path == "/metrics":
            q = parse_qs(u.query)
            fmt = q.get("format", [""])[0]
            accept = self.headers.get("Accept", "")
            if fmt in ("prom", "prometheus") or (
                    not fmt and "text/plain" in accept
                    and "json" not in accept):
                from ..obs.prometheus import CONTENT_TYPE

                self._respond_raw(
                    200, self.app.metrics_prometheus().encode(),
                    content_type=CONTENT_TYPE)
            else:
                self._respond_json(200, self.app.metrics_snapshot())
        elif u.path.startswith("/fleet/trace/"):
            trace_id = unquote(u.path[len("/fleet/trace/"):])
            code, body = self.app.fleet_trace(trace_id)
            self._respond_json(code, body)
        elif u.path == "/fleet/profile":
            q = parse_qs(u.query)
            try:
                seconds = float(q["seconds"][0]) \
                    if "seconds" in q else 1.0
            except ValueError:
                self._respond_json(
                    400, {"error": "seconds must be a number"})
                return
            self._respond_json(200, self.app.fleet_profile(seconds))
        elif u.path == "/fleet/memory":
            q = parse_qs(u.query)
            fmt = q.get("format", [""])[0]
            accept = self.headers.get("Accept", "")
            if fmt in ("prom", "prometheus") or (
                    not fmt and "text/plain" in accept
                    and "json" not in accept):
                from ..obs import prometheus
                from ..obs.memplane import flatten_merged
                from ..obs.prometheus import CONTENT_TYPE

                self._respond_raw(
                    200, prometheus.render(flatten_merged(
                        self.app.fleet_memory())).encode(),
                    content_type=CONTENT_TYPE)
            else:
                self._respond_json(200, self.app.fleet_memory())
        else:
            self._respond_json(404,
                               {"error": f"no route {self.path}"})

    def do_POST(self):  # noqa: N802 — http.server contract
        n = int(self.headers.get("Content-Length", "0"))
        body = self.rfile.read(n)
        if self.path == "/fleet/plan":
            try:
                req = json.loads(body or b"{}")
                kind = req.pop("kind")
            except (ValueError, KeyError):
                self._respond_json(
                    400, {"error": "want a JSON object with 'kind'"})
                return
            self._respond_json(
                200, {"candidates": self.app.plan(kind, req)})
            return
        if not self.path.startswith("/v1/"):
            self._respond_json(404,
                               {"error": f"no route {self.path}"})
            return
        kind = self.path[len("/v1/"):].strip("/")
        code, payload, trace_id = self.app.handle_traced(
            kind, body, self.headers.get(TRACE_HEADER))
        trace_hdr = {TRACE_HEADER: trace_id}
        if isinstance(payload, bytes):
            self._respond_raw(code, payload,
                              extra_headers=trace_hdr)
        else:
            self._respond_json(code, payload,
                               extra_headers=trace_hdr)


class _FederationServer(ThreadingHTTPServer):
    daemon_threads = False
    block_on_close = True
    allow_reuse_address = True


def make_federation_server(app: FederationRouter,
                           host: str = "127.0.0.1",
                           port: int = 0) -> ThreadingHTTPServer:
    srv = _FederationServer((host, port), _FederationHandler)
    srv.app = app
    return srv


class FederationThread:
    """In-process federation harness (tests):
    ``with FederationThread(app) as url: ...``"""

    def __init__(self, app: FederationRouter,
                 host: str = "127.0.0.1", port: int = 0):
        self.app = app
        self.httpd = make_federation_server(app, host, port)
        self._thread = threading.Thread(
            target=self.httpd.serve_forever,
            kwargs={"poll_interval": 0.05},
            daemon=True, name="goleft-federation-http")

    @property
    def base_url(self) -> str:
        host, port = self.httpd.server_address[:2]
        return f"http://{host}:{port}"

    def __enter__(self) -> str:
        self.app.start()
        self._thread.start()
        return self.base_url

    def __exit__(self, *exc):
        self.httpd.shutdown()
        self._thread.join(timeout=30.0)
        self.httpd.server_close()
        self.app.close()
        return False
