"""Admission control for the fleet router: quotas + fair scheduling.

Two independent gates run in front of request forwarding, both built
to degrade loudly BEFORE the workers' own 429 cliff:

  - **per-tenant token buckets** (:class:`QuotaTable`): each tenant
    (the ``tenant`` request field, default ``"default"``) owns a
    bucket refilling at ``rate`` tokens/s up to ``burst``. An empty
    bucket rejects with :class:`QuotaExceeded` carrying an honest
    ``retry_after_s`` (when the next token lands) — one tenant's flood
    burns only its own bucket, every other tenant's traffic is
    untouched.
  - **fair forwarding slots** (:class:`FairScheduler`): at most
    ``max_inflight`` requests forward concurrently; waiters are
    granted slots in priority order with AGING — a waiter's effective
    priority improves by ``aging_rate`` per queued second, so a
    steady stream of high-priority arrivals can delay but never
    starve a low-priority request (starvation-freedom by
    construction: age grows without bound, priority values do not).
    Waiting is deadline-aware: a waiter whose deadline passes fails
    with :class:`SchedulerTimeout` instead of holding a ghost place
    in line.

Priorities are small ints, LOWER = more urgent (0 = interactive
default, larger = batch/best-effort). Deterministic under test: both
classes take an injectable ``clock``.
"""

from __future__ import annotations

import threading
import time


class QuotaExceeded(RuntimeError):
    """Tenant bucket empty — shed with 429 + retry_after_s."""

    def __init__(self, tenant: str, retry_after_s: float):
        super().__init__(
            f"tenant {tenant!r} exceeded its request quota; retry in "
            f"{retry_after_s:.2f}s")
        self.tenant = tenant
        self.retry_after_s = retry_after_s


class SchedulerTimeout(RuntimeError):
    """Deadline passed while waiting for a forwarding slot (504)."""


class TokenBucket:
    """Classic token bucket: ``rate`` tokens/s, capacity ``burst``.

    ``take()`` is non-blocking: (True, 0.0) on success, else
    (False, seconds_until_next_token) — the router turns the latter
    into a 429 with a retry hint instead of queueing denied work.
    Thread-safe.
    """

    def __init__(self, rate: float, burst: float,
                 clock=time.monotonic):
        if rate <= 0 or burst < 1:
            raise ValueError(
                f"need rate > 0 and burst >= 1 (got {rate}, {burst})")
        self.rate = float(rate)
        self.burst = float(burst)
        self._clock = clock
        self._tokens = float(burst)
        self._stamp = clock()
        self._lock = threading.Lock()

    def _refill(self, now: float) -> None:
        self._tokens = min(self.burst, self._tokens
                           + (now - self._stamp) * self.rate)
        self._stamp = now

    def take(self, n: float = 1.0) -> tuple[bool, float]:
        with self._lock:
            now = self._clock()
            self._refill(now)
            if self._tokens >= n:
                self._tokens -= n
                return True, 0.0
            return False, (n - self._tokens) / self.rate


class QuotaTable:
    """Per-tenant buckets from ``tenant=rate:burst`` specs.

    The ``*`` spec is the default every unlisted tenant gets its OWN
    bucket from (lazily — tenants are isolated, not pooled). With no
    ``*`` spec, unlisted tenants are unmetered (admission is opt-in).
    """

    def __init__(self, specs: list[str] | None = None,
                 clock=time.monotonic):
        self._clock = clock
        self._defs: dict[str, tuple[float, float]] = {}
        self._buckets: dict[str, TokenBucket] = {}
        self._lock = threading.Lock()
        for spec in specs or []:
            tenant, _, rb = spec.partition("=")
            tenant = tenant.strip()
            rate, _, burst = rb.partition(":")
            if not tenant or not rate:
                raise ValueError(
                    f"quota spec {spec!r}: want tenant=rate[:burst]")
            try:
                r = float(rate)
                b = float(burst) if burst else max(1.0, r)
            except ValueError:
                raise ValueError(
                    f"quota spec {spec!r}: rate/burst must be "
                    "numbers") from None
            TokenBucket(r, b, clock)  # validate bounds loudly, now
            self._defs[tenant] = (r, b)

    def check(self, tenant: str | None) -> None:
        """Take one token for this tenant or raise
        :class:`QuotaExceeded`; a no-op for unmetered tenants."""
        tenant = tenant or "default"
        definition = self._defs.get(tenant, self._defs.get("*"))
        if definition is None:
            return
        with self._lock:
            bucket = self._buckets.get(tenant)
            if bucket is None:
                bucket = self._buckets[tenant] = TokenBucket(
                    *definition, clock=self._clock)
        ok, retry_after = bucket.take()
        if not ok:
            raise QuotaExceeded(tenant, retry_after)

    @property
    def metered(self) -> bool:
        return bool(self._defs)


class _Waiter:
    __slots__ = ("tenant", "priority", "deadline", "arrived", "seq")

    def __init__(self, tenant, priority, deadline, arrived, seq):
        self.tenant = tenant
        self.priority = priority
        self.deadline = deadline
        self.arrived = arrived
        self.seq = seq


class FairScheduler:
    """Bounded forwarding slots granted in aged-priority order.

    ``acquire`` blocks until a slot is granted (returns the queue-wait
    seconds, the router's queue-age signal) or the deadline passes
    (:class:`SchedulerTimeout`). Grant order among waiters:
    ``priority - age * aging_rate`` ascending, FIFO within ties — so
    urgency wins now and patience wins eventually.
    """

    def __init__(self, max_inflight: int = 8,
                 aging_rate: float = 0.5, clock=time.monotonic):
        if max_inflight < 1:
            raise ValueError(
                f"max_inflight must be >= 1 (got {max_inflight})")
        self.max_inflight = max_inflight
        self.aging_rate = float(aging_rate)
        self._clock = clock
        self._cond = threading.Condition()
        self._inflight = 0
        self._seq = 0
        self._waiters: list[_Waiter] = []

    def _rank(self, w: _Waiter, now: float) -> tuple:
        return (w.priority - (now - w.arrived) * self.aging_rate,
                w.seq)

    def _best(self, now: float) -> _Waiter | None:
        live = [w for w in self._waiters if w.deadline > now]
        return min(live, key=lambda w: self._rank(w, now)) \
            if live else None

    def acquire(self, tenant: str = "default", priority: int = 0,
                timeout_s: float = 30.0) -> float:
        """Block until granted a slot; returns seconds waited."""
        with self._cond:
            now = self._clock()
            me = _Waiter(tenant, int(priority), now + timeout_s, now,
                         self._seq)
            self._seq += 1
            if self._inflight < self.max_inflight \
                    and not self._waiters:
                self._inflight += 1
                return 0.0
            self._waiters.append(me)
            try:
                while True:
                    now = self._clock()
                    if now >= me.deadline:
                        raise SchedulerTimeout(
                            f"no forwarding slot within "
                            f"{timeout_s:g}s (priority {priority}, "
                            f"{len(self._waiters)} waiting)")
                    if self._inflight < self.max_inflight \
                            and self._best(now) is me:
                        self._inflight += 1
                        return now - me.arrived
                    self._cond.wait(timeout=min(
                        0.05, max(0.0, me.deadline - now)) or 0.05)
            finally:
                self._waiters.remove(me)
                self._cond.notify_all()

    def release(self) -> None:
        with self._cond:
            self._inflight = max(0, self._inflight - 1)
            self._cond.notify_all()

    # ---- observability ----

    def queue_depth(self) -> int:
        with self._cond:
            return len(self._waiters)

    def queue_age_s(self) -> float:
        """Age of the OLDEST waiter (0 when nobody waits) — the
        backlog-pressure gauge (``fleet.queue_age_s``)."""
        with self._cond:
            if not self._waiters:
                return 0.0
            now = self._clock()
            return max(now - w.arrived for w in self._waiters)

    def inflight(self) -> int:
        with self._cond:
            return self._inflight
