"""Fleet supervisor: worker lifecycle, crash-loop quarantine, scaling.

PR 9's router survives a dead worker by routing AROUND it, but the
fleet's capacity then decays monotonically — a SIGKILLed or hung
worker stays dead until an operator intervenes. This module is the
self-healing layer: it OWNS the serve subprocesses (the spawn loop
that used to live in commands/fleet.py) and keeps the fleet at its
declared capacity without operator action.

Per worker slot, a small state machine::

    spawning ──► healthy ──► hung ────┐
       ▲            │                 │ SIGKILL
       │            │ process exit    ▼
       │            └───────────► restarting ──► quarantined
       │                              │            (parked)
       └──────── backoff elapsed ◄────┘
    healthy ──► draining ──► stopped          (scale-down only)

  - **death** (``proc.poll()`` returns): the slot restarts with the
    resilience layer's exponential backoff + deterministic jitter
    (:meth:`~goleft_tpu.resilience.policy.RetryPolicy.backoff_s` — the
    SAME schedule cohort shard retries use), non-blocking: the
    supervise loop stores ``next_attempt_at`` instead of sleeping, so
    one slot's backoff never delays another slot's health checks.
  - **hang** (``/healthz`` timeout ``hang_after`` times in a row —
    a SIGSTOPped or wedged worker accepts connections but never
    answers): the worker is SIGKILLed and takes the death path.
  - **crash loop** (``crash_limit`` deaths inside ``crash_window_s``):
    the slot is PARKED — recorded in a
    :class:`~goleft_tpu.resilience.policy.Quarantine` (the same
    manifest/exit-code contract cohortdepth uses for quarantined
    samples: the fleet completes degraded, exits 3, and the manifest
    names what was lost and why) — instead of burning CPU respawning
    a worker that cannot live.
  - **elastic scaling**: within ``[min_workers, max_workers]``, a
    control loop compares the router's ``fleet.queue_age_s`` against
    ``target_queue_age_s``. Backlog above target scales UP (spawn +
    ring add); a queue that stays empty AND idle for
    ``scale_down_idle_ticks`` consecutive ticks scales DOWN — the
    hysteresis that keeps one bursty second from flapping the fleet —
    and every scale event starts a ``scale_cooldown_s`` quiet period.
    Scale-down picks the LEAST-AFFINE worker (smallest
    :meth:`~goleft_tpu.fleet.router.HashRing.ownership` share — the
    removal that remaps the fewest keys), drains it (no new traffic,
    in-flight forwards run to completion, bounded by
    ``drain_timeout_s``), removes it from the ring, then SIGTERMs it.

Membership changes go through :meth:`RouterApp.add_worker` /
``remove_worker`` — copy-on-write ring swaps, so supervision never
perturbs the candidate order of surviving workers (the byte-identity
contract `make fleet-smoke` pins).

With ``shared_cache`` set, every spawned worker gets
``--cache <dir> --cache-shared``: one content-keyed ResultCache
directory behind the whole fleet. Safe across workers by construction
— keys are full content identity (canonical params + every input's
``file_key``) and writes are tmp-file + atomic rename — so a restart
or a ring resize REPLAYS a previously computed response instead of
recomputing it on a cold private cache.

Metrics (the router's registry, so they ride ``GET /metrics``):
``fleet.restarts_total``, ``fleet.slot_quarantines``,
``fleet.scale_events`` (+ ``fleet.scale_up_total`` /
``fleet.scale_down_total``), ``fleet.hangs_total``,
``fleet.spawn_failures_total``, and the ``fleet.capacity`` gauge
(serving slots right now).

Like the router, this module must stay jax-free: the supervisor runs
in the router process (tests/test_fleet.py pins the import graph).
"""

from __future__ import annotations

import json
import subprocess
import sys
import threading
import time
import urllib.request

from ..obs.events import EventJournal, EventLog
from ..obs.logging import get_logger
from ..obs.metrics import MetricsRegistry
from ..resilience.policy import Quarantine, RetryPolicy

log = get_logger("fleet.supervisor")

#: slot states (the docs/fleet.md state machine)
SPAWNING = "spawning"
HEALTHY = "healthy"
HUNG = "hung"
RESTARTING = "restarting"
QUARANTINED = "quarantined"
DRAINING = "draining"
STOPPED = "stopped"


class WorkerSpawnError(RuntimeError):
    """A worker failed to start (exec failure, died before announcing,
    or never printed its URL within ``spawn_timeout_s``)."""


def read_announce(child, timeout_s: float) -> str | None:
    """The ``listening on URL`` line from a child's stdout, or None if
    the child never prints one within ``timeout_s`` (hung interpreter,
    import crash, wedged warmup). The read happens on a daemon thread
    because a pipe readline cannot be interrupted — on timeout the
    caller kills the child, which unblocks (and ends) the reader."""
    box: dict = {}

    def _read():
        try:
            box["line"] = child.stdout.readline()
        except Exception as e:  # noqa: BLE001 — reported via box
            box["error"] = e

    t = threading.Thread(target=_read, daemon=True,
                         name="goleft-fleet-announce")
    t.start()
    t.join(timeout=timeout_s)
    line = box.get("line") or ""
    if "listening on " not in line:
        return None
    return line.rsplit("listening on ", 1)[1].strip()


class WorkerSlot:
    """One supervised worker position. The slot survives its workers:
    processes come and go (restarts, scale events); the slot carries
    the lifecycle state and the crash history."""

    def __init__(self, index: int):
        self.index = index
        self.state = SPAWNING
        self.proc: subprocess.Popen | None = None
        self.url: str | None = None
        self.restarts = 0               # successful respawns
        self.deaths: list[float] = []   # monotonic stamps, windowed
        self.health_misses = 0
        self.next_attempt_at = 0.0      # backoff gate (monotonic)
        self.reason: str | None = None  # why quarantined/stopped

    def to_dict(self) -> dict:
        return {
            "index": self.index,
            "state": self.state,
            "url": self.url,
            "pid": self.proc.pid if self.proc else None,
            "restarts": self.restarts,
            "recent_deaths": len(self.deaths),
            "reason": self.reason,
        }


class Supervisor:
    """Owns the serve subprocesses behind a :class:`RouterApp`.

    Usage (commands/fleet.py and the chaos smoke)::

        sup = Supervisor(worker_args=[...], min_workers=1,
                         max_workers=4, registry=registry)
        urls = sup.spawn_initial(2)   # cleans up after itself on
                                      # failure, raises WorkerSpawnError
        app = RouterApp(urls, registry=registry)
        sup.bind(app)
        app.start(); sup.start()
        ...
        sup.close(); app.close()

    ``spawn_fn(index) -> (Popen, url)`` is injectable so tests can
    supervise cheap jax-free stub processes; the default spawns
    ``goleft-tpu serve --port 0`` workers.
    """

    def __init__(self, *, worker_args: list[str] | None = None,
                 env: dict | None = None,
                 spawn_fn=None,
                 min_workers: int = 1,
                 max_workers: int | None = None,
                 registry: MetricsRegistry | None = None,
                 interval_s: float = 1.0,
                 hang_timeout_s: float = 5.0,
                 hang_after: int = 2,
                 crash_limit: int = 5,
                 crash_window_s: float = 300.0,
                 restart_backoff: RetryPolicy | None = None,
                 target_queue_age_s: float = 0.0,
                 scale_cooldown_s: float = 30.0,
                 scale_down_idle_ticks: int = 5,
                 drain_timeout_s: float = 30.0,
                 spawn_timeout_s: float = 120.0,
                 shared_cache: str | None = None,
                 queue_age_fn=None,
                 events_journal: str | None = None,
                 burn_threshold: float = 0.0,
                 burn_rate_fn=None,
                 mem_recycle_bytes: int = 0):
        if min_workers < 1:
            raise ValueError(
                f"min_workers must be >= 1 (got {min_workers})")
        if max_workers is not None and max_workers < min_workers:
            raise ValueError(
                f"max_workers {max_workers} < min_workers "
                f"{min_workers}")
        self.worker_args = list(worker_args or [])
        self.env = env
        self.shared_cache = shared_cache
        if shared_cache:
            import os

            os.makedirs(shared_cache, exist_ok=True)
            self.worker_args += ["--cache", shared_cache,
                                 "--cache-shared"]
        self._spawn_fn = spawn_fn or self._spawn_serve
        self.min_workers = min_workers
        self.max_workers = max_workers if max_workers is not None \
            else min_workers
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self.interval_s = interval_s
        self.hang_timeout_s = hang_timeout_s
        self.hang_after = hang_after
        self.crash_limit = crash_limit
        self.crash_window_s = crash_window_s
        # backoff only — classification never runs here (a dead
        # process carries no exception); retries is irrelevant because
        # quarantine, not the policy budget, bounds respawns
        self.backoff = restart_backoff if restart_backoff is not None \
            else RetryPolicy(base_delay_s=0.1, max_delay_s=5.0)
        self.target_queue_age_s = target_queue_age_s
        self.scale_cooldown_s = scale_cooldown_s
        self.scale_down_idle_ticks = scale_down_idle_ticks
        self.drain_timeout_s = drain_timeout_s
        self.spawn_timeout_s = spawn_timeout_s
        self.queue_age_fn = queue_age_fn
        # SLO-burn autoscale signal (the fleet plane's second trigger
        # beyond queue age): scale up while the polled fleet burn rate
        # exceeds burn_threshold (>1.0 = budget burning faster than it
        # earns; 0 disables). burn_rate_fn defaults to the bound
        # router's fleet_burn_rate at bind() time.
        self.burn_threshold = burn_threshold
        self.burn_rate_fn = burn_rate_fn
        # the memory hard cap (--mem-recycle-mb; 0 disables): a
        # healthy worker whose /debug/memory RSS exceeds it is
        # drained and recycled DELIBERATELY — before the kernel OOM
        # killer picks a victim — and the recycle does not count
        # toward the crash window (it is maintenance, not a death)
        self.mem_recycle_bytes = int(mem_recycle_bytes)
        # the structured event journal: every lifecycle transition,
        # fsync'd per append (obs/events.py — the checkpoint journal's
        # durability protocol), plus the bounded in-memory ring the
        # router's /metrics `fleet.events` block serves
        self.events = EventLog(
            EventJournal(events_journal) if events_journal else None,
            registry=self.registry)
        self.quarantine = Quarantine()
        self.app = None
        self._slots: list[WorkerSlot] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        # gtlint: ok thr-daemon-io — the loop's only fsync sink is the
        # events journal, whose READERS skip torn tails by contract
        # (obs/events.py: iter_journal_lines stop_on_torn=False, the
        # PR-13 restart-continuation design); close() joins this
        # thread, so only a hard kill can tear — exactly the case the
        # format survives. daemon=True stays: a crashed operator path
        # that never reaches close() must not hang process exit.
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name="goleft-fleet-supervisor")
        self._last_scale = 0.0
        self._idle_ticks = 0

    # ---- spawning ----

    def _spawn_serve(self, index: int):
        """Default spawn: one ``goleft-tpu serve`` child on an
        ephemeral port (the loop commands/fleet.py used to own)."""
        child = subprocess.Popen(
            [sys.executable, "-m", "goleft_tpu", "serve", "--port",
             "0", *self.worker_args],
            stdout=subprocess.PIPE, text=True, env=self.env)
        url = read_announce(child, self.spawn_timeout_s)
        if url is None:
            child.kill()
            child.wait(timeout=10)
            if child.stdout is not None:
                child.stdout.close()
            raise WorkerSpawnError(
                f"worker {index} did not announce its URL within "
                f"{self.spawn_timeout_s:g}s")
        return child, url

    def _try_spawn(self, slot: WorkerSlot) -> bool:
        try:
            proc, url = self._spawn_fn(slot.index)
        except Exception as e:  # noqa: BLE001 — spawn failure is a
            # slot event (counted toward the crash window), never a
            # supervisor death
            self.registry.counter("fleet.spawn_failures_total").inc()
            self.events.emit("spawn_failure", slot=slot.index,
                             error=repr(e))
            log.warning("fleet: slot %d spawn failed: %r",
                        slot.index, e)
            return False
        slot.proc = proc
        slot.url = url.rstrip("/")
        slot.health_misses = 0
        self.events.emit("spawn", slot=slot.index, worker=slot.url,
                         pid=proc.pid)
        return True

    def spawn_initial(self, n: int) -> list[str]:
        """Spawn the first ``n`` workers. If worker i of n fails, every
        already-spawned child is killed before the error propagates —
        a failed ``goleft-tpu fleet`` start must not leave orphan
        daemons behind."""
        n = max(self.min_workers, min(n, self.max_workers))
        slots: list[WorkerSlot] = []
        try:
            for i in range(n):
                slot = WorkerSlot(i)
                if not self._try_spawn(slot):
                    raise WorkerSpawnError(
                        f"worker {i} of {n} failed to spawn")
                slot.state = HEALTHY
                slots.append(slot)
        except BaseException:
            for s in slots:
                self._terminate(s, sig_kill=True)
            raise
        with self._lock:
            self._slots = slots
        self._update_capacity()
        return [s.url for s in slots]

    # ---- wiring + lifecycle ----

    def bind(self, app) -> "Supervisor":
        """Attach the RouterApp whose membership this supervisor
        drives (and whose scheduler + fleet rollup provide the
        autoscale signals: queue age AND SLO burn rate)."""
        self.app = app
        app.supervisor = self
        if self.queue_age_fn is None:
            self.queue_age_fn = app.scheduler.queue_age_s
        if self.burn_rate_fn is None and self.burn_threshold > 0:
            self.burn_rate_fn = app.fleet_burn_rate
        return self

    def events_block(self) -> dict:
        """The router /metrics ``fleet.events`` block."""
        return self.events.block()

    def start(self) -> "Supervisor":
        self._thread.start()
        return self

    def close(self) -> None:
        """Stop supervising, then stop every worker: SIGTERM (the
        serve daemon drains in-flight work on it), bounded wait,
        SIGKILL stragglers."""
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=30.0)
        for slot in self.slots():
            self._terminate(slot)
            if slot.state not in (QUARANTINED,):
                slot.state = STOPPED
        self._update_capacity()
        self.events.emit("stop", detailed_reason="supervisor close")
        self.events.close()

    def _terminate(self, slot: WorkerSlot,
                   sig_kill: bool = False) -> None:
        proc = slot.proc
        if proc is None:
            return
        if proc.poll() is None:
            if sig_kill:
                proc.kill()
            else:
                proc.terminate()
            try:
                proc.wait(timeout=self.drain_timeout_s)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=10)
        if proc.stdout is not None:
            proc.stdout.close()

    # ---- introspection ----

    def slots(self) -> list[WorkerSlot]:
        with self._lock:
            return list(self._slots)

    @property
    def capacity(self) -> int:
        """Slots currently serving traffic."""
        return sum(1 for s in self.slots() if s.state == HEALTHY)

    @property
    def quarantined_slots(self) -> int:
        return sum(1 for s in self.slots()
                   if s.state == QUARANTINED)

    def snapshot(self) -> dict:
        return {
            "slots": [s.to_dict() for s in self.slots()],
            "capacity": self.capacity,
            "min_workers": self.min_workers,
            "max_workers": self.max_workers,
            "autoscale": self.target_queue_age_s > 0,
        }

    def _update_capacity(self) -> None:
        self.registry.gauge("fleet.capacity").set(self.capacity)

    # ---- the supervise loop ----

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the supervisor must
                # outlive any single bad tick (a worker dying mid-
                # check raises from urllib/psutil-ish paths); the
                # failure is logged, the next tick re-inspects
                log.exception("fleet: supervisor tick failed")

    def tick(self) -> None:
        """One supervision pass (public so tests and the chaos smoke
        can drive the state machine deterministically without racing
        the wall-clock loop)."""
        now = time.monotonic()
        for slot in self.slots():
            if slot.state == HEALTHY:
                self._check_slot(slot, now)
            elif slot.state == RESTARTING \
                    and now >= slot.next_attempt_at:
                self._restart(slot, now)
        self._evaluate_scaling(now)

    def _check_slot(self, slot: WorkerSlot, now: float) -> None:
        proc = slot.proc
        if proc is None or proc.poll() is not None:
            rc = proc.returncode if proc is not None else None
            log.warning("fleet: slot %d worker %s exited (rc=%s)",
                        slot.index, slot.url, rc)
            self._on_death(slot, now, f"process exit rc={rc}")
            return
        if self._healthz_ok(slot):
            slot.health_misses = 0
            self._check_memory(slot, now)
            return
        slot.health_misses += 1
        if slot.health_misses < self.hang_after:
            return
        # hung: accepts connections but never answers (SIGSTOP, a
        # wedged dispatcher, a deadlocked handler pool). SIGKILL —
        # SIGTERM would need the process to be scheduled to matter —
        # and recycle through the death path.
        slot.state = HUNG
        self.registry.counter("fleet.hangs_total").inc()
        self.events.emit("hang_kill", slot=slot.index,
                         worker=slot.url,
                         pid=proc.pid if proc else None,
                         misses=slot.health_misses)
        log.warning("fleet: slot %d worker %s hung (%d healthz "
                    "timeouts) — SIGKILL + recycle", slot.index,
                    slot.url, slot.health_misses)
        proc.kill()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pass
        self._on_death(slot, now, "hung (healthz timeout)")

    def _worker_rss(self, slot: WorkerSlot) -> int | None:
        """The worker's current RSS from ``/debug/memory`` (always
        answers, sampler thread or not), or None on any failure —
        a worker too wedged to report memory is the hang path's
        business, not the recycler's."""
        try:
            req = urllib.request.Request(
                slot.url + "/debug/memory",
                headers={"Accept": "application/json"})
            with urllib.request.urlopen(
                    req, timeout=self.hang_timeout_s) as r:
                d = json.loads(r.read().decode())
            return int((d.get("host") or {}).get("rss_bytes") or 0)
        except Exception:  # noqa: BLE001 — no verdict, no recycle
            return None

    def _check_memory(self, slot: WorkerSlot, now: float) -> None:
        if self.mem_recycle_bytes <= 0:
            return
        rss = self._worker_rss(slot)
        if rss is None or rss <= self.mem_recycle_bytes:
            return
        self._recycle_for_memory(slot, rss, now)

    def _recycle_for_memory(self, slot: WorkerSlot, rss_bytes: int,
                            now: float) -> None:
        """Drain-and-recycle a worker past the memory hard cap: the
        scale-down drain choreography (no new traffic, in-flight
        forwards finish, ring removal, SIGTERM so the worker's own
        drain runs) followed by an immediate respawn through the
        restart path. Emits ``memory_recycle`` to the fsync'd event
        journal; deliberately NOT a death — the crash window stays
        untouched, a leaky worker must not quarantine its slot."""
        url = slot.url
        slot.state = DRAINING
        self.registry.counter("memory.recycles_total").inc()
        self.events.emit(
            "memory_recycle", slot=slot.index, worker=url,
            pid=slot.proc.pid if slot.proc else None,
            rss_bytes=rss_bytes, cap_bytes=self.mem_recycle_bytes)
        log.warning(
            "fleet: slot %d worker %s rss %d bytes exceeds the "
            "%d-byte recycle cap — drain + recycle", slot.index,
            url, rss_bytes, self.mem_recycle_bytes)
        if self.app is not None:
            self.app.drain_worker(url)
            deadline = time.monotonic() + self.drain_timeout_s
            while self.app.pool.inflight(url) > 0 \
                    and time.monotonic() < deadline:
                time.sleep(0.02)
            self.app.remove_worker(url)
        self._terminate(slot)
        slot.state = RESTARTING
        slot.next_attempt_at = now  # no backoff: planned maintenance
        self._update_capacity()

    def _healthz_ok(self, slot: WorkerSlot) -> bool:
        try:
            req = urllib.request.Request(
                slot.url + "/healthz",
                headers={"Accept": "application/json"})
            with urllib.request.urlopen(
                    req, timeout=self.hang_timeout_s) as r:
                json.loads(r.read().decode())
            return True
        except Exception:  # noqa: BLE001 — any failure is a miss;
            # the distinction that matters (dead vs hung) is made by
            # proc.poll() above, not by the error shape
            return False

    def _on_death(self, slot: WorkerSlot, now: float,
                  why: str) -> None:
        if self.app is not None and slot.url:
            self.app.remove_worker(slot.url)
        if slot.proc is not None and slot.proc.stdout is not None:
            slot.proc.stdout.close()
        slot.deaths.append(now)
        slot.deaths = [t for t in slot.deaths
                       if now - t <= self.crash_window_s]
        self.events.emit(
            "death", slot=slot.index, worker=slot.url,
            pid=slot.proc.pid if slot.proc else None, why=why,
            deaths_in_window=len(slot.deaths))
        if len(slot.deaths) >= self.crash_limit:
            self._quarantine_slot(slot, why)
            return
        slot.state = RESTARTING
        # non-blocking backoff: the resilience schedule (exponential
        # + deterministic jitter), gated by next_attempt_at so other
        # slots keep getting checked while this one waits
        delay = self.backoff.backoff_s(("fleet-slot", slot.index),
                                       len(slot.deaths))
        slot.next_attempt_at = now + delay
        self.events.emit("backoff", slot=slot.index,
                         delay_s=round(delay, 3),
                         attempt=len(slot.deaths))
        log.warning("fleet: slot %d restarting in %.2fs (%s; death "
                    "%d/%d in window)", slot.index, delay, why,
                    len(slot.deaths), self.crash_limit)
        self._update_capacity()

    def _restart(self, slot: WorkerSlot, now: float) -> None:
        if not self._try_spawn(slot):
            # a failed spawn is another death in the window: a worker
            # that cannot even start is the purest crash loop
            self._on_death(slot, time.monotonic(), "spawn failed")
            return
        slot.state = HEALTHY
        slot.restarts += 1
        self.registry.counter("fleet.restarts_total").inc()
        self.events.emit(
            "restart", slot=slot.index, worker=slot.url,
            pid=slot.proc.pid if slot.proc else None,
            restart=slot.restarts)
        if self.app is not None:
            self.app.add_worker(slot.url)
        log.warning("fleet: slot %d restored at %s (restart #%d)",
                    slot.index, slot.url, slot.restarts)
        self._update_capacity()

    def _quarantine_slot(self, slot: WorkerSlot, why: str) -> None:
        slot.state = QUARANTINED
        slot.reason = (f"crash loop: {len(slot.deaths)} deaths in "
                       f"{self.crash_window_s:g}s ({why})")
        slot.proc = None
        self.registry.counter("fleet.slot_quarantines").inc()
        self.events.emit("quarantine", slot=slot.index,
                         worker=slot.url, reason=slot.reason)
        self.quarantine.add(
            ("fleet-slot", slot.index), f"slot{slot.index}",
            slot.url or "<never started>",
            RuntimeError(slot.reason),
            attempts=len(slot.deaths),
            classification="crash-loop", phase="serve")
        log.error("fleet: slot %d QUARANTINED (%s) — fleet continues "
                  "degraded at capacity %d", slot.index, slot.reason,
                  self.capacity)
        self._update_capacity()

    # ---- elastic scaling ----

    def _evaluate_scaling(self, now: float) -> None:
        age = None
        if self.target_queue_age_s > 0 \
                and self.queue_age_fn is not None:
            age = self.queue_age_fn()
            if age > self.target_queue_age_s:
                self._idle_ticks = 0
                if self.capacity < self.max_workers \
                        and now - self._last_scale \
                        >= self.scale_cooldown_s:
                    self.scale_up(
                        reason=f"queue_age {age:.2f}s > target "
                               f"{self.target_queue_age_s:g}s")
                return
        # second trigger, independent of backlog: the fleet SLO burn
        # rate (obs/fleetplane.py rollup). Errors and p99 blowups burn
        # budget WITHOUT aging the queue — a half-broken fleet answers
        # fast — so queue age alone would never scale it. A breach
        # also resets the idle count: a burning fleet is not idle.
        if self.burn_threshold > 0 and self.burn_rate_fn is not None:
            burn = self.burn_rate_fn()
            if burn > self.burn_threshold:
                self._idle_ticks = 0
                if self.capacity < self.max_workers \
                        and now - self._last_scale \
                        >= self.scale_cooldown_s:
                    self.scale_up(
                        reason=f"slo burn_rate {burn:.2f} > "
                               f"{self.burn_threshold:g} "
                               "(queue age below target)")
                return
        if age is None:
            return
        idle = age == 0.0
        if self.app is not None:
            idle = idle and self.app.scheduler.queue_depth() == 0 \
                and self.app.scheduler.inflight() == 0
        if not idle:
            self._idle_ticks = 0
            return
        self._idle_ticks += 1
        if self._idle_ticks >= self.scale_down_idle_ticks \
                and self.capacity > self.min_workers \
                and now - self._last_scale >= self.scale_cooldown_s:
            self.scale_down(reason=f"idle {self._idle_ticks} ticks")

    def _record_scale(self, direction: str, reason: str) -> None:
        self._last_scale = time.monotonic()
        self._idle_ticks = 0
        self.registry.counter("fleet.scale_events").inc()
        self.registry.counter(f"fleet.scale_{direction}_total").inc()
        log.warning("fleet: scale %s (%s) — capacity now %d",
                    direction, reason, self.capacity)

    def scale_up(self, reason: str = "manual") -> str | None:
        """Spawn one more worker and admit it to the ring. Returns its
        URL, or None if at max capacity / the spawn failed."""
        if self.capacity >= self.max_workers:
            return None
        with self._lock:
            index = (max((s.index for s in self._slots), default=-1)
                     + 1)
            slot = WorkerSlot(index)
            self._slots.append(slot)
        if not self._try_spawn(slot):
            slot.state = STOPPED
            slot.reason = "scale-up spawn failed"
            return None
        slot.state = HEALTHY
        if self.app is not None:
            self.app.add_worker(slot.url)
        self._record_scale("up", reason)
        self.events.emit("scale_up", slot=slot.index,
                         worker=slot.url, reason=reason,
                         capacity=self.capacity)
        self._update_capacity()
        return slot.url

    def pick_scale_down_victim(self) -> WorkerSlot | None:
        """The least-affine serving slot: smallest hash-space share
        (fewest keys remapped by its removal); deterministic
        tie-break by URL."""
        serving = {s.url: s for s in self.slots()
                   if s.state == HEALTHY and s.url}
        if not serving:
            return None
        if self.app is None:
            return serving[sorted(serving)[-1]]
        owned = self.app.ring.ownership()
        url = min(sorted(serving),
                  key=lambda u: owned.get(u, 0.0))
        return serving[url]

    def scale_down(self, reason: str = "manual") -> str | None:
        """Drain and retire the least-affine worker: no new traffic,
        in-flight forwards run to completion (bounded by
        ``drain_timeout_s``), ring removal, SIGTERM (the worker's own
        drain finishes anything the router handed it), reap. Returns
        the retired URL, or None if at min capacity."""
        if self.capacity <= self.min_workers:
            return None
        slot = self.pick_scale_down_victim()
        if slot is None:
            return None
        slot.state = DRAINING
        url = slot.url
        self.events.emit("drain", slot=slot.index, worker=url,
                         reason=reason)
        if self.app is not None:
            self.app.drain_worker(url)
            deadline = time.monotonic() + self.drain_timeout_s
            while self.app.pool.inflight(url) > 0 \
                    and time.monotonic() < deadline:
                time.sleep(0.02)
            self.app.remove_worker(url)
        self._terminate(slot)
        slot.state = STOPPED
        slot.reason = f"scaled down ({reason})"
        self._record_scale("down", reason)
        self.events.emit("scale_down", slot=slot.index, worker=url,
                         reason=reason, capacity=self.capacity)
        self._update_capacity()
        return url
