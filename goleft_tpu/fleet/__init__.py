"""Multi-worker serve fleet: affinity router + admission control.

The serve daemon (goleft_tpu/serve/) is one process — correct and
hardened, but structurally capped at single-process throughput. The
fleet layer scales it horizontally without touching the workers:

  - :mod:`~goleft_tpu.fleet.router`: a thin stdlib HTTP router in
    front of N ``goleft-tpu serve`` workers. Requests route by
    file-identity affinity (consistent hash on the inputs' ``file_key``)
    so each worker's ResultCache and warm jit programs keep seeing the
    same files; workers are health-checked via ``/healthz`` and their
    per-endpoint circuit-breaker state is imported from ``/metrics``,
    so a worker with (say) ``pairhmm`` tripped sheds only pairhmm
    traffic while its depth traffic keeps landing there.
  - :mod:`~goleft_tpu.fleet.admission`: admission control in front of
    the workers' 429 cliff — per-tenant token-bucket quotas (429 +
    ``retry_after_s`` on exhaustion) and deadline-aware, starvation-free
    priority/fairness scheduling of the forwarding slots.
  - :mod:`~goleft_tpu.fleet.supervisor`: the self-healing layer — it
    OWNS the serve subprocesses: worker death and hangs are detected
    and restarted with the resilience backoff, crash-looping slots
    are quarantined (cohortdepth's manifest/exit-3 contract), the
    fleet scales elastically between ``--min-workers`` and
    ``--max-workers`` against the router's queue-age signal, and
    ``--shared-cache`` puts one content-keyed ResultCache tier behind
    every worker so restarts and ring resizes replay instead of
    recompute.
  - :mod:`~goleft_tpu.fleet.smoke`: the ``make fleet-smoke`` body —
    real subprocess daemons proving byte identity (continuous vs
    window batching vs the one-shot CLIs), cross-request step dedup,
    router-level retry across a SIGKILLed worker, and per-tenant quota
    isolation — plus the ``make fleet-chaos`` supervisor legs
    (SIGKILL storm, SIGSTOP hang, crash-loop quarantine, elastic
    scale up/down, shared-cache replay across a restart).

``goleft-tpu fleet`` (commands/fleet.py) spawns the workers and runs
the router; see docs/fleet.md.
"""

from .admission import (  # noqa: F401
    FairScheduler, QuotaExceeded, QuotaTable, SchedulerTimeout,
    TokenBucket,
)
from .router import HashRing, RouterApp, WorkerPool  # noqa: F401
from .supervisor import (  # noqa: F401
    Supervisor, WorkerSlot, WorkerSpawnError,
)
