"""End-to-end federation chaos: the ``make federation-chaos`` body.

Real subprocess tiers all the way down — a federation router fronting
TWO real ``goleft-tpu fleet`` processes (each a supervised fleet of
one serve worker), because the federation's contracts are precisely
about whole-process failure domains:

  1. **tenant-scoped overload isolation**: a flooding tenant
     (``mallory``, best-effort priority, hammering a fleet-level
     quota) drives its ``federation.tenant.burn_rate.mallory`` gauge
     over the threshold and is SHED at the federation front door
     (429, ``shed: tenant-burn``, honest ``retry_after_s``) — while a
     quiet tenant's (``alice``) concurrent requests ALL land with
     byte-identical bodies. Isolation by contract, not side effect.
  2. **whole-fleet failover**: SIGKILL of the affinity home fleet's
     ROUTER (the fleet's single point of failure) mid-flight yields
     byte-identical 200s through the surviving fleet, within the
     client's retry budget — capacity degrades, availability does
     not.
  3. **half-open rejoin + key migration home**: the killed fleet's
     router is restarted (attach mode, fronting the worker that
     survived it), the federation's poller half-opens it, and the
     next request for its affinity key routes HOME again —
     byte-identically, with the probe/rejoin counters telling the
     story.

Run directly::

    python -m goleft_tpu.fleet.federation_smoke
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request


def _wait_until(pred, timeout_s: float, what: str,
                interval_s: float = 0.1):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(interval_s)
    raise RuntimeError(f"timed out waiting for {what}")


def _get_json(url: str, timeout_s: float = 30.0):
    req = urllib.request.Request(
        url, headers={"Accept": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout_s) as r:
        return json.loads(r.read().decode())


def _post(url: str, body: dict, timeout_s: float = 120.0):
    """(status, parsed body) — non-2xx included, no retries."""
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json",
                 "Accept": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        raw = e.read()
        try:
            return e.code, json.loads(raw.decode())
        except ValueError:
            return e.code, {}


def _spawn(args: list[str], env: dict) -> tuple:
    """Spawn a goleft-tpu subcommand, return (proc, announced url)."""
    proc = subprocess.Popen(
        [sys.executable, "-m", "goleft_tpu", *args],
        stdout=subprocess.PIPE, text=True, env=env)
    line = ""
    deadline = time.monotonic() + 300
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line or "listening on " in line:
            break
    if "listening on " not in line:
        proc.kill()
        proc.wait(timeout=10)
        raise RuntimeError(
            f"{args[0]} never announced (last line {line!r})")
    return proc, line.rsplit("listening on ", 1)[1].strip()


def _kill(proc, sig=signal.SIGTERM, timeout_s: float = 60.0):
    if proc is None:
        return
    if proc.poll() is None:
        proc.send_signal(sig)
        try:
            proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)
    if proc.stdout is not None:
        proc.stdout.close()


def _leg_tenant_shed(fed_url, bam, fai, verbose):
    baseline = _post(fed_url + "/v1/depth",
                     {"bam": bam, "fai": fai, "tenant": "alice"})
    if baseline[0] != 200 or not baseline[1].get("depth_bed"):
        raise RuntimeError(f"baseline depth failed: {baseline}")
    base_bed = baseline[1]["depth_bed"]

    mallory_codes: list[tuple] = []

    def flood():
        for _ in range(14):
            code, body = _post(
                fed_url + "/v1/depth",
                {"bam": bam, "fai": fai, "tenant": "mallory",
                 "priority": 1}, timeout_s=120.0)
            mallory_codes.append((code, body))

    t = threading.Thread(target=flood)
    t.start()
    alice_beds = []
    for _ in range(3):
        code, body = _post(fed_url + "/v1/depth",
                           {"bam": bam, "fai": fai,
                            "tenant": "alice"})
        if code != 200:
            raise RuntimeError(
                f"quiet tenant alice got {code} during the flood: "
                f"{body}")
        alice_beds.append(body.get("depth_bed"))
    t.join(timeout=300)
    if any(bed != base_bed for bed in alice_beds):
        raise RuntimeError(
            "quiet tenant's responses were not byte-identical "
            "during the flood")
    sheds = [b for c, b in mallory_codes
             if c == 429 and b.get("shed") == "tenant-burn"]
    if not sheds:
        raise RuntimeError(
            "flooding tenant was never federation-shed: "
            f"{[(c, b.get('error', '')[:40]) for c, b in mallory_codes]}")
    if any(not isinstance(b.get("retry_after_s"), (int, float))
           or b["retry_after_s"] <= 0 for b in sheds):
        raise RuntimeError("a tenant shed carried no honest "
                           "retry_after_s")
    m = _get_json(fed_url + "/metrics")
    burn = m["gauges"].get("federation.tenant.burn_rate.mallory", 0)
    if burn <= 2.0:
        raise RuntimeError(
            f"mallory burn gauge {burn} not breaching in JSON")
    if m["counters"].get(
            "federation.tenant_shed_total.mallory", 0) < 1:
        raise RuntimeError("tenant shed counter missing")
    if "federation.tenant_shed_total.alice" in m["counters"]:
        raise RuntimeError("quiet tenant was shed")
    # the same gauge through the Prometheus encoding
    req = urllib.request.Request(
        fed_url + "/metrics?format=prom",
        headers={"Accept": "text/plain"})
    with urllib.request.urlopen(req, timeout=30) as r:
        prom = r.read().decode()
    if "federation_tenant_burn_rate_mallory" not in prom:
        raise RuntimeError("burn gauge missing from prom encoding")
    if verbose:
        print("federation-chaos: flooding mallory shed at the "
              f"federation ({len(sheds)} sheds, burn {burn:.1f}) "
              "while alice's 3 concurrent requests all landed "
              "byte-identical, gauges in both encodings")
    return base_bed


def _leg_fleet_failover(fed_url, fleets, bam, fai, base_bed,
                        verbose):
    plan = _post(fed_url + "/fleet/plan",
                 {"kind": "depth", "bam": bam, "fai": fai})[1]
    home_url = plan["candidates"][0]
    home = fleets[home_url]

    results: list = []

    def inflight():
        results.append(_post(fed_url + "/v1/depth",
                             {"bam": bam, "fai": fai,
                              "tenant": "alice"}, timeout_s=180.0))

    t = threading.Thread(target=inflight)
    t.start()
    time.sleep(0.05)
    # SIGKILL the ENTIRE fleet's router — the fleet tier's single
    # point of failure (its supervisor and worker die with... no:
    # the worker survives as an orphan; the fleet as a SERVING unit
    # is gone, which is exactly the failure domain under test)
    home["proc"].kill()
    home["proc"].wait(timeout=30)
    t.join(timeout=300)
    code, body = results[0]
    if code != 200 or body.get("depth_bed") != base_bed:
        raise RuntimeError(
            f"in-flight request over the SIGKILL was not a "
            f"byte-identical 200 (code {code})")
    # and a fresh request after the kill fails over identically
    code, body = _post(fed_url + "/v1/depth",
                       {"bam": bam, "fai": fai, "tenant": "alice"},
                       timeout_s=180.0)
    if code != 200 or body.get("depth_bed") != base_bed:
        raise RuntimeError(
            f"post-kill request not byte-identical 200 ({code})")
    m = _get_json(fed_url + "/metrics")
    if m["counters"].get("federation.fleet_down_total", 0) < 1:
        raise RuntimeError("fleet_down_total never counted")
    h = _get_json(fed_url + "/healthz")
    if h["fleets_up"] >= h["fleets"]:
        raise RuntimeError("healthz does not report the lost fleet")
    if verbose:
        print("federation-chaos: home fleet router SIGKILLed "
              "mid-flight -> byte-identical 200s via the surviving "
              f"fleet (fleets_up={h['fleets_up']}/{h['fleets']})")
    return home_url


def _leg_rejoin_routes_home(fed_url, fleets, home_url, bam, fai,
                            base_bed, env, verbose):
    home = fleets[home_url]
    port = home_url.rsplit(":", 1)[-1]
    # restart the fleet ROUTER on its old port, attaching the worker
    # that survived the router's death (attach mode: the healed
    # fleet fronts the same warm worker)
    proc, url = _spawn(["fleet", "--port", port,
                        "--worker", home["worker_url"],
                        "--poll-interval-s", "0.3",
                        "--down-after", "1",
                        *home["quota_args"]], env)
    if url.rstrip("/") != home_url:
        raise RuntimeError(f"restarted fleet landed at {url}, "
                           f"want {home_url}")
    fleets[home_url]["proc"] = proc
    rejoins0 = _get_json(fed_url + "/metrics")["counters"].get(
        "federation.fleet_rejoin_total", 0)

    def half_open():
        m = _get_json(fed_url + "/metrics")
        return m["fleets"][home_url]["state"] in ("probe", "up")

    _wait_until(half_open, 60.0, "federation to half-open the "
                                 "healed fleet")
    # the next request for the fleet's affinity key is the probe —
    # and it must route HOME, byte-identically
    code, body = _post(fed_url + "/v1/depth",
                       {"bam": bam, "fai": fai, "tenant": "alice"},
                       timeout_s=180.0)
    if code != 200 or body.get("depth_bed") != base_bed:
        raise RuntimeError(
            f"post-rejoin request not byte-identical 200 ({code})")
    m = _get_json(fed_url + "/metrics")
    if m["fleets"][home_url]["state"] != "up":
        raise RuntimeError(
            f"healed fleet not UP after the probe: "
            f"{m['fleets'][home_url]}")
    if m["counters"].get("federation.fleet_rejoin_total",
                         0) <= rejoins0:
        raise RuntimeError("rejoin never counted")
    routed = m["counters"].get(
        f"federation.routed_total.{port}.depth", 0)
    if routed < 1:
        raise RuntimeError(
            f"request did not route home after rejoin "
            f"(routed_total.{port}.depth={routed})")
    plan = _post(fed_url + "/fleet/plan",
                 {"kind": "depth", "bam": bam, "fai": fai})[1]
    if plan["candidates"][0] != home_url:
        raise RuntimeError("affinity plan no longer homes the key")
    if verbose:
        print("federation-chaos: healed fleet half-open probed, "
              "rejoined, and its affinity key routed home "
              "byte-identically")


def run_smoke(timeout_s: float = 600.0, verbose: bool = True) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",     # CI has no accelerator
               GOLEFT_TPU_PROBE="0")    # don't pay a probe timeout
    env.pop("GOLEFT_TPU_FAULTS", None)  # hermetic
    from ..resilience.smoke import _make_cohort

    t0 = time.monotonic()
    quota_args = ["--quota", "mallory=1:1"]
    fleets: dict[str, dict] = {}
    fed = None
    with tempfile.TemporaryDirectory(prefix="goleft_fedc_") as d:
        bams, fai, _bed = _make_cohort(d, ref_len=20_000)
        bam = bams[0]
        try:
            for i in range(2):
                proc, url = _spawn(
                    ["fleet", "--port", "0", "--workers", "1",
                     "--poll-interval-s", "0.3", "--down-after", "1",
                     "--supervise-interval-s", "0.1",
                     *quota_args, "--worker-args=--no-warmup"], env)
                url = url.rstrip("/")
                slots = _get_json(url + "/metrics")["supervisor"][
                    "slots"]
                fleets[url] = {"proc": proc,
                               "worker_url": slots[0]["url"],
                               "worker_pid": slots[0]["pid"],
                               "quota_args": quota_args}
                if verbose:
                    print(f"federation-chaos: fleet {i} at {url} "
                          f"(worker {slots[0]['url']})")
            fed, fed_url = _spawn(
                ["federation", "--port", "0",
                 *[a for u in fleets for a in ("--fleet", u)],
                 "--poll-interval-s", "0.3", "--down-after", "1",
                 "--tenant-burn-threshold", "2.0",
                 "--tenant-shed-min", "4"], env)

            def fleets_up():
                try:
                    return _get_json(fed_url + "/healthz")[
                        "fleets_up"] == 2
                except Exception:  # noqa: BLE001 — 503 while down
                    return False

            _wait_until(fleets_up, 120.0, "both fleets up")
            base_bed = _leg_tenant_shed(fed_url, bam, fai, verbose)
            home_url = _leg_fleet_failover(fed_url, fleets, bam,
                                           fai, base_bed, verbose)
            _leg_rejoin_routes_home(fed_url, fleets, home_url, bam,
                                    fai, base_bed, env, verbose)
        finally:
            _kill(fed)
            for rec in fleets.values():
                _kill(rec["proc"])
            # the failover leg's SIGKILL orphans that fleet's worker
            # (the restarted router attaches but does not own it) —
            # reap by pid so the smoke leaves nothing behind
            for rec in fleets.values():
                try:
                    os.kill(rec["worker_pid"], signal.SIGKILL)
                except (OSError, ProcessLookupError):
                    pass
        if time.monotonic() - t0 > timeout_s:
            raise RuntimeError(
                f"federation-chaos exceeded its {timeout_s:g}s "
                "budget")
    if verbose:
        print(f"federation-chaos: PASS "
              f"({time.monotonic() - t0:.1f}s)")
    return 0


if __name__ == "__main__":
    sys.exit(run_smoke())
