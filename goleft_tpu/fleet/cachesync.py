"""Cross-fleet shared-cache replication (the warm-failover tier).

PR 14's federation made whole-fleet failover *available*; this module
makes it *cheap*. Each fleet advertises its shared result-cache
directory over the router's ``/fleet/cache`` endpoint (list entries,
fetch one, accept a push); :class:`CacheSync`, running on the
federation process, runs anti-entropy rounds over the UP fleets:
every entry any fleet holds is pushed to every fleet missing it. When
the home fleet dies, the survivor already holds its content-keyed
results — failover is cache replay, not recompute (the dataplane
smoke pins ``serve_device_passes_total == 0`` on the survivor). A
half-open rejoin kicks off an immediate round on a background thread
(the federation wires ``FleetPool.on_rejoin`` to
:meth:`CacheSync.sync_soon` — the hook fires inside a live request's
``settle_forward``, so the warm-up must never run inline), re-warming
a healed fleet while it serves.

Safety argument (why replication cannot corrupt results):

  - pushes are **authenticated**: cache entries are pickles, so an
    unauthenticated PUT endpoint would hand code execution to anyone
    who can reach the router port. Every push carries an HMAC-SHA256
    over ``name NUL data`` keyed by the shared fleet secret
    (``GOLEFT_TPU_FLEET_SECRET``); a router without the secret
    refuses pushes outright — replication is strictly opt-in;
  - existing entries are **never overwritten**: names are
    content-keyed (a ResultCache filename is
    ``sha256(repr(key))[:32] + ".pkl"`` where the key pins every
    input's content identity, ``file_key``/``remote_file_key``, plus
    the canonical parameters), so "same name" means "same bytes" and
    a replayed or duplicate push is an idempotent no-op;
  - writes are **atomic** (tmp + ``os.replace`` on the receiving
    router), so readers never observe a torn entry;
  - the name alphabet (32 hex chars + ``.pkl``) is validated on both
    ends — no traversal, and nothing that is not a ResultCache entry
    replicates;
  - entries above :data:`MAX_ENTRY_BYTES` are refused server-side
    (413 before the body is read), so a misbehaving peer cannot
    exhaust the jax-free router's memory.

Replication is best-effort by design: a failed pull/push is counted
(``cachesync.errors_total``) and retried on the next round; the cache
is an optimization tier and correctness never depends on it.
"""

from __future__ import annotations

import hmac as _hmac
import os
import threading
import urllib.error
import urllib.request

from ..obs.logging import get_logger

log = get_logger("fleet.cachesync")

#: header carrying the push's HMAC (hex) — see :func:`entry_hmac`
CACHE_AUTH_HEADER = "X-Goleft-Cache-Auth"


def fleet_secret() -> str | None:
    """The shared fleet secret (``GOLEFT_TPU_FLEET_SECRET``), or None
    when replication is disabled."""
    return os.environ.get("GOLEFT_TPU_FLEET_SECRET") or None


def entry_hmac(secret: str, name: str) -> "_hmac.HMAC":
    """A fresh HMAC-SHA256 over ``name NUL data`` keyed by the fleet
    secret; callers ``update()`` with the entry bytes (streamed or
    whole) and compare hexdigests with ``compare_digest``."""
    return _hmac.new(secret.encode(), name.encode() + b"\x00",
                     "sha256")

#: don't replicate entries bigger than this (a runaway pickle should
#: not saturate the control plane); env-free constant — the cap is a
#: safety valve, not a tuning knob
MAX_ENTRY_BYTES = 256 << 20


class CacheSync:
    """Anti-entropy replication over the fleets' cache endpoints.

    ``fleet_urls`` is a callable returning the base URLs to sync
    across (the federation passes its UP set, so a DOWN fleet is
    never waited on). One round: list every fleet's entries, compute
    the union, pull each missing entry from a holder, push it to each
    fleet that lacks it.
    """

    def __init__(self, fleet_urls, interval_s: float = 5.0,
                 registry=None, timeout_s: float = 30.0,
                 secret: str | None = None):
        self.fleet_urls = fleet_urls
        self.interval_s = interval_s
        self.timeout_s = timeout_s
        self.secret = secret if secret is not None else fleet_secret()
        self._registry = registry
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()
        self._warned_no_secret = False

    # ---- registry plumbing (works with or without metrics) ----

    def _inc(self, name: str, n: int = 1) -> None:
        if self._registry is not None:
            self._registry.counter(name).inc(n)

    # ---- HTTP plumbing (stdlib, no retries: next round retries) ----

    def _get(self, url: str):
        req = urllib.request.Request(url)
        with urllib.request.urlopen(req,
                                    timeout=self.timeout_s) as r:
            return r.read()

    def _put(self, url: str, name: str, data: bytes) -> None:
        mac = entry_hmac(self.secret, name)
        mac.update(data)
        req = urllib.request.Request(
            url, data=data, method="PUT",
            headers={CACHE_AUTH_HEADER: mac.hexdigest()})
        with urllib.request.urlopen(req,
                                    timeout=self.timeout_s) as r:
            r.read()

    def _list(self, fleet: str) -> set | None:
        import json

        try:
            body = json.loads(self._get(
                fleet.rstrip("/") + "/fleet/cache/").decode())
            return {e["name"] for e in body.get("entries", ())
                    if e.get("size", 0) <= MAX_ENTRY_BYTES}
        except Exception as e:  # noqa: BLE001 — best-effort tier
            log.debug("cache list failed for %s: %s", fleet, e)
            self._inc("cachesync.errors_total")
            return None

    # ---- the round ----

    def sync_now(self, reason: str = "interval") -> dict:
        """One anti-entropy round; returns a summary dict (the tests'
        observable). Serialized under a lock — a rejoin-triggered
        round never interleaves with the timer's."""
        with self._lock:
            return self._sync_locked(reason)

    def sync_soon(self, reason: str = "rejoin") -> threading.Thread:
        """Run one round on a background daemon thread and return it
        (tests join it). This is what event hooks wire up — a full
        round lists/pulls/pushes every entry across every fleet under
        per-call network timeouts, so running it synchronously from
        ``FleetPool.settle_forward`` would stall the live client
        request that triggered the rejoin."""
        def _run():
            try:
                self.sync_now(reason)
            except Exception as e:  # noqa: BLE001 — hook must not raise
                log.warning("cachesync %s round failed: %s", reason, e)
                self._inc("cachesync.errors_total")

        t = threading.Thread(target=_run, name="cachesync-" + reason,
                             daemon=True)
        t.start()
        return t

    def _sync_locked(self, reason: str) -> dict:
        fleets = [u.rstrip("/") for u in self.fleet_urls()]
        summary = {"reason": reason, "fleets": len(fleets),
                   "replicated": 0, "bytes": 0, "errors": 0}
        self._inc("cachesync.rounds_total")
        if reason == "rejoin":
            self._inc("cachesync.rejoin_syncs_total")
        if self.secret is None:
            # pushes would be refused (403) without the shared
            # secret — don't burn pulls on rounds that cannot land
            if not self._warned_no_secret:
                self._warned_no_secret = True
                log.warning(
                    "cachesync: no fleet secret configured (set "
                    "GOLEFT_TPU_FLEET_SECRET on every fleet and the "
                    "federation) — cache replication is disabled")
            summary["disabled"] = True
            return summary
        if len(fleets) < 2:
            return summary
        have: dict = {}
        for f in fleets:
            names = self._list(f)
            if names is not None:
                have[f] = names
        if len(have) < 2:
            summary["errors"] = 1
            return summary
        union: set = set()
        for names in have.values():
            union |= names
        for name in sorted(union):
            holders = [f for f, names in have.items() if name in names]
            missing = [f for f in have if name not in have[f]]
            if not holders or not missing:
                continue
            data = None
            for h in holders:
                try:
                    data = self._get(
                        h + "/fleet/cache/" + name)
                    break
                except Exception as e:  # noqa: BLE001 — try next holder
                    log.debug("cache pull %s from %s failed: %s",
                              name, h, e)
                    self._inc("cachesync.errors_total")
                    summary["errors"] += 1
            if data is None:
                continue
            for m in missing:
                try:
                    self._put(m + "/fleet/cache/" + name, name, data)
                    self._inc("cachesync.entries_replicated_total")
                    self._inc("cachesync.bytes_replicated_total",
                              len(data))
                    summary["replicated"] += 1
                    summary["bytes"] += len(data)
                except Exception as e:  # noqa: BLE001 — next round retries
                    log.debug("cache push %s to %s failed: %s",
                              name, m, e)
                    self._inc("cachesync.errors_total")
                    summary["errors"] += 1
        if summary["replicated"]:
            log.info("cachesync (%s): replicated %d entr%s / %d "
                     "bytes across %d fleets", reason,
                     summary["replicated"],
                     "y" if summary["replicated"] == 1 else "ies",
                     summary["bytes"], len(have))
        return summary

    # ---- lifecycle ----

    def start(self) -> "CacheSync":
        if self.interval_s <= 0:
            return self  # sync_now-only mode (rejoin hook still works)
        self._thread = threading.Thread(
            target=self._loop, name="cachesync", daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self.interval_s)
            self._wake.clear()
            if self._stop.is_set():
                return
            try:
                self.sync_now("interval")
            except Exception as e:  # noqa: BLE001 — the loop survives
                log.warning("cachesync round failed: %s", e)
                self._inc("cachesync.errors_total")

    def poke(self) -> None:
        """Wake the timer loop early (tests)."""
        self._wake.set()

    def close(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
