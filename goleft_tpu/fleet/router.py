"""File-affinity router: one thin process in front of N serve workers.

Pure stdlib (urllib + http.server), deliberately ignorant of jax and
the workloads — the router never decodes an input or touches a device,
so it stays cheap enough to front many workers. What it DOES know:

  - **affinity** (:class:`HashRing`): requests route by consistent
    hash of their input files' ``file_key`` (path + size + mtime_ns),
    so the same file keeps landing on the same worker — that worker's
    ResultCache replays it and its jitted programs stay warm for the
    geometries that file produces. Consistent hashing means adding or
    losing a worker remaps only the keys that worker owned, not the
    whole fleet's cache locality.
  - **health**: a background poller hits each worker's ``/healthz``
    (and ``/metrics``) every ``poll_interval_s``; a worker that fails
    ``down_after`` consecutive polls (or reports draining) stops
    receiving traffic until it recovers.
  - **per-site breaker import**: the poller reads each worker's
    ``breakers`` block from ``/metrics``. A worker whose ``pairhmm``
    breaker is open is excluded from pairhmm candidates ONLY — its
    depth/indexcov/cohortdepth traffic keeps landing there. The same
    worker 503 (a breaker answer carrying ``retry_after_s``) is also
    handled reactively: the request is re-routed to the next ring
    candidate immediately, before the next poll could notice.
  - **retry on worker death**: a connection-level failure (refused,
    reset mid-flight — a SIGKILLed worker) marks the worker down and
    retries the request on the next ring candidate
    (``fleet.retries_total``). Safe because every workload here is a
    deterministic read-only computation; the worker answers or it
    doesn't.
  - **admission** (:mod:`~goleft_tpu.fleet.admission`): per-tenant
    token-bucket quotas (429 + ``retry_after_s``) and fair,
    deadline-aware forwarding slots run BEFORE any bytes are
    forwarded. An optional availability shed (``shed_below``) drops
    best-effort traffic (priority > 0) with 503 while the fleet's
    polled SLO availability is under the threshold.

``redirect=True`` answers ``307 Temporary Redirect`` with the chosen
worker's URL instead of proxying the body — for clients that can
follow redirects (serve/client.py does), this takes the router out of
the data path entirely.

Routes: ``POST /v1/<kind>`` (proxied), ``GET /healthz`` (fleet
summary), ``GET /metrics`` (router registry snapshot + per-worker
state), ``POST /fleet/plan`` (debug: the candidate order a body would
route to, no forwarding).
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
import urllib.error
import urllib.request
from bisect import bisect_right
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .. import obs
from ..obs.fleetplane import (
    TRACE_HEADER, format_trace_header, merge_worker_metrics,
    parse_trace_header, perfetto_export, poll_jitter_frac,
    rollup_registry_snapshot, stitch_trace,
)
from ..obs.logging import get_logger
from ..obs.metrics import MetricsRegistry
from .admission import (
    FairScheduler, QuotaExceeded, QuotaTable, SchedulerTimeout,
)

log = get_logger("fleet.router")

def _file_key(path: str) -> tuple:
    """(abspath, size, mtime_ns) — the SAME definition as
    ``parallel.scheduler.file_key`` (pinned by tests/test_fleet.py),
    duplicated here because importing it drags the whole
    ``goleft_tpu.parallel`` package — and jax — into the router
    process, whose entire point is staying a cheap jax-free
    forwarder. Remote URLs route through
    ``io.remote.routing_file_key`` (jax-free, parity-pinned — on
    success it returns the SAME (url, length, etag) identity as
    ``remote_file_key``, keeping fleet and worker affinity aligned),
    whose probe gets ONE attempt under a tight routing timeout with
    failures negative-cached: a slow object store must not stall the
    request path for the full fetch retry budget."""
    import os

    if "://" in path:
        from ..io import remote

        if remote.is_remote(path):
            return remote.routing_file_key(path)
    st = os.stat(path)
    return (os.path.abspath(path), st.st_size, st.st_mtime_ns)


#: request field naming the files whose identity is the affinity key
AFFINITY_FIELDS = {
    "depth": ("bam",),
    "indexcov": ("bams",),
    "cohortdepth": ("bams",),
    "cohortscan": ("bams",),
    "pairhmm": ("input",),
    "map": ("fastq",),
}


def request_affinity_key(kind: str, req: dict) -> str:
    """The ring key for one request: every input file's content
    identity, in order. Falls back to the raw path when the file
    cannot be stat'd (routing must not 500 a request validation will
    400) and to the canonical body when the request names no file.
    Shared by the fleet router (worker affinity) and the federation
    tier (fleet affinity) — the SAME key at both levels is what keeps
    a file's whole serving path (fleet, worker, caches, jits) warm."""
    paths: list[str] = []
    for field in AFFINITY_FIELDS.get(kind, ()):
        v = req.get(field)
        if isinstance(v, str):
            paths.append(v)
        elif isinstance(v, (list, tuple)):
            paths.extend(p for p in v if isinstance(p, str))
    if not paths:
        return kind + ":" + json.dumps(
            {k: v for k, v in sorted(req.items())
             if k not in ("tenant", "priority", "timeout_s")},
            sort_keys=True, default=str)
    parts = []
    for p in paths:
        try:
            parts.append(repr(_file_key(p)))
        except (OSError, ValueError):
            # OSError: unstat'able path / unreachable URL past the
            # fetch retry budget; ValueError: unresolvable scheme —
            # either way the raw path still routes deterministically
            parts.append(p)
    return "|".join(parts)


class HashRing:
    """Consistent hash ring with virtual nodes.

    ``candidates(key)`` returns EVERY node, ordered by ring walk from
    the key's position — element 0 is the affinity home, the rest are
    the deterministic failover order. Adding/removing a node moves
    only ~1/N of the keyspace (the property that keeps worker caches
    warm across fleet resizes).

    Membership changes are **copy-on-write**: ``with_node`` /
    ``without_node`` return a NEW ring sharing nothing mutable, so the
    router can swap its ring reference atomically while handler
    threads keep walking the old one — no lock on the request path,
    and a key's candidate order over the surviving nodes is provably
    identical before and after a resize (each node contributes its own
    hash points and nothing else; removing a node deletes exactly its
    points). Point positions depend only on (node name, vnode index)
    through sha256, so every process that builds a ring from the same
    membership computes the same plan — the cross-process determinism
    the smoke tests and the supervisor both lean on.
    """

    def __init__(self, nodes: list[str], vnodes: int = 64):
        if not nodes:
            raise ValueError("HashRing needs at least one node")
        self.nodes = list(nodes)
        self.vnodes = vnodes
        self._points: list[tuple[int, str]] = sorted(
            (self._hash(f"{node}#{i}"), node)
            for node in nodes for i in range(vnodes))
        self._keys = [p for p, _ in self._points]

    @staticmethod
    def _hash(s: str) -> int:
        return int.from_bytes(
            hashlib.sha256(s.encode()).digest()[:8], "big")

    def candidates(self, key: str) -> list[str]:
        start = bisect_right(self._keys, self._hash(key))
        seen: list[str] = []
        n = len(self._points)
        for i in range(n):
            node = self._points[(start + i) % n][1]
            if node not in seen:
                seen.append(node)
                if len(seen) == len(self.nodes):
                    break
        return seen

    # ---- dynamic membership (copy-on-write) ----

    def with_node(self, node: str) -> "HashRing":
        """A new ring with ``node`` added (idempotent)."""
        if node in self.nodes:
            return self
        return HashRing(self.nodes + [node], vnodes=self.vnodes)

    def without_node(self, node: str) -> "HashRing":
        """A new ring with ``node`` removed. Removing the LAST node
        returns the ring unchanged: an empty ring cannot answer
        ``candidates`` at all, and a fleet that lost every worker
        still wants a deterministic plan for when one returns — the
        pool's eligibility filter (not the ring) is what actually
        stops traffic."""
        if node not in self.nodes or len(self.nodes) == 1:
            return self
        return HashRing([n for n in self.nodes if n != node],
                        vnodes=self.vnodes)

    def ownership(self) -> dict:
        """{node: fraction of the hash space it owns}. The supervisor
        uses this to pick the LEAST-AFFINE scale-down victim: removing
        the smallest owner remaps the fewest keys (and therefore
        invalidates the least private-cache locality)."""
        span = 2.0 ** 64
        owned = {n: 0.0 for n in self.nodes}
        pts = self._points
        for i, (pos, _node) in enumerate(pts):
            # the arc (previous point, this point] belongs to the node
            # AT this point (bisect_right walks clockwise to it)
            prev = pts[i - 1][0] if i else pts[-1][0] - span
            owned[pts[i][1]] += (pos - prev) / span
        return owned


class _Worker:
    """Mutable polled state for one worker (lock: the pool's)."""

    def __init__(self, url: str):
        self.url = url.rstrip("/")
        self.healthy = True      # optimistic until a poll says otherwise
        self.draining = False
        self.admin_draining = False  # supervisor-imposed (scale-down):
        # the poller must NOT clear it — it reflects an operator/
        # supervisor decision, not the worker's self-reported state
        self.inflight = 0        # forwards currently inside _forward
        self.consecutive_fails = 0
        self.open_breakers: frozenset[str] = frozenset()
        self.availability: float | None = None
        self.clock_offset_s: float | None = None  # estimated wall-
        # clock skew (positive = this worker's clock runs AHEAD of
        # ours), midpoint-of-poll estimate, EWMA-smoothed — the
        # stitcher's cross-host rebase correction
        self.last_poll_s: float | None = None
        self.last_metrics: dict | None = None  # full polled /metrics
        # body — the fleet rollup's raw material (None until a poll
        # lands; cleared never: a stale snapshot beats an empty fleet
        # view during a worker's restart window)
        self.next_poll_at = 0.0  # monotonic; phase-offset per worker


class WorkerPool:
    """Polled worker state + the poller thread."""

    def __init__(self, urls: list[str], poll_interval_s: float = 2.0,
                 down_after: int = 2, timeout_s: float = 5.0,
                 registry: MetricsRegistry | None = None):
        self.workers = {u.rstrip("/"): _Worker(u) for u in urls}
        self.poll_interval_s = poll_interval_s
        self.down_after = down_after
        self.timeout_s = timeout_s
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        for w in self.workers.values():
            self._schedule_first_poll(w)
        self._thread = threading.Thread(
            target=self._poll_loop, daemon=True,
            name="goleft-fleet-poller")

    def _schedule_first_poll(self, w: _Worker) -> None:
        # deterministic hash jitter (the RetryPolicy trick): each
        # worker's scrape phase is offset by a stable fraction of the
        # interval, so N workers spread across it instead of being
        # scraped in one tick burst every poll_interval_s
        w.next_poll_at = time.monotonic() + \
            poll_jitter_frac(w.url) * self.poll_interval_s

    def start(self) -> "WorkerPool":
        self.poll_all()  # synchronous first poll: route on real state
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=10.0)

    # ---- polling ----

    def _fetch_json(self, url: str) -> dict:
        req = urllib.request.Request(
            url, headers={"Accept": "application/json"})
        with urllib.request.urlopen(req,
                                    timeout=self.timeout_s) as r:
            return json.loads(r.read().decode())

    def _poll_one(self, w: _Worker) -> None:
        try:
            t0_wall = time.time()
            h = self._fetch_json(w.url + "/healthz")
            t1_wall = time.time()
            m = self._fetch_json(w.url + "/metrics")
        except Exception as e:  # noqa: BLE001 — any poll failure = a miss
            with self._lock:
                w.consecutive_fails += 1
                w.last_poll_s = time.monotonic()
                if w.consecutive_fails >= self.down_after \
                        and w.healthy:
                    w.healthy = False
                    log.warning("fleet: worker %s marked DOWN (%r)",
                                w.url, e)
                    self.registry.counter(
                        "fleet.worker_down_total").inc()
            return
        from ..resilience.breaker import is_shedding

        breakers = frozenset(
            kind for kind, state in (m.get("breakers") or {}).items()
            if is_shedding(state))
        slo = m.get("slo") or {}
        # clock handshake: the worker stamped its wall clock into the
        # healthz body; the midpoint of our request/response wall
        # stamps is the unbiased estimate of when that stamp was taken
        # on OUR clock, so the difference is the worker's skew.
        # EWMA-smoothed: one slow poll (asymmetric network time) must
        # not jerk the stitched timeline around.
        offset = None
        if isinstance(h.get("now"), (int, float)) \
                and not isinstance(h.get("now"), bool):
            offset = float(h["now"]) - (t0_wall + t1_wall) / 2.0
        with self._lock:
            if not w.healthy:
                log.warning("fleet: worker %s recovered", w.url)
            w.consecutive_fails = 0
            w.healthy = h.get("status") == "ok"
            w.draining = h.get("status") == "draining"
            w.open_breakers = breakers
            w.availability = slo.get("availability")
            if offset is not None:
                w.clock_offset_s = offset if w.clock_offset_s is None \
                    else 0.7 * w.clock_offset_s + 0.3 * offset
            w.last_metrics = m
            w.last_poll_s = time.monotonic()

    def poll_all(self) -> None:
        for w in list(self.workers.values()):
            self._poll_one(w)

    def _due_workers(self, now: float) -> list[_Worker]:
        """The workers whose scheduled poll time has arrived — read
        under the pool lock: the supervisor's ``add()`` writes a new
        worker's phase offset concurrently (gtlint lck-foreign-write;
        every ``_Worker`` field access shares the pool lock)."""
        with self._lock:
            return [w for w in self.workers.values()
                    if w.next_poll_at <= now]

    def _advance_schedule(self, w: _Worker) -> None:
        """Step one worker's schedule by an interval (under the pool
        lock — same discipline as :meth:`_due_workers`); a worker that
        fell behind (slow worker, long timeout) is re-phased rather
        than burst-caught-up."""
        with self._lock:
            w.next_poll_at += self.poll_interval_s
            if w.next_poll_at <= time.monotonic():
                w.next_poll_at = time.monotonic() \
                    + self.poll_interval_s

    def _next_poll_due(self, default: float) -> float:
        with self._lock:
            return min((w.next_poll_at
                        for w in self.workers.values()),
                       default=default)

    def _poll_loop(self) -> None:
        # per-worker periodic schedule with the deterministic phase
        # offsets from _schedule_first_poll: the loop wakes for the
        # earliest due worker, polls whatever is due, and sleeps again
        # — never the whole fleet in one burst
        while not self._stop.is_set():
            now = time.monotonic()
            for w in self._due_workers(now):
                self._poll_one(w)
                self._advance_schedule(w)
            nxt = self._next_poll_due(now + self.poll_interval_s)
            wait = min(self.poll_interval_s,
                       max(0.02, nxt - time.monotonic()))
            self._stop.wait(wait)

    def clock_offsets(self) -> dict[str, float]:
        """{url: estimated wall-clock offset seconds} over workers
        with an estimate — the trace stitcher's rebase correction."""
        with self._lock:
            return {u: w.clock_offset_s
                    for u, w in sorted(self.workers.items())
                    if w.clock_offset_s is not None}

    def metrics_by_worker(self) -> dict[str, dict]:
        """{label: last polled /metrics body} over workers that have
        reported at least once — the fleet rollup's input. The label
        is the port (the stable short form the counters already use)."""
        with self._lock:
            items = [(w.url.rsplit(":", 1)[-1], w.last_metrics)
                     for w in self.workers.values()]
        return {label: m for label, m in items if m is not None}

    # ---- dynamic membership (the supervisor's levers) ----

    def add(self, url: str) -> None:
        """Admit a new worker (idempotent). It enters optimistic (the
        supervisor only adds a worker that already announced its URL);
        the next poll replaces optimism with evidence."""
        url = url.rstrip("/")
        with self._lock:
            if url not in self.workers:
                w = self.workers[url] = _Worker(url)
                self._schedule_first_poll(w)

    def remove(self, url: str) -> None:
        """Forget a worker entirely (idempotent) — after its process
        exited or its drain completed. In-flight forwards to it (if
        any) finish on their own; end_forward tolerates the missing
        entry."""
        with self._lock:
            self.workers.pop(url.rstrip("/"), None)

    def set_draining(self, url: str, draining: bool = True) -> None:
        """Administratively drain a worker: it stops receiving NEW
        traffic (``eligible`` excludes it) while in-flight forwards
        run to completion — the scale-down half of drain-before-
        removal."""
        w = self.workers.get(url.rstrip("/"))
        if w is None:
            return
        with self._lock:
            w.admin_draining = draining

    def begin_forward(self, url: str) -> None:
        w = self.workers.get(url.rstrip("/"))
        if w is None:
            return
        with self._lock:
            w.inflight += 1

    def end_forward(self, url: str) -> None:
        w = self.workers.get(url.rstrip("/"))
        if w is None:
            return
        with self._lock:
            w.inflight = max(0, w.inflight - 1)

    def inflight(self, url: str) -> int:
        w = self.workers.get(url.rstrip("/"))
        if w is None:
            return 0
        with self._lock:
            return w.inflight

    # ---- routing state ----

    def mark_failed(self, url: str) -> None:
        """A forward to this worker died at the connection level: take
        it out of rotation NOW (the poller re-admits it when /healthz
        answers again)."""
        w = self.workers.get(url.rstrip("/"))
        if w is None:
            return
        with self._lock:
            if w.healthy:
                log.warning("fleet: worker %s marked DOWN "
                            "(connection failure mid-request)", w.url)
                self.registry.counter("fleet.worker_down_total").inc()
            w.healthy = False
            w.consecutive_fails = max(w.consecutive_fails,
                                      self.down_after)

    def eligible(self, kind: str) -> set[str]:
        """Workers that may serve ``kind`` right now: healthy, not
        draining (self-reported or supervisor-imposed), and without an
        open breaker for that endpoint."""
        with self._lock:
            return {
                u for u, w in self.workers.items()
                if w.healthy and not w.draining
                and not w.admin_draining
                and kind not in w.open_breakers
            }

    def fleet_availability(self) -> float | None:
        """Mean polled SLO availability over healthy workers (None
        until any worker reported one) — the admission shed signal."""
        with self._lock:
            vals = [w.availability for w in self.workers.values()
                    if w.healthy and w.availability is not None]
        return sum(vals) / len(vals) if vals else None

    def snapshot(self) -> dict:
        with self._lock:
            return {
                u: {
                    "healthy": w.healthy,
                    "draining": w.draining,
                    "admin_draining": w.admin_draining,
                    "inflight": w.inflight,
                    "consecutive_fails": w.consecutive_fails,
                    "open_breakers": sorted(w.open_breakers),
                    "availability": w.availability,
                }
                for u, w in sorted(self.workers.items())
            }


class RouterApp:
    """Routing + admission logic, independent of any socket (tests and
    the bench drive it in-process, commands/fleet.py serves it)."""

    def __init__(self, worker_urls: list[str],
                 quotas: list[str] | None = None,
                 max_inflight: int = 16,
                 aging_rate: float = 0.5,
                 default_timeout_s: float = 120.0,
                 poll_interval_s: float = 2.0,
                 down_after: int = 2,
                 shed_below: float = 0.0,
                 redirect: bool = False,
                 vnodes: int = 64,
                 registry: MetricsRegistry | None = None,
                 error_budget: float = 0.01,
                 flight_records: int = 64,
                 cache_dir: str | None = None,
                 cache_secret: str | None = None):
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self.ring = HashRing(worker_urls, vnodes=vnodes)
        self.pool = WorkerPool(worker_urls,
                               poll_interval_s=poll_interval_s,
                               down_after=down_after,
                               registry=self.registry)
        self.quotas = QuotaTable(quotas)
        self.scheduler = FairScheduler(max_inflight=max_inflight,
                                       aging_rate=aging_rate)
        self.default_timeout_s = default_timeout_s
        self.shed_below = shed_below
        self.redirect = redirect
        self.error_budget = error_budget
        self.started = time.time()
        # set by Supervisor.bind(); the router itself never calls it
        self.supervisor = None
        # the router's own flight ring: fleet.request.* trees (root +
        # per-attempt forward spans) retained by trace id — the
        # router-process half of every stitched /fleet/trace answer.
        # serve/flight.py is stdlib-only, so the router stays jax-free.
        from ..serve.flight import FlightRecorder

        self.flight = FlightRecorder(max_records=flight_records)
        self._tracer = obs.get_tracer()
        self._tracer.add_listener(self.flight.on_span)
        # the fleet's shared result-cache directory, advertised at
        # GET/PUT /fleet/cache/* for cross-fleet replication (the
        # federation's CacheSync pulls/pushes content-keyed entries).
        # Entries are pickles, so PUT requires an HMAC keyed by the
        # shared fleet secret — without one, pushes are refused
        from .cachesync import fleet_secret

        self.cache_dir = cache_dir
        self.cache_secret = cache_secret if cache_secret is not None \
            else fleet_secret()

    # ---- the cache replication endpoint (fleet/cachesync.py) ----

    _CACHE_NAME_RE = None  # compiled lazily (class attr, shared)

    @classmethod
    def _cache_name_ok(cls, name: str) -> bool:
        """Only ResultCache's own filenames replicate: 32 hex chars +
        ``.pkl`` — content-keyed by construction, and no path
        traversal is expressible in the alphabet."""
        import re as _re

        if cls._CACHE_NAME_RE is None:
            cls._CACHE_NAME_RE = _re.compile(r"^[0-9a-f]{32}\.pkl$")
        return bool(cls._CACHE_NAME_RE.match(name))

    def cache_list(self) -> tuple[int, dict]:
        if not self.cache_dir:
            return 404, {"error": "no shared cache on this fleet"}
        entries = []
        try:
            # gtlint: ok det-unsorted-iter — sorted below
            for name in os.listdir(self.cache_dir):
                if not self._cache_name_ok(name):
                    continue
                try:
                    st = os.stat(os.path.join(self.cache_dir, name))
                except OSError:
                    continue
                entries.append({"name": name, "size": st.st_size})
        except OSError as e:
            return 503, {"error": f"cache dir unreadable: {e}"}
        entries.sort(key=lambda e: e["name"])
        return 200, {"entries": entries}

    def cache_open(self, name: str):
        """(code, file-handle-or-error-dict, size) for one entry —
        the streaming form the HTTP handler uses (the whole entry is
        never buffered in router memory). Entries above the
        replication size cap are refused: nothing that big should
        have replicated in."""
        from .cachesync import MAX_ENTRY_BYTES

        if not self.cache_dir:
            return 404, {"error": "no shared cache on this fleet"}, 0
        if not self._cache_name_ok(name):
            return 400, {"error": f"bad cache entry name {name!r}"}, 0
        path = os.path.join(self.cache_dir, name)
        try:
            size = os.stat(path).st_size
            if size > MAX_ENTRY_BYTES:
                return 413, {"error": f"cache entry {name} exceeds "
                                      f"{MAX_ENTRY_BYTES} bytes"}, 0
            fh = open(path, "rb")
        except FileNotFoundError:
            return 404, {"error": f"no cache entry {name}"}, 0
        except OSError as e:
            return 503, {"error": f"cache read failed: {e}"}, 0
        self.registry.counter("fleet.cache_served_total").inc()
        return 200, fh, size

    def cache_get(self, name: str):
        """(code, bytes-or-error-dict) for one entry's raw bytes —
        the in-process convenience over :meth:`cache_open`."""
        code, body, _size = self.cache_open(name)
        if code != 200:
            return code, body
        with body:
            return 200, body.read()

    def cache_put(self, name: str, body, length: int | None = None,
                  auth: str | None = None) -> tuple[int, dict]:
        """Store one replicated entry. ``body`` is bytes or a
        file-like reader (``length`` required for a reader — the HTTP
        handler streams the request body straight to the tmp file in
        chunks). The write is tmp + atomic rename, so a reader never
        sees a torn entry.

        Entries are pickles, so this endpoint is the fleet's code-
        execution boundary and every push must authenticate: ``auth``
        carries an HMAC-SHA256 over ``name NUL data`` keyed by the
        shared fleet secret. No secret configured ⇒ replication is
        disabled (403). An entry that already exists is NEVER
        overwritten — names are content-keyed, so the push is an
        idempotent no-op (204) — meaning even a leaked signature
        cannot replace an existing result."""
        from .cachesync import (
            CACHE_AUTH_HEADER, MAX_ENTRY_BYTES, entry_hmac,
        )

        reject = self.registry.counter("fleet.cache_put_rejected_total")
        if not self.cache_dir:
            return 404, {"error": "no shared cache on this fleet"}
        if not self._cache_name_ok(name):
            reject.inc()
            return 400, {"error": f"bad cache entry name {name!r}"}
        if isinstance(body, (bytes, bytearray)):
            length = len(body)
        elif length is None:
            reject.inc()
            return 400, {"error": "length required for streamed put"}
        if length > MAX_ENTRY_BYTES:
            reject.inc()
            return 413, {"error": f"cache entry {name} exceeds "
                                  f"{MAX_ENTRY_BYTES} bytes"}
        if not self.cache_secret:
            reject.inc()
            return 403, {"error":
                         "cache replication disabled: no fleet secret "
                         "(set GOLEFT_TPU_FLEET_SECRET)"}
        if auth is None:
            reject.inc()
            return 401, {"error": f"missing {CACHE_AUTH_HEADER}"}
        dest = os.path.join(self.cache_dir, name)
        if os.path.exists(dest):
            # content-keyed: same name ⇒ same bytes — idempotent no-op
            return 204, {}
        mac = entry_hmac(self.cache_secret, name)
        tmp = dest + f".push.{os.getpid()}.tmp"
        try:
            os.makedirs(self.cache_dir, exist_ok=True)
            with open(tmp, "wb") as fh:
                if isinstance(body, (bytes, bytearray)):
                    mac.update(body)
                    fh.write(body)
                else:
                    remaining = length
                    while remaining > 0:
                        chunk = body.read(min(remaining, 1 << 20))
                        if not chunk:
                            raise OSError(
                                f"truncated push body for {name}: "
                                f"{remaining} bytes short")
                        mac.update(chunk)
                        fh.write(chunk)
                        remaining -= len(chunk)
            import hmac as _hmac_mod

            if not _hmac_mod.compare_digest(mac.hexdigest(),
                                            auth.strip().lower()):
                os.unlink(tmp)
                reject.inc()
                return 403, {"error": "bad cache entry signature"}
            os.replace(tmp, dest)
        except OSError as e:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return 503, {"error": f"cache write failed: {e}"}
        self.registry.counter("fleet.cache_stored_total").inc()
        return 204, {}

    def start(self) -> "RouterApp":
        self.pool.start()
        return self

    def close(self) -> None:
        self.pool.close()
        self._tracer.remove_listener(self.flight.on_span)

    # ---- dynamic membership ----
    #
    # Ring updates are copy-on-write reference swaps (atomic in
    # CPython), pool updates take the pool's lock — handler threads
    # racing a resize see either the old membership or the new one,
    # both internally consistent. A worker present in the ring but
    # absent from eligibility is harmless (it lands in the plan's
    # ineligible tail); the reverse (eligible but not in the ring) is
    # avoided by ordering: add ring-first, remove pool-visibility-first.

    def add_worker(self, url: str) -> None:
        url = url.rstrip("/")
        self.pool.add(url)
        ring = self.ring.with_node(url)
        # prune ghosts: when the LAST worker died, its node stayed on
        # the ring (an empty ring cannot plan) — drop any node the
        # pool no longer knows now that the ring is non-trivial again
        for node in ring.nodes:
            if node != url and node not in self.pool.workers:
                ring = ring.without_node(node)
        self.ring = ring

    def remove_worker(self, url: str) -> None:
        url = url.rstrip("/")
        self.pool.remove(url)
        self.ring = self.ring.without_node(url)

    def drain_worker(self, url: str) -> None:
        """Stop routing NEW traffic to ``url``; in-flight forwards
        finish (``pool.inflight(url)`` reaches 0 when they have)."""
        self.pool.set_draining(url, True)

    # ---- routing ----

    def affinity_key(self, kind: str, req: dict) -> str:
        """The ring key (module-level
        :func:`request_affinity_key`, shared with the federation)."""
        return request_affinity_key(kind, req)

    def plan(self, kind: str, req: dict) -> list[str]:
        """Candidate worker order for this request: the ring walk from
        its affinity key, eligible workers first (affinity preserved
        within each class)."""
        order = self.ring.candidates(self.affinity_key(kind, req))
        ok = self.pool.eligible(kind)
        return [u for u in order if u in ok] \
            + [u for u in order if u not in ok]

    def _forward(self, url: str, kind: str, body: bytes,
                 timeout_s: float,
                 trace: tuple[str, int] | None = None) \
            -> tuple[int, bytes]:
        headers = {"Content-Type": "application/json",
                   "Accept": "application/json"}
        if trace is not None:
            # the cross-process context: this trace's id + the forward
            # span's id, which the worker's request root records as
            # remote_parent — the graft point /fleet/trace stitches on
            headers[TRACE_HEADER] = format_trace_header(*trace)
        req = urllib.request.Request(
            url + "/v1/" + kind, data=body, headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=timeout_s) as r:
                return r.status, r.read()
        except urllib.error.HTTPError as e:
            return e.code, e.read()

    def handle_traced(self, kind: str, body: bytes,
                      trace_header: str | None = None) \
            -> tuple[int, dict | bytes, str]:
        """One routed request under a fleet-wide trace → (status,
        response bytes-or-dict, trace_id). The root adopts the
        client's ``x-goleft-trace`` context when one arrived (a traced
        ServeClient), else mints the fleet id itself; either way the
        id is echoed to the client as a response header and every
        forward carries it downstream."""
        parsed = parse_trace_header(trace_header)
        tid, remote_parent = parsed if parsed else (None, None)
        with obs.trace(f"fleet.request.{kind}", kind="serve",
                       trace_id=tid,
                       remote_parent=remote_parent) as root:
            code, payload = self.handle(kind, body)
            root.attrs["status"] = code
            return code, payload, root.trace_id

    def handle(self, kind: str, body: bytes) -> tuple[int, dict | bytes]:
        """One routed request → (status, response bytes-or-dict)."""
        try:
            req = json.loads(body or b"{}")
            if not isinstance(req, dict):
                raise ValueError("request body must be a JSON object")
        except ValueError as e:
            return 400, {"error": f"bad JSON body: {e}"}
        tenant = str(req.get("tenant") or "default")
        priority = int(req.get("priority", 0))
        timeout_s = float(req.get("timeout_s", self.default_timeout_s))
        c = self.registry.counter
        c(f"fleet.requests_total.{kind}").inc()

        # gate 1: per-tenant quota — one tenant's flood 429s only
        # itself, with an honest refill hint
        try:
            self.quotas.check(tenant)
        except QuotaExceeded as e:
            c(f"fleet.quota_rejected_total.{tenant}").inc()
            return 429, {"error": str(e),
                         "retry_after_s": round(e.retry_after_s, 3),
                         "tenant": tenant}

        # gate 2: availability shed — while the fleet is failing its
        # SLO, best-effort traffic (priority > 0) is shed so the
        # remaining capacity serves the interactive class
        if self.shed_below > 0 and priority > 0:
            avail = self.pool.fleet_availability()
            if avail is not None and avail < self.shed_below:
                c("fleet.shed_total").inc()
                return 503, {
                    "error": f"fleet availability {avail:.3f} below "
                             f"{self.shed_below:g}; best-effort "
                             "traffic shed",
                    "retry_after_s": self.pool.poll_interval_s}

        # gate 3: a fair forwarding slot (deadline-aware, aged)
        try:
            waited = self.scheduler.acquire(tenant, priority,
                                            timeout_s=timeout_s)
        except SchedulerTimeout as e:
            c("fleet.scheduler_timeouts_total").inc()
            return 504, {"error": str(e)}
        self.registry.histogram("fleet.queue_wait_s").observe(waited)
        try:
            return self._route(kind, req, body, timeout_s)
        finally:
            self.scheduler.release()

    def _route(self, kind: str, req: dict, body: bytes,
               timeout_s: float) -> tuple[int, dict | bytes]:
        candidates = self.plan(kind, req)
        eligible = self.pool.eligible(kind)
        live = [u for u in candidates if u in eligible]
        if not live:
            self.registry.counter("fleet.no_worker_total").inc()
            return 503, {
                "error": f"no healthy worker for {kind!r} "
                         f"({len(candidates)} known, 0 eligible)",
                "retry_after_s": self.pool.poll_interval_s}
        if self.redirect:
            # hand the client the home worker and get out of the way
            self.registry.counter(
                f"fleet.redirects_total.{kind}").inc()
            return 307, {"location": live[0] + "/v1/" + kind}
        last_err: dict | None = None
        for i, url in enumerate(live):
            if i > 0:
                self.registry.counter("fleet.retries_total").inc()
            wk = url.rsplit(":", 1)[-1]  # port: the stable short label
            self.pool.begin_forward(url)
            try:
                # one span per forward ATTEMPT: its span id rides the
                # trace header, so the worker tree grafts under the
                # attempt that actually served it (a retried request
                # shows the dead-end forward AND the successful one)
                with obs.span(f"fleet.forward.{kind}", url=url,
                              attempt=i) as fsp:
                    status, payload = self._forward(
                        url, kind, body, timeout_s,
                        trace=(fsp.trace_id, fsp.span_id))
                    fsp.attrs["status"] = status
            except Exception as e:  # noqa: BLE001 — connection-level
                # death (refused/reset/timeout): the worker, not the
                # request — eject it and try the next ring candidate
                self.pool.mark_failed(url)
                self.registry.counter(
                    f"fleet.worker_errors_total.{wk}").inc()
                last_err = {"error": f"worker {url} unreachable: "
                                     f"{e!r}"}
                continue
            finally:
                self.pool.end_forward(url)
            if status == 503:
                # the worker is shedding (breaker open / draining):
                # re-route reactively instead of bouncing the client —
                # the poller will import the breaker state for next
                # time
                self.registry.counter(
                    f"fleet.worker_shed_total.{wk}").inc()
                try:
                    last_err = json.loads(payload.decode())
                except ValueError:
                    last_err = {"error": f"worker {url} shed (503)"}
                continue
            self.registry.counter(
                f"fleet.routed_total.{wk}.{kind}").inc()
            if i == 0:
                self.registry.counter(
                    f"fleet.affinity_hits_total.{kind}").inc()
            return status, payload
        return 503, {**(last_err or {"error": "all workers failed"}),
                     "retry_after_s": self.pool.poll_interval_s}

    # ---- operability ----

    def healthz(self) -> tuple[int, dict]:
        snap = self.pool.snapshot()
        n_up = sum(1 for w in snap.values() if w["healthy"])
        body = {
            "status": "ok" if n_up else "degraded",
            "workers": len(snap), "healthy": n_up,
            "uptime_s": round(time.time() - self.started, 1),
            # wall clock for the tier ABOVE this one: the federation
            # poller runs the same midpoint clock handshake against
            # fleet routers that this router runs against workers
            "now": round(time.time(), 6),
        }
        if self.supervisor is not None:
            body["capacity"] = self.supervisor.capacity
            body["quarantined_slots"] = \
                self.supervisor.quarantined_slots
            if body["quarantined_slots"]:
                body["status"] = "degraded" if n_up else body["status"]
        return (200 if n_up else 503), body

    def metrics_snapshot(self) -> dict:
        g = self.registry.gauge
        g("fleet.queue_depth").set(self.scheduler.queue_depth())
        g("fleet.queue_age_s").set(
            round(self.scheduler.queue_age_s(), 4))
        g("fleet.inflight").set(self.scheduler.inflight())
        avail = self.pool.fleet_availability()
        if avail is not None:
            g("fleet.availability").set(round(avail, 6))
        self._rollup()  # refresh fleet.slo.burn_rate.* gauges
        snap = self.registry.snapshot()
        out = {
            "counters": snap["counters"],
            "gauges": snap["gauges"],
            "histograms": snap.get("histograms", {}),
            "workers": self.pool.snapshot(),
        }
        if self.supervisor is not None:
            out["supervisor"] = self.supervisor.snapshot()
            out["fleet.events"] = self.supervisor.events_block()
        return out

    # ---- the fleet observability plane ----

    def _rollup(self) -> dict:
        """Merge the poller's per-worker metrics snapshots
        (obs/fleetplane.py rules) and publish the fleet SLO burn-rate
        gauges into the router registry — so they ride the plain
        /metrics body too, not just /fleet/metrics."""
        merged = merge_worker_metrics(self.pool.metrics_by_worker(),
                                      error_budget=self.error_budget)
        g = self.registry.gauge
        slo = merged["slo"]
        g("fleet.slo.error_rate").set(slo["error_rate"])
        g("fleet.slo.burn_rate_max").set(slo["burn_rate_max"])
        for ep, r in slo["burn_rate"].items():
            g(f"fleet.slo.burn_rate.{ep}").set(r)
        for tenant, rec in (slo.get("tenants") or {}).items():
            g(f"fleet.slo.tenant.burn_rate.{tenant}").set(
                rec["burn_rate"])
        return merged

    def fleet_burn_rate(self) -> float:
        """Worst per-endpoint SLO burn rate across the fleet right now
        (>1.0 = burning budget faster than earning it) — the
        supervisor autoscaler's scale-up signal beyond queue age."""
        return self._rollup()["slo"]["burn_rate_max"]

    def fleet_metrics(self) -> dict:
        """The ``GET /fleet/metrics`` JSON body: the full rollup plus
        the router's own registry snapshot alongside (two layers, one
        document — worker evidence and router evidence never mix
        namespaces)."""
        merged = self._rollup()
        merged["router"] = self.registry.snapshot()
        return merged

    def fleet_metrics_prometheus(self) -> str:
        """The same rollup as Prometheus text exposition: the merged
        worker registry flattened (fleet.worker.*, fleet.slo.*) plus
        the router's own registry — one scrape target for the whole
        fleet."""
        from ..obs import prometheus

        merged = self._rollup()
        flat = rollup_registry_snapshot(merged)
        router_snap = self.registry.snapshot()
        for group in ("counters", "gauges", "histograms"):
            flat[group].update(router_snap.get(group, {}))
        return prometheus.render(flat)

    def fleet_trace(self, trace_id: str) -> tuple[int, dict]:
        """``GET /fleet/trace/<id>``: pull every worker's flight
        records for ``trace_id`` (the ``?trace_id=`` filter), stitch
        them under this router's own record, and attach the Perfetto
        export. 404 only when NO process holds the trace (evicted
        rings or a never-seen id)."""
        from urllib.parse import quote

        own = self.flight.snapshot(trace_id=trace_id)
        worker_records: dict[str, list] = {}
        for url in sorted(self.pool.workers):
            try:
                d = self.pool._fetch_json(
                    url + "/debug/flight?trace_id="
                    + quote(trace_id))
                worker_records[url] = d.get("records") or []
            except Exception:  # noqa: BLE001 — a dead worker cannot
                # veto the stitched view of everyone else's spans
                worker_records[url] = []
        stitched = stitch_trace(trace_id, own, worker_records,
                                clock_offsets=self.pool
                                .clock_offsets())
        if stitched is None:
            return 404, {
                "error": f"no flight record for trace {trace_id!r} "
                         "in the router or any worker (rings are "
                         "bounded — the trace may have been evicted)"}
        stitched["perfetto"] = perfetto_export(trace_id, stitched)
        return 200, stitched

    def fleet_profile(self, seconds: float) -> dict:
        """``GET /fleet/profile?seconds=N``: collect every worker's
        ``/debug/profile`` window IN PARALLEL (the windows must
        overlap — serial collection would profile N disjoint
        intervals) and merge stack-wise: each merged counter is the
        exact arithmetic sum of the workers' counters, the PR-13
        metrics-rollup discipline. A dead or profiling-disabled
        worker cannot veto the rest — it is reported per-worker and
        counted (``fleet.profile.worker_errors_total``)."""
        from urllib.parse import quote

        from ..obs.profiler import MAX_WINDOW_S, merge_profiles

        seconds = max(0.0, min(float(seconds), MAX_WINDOW_S))
        self.registry.counter("fleet.profile.requests_total").inc()
        urls = sorted(self.pool.workers)
        bodies: list[dict | None] = [None] * len(urls)
        errors: dict[str, str] = {}

        def fetch(i: int, url: str) -> None:
            # a dedicated request, NOT pool._fetch_json: the worker
            # intentionally sleeps the whole window before answering,
            # which would blow the pool's short poll timeout
            req = urllib.request.Request(
                url + f"/debug/profile?seconds={quote(str(seconds))}",
                headers={"Accept": "application/json"})
            try:
                with urllib.request.urlopen(
                        req, timeout=seconds + 10.0) as r:
                    bodies[i] = json.loads(r.read().decode())
            except Exception as e:  # noqa: BLE001 — per-worker fault
                errors[url] = str(e)

        threads: list[threading.Thread] = []
        for i, url in enumerate(urls):
            t = threading.Thread(target=fetch, args=(i, url),
                                 name=f"goleft-fleet-profile-{i}")
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=seconds + 30.0)
        if errors:
            self.registry.counter(
                "fleet.profile.worker_errors_total").inc(len(errors))
        merged = merge_profiles([b for b in bodies if b is not None])
        merged["seconds"] = seconds
        merged["per_worker"] = {
            url: ({"error": errors[url]} if url in errors else {
                "samples_total":
                    int((bodies[i] or {}).get("samples_total") or 0),
                "stacks": len((bodies[i] or {}).get("stacks") or {}),
                "enabled":
                    bool((bodies[i] or {}).get("enabled")),
            })
            for i, url in enumerate(urls)
        }
        return merged

    def fleet_compiles(self) -> dict:
        """``GET /fleet/compiles``: every worker's compile observatory
        merged into one fleet-wide warmup manifest (merge-on-update
        semantics — per-signature tallies sum across workers)."""
        from ..obs.compiles import (
            WARMUP_SCHEMA, merge_warmup_docs, validate_warmup_manifest,
        )

        manifests = []
        per_worker: dict[str, dict] = {}
        for url in sorted(self.pool.workers):
            try:
                d = self.pool._fetch_json(url + "/debug/compiles")
                m = {"schema": WARMUP_SCHEMA,
                     "signatures": d.get("signatures") or []}
                validate_warmup_manifest(m)
                manifests.append(m)
                per_worker[url] = {
                    "events_total": int(d.get("events_total") or 0),
                    "compiles_total":
                        int(d.get("compiles_total") or 0),
                    "signatures": len(m["signatures"]),
                }
            except Exception as e:  # noqa: BLE001 — per-worker fault
                per_worker[url] = {"error": str(e)}
        merged = merge_warmup_docs(*manifests) if manifests \
            else {"schema": WARMUP_SCHEMA, "signatures": []}
        merged["per_worker"] = per_worker
        return merged

    def fleet_memory(self) -> dict:
        """``GET /fleet/memory``: every worker's ``/debug/memory``
        merged — counters as EXACT arithmetic sums of the worker
        bodies (pinned by test, JSON and prom encodings both),
        gauges as per-worker {min, max, sum}, device family bytes
        summed family-wise. Collection is instant (each worker
        answers from current state, no window to overlap), so the
        serial /fleet/compiles pattern is right here; a dead worker
        is reported per-worker and counted
        (``fleet.memory.worker_errors_total``) but cannot veto the
        merge."""
        from ..obs.memplane import merge_memory

        bodies: list[dict] = []
        per_worker: dict[str, dict] = {}
        n_err = 0
        for url in sorted(self.pool.workers):
            try:
                d = self.pool._fetch_json(url + "/debug/memory")
                bodies.append(d)
                per_worker[url] = {
                    "rss_bytes": int((d.get("host") or {})
                                     .get("rss_bytes") or 0),
                    "device_live_bytes":
                        int((d.get("device") or {})
                            .get("total_bytes") or 0),
                    "pressure": (d.get("pressure") or {})
                    .get("state") or "?",
                    "enabled": bool(d.get("enabled")),
                }
            except Exception as e:  # noqa: BLE001 — per-worker fault
                per_worker[url] = {"error": str(e)}
                n_err += 1
        if n_err:
            self.registry.counter(
                "fleet.memory.worker_errors_total").inc(n_err)
        merged = merge_memory(bodies)
        merged["per_worker"] = per_worker
        return merged

    def fleet_memory_prometheus(self) -> str:
        """The same merged document as Prometheus text exposition:
        counter sums ride verbatim (``memory_*_total`` lines ARE the
        exact worker sums), gauges flatten to ``_min/_max/_sum``
        series."""
        from ..obs import prometheus
        from ..obs.memplane import flatten_merged

        return prometheus.render(flatten_merged(self.fleet_memory()))


class _RouterHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        log.debug("%s " + fmt, self.address_string(), *args)

    @property
    def app(self) -> RouterApp:
        return self.server.app

    def _respond_json(self, code: int, body: dict,
                      extra_headers: dict | None = None) -> None:
        data = json.dumps(body).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.send_header("Connection", "close")
        self.end_headers()
        self.wfile.write(data)
        self.close_connection = True

    def _respond_raw(self, code: int, data: bytes,
                     extra_headers: dict | None = None) -> None:
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.send_header("Connection", "close")
        self.end_headers()
        self.wfile.write(data)
        self.close_connection = True

    def do_GET(self):  # noqa: N802 — http.server contract
        from urllib.parse import parse_qs, unquote, urlparse

        u = urlparse(self.path)
        if u.path == "/healthz":
            code, body = self.app.healthz()
            self._respond_json(code, body)
        elif u.path == "/fleet/metrics":
            q = parse_qs(u.query)
            fmt = q.get("format", [""])[0]
            accept = self.headers.get("Accept", "")
            if fmt in ("prom", "prometheus") or (
                    not fmt and "text/plain" in accept
                    and "json" not in accept):
                from ..obs.prometheus import CONTENT_TYPE

                data = self.app.fleet_metrics_prometheus().encode()
                self.send_response(200)
                self.send_header("Content-Type", CONTENT_TYPE)
                self.send_header("Content-Length", str(len(data)))
                self.send_header("Connection", "close")
                self.end_headers()
                self.wfile.write(data)
                self.close_connection = True
            else:
                self._respond_json(200, self.app.fleet_metrics())
        elif u.path.startswith("/fleet/trace/"):
            trace_id = unquote(u.path[len("/fleet/trace/"):])
            code, body = self.app.fleet_trace(trace_id)
            self._respond_json(code, body)
        elif u.path == "/fleet/profile":
            q = parse_qs(u.query)
            try:
                seconds = float(q["seconds"][0]) \
                    if "seconds" in q else 1.0
            except ValueError:
                self._respond_json(
                    400, {"error": "seconds must be a number"})
                return
            self._respond_json(200, self.app.fleet_profile(seconds))
        elif u.path == "/fleet/compiles":
            self._respond_json(200, self.app.fleet_compiles())
        elif u.path == "/fleet/memory":
            q = parse_qs(u.query)
            fmt = q.get("format", [""])[0]
            accept = self.headers.get("Accept", "")
            if fmt in ("prom", "prometheus") or (
                    not fmt and "text/plain" in accept
                    and "json" not in accept):
                from ..obs.prometheus import CONTENT_TYPE

                data = self.app.fleet_memory_prometheus().encode()
                self.send_response(200)
                self.send_header("Content-Type", CONTENT_TYPE)
                self.send_header("Content-Length", str(len(data)))
                self.send_header("Connection", "close")
                self.end_headers()
                self.wfile.write(data)
                self.close_connection = True
            else:
                self._respond_json(200, self.app.fleet_memory())
        elif u.path == "/fleet/cache/" or u.path == "/fleet/cache":
            code, body = self.app.cache_list()
            self._respond_json(code, body)
        elif u.path.startswith("/fleet/cache/"):
            name = unquote(u.path[len("/fleet/cache/"):])
            code, body, size = self.app.cache_open(name)
            if code == 200:
                # stream the entry file in chunks — the router never
                # holds a whole (up to MAX_ENTRY_BYTES) entry in memory
                self.send_response(code)
                self.send_header("Content-Type",
                                 "application/octet-stream")
                self.send_header("Content-Length", str(size))
                self.send_header("Connection", "close")
                self.end_headers()
                with body:
                    while True:
                        chunk = body.read(1 << 20)
                        if not chunk:
                            break
                        self.wfile.write(chunk)
                self.close_connection = True
            else:
                self._respond_json(code, body)
        elif u.path == "/metrics":
            self._respond_json(200, self.app.metrics_snapshot())
        else:
            self._respond_json(404,
                               {"error": f"no route {self.path}"})

    def do_PUT(self):  # noqa: N802 — http.server contract
        from urllib.parse import unquote, urlparse

        from .cachesync import CACHE_AUTH_HEADER, MAX_ENTRY_BYTES

        u = urlparse(self.path)
        if not u.path.startswith("/fleet/cache/"):
            self._respond_json(404,
                               {"error": f"no route {self.path}"})
            return
        try:
            n = int(self.headers.get("Content-Length", "0"))
        except ValueError:
            self._respond_json(400, {"error": "bad Content-Length"})
            self.close_connection = True
            return
        if n > MAX_ENTRY_BYTES:
            # refuse BEFORE reading: an oversized push must not
            # buffer (or even transit) on the jax-free router
            self._respond_json(
                413, {"error": f"entry exceeds {MAX_ENTRY_BYTES} "
                               "bytes"})
            self.close_connection = True
            return
        name = unquote(u.path[len("/fleet/cache/"):])
        code, body = self.app.cache_put(
            name, self.rfile, length=n,
            auth=self.headers.get(CACHE_AUTH_HEADER))
        if code == 204:
            self.send_response(204)
            self.send_header("Content-Length", "0")
            self.send_header("Connection", "close")
            self.end_headers()
            self.close_connection = True
        else:
            self._respond_json(code, body)

    def do_POST(self):  # noqa: N802 — http.server contract
        n = int(self.headers.get("Content-Length", "0"))
        body = self.rfile.read(n)
        if self.path == "/fleet/plan":
            try:
                req = json.loads(body or b"{}")
                kind = req.pop("kind")
            except (ValueError, KeyError):
                self._respond_json(
                    400, {"error": "want a JSON object with 'kind'"})
                return
            self._respond_json(
                200, {"candidates": self.app.plan(kind, req)})
            return
        if not self.path.startswith("/v1/"):
            self._respond_json(404,
                               {"error": f"no route {self.path}"})
            return
        kind = self.path[len("/v1/"):].strip("/")
        code, payload, trace_id = self.app.handle_traced(
            kind, body, self.headers.get(TRACE_HEADER))
        # echo the fleet trace id (minted here when the client sent
        # none) so ANY client can follow up with
        # `goleft-tpu trace <id> --router URL`
        trace_hdr = {TRACE_HEADER: trace_id}
        if code == 307:
            # redirect mode: Location + a JSON body naming it (for
            # clients that refuse to follow)
            self._respond_json(code, payload,
                               extra_headers={
                                   "Location": payload["location"],
                                   **trace_hdr})
        elif isinstance(payload, bytes):
            self._respond_raw(code, payload, extra_headers=trace_hdr)
        else:
            self._respond_json(code, payload,
                               extra_headers=trace_hdr)


class _RouterServer(ThreadingHTTPServer):
    daemon_threads = False
    block_on_close = True
    allow_reuse_address = True


def make_router_server(app: RouterApp, host: str = "127.0.0.1",
                       port: int = 0) -> ThreadingHTTPServer:
    srv = _RouterServer((host, port), _RouterHandler)
    srv.app = app
    return srv


class RouterThread:
    """In-process router harness (tests, the bench):
    ``with RouterThread(app) as url: ...``"""

    def __init__(self, app: RouterApp, host: str = "127.0.0.1",
                 port: int = 0):
        self.app = app
        self.httpd = make_router_server(app, host, port)
        self._thread = threading.Thread(
            target=self.httpd.serve_forever,
            kwargs={"poll_interval": 0.05},
            daemon=True, name="goleft-fleet-http")

    @property
    def base_url(self) -> str:
        host, port = self.httpd.server_address[:2]
        return f"http://{host}:{port}"

    def __enter__(self) -> str:
        self.app.start()
        self._thread.start()
        return self.base_url

    def __exit__(self, *exc):
        self.httpd.shutdown()
        self._thread.join(timeout=30.0)
        self.httpd.server_close()
        self.app.close()
        return False
