"""End-to-end fleet smoke: the ``make fleet-smoke`` body.

Real subprocess daemons all the way down (the acceptance contract):

  1. **byte identity**: a continuous-batching daemon and a
     window-batching daemon answer depth / indexcov / cohortdepth /
     pairhmm identically, and the payloads that ARE one-shot-CLI bytes
     (depth beds, the cohortdepth matrix, the pairhmm table) equal the
     CLI bodies run in-process on the same fixtures. (The indexcov
     serve response has been a JSON summary — not CLI file bytes —
     since PR 2; it is pinned continuous == window.)
  2. **cross-request step dedup**: two concurrent identical depth
     requests against a daemon whose first device pass is held open by
     an injected ``hang`` fault produce ONE device pass
     (``serve_device_passes_total == 1``,
     ``plan_steps_deduped_total >= 1``) and two byte-identical 200s.
  3. **router retry across worker death**: a depth request is routed
     to its affinity home, the home worker is SIGKILLed mid-flight,
     and the router retries on the sibling — the client sees one
     byte-identical 200 (``fleet.retries_total`` incremented).
  4. **per-site breaker shed**: a worker whose ``pairhmm`` breaker is
     tripped (injected permanent faults) loses only its pairhmm
     traffic after the router imports its breaker state; depth
     traffic with affinity to that worker keeps landing on it.
  5. **per-tenant quotas**: a tenant exhausting its token bucket gets
     429 + ``retry_after_s`` while another tenant's requests sail
     through; a retry-aware client (serve/client.py ``retries=1``)
     honors the hint and lands the follow-up 200.

Run directly::

    python -m goleft_tpu.fleet.smoke
"""

from __future__ import annotations

import io
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time

from ..resilience.smoke import _make_cohort, _stop_daemon


def _spawn(args, env):
    """A goleft-tpu child announcing ``listening on URL``; returns
    (child, url)."""
    child = subprocess.Popen(
        [sys.executable, "-m", "goleft_tpu", *args],
        stdout=subprocess.PIPE, text=True, env=env)
    line = child.stdout.readline()
    if "listening on " not in line:
        child.kill()
        raise RuntimeError(f"child did not announce its port: "
                           f"{line!r} (args {args})")
    return child, line.rsplit("listening on ", 1)[1].strip()


def _spawn_worker(env, *extra):
    return _spawn(["serve", "--port", "0", "--no-warmup", *extra],
                  env)


def _spawn_router(env, worker_urls, *extra):
    args = ["fleet", "--port", "0", "--poll-interval-s", "0.3",
            "--down-after", "1"]
    for u in worker_urls:
        args += ["--worker", u]
    return _spawn(args + list(extra), env)


def _write_windows(d: str) -> str:
    """The pairhmm fixture (the pairhmm smoke's shape: one informative
    window, one far-away window)."""
    import numpy as np

    rng = np.random.default_rng(6)
    bases = list("ACGT")
    ref = "".join(rng.choice(bases, 60))
    alt = ref[:29] + ("A" if ref[29] != "A" else "C") + ref[30:]
    reads = [{"seq": (ref if i % 2 else alt)[s:s + 40], "quals": 35}
             for i, s in ((i, int(rng.integers(0, 10)))
                          for i in range(8))]
    doc = {"schema": "goleft-tpu.pairhmm-windows/1",
           "windows": [
               {"chrom": "chr1", "start": 100, "end": 400,
                "haplotypes": [ref, alt], "reads": reads},
               {"chrom": "chr1", "start": 4000, "end": 4100,
                "haplotypes": [ref], "reads": reads[:2]},
           ]}
    path = os.path.join(d, "windows.json")
    with open(path, "w") as fh:
        json.dump(doc, fh)
    return path


def _prom_counter(prom: str, name: str) -> int:
    import re

    m = re.search(rf"^{re.escape(name)} (\d+)", prom, re.M)
    return int(m.group(1)) if m else 0


def _leg_byte_identity(d, bams, fai, windows, env, verbose):
    """Leg 1: continuous == window == one-shot CLI bytes."""
    from ..commands.cohortdepth import run_cohortdepth
    from ..commands.depth import run_depth
    from ..commands.pairhmm_cmd import run_pairhmm
    from ..serve.client import ServeClient

    # in-process one-shot CLI references (run_* ARE the CLI bodies)
    dp, cp = run_depth(bams[0], os.path.join(d, "ref-depth"),
                       fai=fai, window=200)
    with open(dp) as fh:
        ref_depth = fh.read()
    with open(cp) as fh:
        ref_callable = fh.read()
    buf = io.StringIO()
    assert run_cohortdepth(bams, fai=fai, window=200, out=buf,
                           processes=2) == 0
    ref_matrix = buf.getvalue()
    buf = io.StringIO()
    assert run_pairhmm(windows, out=buf) == 0
    ref_table = buf.getvalue()

    responses = {}
    for mode in ("continuous", "window"):
        child, url = _spawn_worker(env, "--batch-mode", mode)
        try:
            client = ServeClient(url, timeout_s=120.0)
            responses[mode] = {
                "depth": client.depth(bams[0], fai=fai, window=200),
                "indexcov": client.indexcov(bams, fai),
                "cohortdepth": client.cohortdepth(bams, fai=fai,
                                                  window=200),
                "pairhmm": client.pairhmm(windows),
            }
        finally:
            _stop_daemon(child)
    cont, win = responses["continuous"], responses["window"]
    for kind in ("depth", "indexcov", "cohortdepth", "pairhmm"):
        if cont[kind] != win[kind]:
            raise RuntimeError(
                f"continuous vs window responses differ for {kind}")
    if cont["depth"]["depth_bed"] != ref_depth \
            or cont["depth"]["callable_bed"] != ref_callable:
        raise RuntimeError("serve depth != one-shot CLI bytes")
    if cont["cohortdepth"]["matrix_tsv"] != ref_matrix:
        raise RuntimeError("serve cohortdepth != one-shot CLI bytes")
    if cont["pairhmm"]["likelihoods_tsv"] != ref_table:
        raise RuntimeError("serve pairhmm != one-shot CLI bytes")
    if verbose:
        print("fleet-smoke: continuous == window == one-shot CLI "
              "bytes (depth/indexcov/cohortdepth/pairhmm)")


def _leg_dedup(d, bams, fai, env, verbose):
    """Leg 2: two concurrent identical requests → one device pass."""
    from ..serve.client import ServeClient

    # hold the FIRST device pass open 1.5s so the second (identical)
    # request provably arrives while the leader is in flight
    env = dict(env, GOLEFT_TPU_FAULTS="device:after=1:hang=1.5")
    child, url = _spawn_worker(env)
    try:
        client = ServeClient(url, timeout_s=120.0)
        out = [None, None]
        errs = []

        def fire(i):
            try:
                out[i] = client.depth(bams[0], fai=fai, window=180)
            except Exception as e:  # noqa: BLE001 — asserted below
                errs.append(e)

        t0 = threading.Thread(target=fire, args=(0,))
        t0.start()
        time.sleep(0.6)  # leader is inside the 1.5s hang
        t1 = threading.Thread(target=fire, args=(1,))
        t1.start()
        for t in (t0, t1):
            t.join(timeout=120)
        if errs:
            raise RuntimeError(f"dedup leg request failed: {errs}")
        if out[0] != out[1] or not out[0]["depth_bed"]:
            raise RuntimeError("deduped responses are not "
                               "byte-identical")
        prom = client.metrics_prometheus()
        passes = _prom_counter(prom, "serve_device_passes_total")
        deduped = _prom_counter(prom, "plan_steps_deduped_total")
        req_dedup = _prom_counter(prom,
                                  "serve_request_deduped_total_depth")
        if passes != 1:
            raise RuntimeError(
                f"two identical concurrent requests cost {passes} "
                "device pass(es), want exactly 1")
        if deduped < 1 or req_dedup != 1:
            raise RuntimeError(
                f"dedup counters wrong: plan={deduped}, "
                f"request={req_dedup}")
        if verbose:
            print("fleet-smoke: concurrent identical requests "
                  f"deduped (1 device pass, {deduped} plan-level "
                  "join(s), byte-identical 200s)")
    finally:
        _stop_daemon(child)


def _leg_router_sigkill_retry(d, bams, fai, env, verbose):
    """Leg 3: SIGKILL the affinity home mid-flight → router retries
    on the sibling → byte-identical 200."""
    from ..commands.depth import run_depth
    from ..serve.client import ServeClient

    dp, _ = run_depth(bams[1], os.path.join(d, "ref-kill"),
                      fai=fai, window=175)
    with open(dp) as fh:
        ref_bed = fh.read()
    # every device pass hangs 2s (twice): the mid-flight window we
    # kill into, on whichever worker gets the request
    wenv = dict(env, GOLEFT_TPU_FAULTS="device:every=1:hang=2:times=2")
    w0, u0 = _spawn_worker(wenv)
    w1, u1 = _spawn_worker(wenv)
    router = None
    try:
        router, rurl = _spawn_router(env, [u0, u1])
        client = ServeClient(rurl, timeout_s=120.0)
        home = client.route_plan("depth", bam=bams[1])[0]
        victim = w0 if home == u0 else w1
        out = {}
        errs = []

        def fire():
            try:
                out["r"] = client.depth(bams[1], fai=fai, window=175)
            except Exception as e:  # noqa: BLE001 — asserted below
                errs.append(e)

        t = threading.Thread(target=fire)
        t.start()
        time.sleep(0.9)  # forwarded; home is inside its 2s hang
        victim.kill()    # SIGKILL, not SIGTERM: no drain, no goodbye
        victim.wait(timeout=10)
        t.join(timeout=120)
        if errs:
            raise RuntimeError(
                f"request did not survive the worker kill: {errs}")
        if out["r"]["depth_bed"] != ref_bed:
            raise RuntimeError(
                "post-retry response is not byte-identical to the "
                "one-shot CLI")
        m = client.metrics()
        if m["counters"].get("fleet.retries_total", 0) < 1:
            raise RuntimeError("router did not count the retry")
        if m["workers"][home]["healthy"]:
            raise RuntimeError("dead worker still marked healthy")
        if verbose:
            print("fleet-smoke: SIGKILLed the affinity home "
                  "mid-flight; router retried on the sibling "
                  "(byte-identical 200, retries_total="
                  f"{m['counters']['fleet.retries_total']})")
    finally:
        if router is not None:
            _stop_daemon(router)
        for w in (w0, w1):
            if w.poll() is None:
                w.kill()
                w.wait(timeout=10)
            w.stdout.close()


def _leg_breaker_shed_and_quota(d, bams, fai, windows, env, verbose):
    """Legs 4+5: per-site breaker shed via the router, then tenant
    quotas (one router hosts both: quotas configured at spawn)."""
    import shutil

    from ..serve.client import ServeClient, ServeError

    # w_fault: every pairhmm dispatch fails permanently; threshold 2
    # trips its breaker. w_clean: healthy sibling.
    fenv = dict(env, GOLEFT_TPU_FAULTS="pairhmm:every=1:permanent")
    w_fault, uf = _spawn_worker(fenv, "--breaker-threshold", "2",
                                "--breaker-cooldown-s", "600")
    w_clean, uc = _spawn_worker(env)
    router = None
    try:
        router, rurl = _spawn_router(
            env, [uf, uc], "--quota", "alice=0.5:2")
        client = ServeClient(rurl, timeout_s=120.0)

        # trip w_fault's pairhmm breaker DIRECTLY (not via the
        # router: the trip itself is the worker's own 500 story)
        direct = ServeClient(uf, timeout_s=60.0)
        for _ in range(2):
            try:
                direct.pairhmm(windows)
                raise RuntimeError("faulted pairhmm unexpectedly ok")
            except ServeError as e:
                if e.status != 500:
                    raise RuntimeError(
                        f"want 500 from faulted worker, got "
                        f"{e.status}")
        if direct.metrics()["breakers"]["pairhmm"] != "open":
            raise RuntimeError("pairhmm breaker did not trip")
        time.sleep(0.8)  # two poll intervals: router imports state

        # pairhmm now avoids w_fault entirely…
        plan = client.route_plan("pairhmm", input=windows)
        if plan[0] == uf:
            raise RuntimeError(
                "router still plans pairhmm onto the tripped worker")
        r = client.pairhmm(windows)
        if not r.get("likelihoods_tsv"):
            raise RuntimeError("re-routed pairhmm response empty")
        # …while depth traffic whose affinity home IS w_fault keeps
        # landing there (shed is per-site, not per-worker). Find —
        # or mint — a bam homed on w_fault (content identity includes
        # the path, so copies re-roll the ring position).
        probe = None
        for i in range(24):
            cand = bams[2] if i == 0 \
                else os.path.join(d, f"homed{i}.bam")
            if i > 0:
                shutil.copy(bams[2], cand)
                shutil.copy(bams[2] + ".bai", cand + ".bai")
            if client.route_plan("depth", bam=cand)[0] == uf:
                probe = cand
                break
        if probe is None:
            raise RuntimeError(
                "could not mint a bam homed on the tripped worker")
        if not client.depth(probe, fai=fai,
                            window=200)["depth_bed"]:
            raise RuntimeError("depth via tripped-pairhmm worker "
                               "failed")
        port_f = uf.rsplit(":", 1)[-1]
        m = client.metrics()
        if m["counters"].get(
                f"fleet.routed_total.{port_f}.depth", 0) < 1:
            raise RuntimeError(
                "depth request did not land on the tripped worker")
        if m["counters"].get(
                f"fleet.routed_total.{port_f}.pairhmm", 0) != 0:
            raise RuntimeError(
                "pairhmm traffic still reached the tripped worker")
        if verbose:
            print("fleet-smoke: tripped pairhmm breaker sheds ONLY "
                  "pairhmm traffic (depth still lands on the "
                  "worker)")

        # leg 5: tenant quotas. alice has burst 2 at 0.5/s; bob is
        # unmetered. Distinct cache_busters keep requests distinct.
        client.depth(probe, fai=fai, window=200, tenant="alice",
                     cache_buster=1)
        client.depth(probe, fai=fai, window=200, tenant="alice",
                     cache_buster=2)
        try:
            client.depth(probe, fai=fai, window=200, tenant="alice",
                         cache_buster=3)
            raise RuntimeError("alice's third burst request was not "
                               "shed")
        except ServeError as e:
            if e.status != 429 or not e.retry_after_s:
                raise RuntimeError(
                    f"want 429 + retry_after_s, got {e.status} "
                    f"{e.retry_after_s!r}")
            hint = e.retry_after_s
        # bob is untouched by alice's exhaustion
        if not client.depth(probe, fai=fai, window=200,
                            tenant="bob")["depth_bed"]:
            raise RuntimeError("bob's request failed during alice's "
                               "quota exhaustion")
        # the retry-aware client honors the hint and lands the 200
        patient = ServeClient(rurl, timeout_s=120.0, retries=1)
        t0 = time.monotonic()
        r = patient.depth(probe, fai=fai, window=200,
                          tenant="alice", cache_buster=4)
        waited = time.monotonic() - t0
        if not r["depth_bed"] or waited < min(hint, 1.0) * 0.5:
            raise RuntimeError(
                f"retry-aware client did not honor retry_after_s "
                f"(waited {waited:.2f}s, hint {hint:.2f}s)")
        if verbose:
            print("fleet-smoke: tenant quota shed alice with 429 + "
                  f"retry_after_s={hint:.2f} (bob unaffected; "
                  "retry-aware client honored the hint)")
    finally:
        if router is not None:
            _stop_daemon(router)
        for w in (w_fault, w_clean):
            _stop_daemon(w)


def run_smoke(timeout_s: float = 600.0, verbose: bool = True) -> int:
    """Returns 0 on success; raises on any failed step."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",     # CI has no accelerator
               GOLEFT_TPU_PROBE="0")    # don't pay a probe timeout
    env.pop("GOLEFT_TPU_FAULTS", None)  # hermetic
    t0 = time.monotonic()
    with tempfile.TemporaryDirectory(prefix="goleft_fleet_") as d:
        # ref_len 20k: indexcov needs at least one full 16kb index
        # tile per chromosome to have usable bins
        bams, fai, _bed = _make_cohort(d, ref_len=20_000)
        windows = _write_windows(d)
        _leg_byte_identity(d, bams, fai, windows, env, verbose)
        _leg_dedup(d, bams, fai, env, verbose)
        _leg_router_sigkill_retry(d, bams, fai, env, verbose)
        _leg_breaker_shed_and_quota(d, bams, fai, windows, env,
                                    verbose)
        if time.monotonic() - t0 > timeout_s:
            raise RuntimeError(
                f"fleet-smoke exceeded its {timeout_s:g}s budget")
        if verbose:
            print(f"fleet-smoke: PASS "
                  f"({time.monotonic() - t0:.1f}s)")
    return 0


if __name__ == "__main__":
    sys.exit(run_smoke())
